"""pyarrow RecordBatch <-> DeviceBatch conversion.

This is the host<->device boundary, the analogue of the reference's Arrow
C-FFI import/export between JVM and native (reference: auron-core/src/main/
java/org/apache/auron/arrowio/..., native-engine/auron/src/rt.rs:252-282).
On TPU the transfer is a single jax.device_put of dense padded buffers per
column — no per-row work on either side of the wall.
"""

from __future__ import annotations

import numpy as np
import pyarrow as pa

import jax.numpy as jnp

from auron_tpu.columnar.batch import (DeviceBatch, ListColumn,
                                      PrimitiveColumn, StringColumn)
from auron_tpu.columnar.schema import DataType, Field, Schema
from auron_tpu.utils.shapes import bucket_rows, bucket_string_width

#: fallback precision for a LIST-of-decimal field whose precision slot is
#: 0 (pre-fix partial layouts): ONE constant shared by schema_to_arrow
#: and every child-array render site — diverging fallbacks (38 in the
#: schema vs 18 in the HostList child) made the child array type
#: mismatch the declared schema at table assembly (ADVICE round 5)
_LIST_DECIMAL_FALLBACK_PRECISION = 38

_PA_TO_DT = {
    pa.bool_(): DataType.BOOL,
    pa.int8(): DataType.INT8,
    pa.int16(): DataType.INT16,
    pa.int32(): DataType.INT32,
    pa.int64(): DataType.INT64,
    pa.float32(): DataType.FLOAT32,
    pa.float64(): DataType.FLOAT64,
    pa.date32(): DataType.DATE32,
    pa.timestamp("us"): DataType.TIMESTAMP_US,
    pa.string(): DataType.STRING,
    pa.large_string(): DataType.STRING,
    pa.null(): DataType.NULL,
}


def schema_from_arrow(sch: pa.Schema) -> Schema:
    fields = []
    for f in sch:
        t = f.type
        if pa.types.is_decimal(t):
            if t.precision > 38:
                raise NotImplementedError(
                    f"decimal precision {t.precision} > 38 not supported")
            fields.append(Field(f.name, DataType.DECIMAL, f.nullable, t.precision, t.scale))
        elif pa.types.is_dictionary(t):
            inner = _PA_TO_DT.get(t.value_type)
            if inner is None:
                raise NotImplementedError(f"dictionary of {t.value_type}")
            fields.append(Field(f.name, inner, f.nullable))
        elif t in _PA_TO_DT:
            fields.append(Field(f.name, _PA_TO_DT[t], f.nullable))
        elif pa.types.is_timestamp(t):
            fields.append(Field(f.name, DataType.TIMESTAMP_US, f.nullable))
        elif pa.types.is_list(t) or pa.types.is_large_list(t):
            if pa.types.is_string(t.value_type) \
                    or pa.types.is_large_string(t.value_type):
                fields.append(Field(f.name, DataType.LIST, f.nullable,
                                    elem=DataType.STRING))
            elif pa.types.is_struct(t.value_type):
                # entry list — list<struct<K, V>> with two primitive
                # children (the map_entries / map_from_entries shape,
                # reference: spark_map.rs:553 MapFromEntries). Carried on
                # device by the MapColumn layout; Field.children hold the
                # entry struct's fields.
                st = t.value_type
                if st.num_fields != 2:
                    raise NotImplementedError(
                        f"list of {st}: only 2-field entry structs "
                        "(key/value) are materialized")
                kids = []
                for i in range(st.num_fields):
                    cf = st.field(i)
                    cdt = _PA_TO_DT.get(cf.type)
                    if cdt in (None, DataType.NULL, DataType.STRING):
                        # the MapColumn carrier holds numeric matrices
                        # only — no char-tensor slot for string children
                        raise NotImplementedError(
                            f"entry-struct child {cf.name}: {cf.type} "
                            "(numeric primitive children only)")
                    kids.append(Field(cf.name, cdt, cf.nullable))
                fields.append(Field(f.name, DataType.LIST, f.nullable,
                                    elem=DataType.STRUCT,
                                    children=tuple(kids)))
            elif pa.types.is_decimal(t.value_type):
                if t.value_type.precision > 38:
                    raise NotImplementedError(
                        f"list of {t.value_type}: precision > 38")
                fields.append(Field(f.name, DataType.LIST, f.nullable,
                                    t.value_type.precision,
                                    t.value_type.scale,
                                    elem=DataType.DECIMAL))
            else:
                elem = _PA_TO_DT.get(t.value_type)
                if elem is None or elem == DataType.NULL:
                    raise NotImplementedError(f"list of {t.value_type}")
                fields.append(Field(f.name, DataType.LIST, f.nullable,
                                    elem=elem))
        elif pa.types.is_map(t):
            key = _PA_TO_DT.get(t.key_type)
            val = _PA_TO_DT.get(t.item_type)
            if key == DataType.STRING and val == DataType.STRING:
                fields.append(Field(f.name, DataType.MAP, f.nullable,
                                    elem=DataType.STRING,
                                    key=DataType.STRING))
            elif key in (None, DataType.STRING, DataType.NULL) \
                    or val in (None, DataType.STRING, DataType.NULL):
                raise NotImplementedError(
                    f"map<{t.key_type}, {t.item_type}>: primitive "
                    "keys/values or map<string,string> only")
            else:
                fields.append(Field(f.name, DataType.MAP, f.nullable,
                                    elem=val, key=key))
        elif pa.types.is_struct(t):
            kids = []
            for i in range(t.num_fields):
                cf = t.field(i)
                sub = schema_from_arrow(pa.schema([cf]))
                if sub[0].dtype in (DataType.MAP, DataType.STRUCT,
                                    DataType.LIST):
                    raise NotImplementedError(
                        f"struct child {cf.name}: nested map/struct/list "
                        "children are not materialized yet")
                kids.append(sub[0])
            fields.append(Field(f.name, DataType.STRUCT, f.nullable,
                                children=tuple(kids)))
        else:
            raise NotImplementedError(f"arrow type {t} not supported")
    return Schema(tuple(fields))


def schema_to_arrow(schema: Schema) -> pa.Schema:
    out = []
    for f in schema:
        if f.dtype == DataType.STRING:
            t = pa.string()
        elif f.dtype == DataType.DECIMAL:
            t = pa.decimal128(f.precision, f.scale)
        elif f.dtype == DataType.DATE32:
            t = pa.date32()
        elif f.dtype == DataType.TIMESTAMP_US:
            t = pa.timestamp("us")
        elif f.dtype == DataType.NULL:
            t = pa.null()
        elif f.dtype == DataType.LIST:
            if f.elem == DataType.STRUCT:
                t = pa.list_(pa.struct(
                    [pa.field(cf.name, pa.from_numpy_dtype(cf.dtype.to_np()),
                              cf.nullable) for cf in f.children]))
            elif f.elem == DataType.DECIMAL:
                # element (p, s) rides the LIST field's precision/scale
                # slots (wide collect_* results; ops/agg.py make_acc_spec)
                t = pa.list_(pa.decimal128(
                    f.precision or _LIST_DECIMAL_FALLBACK_PRECISION,
                    f.scale))
            else:
                t = pa.list_(pa.string() if f.elem == DataType.STRING
                             else pa.from_numpy_dtype(f.elem.to_np()))
        elif f.dtype == DataType.MAP:
            t = pa.map_(pa.string() if f.key == DataType.STRING
                        else pa.from_numpy_dtype(f.key.to_np()),
                        pa.string() if f.elem == DataType.STRING
                        else pa.from_numpy_dtype(f.elem.to_np()))
        elif f.dtype == DataType.STRUCT:
            t = pa.struct([schema_to_arrow(Schema((cf,)))[0]
                           for cf in f.children])
        else:
            t = pa.from_numpy_dtype(f.dtype.to_np())
        out.append(pa.field(f.name, t, f.nullable))
    return pa.schema(out)


def _string_arrays(arr: pa.Array, capacity: int, width: int | None):
    """Extract (chars[cap, w], lens[cap], validity[cap]) from a pyarrow
    string array using its offsets/data buffers (no per-row Python)."""
    arr = arr.cast(pa.string()) if not pa.types.is_string(arr.type) else arr
    arr = arr.combine_chunks() if isinstance(arr, pa.ChunkedArray) else arr
    n = len(arr)
    offsets = np.frombuffer(arr.buffers()[1], dtype=np.int32,
                            count=n + 1, offset=arr.offset * 4)
    data_buf = arr.buffers()[2]
    data = np.frombuffer(data_buf, dtype=np.uint8) if data_buf is not None else np.zeros(0, np.uint8)
    lens = (offsets[1:] - offsets[:-1]).astype(np.int32)
    max_len = int(lens.max()) if n else 0
    w = width if width is not None else bucket_string_width(max_len)
    if max_len > w:
        raise ValueError(f"string of length {max_len} exceeds width bucket {w}")
    chars = np.zeros((capacity, w), dtype=np.uint8)
    if n:
        # Gather bytes: chars[i, j] = data[offsets[i] + j] for j < lens[i].
        col_idx = np.arange(w, dtype=np.int64)[None, :]
        src = offsets[:-1, None].astype(np.int64) + col_idx
        in_range = col_idx < lens[:, None]
        src = np.where(in_range, src, 0)
        if data.size == 0:
            data = np.zeros(1, np.uint8)
        chars[:n] = np.where(in_range, data[np.clip(src, 0, data.size - 1)], 0)
    lens_full = np.zeros(capacity, np.int32)
    lens_full[:n] = lens
    validity = np.zeros(capacity, bool)
    if arr.null_count:
        validity[:n] = ~np.asarray(arr.is_null())
    else:
        validity[:n] = True
    lens_full[:capacity][~validity] = 0
    return chars, lens_full, validity


def _list_arrays(arr: pa.Array, capacity: int, elem_np) -> tuple:
    """Extract (values[cap, m], elem_valid[cap, m], lens[cap], validity[cap])
    from a pyarrow list array via its offsets (no per-row Python)."""
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    arr = arr.cast(pa.list_(arr.type.value_type))
    n = len(arr)
    offsets = np.asarray(arr.offsets)[: n + 1]
    child = arr.values
    child_np = np.asarray(child.fill_null(0)).astype(elem_np)
    child_valid = (~np.asarray(child.is_null()) if child.null_count
                   else np.ones(len(child), bool))
    lens = (offsets[1:] - offsets[:-1]).astype(np.int32)
    validity = (~np.asarray(arr.is_null()) if arr.null_count
                else np.ones(n, bool))
    lens = np.where(validity, lens, 0)
    m = max(int(lens.max()) if n else 0, 1)
    values = np.zeros((capacity, m), elem_np)
    elem_valid = np.zeros((capacity, m), bool)
    if n:
        col_idx = np.arange(m, dtype=np.int64)[None, :]
        src = offsets[:-1, None].astype(np.int64) + col_idx
        in_range = col_idx < lens[:, None]
        src = np.clip(src, 0, max(len(child_np) - 1, 0))
        if len(child_np) == 0:
            child_np = np.zeros(1, elem_np)
            child_valid = np.zeros(1, bool)
        values[:n] = np.where(in_range, child_np[src], 0)
        elem_valid[:n] = in_range & child_valid[src]
    lens_full = np.zeros(capacity, np.int32)
    lens_full[:n] = lens
    validity_full = np.zeros(capacity, bool)
    validity_full[:n] = validity
    return values, elem_valid, lens_full, validity_full


def _map_to_device(field: Field, arr: pa.Array, cap: int):
    """MapArray → MapColumn via two list-view extractions over the shared
    offsets (keys carry no element validity — Spark map keys are
    non-null)."""
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    return _kv_lists_to_map_column(arr, arr.keys, arr.items,
                                   field.key.to_np(), field.elem.to_np(),
                                   cap)


def _kv_lists_to_map_column(arr: pa.Array, karr: pa.Array, varr: pa.Array,
                            key_np, val_np, cap: int):
    """Shared MapColumn-carrier assembly for every offsets-over-(K,V)
    arrow shape (MapArray, entry-list ListArray): two list-view
    extractions over the shared offsets, null-row len zeroing, and
    element-bucket unification."""
    from auron_tpu.columnar.batch import MapColumn
    n = len(arr)
    offsets = np.asarray(arr.offsets)[: n + 1]
    off = pa.array(offsets.astype(np.int32), pa.int32())
    keys_list = pa.ListArray.from_arrays(off, karr)
    items_list = pa.ListArray.from_arrays(off, varr)
    kv, _kev, lens, _ = _list_arrays(keys_list, cap, key_np)
    vv, vev, _vlens, _ = _list_arrays(items_list, cap, val_np)
    validity = np.zeros(cap, bool)
    validity[:n] = (~np.asarray(arr.is_null()) if arr.null_count
                    else np.ones(n, bool))
    lens = np.where(validity, lens, 0).astype(np.int32)
    m = max(kv.shape[1], vv.shape[1])
    kv = np.pad(kv, ((0, 0), (0, m - kv.shape[1])))
    vv = np.pad(vv, ((0, 0), (0, m - vv.shape[1])))
    vev = np.pad(vev, ((0, 0), (0, m - vev.shape[1])))
    return MapColumn(jnp.asarray(kv), jnp.asarray(vv), jnp.asarray(vev),
                     jnp.asarray(lens), jnp.asarray(validity))


def _decimal_list_to_device(field: Field, arr: pa.Array, cap: int):
    """list<decimal128(p,s)> → ListColumn with scaled-int64 payload
    (p<=18) or the MapColumn limb carrier (p>18). The child decimal
    buffer IS two little-endian int64 limbs per value, so the limbs are
    a zero-copy view re-wrapped as int64 list arrays over the shared
    offsets."""
    from auron_tpu.columnar.batch import ListColumn, MapColumn
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    n = len(arr)
    child = arr.values
    limbs = np.frombuffer(child.buffers()[1], dtype=np.int64,
                          count=2 * len(child) if len(child) else 0,
                          offset=child.offset * 16).reshape(-1, 2)
    mask = (np.asarray(child.is_null()) if child.null_count
            else np.zeros(len(child), bool))
    offsets = np.asarray(arr.offsets)[: n + 1]
    off = pa.array(offsets.astype(np.int32), pa.int32())
    lo_list = pa.ListArray.from_arrays(
        off, pa.array(np.ascontiguousarray(limbs[:, 0]), pa.int64(),
                      mask=mask))
    lo_m, ev, lens, _ = _list_arrays(lo_list, cap, np.int64)
    validity = np.zeros(cap, bool)
    validity[:n] = (~np.asarray(arr.is_null()) if arr.null_count
                    else np.ones(n, bool))
    lens = np.where(validity, lens, 0).astype(np.int32)
    if field.precision <= 18:
        return ListColumn(jnp.asarray(lo_m), jnp.asarray(ev),
                          jnp.asarray(lens), jnp.asarray(validity))
    hi_list = pa.ListArray.from_arrays(
        off, pa.array(np.ascontiguousarray(limbs[:, 1]), pa.int64(),
                      mask=mask))
    hi_m, _hev, _l, _ = _list_arrays(hi_list, cap, np.int64)
    return MapColumn(jnp.asarray(hi_m), jnp.asarray(lo_m),
                     jnp.asarray(ev), jnp.asarray(lens),
                     jnp.asarray(validity))


def _entry_list_to_device(field: Field, arr: pa.Array, cap: int):
    """list<struct<K,V>> (entry list) → MapColumn carrier: the parallel
    key/value matrices + shared lens ARE the list-of-entry-structs layout
    (reference renders MapArray the same offsets-over-struct way).

    A row containing a NULL entry struct renders as a NULL row — the
    reference's map_from_entries semantics ('null array entry => null',
    spark_map.rs) — by folding those rows into the carrier's row
    validity, so the dead entries never need a slot. NULL first-child
    ("key") values in surviving rows still fail fast: Spark map keys are
    non-null."""
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    struct_child = arr.values
    n = len(arr)
    if struct_child.null_count:
        entry_null = np.asarray(struct_child.is_null())
        offsets = np.asarray(arr.offsets)[: n + 1].astype(np.int64)
        cum = np.concatenate([[0], np.cumsum(entry_null)])
        row_has_null = (cum[offsets[1:]] - cum[offsets[:-1]]) > 0
        validity = (~np.asarray(arr.is_null()) if arr.null_count
                    else np.ones(n, bool)) & ~row_has_null
        arr = pa.ListArray.from_arrays(
            pa.array(offsets.astype(np.int32), pa.int32()), struct_child,
            mask=pa.array(~validity))
    else:
        entry_null = None
    kf, vf = field.children
    karr = struct_child.field(0)
    if karr.null_count:
        # keys inside dead entries (null structs, entries of NULL rows)
        # have no semantics and no carrier slot; only a null key of a
        # LIVE entry in a surviving row raises
        key_null = np.asarray(karr.is_null())
        offsets = np.asarray(arr.offsets)[: n + 1].astype(np.int64)
        live_row = (~np.asarray(arr.is_null()) if arr.null_count
                    else np.ones(n, bool))
        ne = len(key_null)
        mark = np.zeros(ne + 1, np.int32)
        np.add.at(mark, np.clip(offsets[:-1][live_row], 0, ne), 1)
        np.add.at(mark, np.clip(offsets[1:][live_row], 0, ne), -1)
        key_null = key_null & (np.cumsum(mark[:ne]) > 0)
        if entry_null is not None:
            key_null = key_null & ~entry_null
        if key_null.any():
            raise NotImplementedError(
                "entry list with NULL key children (Spark map keys are "
                "non-null)")
    return _kv_lists_to_map_column(arr, karr, struct_child.field(1),
                                   kf.dtype.to_np(), vf.dtype.to_np(), cap)


def _struct_to_device(field: Field, arr: pa.Array, cap: int):
    from auron_tpu.columnar.batch import StructColumn
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    n = len(arr)
    kids = tuple(
        _column_to_device(cf, arr.field(i), cap, None)
        for i, cf in enumerate(field.children))
    validity = np.zeros(cap, bool)
    validity[:n] = (~np.asarray(arr.is_null()) if arr.null_count
                    else np.ones(n, bool))
    return StructColumn(kids, jnp.asarray(validity))


def to_device(rb: pa.RecordBatch, capacity: int | None = None,
              string_widths: dict[str, int] | None = None) -> tuple[DeviceBatch, Schema]:
    """Convert a pyarrow RecordBatch into a padded DeviceBatch."""
    schema = schema_from_arrow(rb.schema)
    n = rb.num_rows
    cap = capacity if capacity is not None else bucket_rows(n)
    if n > cap:
        raise ValueError(f"batch of {n} rows exceeds capacity {cap}")
    cols = [_column_to_device(field, arr, cap, string_widths)
            for field, arr in zip(schema, rb.columns)]
    return DeviceBatch(tuple(cols), jnp.asarray(n, jnp.int32)), schema


def _string_list_to_device(arr: pa.Array, cap: int):
    """pyarrow list<string> → StringListColumn (padded char tensor)."""
    from auron_tpu.columnar.batch import StringListColumn
    from auron_tpu.utils.shapes import bucket_string_width
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    arr = arr.cast(pa.list_(pa.string()))
    n = len(arr)
    pyrows = arr.to_pylist()
    max_e, max_w = 1, 1
    for row in pyrows:
        if row:
            max_e = max(max_e, len(row))
            for s in row:
                if s is not None:
                    max_w = max(max_w, len(s.encode()))
    width = bucket_string_width(max_w)
    chars = np.zeros((cap, max_e, width), np.uint8)
    slens = np.zeros((cap, max_e), np.int32)
    ev = np.zeros((cap, max_e), bool)
    lens = np.zeros(cap, np.int32)
    validity = np.zeros(cap, bool)
    for i, row in enumerate(pyrows):
        if row is None:
            continue
        validity[i] = True
        lens[i] = len(row)
        for j, s in enumerate(row):
            if s is None:
                continue
            b = s.encode()
            chars[i, j, :len(b)] = np.frombuffer(b, np.uint8)
            slens[i, j] = len(b)
            ev[i, j] = True
    return StringListColumn(jnp.asarray(chars), jnp.asarray(slens),
                            jnp.asarray(ev), jnp.asarray(lens),
                            jnp.asarray(validity))


def _string_map_to_device(arr: pa.Array, cap: int):
    """pyarrow map<string,string> → StringMapColumn."""
    from auron_tpu.columnar.batch import StringMapColumn
    from auron_tpu.utils.shapes import bucket_string_width
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    pyrows = arr.to_pylist()
    max_e, kw, vw = 1, 1, 1
    for row in pyrows:
        if row:
            max_e = max(max_e, len(row))
            for k, v in row:
                kw = max(kw, len(k.encode()))
                if v is not None:
                    vw = max(vw, len(v.encode()))
    kw, vw = bucket_string_width(kw), bucket_string_width(vw)
    kchars = np.zeros((cap, max_e, kw), np.uint8)
    kslens = np.zeros((cap, max_e), np.int32)
    vchars = np.zeros((cap, max_e, vw), np.uint8)
    vslens = np.zeros((cap, max_e), np.int32)
    vv = np.zeros((cap, max_e), bool)
    lens = np.zeros(cap, np.int32)
    validity = np.zeros(cap, bool)
    for i, row in enumerate(pyrows):
        if row is None:
            continue
        validity[i] = True
        lens[i] = len(row)
        for j, (k, v) in enumerate(row):
            kb = k.encode()
            kchars[i, j, :len(kb)] = np.frombuffer(kb, np.uint8)
            kslens[i, j] = len(kb)
            if v is not None:
                vb = v.encode()
                vchars[i, j, :len(vb)] = np.frombuffer(vb, np.uint8)
                vslens[i, j] = len(vb)
                vv[i, j] = True
    return StringMapColumn(jnp.asarray(kchars), jnp.asarray(kslens),
                           jnp.asarray(vchars), jnp.asarray(vslens),
                           jnp.asarray(vv), jnp.asarray(lens),
                           jnp.asarray(validity))


def _column_to_device(field: Field, arr, cap: int,
                      string_widths: dict[str, int] | None):
    n = len(arr)
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    if pa.types.is_dictionary(arr.type):
        arr = arr.dictionary_decode()
    if field.dtype == DataType.STRING:
        w = (string_widths or {}).get(field.name)
        chars, lens, validity = _string_arrays(arr, cap, w)
        return StringColumn(jnp.asarray(chars), jnp.asarray(lens),
                            jnp.asarray(validity))
    if field.dtype == DataType.LIST:
        if field.elem == DataType.STRING:
            return _string_list_to_device(arr, cap)
        if field.elem == DataType.STRUCT:
            return _entry_list_to_device(field, arr, cap)
        if field.elem == DataType.DECIMAL:
            return _decimal_list_to_device(field, arr, cap)
        values, ev, lens, validity = _list_arrays(arr, cap,
                                                  field.elem.to_np())
        return ListColumn(jnp.asarray(values), jnp.asarray(ev),
                          jnp.asarray(lens), jnp.asarray(validity))
    if field.dtype == DataType.MAP:
        if field.key == DataType.STRING:
            return _string_map_to_device(arr, cap)
        return _map_to_device(field, arr, cap)
    if field.dtype == DataType.STRUCT:
        return _struct_to_device(field, arr, cap)
    np_dtype = field.dtype.to_np()
    validity = np.zeros(cap, bool)
    data = np.zeros(cap, np_dtype)
    if field.dtype == DataType.NULL:
        return PrimitiveColumn(jnp.asarray(data), jnp.asarray(validity))
    if field.dtype == DataType.DECIMAL:
        pyvals = arr.to_pylist()
        if field.precision > 18:
            # precision 19..38: two-limb device representation
            # (columnar/decimal128.py; reference stores Decimal128 and
            # computes in i128, arrow/cast.rs decimal paths)
            from auron_tpu.columnar.decimal128 import (Decimal128Column,
                                                       limbs_from_ints)
            import decimal as _dec
            with _dec.localcontext() as _ctx:
                # default context (prec=28) would silently round
                # 29-38 digit values during scaleb
                _ctx.prec = 60
                ints = [None if v is None
                        else int(v.scaleb(field.scale)
                                 .to_integral_value())
                        for v in pyvals]
            hi, lo, valid128 = limbs_from_ints(ints, cap)
            return Decimal128Column(jnp.asarray(hi), jnp.asarray(lo),
                                    jnp.asarray(valid128))
        # <=18 digits: unscaled int64 payload (reference:
        # datafusion-ext-functions/src/spark_make_decimal.rs)
        unscaled = np.zeros(n, np.int64)
        for i, v in enumerate(pyvals):
            if v is not None:
                unscaled[i] = int(v.scaleb(field.scale).to_integral_value())
        data[:n] = unscaled
        validity[:n] = [v is not None for v in pyvals]
    elif field.dtype == DataType.TIMESTAMP_US:
        arr_us = arr.cast(pa.timestamp("us"))
        vals = arr_us.cast(pa.int64())
        data[:n] = np.asarray(vals.fill_null(0))
        validity[:n] = ~np.asarray(arr.is_null()) if arr.null_count else True
    elif field.dtype == DataType.DATE32:
        vals = arr.cast(pa.int32())
        data[:n] = np.asarray(vals.fill_null(0))
        validity[:n] = ~np.asarray(arr.is_null()) if arr.null_count else True
    else:
        vals = arr.fill_null(False) if field.dtype == DataType.BOOL else arr.fill_null(0)
        data[:n] = np.asarray(vals)
        validity[:n] = ~np.asarray(arr.is_null()) if arr.null_count else True
    return PrimitiveColumn(jnp.asarray(data), jnp.asarray(validity))


def to_arrow(batch: DeviceBatch, schema: Schema) -> pa.RecordBatch:
    """Materialize a DeviceBatch back to a pyarrow RecordBatch — ONE packed
    device→host transfer for the whole batch (columnar.serde.fetch_batch_numpy;
    per-array fetches pay ~70 ms tunnel latency EACH on remote accelerators).
    Every column routes through the one host→arrow converter
    (_host_col_to_arrow) so top-level and struct-child renderings of the
    same logical type cannot drift."""
    from auron_tpu.columnar.serde import (_slice_host_col, fetch_batch_numpy,
                                          host_col_from_device)
    fetched, n = fetch_batch_numpy(batch)
    arrays = []
    for field, col, col_arrs in zip(schema, batch.columns, fetched):
        hc = _slice_host_col(host_col_from_device(col, iter(col_arrs)), 0, n)
        arrays.append(_host_col_to_arrow(field, hc, n))
    return pa.RecordBatch.from_arrays(arrays, schema=schema_to_arrow(schema))


def _list_offsets(lens: np.ndarray, validity: np.ndarray, n: int):
    """int32 Arrow offsets (+ None at null rows) from per-row lengths —
    shared by every list-shaped to-arrow arm (list / string list / map)."""
    offsets = np.zeros(n + 1, np.int32)
    np.cumsum(lens, out=offsets[1:])
    if validity.all():
        return pa.array(offsets, pa.int32())
    return pa.array(
        [None if not v else int(o)
         for o, v in zip(offsets[:-1], validity)] + [int(offsets[-1])],
        pa.int32())


def _host_col_to_arrow(field: Field, hc, n: int) -> pa.Array:
    """ONE host column → pyarrow array; the single conversion point for
    every logical type (top-level columns and struct children alike)."""
    from auron_tpu.columnar.serde import (HostDecimal128, HostList, HostMap,
                                          HostString, HostStringList,
                                          HostStringMap, HostStruct)
    if isinstance(hc, HostStringMap):
        validity = hc.validity
        lens = np.where(validity, hc.lens.astype(np.int64), 0)
        keys, vals = [], []
        for i in range(n):
            for j in range(int(lens[i])):
                keys.append(bytes(hc.kchars[i, j, :hc.kslens[i, j]])
                            .decode("utf-8", "replace"))
                vals.append(
                    bytes(hc.vchars[i, j, :hc.vslens[i, j]])
                    .decode("utf-8", "replace")
                    if hc.val_valid[i, j] else None)
        off_arr = _list_offsets(lens, validity, n)
        return pa.MapArray.from_arrays(off_arr,
                                       pa.array(keys, pa.string()),
                                       pa.array(vals, pa.string()))
    if isinstance(hc, HostStringList):
        validity = hc.validity
        lens = np.where(validity, hc.lens.astype(np.int64), 0)
        vals = []
        for i in range(n):
            for j in range(int(lens[i])):
                if hc.elem_valid[i, j]:
                    vals.append(bytes(
                        hc.chars[i, j, :hc.slens[i, j]]).decode(
                            "utf-8", "replace"))
                else:
                    vals.append(None)
        child = pa.array(vals, pa.string())
        off_arr = _list_offsets(lens, validity, n)
        return pa.ListArray.from_arrays(off_arr, child)
    if isinstance(hc, HostList):
        validity = hc.validity
        lens = np.where(validity, hc.lens.astype(np.int64), 0)
        take = np.arange(hc.values.shape[1])[None, :] < lens[:, None]
        flat_vals = hc.values[take]
        flat_valid = hc.elem_valid[take]
        if field.elem == DataType.DECIMAL:
            # scaled-int64 payload → decimal(p,s) child (narrow lists;
            # wide ones ride the HostMap limb carrier)
            child = pa.array(
                [_int_to_decimal(int(x), field.scale) for x in flat_vals],
                pa.decimal128(
                    field.precision or _LIST_DECIMAL_FALLBACK_PRECISION,
                    field.scale))
        else:
            child = pa.array(flat_vals,
                             pa.from_numpy_dtype(field.elem.to_np()))
        if not flat_valid.all():
            child = _with_nulls(child, flat_valid)
        off_arr = _list_offsets(lens, validity, n)
        return pa.ListArray.from_arrays(off_arr, child)
    if isinstance(hc, HostMap):
        validity = hc.validity
        lens = np.where(validity, hc.lens, 0).astype(np.int64)
        take = np.arange(hc.keys.shape[1])[None, :] < lens[:, None]
        if field.dtype == DataType.LIST and field.elem == DataType.DECIMAL:
            # list<decimal128>: the carrier's keys/values matrices are the
            # hi/lo limbs of each element; element nulls ride val_valid
            from auron_tpu.columnar.decimal128 import ints_from_limbs
            flat_hi = hc.keys[take]
            flat_lo = hc.values[take]
            flat_vv = hc.val_valid[take]
            ints = ints_from_limbs(flat_hi, flat_lo, flat_vv)
            vals = [None if x is None else _int_to_decimal(x, field.scale)
                    for x in ints]
            child = pa.array(vals, pa.decimal128(
                field.precision or _LIST_DECIMAL_FALLBACK_PRECISION,
                field.scale))
            off_arr = _list_offsets(lens, validity, n)
            return pa.ListArray.from_arrays(off_arr, child)
        if field.dtype == DataType.LIST:
            # entry list: same carrier, rendered as list<struct<K,V>>
            kf, vf = field.children
            karr = pa.array(hc.keys[take],
                            pa.from_numpy_dtype(kf.dtype.to_np()))
            varr = pa.array(hc.values[take],
                            pa.from_numpy_dtype(vf.dtype.to_np()))
            flat_vv = hc.val_valid[take]
            if not flat_vv.all():
                varr = _with_nulls(varr, flat_vv)
            entries = pa.StructArray.from_arrays(
                [karr, varr], names=[kf.name, vf.name])
            off_arr = _list_offsets(lens, validity, n)
            return pa.ListArray.from_arrays(off_arr, entries)
        karr = pa.array(hc.keys[take],
                        pa.from_numpy_dtype(field.key.to_np()))
        varr = pa.array(hc.values[take],
                        pa.from_numpy_dtype(field.elem.to_np()))
        flat_vv = hc.val_valid[take]
        if not flat_vv.all():
            varr = _with_nulls(varr, flat_vv)
        off_arr = _list_offsets(lens, validity, n)
        return pa.MapArray.from_arrays(off_arr, karr, varr)
    if isinstance(hc, HostStruct):
        kids = [_host_col_to_arrow(cf, ch, n)
                for cf, ch in zip(field.children, hc.children)]
        mask = None if hc.validity.all() \
            else pa.array(~hc.validity, pa.bool_())
        arr = pa.StructArray.from_arrays(
            kids, names=[cf.name for cf in field.children], mask=mask)
        return arr.cast(schema_to_arrow(Schema((field,)))[0].type)
    if isinstance(hc, HostString):
        validity = hc.validity
        lens = np.where(validity, hc.lens.astype(np.int64), 0)
        offsets = np.zeros(n + 1, np.int32)
        np.cumsum(lens, out=offsets[1:])
        take = np.arange(hc.chars.shape[1])[None, :] < lens[:, None]
        flat = hc.chars[take].astype(np.uint8)
        return pa.StringArray.from_buffers(
            n, pa.py_buffer(offsets.tobytes()),
            pa.py_buffer(flat.tobytes()),
            pa.py_buffer(np.packbits(validity,
                                     bitorder="little").tobytes()),
            int((~validity).sum()))
    if isinstance(hc, HostDecimal128):
        from auron_tpu.columnar.decimal128 import ints_from_limbs
        ints = ints_from_limbs(hc.hi, hc.lo, hc.validity)
        vals = [None if x is None else _int_to_decimal(x, field.scale)
                for x in ints]
        return pa.array(vals,
                        type=pa.decimal128(field.precision, field.scale))
    # primitives
    data, validity = hc.data, hc.validity
    if field.dtype == DataType.NULL:
        return pa.nulls(n)
    if field.dtype == DataType.DECIMAL:
        vals = [None if not v else _int_to_decimal(int(x), field.scale)
                for x, v in zip(data, validity)]
        return pa.array(vals,
                        type=pa.decimal128(field.precision, field.scale))
    if field.dtype == DataType.DATE32:
        a = pa.array(np.where(validity, data, 0), pa.int32()).cast(pa.date32())
        return a if validity.all() else _with_nulls(a, validity)
    if field.dtype == DataType.TIMESTAMP_US:
        a = pa.array(np.where(validity, data, 0),
                     pa.int64()).cast(pa.timestamp("us"))
        return a if validity.all() else _with_nulls(a, validity)
    a = pa.array(data)
    return a if validity.all() else _with_nulls(a, validity)


def _with_nulls(arr: pa.Array, validity: np.ndarray) -> pa.Array:
    return pa.array(
        [v if ok else None for v, ok in zip(arr.to_pylist(), validity)],
        type=arr.type)


def _int_to_decimal(unscaled: int, scale: int):
    import decimal
    with decimal.localcontext() as ctx:
        ctx.prec = 60   # default prec=28 rounds away 29-38 digit values
        return decimal.Decimal(unscaled).scaleb(-scale)
