"""pyarrow RecordBatch <-> DeviceBatch conversion.

This is the host<->device boundary, the analogue of the reference's Arrow
C-FFI import/export between JVM and native (reference: auron-core/src/main/
java/org/apache/auron/arrowio/..., native-engine/auron/src/rt.rs:252-282).
On TPU the transfer is a single jax.device_put of dense padded buffers per
column — no per-row work on either side of the wall.
"""

from __future__ import annotations

import numpy as np
import pyarrow as pa

import jax.numpy as jnp

from auron_tpu.columnar.batch import (DeviceBatch, ListColumn,
                                      PrimitiveColumn, StringColumn)
from auron_tpu.columnar.schema import DataType, Field, Schema
from auron_tpu.utils.shapes import bucket_rows, bucket_string_width

_PA_TO_DT = {
    pa.bool_(): DataType.BOOL,
    pa.int8(): DataType.INT8,
    pa.int16(): DataType.INT16,
    pa.int32(): DataType.INT32,
    pa.int64(): DataType.INT64,
    pa.float32(): DataType.FLOAT32,
    pa.float64(): DataType.FLOAT64,
    pa.date32(): DataType.DATE32,
    pa.timestamp("us"): DataType.TIMESTAMP_US,
    pa.string(): DataType.STRING,
    pa.large_string(): DataType.STRING,
    pa.null(): DataType.NULL,
}


def schema_from_arrow(sch: pa.Schema) -> Schema:
    fields = []
    for f in sch:
        t = f.type
        if pa.types.is_decimal(t):
            if t.precision > 38:
                raise NotImplementedError(
                    f"decimal precision {t.precision} > 38 not supported")
            fields.append(Field(f.name, DataType.DECIMAL, f.nullable, t.precision, t.scale))
        elif pa.types.is_dictionary(t):
            inner = _PA_TO_DT.get(t.value_type)
            if inner is None:
                raise NotImplementedError(f"dictionary of {t.value_type}")
            fields.append(Field(f.name, inner, f.nullable))
        elif t in _PA_TO_DT:
            fields.append(Field(f.name, _PA_TO_DT[t], f.nullable))
        elif pa.types.is_timestamp(t):
            fields.append(Field(f.name, DataType.TIMESTAMP_US, f.nullable))
        elif pa.types.is_list(t) or pa.types.is_large_list(t):
            elem = _PA_TO_DT.get(t.value_type)
            if elem is None or elem in (DataType.STRING, DataType.NULL):
                raise NotImplementedError(f"list of {t.value_type}")
            fields.append(Field(f.name, DataType.LIST, f.nullable, elem=elem))
        else:
            raise NotImplementedError(f"arrow type {t} not supported")
    return Schema(tuple(fields))


def schema_to_arrow(schema: Schema) -> pa.Schema:
    out = []
    for f in schema:
        if f.dtype == DataType.STRING:
            t = pa.string()
        elif f.dtype == DataType.DECIMAL:
            t = pa.decimal128(f.precision, f.scale)
        elif f.dtype == DataType.DATE32:
            t = pa.date32()
        elif f.dtype == DataType.TIMESTAMP_US:
            t = pa.timestamp("us")
        elif f.dtype == DataType.NULL:
            t = pa.null()
        elif f.dtype == DataType.LIST:
            t = pa.list_(pa.from_numpy_dtype(f.elem.to_np()))
        else:
            t = pa.from_numpy_dtype(f.dtype.to_np())
        out.append(pa.field(f.name, t, f.nullable))
    return pa.schema(out)


def _string_arrays(arr: pa.Array, capacity: int, width: int | None):
    """Extract (chars[cap, w], lens[cap], validity[cap]) from a pyarrow
    string array using its offsets/data buffers (no per-row Python)."""
    arr = arr.cast(pa.string()) if not pa.types.is_string(arr.type) else arr
    arr = arr.combine_chunks() if isinstance(arr, pa.ChunkedArray) else arr
    n = len(arr)
    offsets = np.frombuffer(arr.buffers()[1], dtype=np.int32,
                            count=n + 1, offset=arr.offset * 4)
    data_buf = arr.buffers()[2]
    data = np.frombuffer(data_buf, dtype=np.uint8) if data_buf is not None else np.zeros(0, np.uint8)
    lens = (offsets[1:] - offsets[:-1]).astype(np.int32)
    max_len = int(lens.max()) if n else 0
    w = width if width is not None else bucket_string_width(max_len)
    if max_len > w:
        raise ValueError(f"string of length {max_len} exceeds width bucket {w}")
    chars = np.zeros((capacity, w), dtype=np.uint8)
    if n:
        # Gather bytes: chars[i, j] = data[offsets[i] + j] for j < lens[i].
        col_idx = np.arange(w, dtype=np.int64)[None, :]
        src = offsets[:-1, None].astype(np.int64) + col_idx
        in_range = col_idx < lens[:, None]
        src = np.where(in_range, src, 0)
        if data.size == 0:
            data = np.zeros(1, np.uint8)
        chars[:n] = np.where(in_range, data[np.clip(src, 0, data.size - 1)], 0)
    lens_full = np.zeros(capacity, np.int32)
    lens_full[:n] = lens
    validity = np.zeros(capacity, bool)
    if arr.null_count:
        validity[:n] = ~np.asarray(arr.is_null())
    else:
        validity[:n] = True
    lens_full[:capacity][~validity] = 0
    return chars, lens_full, validity


def _list_arrays(arr: pa.Array, capacity: int, elem_np) -> tuple:
    """Extract (values[cap, m], elem_valid[cap, m], lens[cap], validity[cap])
    from a pyarrow list array via its offsets (no per-row Python)."""
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    arr = arr.cast(pa.list_(arr.type.value_type))
    n = len(arr)
    offsets = np.asarray(arr.offsets)[: n + 1]
    child = arr.values
    child_np = np.asarray(child.fill_null(0)).astype(elem_np)
    child_valid = (~np.asarray(child.is_null()) if child.null_count
                   else np.ones(len(child), bool))
    lens = (offsets[1:] - offsets[:-1]).astype(np.int32)
    validity = (~np.asarray(arr.is_null()) if arr.null_count
                else np.ones(n, bool))
    lens = np.where(validity, lens, 0)
    m = max(int(lens.max()) if n else 0, 1)
    values = np.zeros((capacity, m), elem_np)
    elem_valid = np.zeros((capacity, m), bool)
    if n:
        col_idx = np.arange(m, dtype=np.int64)[None, :]
        src = offsets[:-1, None].astype(np.int64) + col_idx
        in_range = col_idx < lens[:, None]
        src = np.clip(src, 0, max(len(child_np) - 1, 0))
        if len(child_np) == 0:
            child_np = np.zeros(1, elem_np)
            child_valid = np.zeros(1, bool)
        values[:n] = np.where(in_range, child_np[src], 0)
        elem_valid[:n] = in_range & child_valid[src]
    lens_full = np.zeros(capacity, np.int32)
    lens_full[:n] = lens
    validity_full = np.zeros(capacity, bool)
    validity_full[:n] = validity
    return values, elem_valid, lens_full, validity_full


def to_device(rb: pa.RecordBatch, capacity: int | None = None,
              string_widths: dict[str, int] | None = None) -> tuple[DeviceBatch, Schema]:
    """Convert a pyarrow RecordBatch into a padded DeviceBatch."""
    schema = schema_from_arrow(rb.schema)
    n = rb.num_rows
    cap = capacity if capacity is not None else bucket_rows(n)
    if n > cap:
        raise ValueError(f"batch of {n} rows exceeds capacity {cap}")
    cols: list = []
    for field, arr in zip(schema, rb.columns):
        if isinstance(arr, pa.ChunkedArray):
            arr = arr.combine_chunks()
        if pa.types.is_dictionary(arr.type):
            arr = arr.dictionary_decode()
        if field.dtype == DataType.STRING:
            w = (string_widths or {}).get(field.name)
            chars, lens, validity = _string_arrays(arr, cap, w)
            cols.append(StringColumn(jnp.asarray(chars), jnp.asarray(lens),
                                     jnp.asarray(validity)))
            continue
        if field.dtype == DataType.LIST:
            values, ev, lens, validity = _list_arrays(arr, cap,
                                                      field.elem.to_np())
            cols.append(ListColumn(jnp.asarray(values), jnp.asarray(ev),
                                   jnp.asarray(lens), jnp.asarray(validity)))
            continue
        np_dtype = field.dtype.to_np()
        validity = np.zeros(cap, bool)
        data = np.zeros(cap, np_dtype)
        if field.dtype == DataType.NULL:
            cols.append(PrimitiveColumn(jnp.asarray(data), jnp.asarray(validity)))
            continue
        if field.dtype == DataType.DECIMAL:
            pyvals = arr.to_pylist()
            if field.precision > 18:
                # precision 19..38: two-limb device representation
                # (columnar/decimal128.py; reference stores Decimal128 and
                # computes in i128, arrow/cast.rs decimal paths)
                from auron_tpu.columnar.decimal128 import (Decimal128Column,
                                                           limbs_from_ints)
                import decimal as _dec
                with _dec.localcontext() as _ctx:
                    # default context (prec=28) would silently round
                    # 29-38 digit values during scaleb
                    _ctx.prec = 60
                    ints = [None if v is None
                            else int(v.scaleb(field.scale)
                                     .to_integral_value())
                            for v in pyvals]
                hi, lo, valid128 = limbs_from_ints(ints, cap)
                cols.append(Decimal128Column(jnp.asarray(hi),
                                             jnp.asarray(lo),
                                             jnp.asarray(valid128)))
                continue
            # <=18 digits: unscaled int64 payload (reference:
            # datafusion-ext-functions/src/spark_make_decimal.rs)
            unscaled = np.zeros(n, np.int64)
            for i, v in enumerate(pyvals):
                if v is not None:
                    unscaled[i] = int(v.scaleb(field.scale).to_integral_value())
            data[:n] = unscaled
            validity[:n] = [v is not None for v in pyvals]
        elif field.dtype == DataType.TIMESTAMP_US:
            arr_us = arr.cast(pa.timestamp("us"))
            vals = arr_us.cast(pa.int64())
            data[:n] = np.asarray(vals.fill_null(0))
            validity[:n] = ~np.asarray(arr.is_null()) if arr.null_count else True
        elif field.dtype == DataType.DATE32:
            vals = arr.cast(pa.int32())
            data[:n] = np.asarray(vals.fill_null(0))
            validity[:n] = ~np.asarray(arr.is_null()) if arr.null_count else True
        else:
            vals = arr.fill_null(False) if field.dtype == DataType.BOOL else arr.fill_null(0)
            data[:n] = np.asarray(vals)
            validity[:n] = ~np.asarray(arr.is_null()) if arr.null_count else True
        cols.append(PrimitiveColumn(jnp.asarray(data), jnp.asarray(validity)))
    return DeviceBatch(tuple(cols), jnp.asarray(n, jnp.int32)), schema


def to_arrow(batch: DeviceBatch, schema: Schema) -> pa.RecordBatch:
    """Materialize a DeviceBatch back to a pyarrow RecordBatch — ONE packed
    device→host transfer for the whole batch (columnar.serde.fetch_batch_numpy;
    per-array fetches pay ~70 ms tunnel latency EACH on remote accelerators)."""
    from auron_tpu.columnar.serde import fetch_batch_numpy
    fetched, n = fetch_batch_numpy(batch)
    arrays = []
    for field, col, col_arrs in zip(schema, batch.columns, fetched):
        if isinstance(col, StringColumn):
            chars = col_arrs[0][:n]
            lens = col_arrs[1][:n].astype(np.int64)
            validity = col_arrs[2][:n]
            lens = np.where(validity, lens, 0)
            offsets = np.zeros(n + 1, np.int32)
            np.cumsum(lens, out=offsets[1:])
            take_mask = np.arange(chars.shape[1])[None, :] < lens[:, None]
            flat = chars[take_mask].astype(np.uint8)
            arrays.append(pa.StringArray.from_buffers(
                n, pa.py_buffer(offsets.tobytes()), pa.py_buffer(flat.tobytes()),
                pa.py_buffer(np.packbits(validity, bitorder="little").tobytes()),
                int((~validity).sum())))
            continue
        if isinstance(col, ListColumn):
            values = col_arrs[0][:n]
            ev = col_arrs[1][:n]
            validity = col_arrs[3][:n]
            lens = np.where(validity, col_arrs[2][:n], 0)
            take = np.arange(col.max_elems)[None, :] < lens[:, None]
            flat_vals = values[take]
            flat_valid = ev[take]
            child = pa.array(flat_vals,
                             pa.from_numpy_dtype(field.elem.to_np()))
            if not flat_valid.all():
                child = _with_nulls(child, flat_valid)
            offsets = np.zeros(n + 1, np.int32)
            np.cumsum(lens, out=offsets[1:])
            off_arr = pa.array(
                [None if not v else int(o)
                 for o, v in zip(offsets[:-1], validity)] + [int(offsets[-1])],
                pa.int32()) if not validity.all() else \
                pa.array(offsets, pa.int32())
            arrays.append(pa.ListArray.from_arrays(off_arr, child))
            continue
        from auron_tpu.columnar.decimal128 import Decimal128Column
        if isinstance(col, Decimal128Column):
            from auron_tpu.columnar.decimal128 import ints_from_limbs
            ints = ints_from_limbs(col_arrs[0][:n], col_arrs[1][:n],
                                   col_arrs[2][:n])
            vals = [None if x is None else _int_to_decimal(x, field.scale)
                    for x in ints]
            arrays.append(pa.array(
                vals, type=pa.decimal128(field.precision, field.scale)))
            continue
        data = col_arrs[0][:n]
        validity = col_arrs[1][:n]
        if field.dtype == DataType.DECIMAL:
            vals = [None if not v else _int_to_decimal(int(x), field.scale)
                    for x, v in zip(data, validity)]
            arrays.append(pa.array(vals, type=pa.decimal128(field.precision, field.scale)))
        elif field.dtype == DataType.DATE32:
            arrays.append(pa.array(np.where(validity, data, 0), pa.int32())
                          .cast(pa.date32()))
            if not validity.all():
                arrays[-1] = _with_nulls(arrays[-1], validity)
        elif field.dtype == DataType.TIMESTAMP_US:
            a = pa.array(np.where(validity, data, 0), pa.int64()).cast(pa.timestamp("us"))
            arrays.append(a if validity.all() else _with_nulls(a, validity))
        elif field.dtype == DataType.NULL:
            arrays.append(pa.nulls(n))
        else:
            a = pa.array(data)
            arrays.append(a if validity.all() else _with_nulls(a, validity))
    return pa.RecordBatch.from_arrays(arrays, schema=schema_to_arrow(schema))


def _with_nulls(arr: pa.Array, validity: np.ndarray) -> pa.Array:
    return pa.array(
        [v if ok else None for v, ok in zip(arr.to_pylist(), validity)],
        type=arr.type)


def _int_to_decimal(unscaled: int, scale: int):
    import decimal
    with decimal.localcontext() as ctx:
        ctx.prec = 60   # default prec=28 rounds away 29-38 digit values
        return decimal.Decimal(unscaled).scaleb(-scale)
