"""128-bit decimal columns as two 64-bit limbs — precision 19..38.

The reference stores Spark decimals as Arrow Decimal128 and does the
arithmetic in Rust i128 (reference: datafusion-ext-commons/src/arrow/
cast.rs decimal paths, datafusion-ext-functions/src/spark_check_overflow
.rs, spark_make_decimal.rs). TPUs have no 128-bit (or even native 64-bit)
integers, so here a decimal(p>18) column is a pair of int64 arrays —
``hi`` (signed high limb) and ``lo`` (low limb, the bit pattern of an
unsigned 64-bit value) — and every operation is branch-free limb
arithmetic that XLA lowers to 32-bit pairs on TPU:

  - add/sub: unsigned-compare carry propagation;
  - mul: 32-bit half-limb schoolbook multiply keeping the low 128 bits;
  - scale by 10^k: constant multiply / chunked long division in base 2^32
    with divisor chunks <= 10^9 so partial remainders fit int63;
  - compare: signed hi then unsigned lo.

Values are two's-complement 128-bit integers; precision 38 bounds
|value| < 10^38 < 2^127, so no operation here can overflow the
representation itself — overflow beyond the DECLARED precision is
detected against 10^p bounds and nulled (Spark non-ANSI semantics).
"""

from __future__ import annotations

from dataclasses import dataclass, replace

import numpy as np

import jax
import jax.numpy as jnp

I64 = jnp.int64
#: 1 << 63 as an int64 bit pattern. Plain python int — a module-level
#: jnp array would force jax backend init at import time, which breaks
#: child processes that must control platform selection before first use
#: (the round-2 dryrun lesson; see ops/hashing.py).
_SIGN = -0x8000000000000000
MAX_PRECISION = 38


@jax.tree_util.register_dataclass
@dataclass(frozen=True)
class Decimal128Column:
    """Two-limb decimal column: value = hi * 2^64 + u64(lo)."""

    hi: jax.Array        # int64[capacity], signed high limb
    lo: jax.Array        # int64[capacity], bit pattern of unsigned low limb
    validity: jax.Array  # bool[capacity]

    @property
    def capacity(self) -> int:
        return self.hi.shape[0]

    def with_validity(self, validity: jax.Array) -> "Decimal128Column":
        return replace(self, validity=validity)


# ---------------------------------------------------------------------------
# unsigned-64 helpers on int64 bit patterns
# ---------------------------------------------------------------------------

def _ult(a, b):
    """Unsigned a < b over int64 bit patterns (flip the sign bit)."""
    return (a ^ _SIGN) < (b ^ _SIGN)


def _u32_parts(x):
    lo = x & jnp.int64(0xFFFFFFFF)
    hi = (x >> 32) & jnp.int64(0xFFFFFFFF)
    return hi, lo


def _lsr32(x):
    """Logical (unsigned) right shift by 32 of an int64 bit pattern —
    32x32 partial products can exceed int63, so arithmetic shifts would
    sign-extend garbage into the carries."""
    return (x >> 32) & jnp.int64(0xFFFFFFFF)


def _mul_u64(a, b):
    """Full 64x64 -> 128 unsigned multiply of int64 bit patterns; returns
    (hi64, lo64) bit patterns."""
    ah, al = _u32_parts(a)
    bh, bl = _u32_parts(b)
    ll = al * bl          # may exceed int63: treat as u64 bit pattern
    lh = al * bh
    hl = ah * bl
    hh = ah * bh
    mid = _lsr32(ll) + (lh & jnp.int64(0xFFFFFFFF)) \
        + (hl & jnp.int64(0xFFFFFFFF))
    lo = (ll & jnp.int64(0xFFFFFFFF)) | (mid << 32)
    hi = hh + _lsr32(lh) + _lsr32(hl) + _lsr32(mid)
    return hi, lo


# ---------------------------------------------------------------------------
# core 128-bit ops (elementwise over (hi, lo) pairs)
# ---------------------------------------------------------------------------

def add128(ah, al, bh, bl):
    lo = al + bl
    carry = _ult(lo, al).astype(I64)
    return ah + bh + carry, lo


def neg128(h, l):
    nl = (~l) + 1
    borrow = (nl == 0).astype(I64)
    return (~h) + borrow, nl


def sub128(ah, al, bh, bl):
    nh, nl = neg128(bh, bl)
    return add128(ah, al, nh, nl)


def mul128(ah, al, bh, bl):
    """Low 128 bits of a*b (two's complement — low bits are sign-correct)."""
    hi, lo = _mul_u64(al, bl)
    hi = hi + al * bh + ah * bl
    return hi, lo


def cmp128(ah, al, bh, bl):
    """(lt, eq) for signed 128-bit comparison."""
    eq = (ah == bh) & (al == bl)
    lt = (ah < bh) | ((ah == bh) & _ult(al, bl))
    return lt, eq


def is_negative(h, _l):
    return h < 0


def abs128(h, l):
    neg = is_negative(h, l)
    nh, nl = neg128(h, l)
    return jnp.where(neg, nh, h), jnp.where(neg, nl, l)


def from_int64(x):
    """Sign-extend an int64 (e.g. a scaled decimal(<=18)) into limbs."""
    return jnp.where(x < 0, jnp.int64(-1), jnp.int64(0)), x


def to_int64(h, l):
    """(value as int64, fits flag): exact when the 128-bit value is within
    int64 range (hi is pure sign extension of lo)."""
    fits = h == jnp.where(l < 0, jnp.int64(-1), jnp.int64(0))
    return l, fits


# ---------------------------------------------------------------------------
# powers of ten
# ---------------------------------------------------------------------------

def _pow10_limbs(k: int) -> tuple[int, int]:
    v = 10 ** k
    lo = v & ((1 << 64) - 1)
    hi = v >> 64
    if lo >= 1 << 63:
        lo -= 1 << 64
    return hi, lo


def mul_pow10(h, l, k: int):
    """value * 10^k (k in [0, 38])."""
    if k == 0:
        return h, l
    ph, pl = _pow10_limbs(k)
    rh, rl = mul128(h, l, jnp.int64(ph), jnp.int64(pl))
    return rh, rl


def _divmod_small(h, l, d: int):
    """Unsigned (h,l) // d and remainder for 1 <= d <= 10^9, via base-2^32
    long division (every partial value < d * 2^32 < 2^62 fits int64)."""
    assert 1 <= d <= 10 ** 9
    limbs = [(h >> 32) & jnp.int64(0xFFFFFFFF), h & jnp.int64(0xFFFFFFFF),
             (l >> 32) & jnp.int64(0xFFFFFFFF), l & jnp.int64(0xFFFFFFFF)]
    q = []
    r = jnp.zeros_like(h)
    for limb in limbs:
        cur = (r << 32) | limb
        q.append(cur // d)
        r = cur % d
    qh = (q[0] << 32) | q[1]
    ql = (q[2] << 32) | q[3]
    return qh, ql, r


def _divmod_u64_runtime(ah, al, d):
    """Unsigned (ah,al) // d and remainder for a RUNTIME int64 divisor
    1 <= d < 2^31 (base-2^32 long division keeps every partial value
    r*2^32 + limb < d*2^32 < 2^63). The pow10 dividers above only take
    compile-time divisor constants."""
    limbs = [(ah >> 32) & jnp.int64(0xFFFFFFFF), ah & jnp.int64(0xFFFFFFFF),
             (al >> 32) & jnp.int64(0xFFFFFFFF), al & jnp.int64(0xFFFFFFFF)]
    q = []
    r = jnp.zeros_like(ah)
    for limb in limbs:
        cur = (r << 32) | limb
        q.append(cur // d)
        r = cur % d
    return (q[0] << 32) | q[1], (q[2] << 32) | q[3], r


def avg_pow10_div_half_up(h, l, count, k: int):
    """(value * 10^k) / count with HALF_UP, for avg finalizers: the sum
    accumulates UNSHIFTED (so only genuinely-overflowing totals wrap
    2^127) and the result-scale shift composes with the division here as
    q*10^k + round((r*10^k)/count), which never widens past the result.
    Returns (hi, lo, fits) — fits=False when |q| >= 10^(38-k), i.e. the
    scaled average cannot fit decimal(38) and Spark nulls it."""
    assert 0 <= k <= 9   # frac term: 2*r*10^k < 2^32 * 10^9 < 2^63
    neg = is_negative(h, l)
    ah, al = abs128(h, l)
    qh, ql, r = _divmod_u64_runtime(ah, al, count)
    fits = fits_precision(qh, ql, 38 - k)
    # the long-division invariant needs count < 2^31; a group larger than
    # that nulls rather than silently mis-dividing (Spark would compute it
    # — an accepted engine bound, >2.1e9 rows in ONE group)
    fits = fits & (count < (1 << 31))
    qh, ql = mul_pow10(qh, ql, k)
    # r < count < 2^31 and 10^k <= 10^38's low digits… keep k small enough
    # for int64: the avg shift is at most 4 digits (s+4 result scale), so
    # 2*r*10^k < 2^32 * 2e4 < 2^63
    frac = (2 * r * (10 ** k) + count) // (2 * count)
    qh, ql = add128(qh, ql, jnp.zeros_like(h), frac)
    nh, nl = neg128(qh, ql)
    return jnp.where(neg, nh, qh), jnp.where(neg, nl, ql), fits


def div_pow10_half_up(h, l, k: int):
    """value / 10^k with HALF_UP rounding (Spark decimal rescale-down)."""
    if k == 0:
        return h, l
    if k >= 39:
        # |value| < 10^38 < 0.5 * 10^k: always rounds to zero
        return jnp.zeros_like(h), jnp.zeros_like(l)
    neg = is_negative(h, l)
    ah, al = abs128(h, l)
    # q, r = divmod(value, 10^k) in <=9-digit chunks. Dividing by d1 then
    # d2: value = q2*d1*d2 + r2*d1 + r1, so the full remainder rebuilds as
    # r = r1 + r2*d1 + r3*d1*d2 + ... (rem_exp tracks the 10^j factor).
    rem_h = jnp.zeros_like(h)
    rem_l = jnp.zeros_like(l)
    rem_exp = 0
    kk = k
    while kk > 0:
        step = min(kk, 9)
        d = 10 ** step
        ah, al, r = _divmod_small(ah, al, d)
        sh, sl = _pow10_limbs(rem_exp)
        rh_, rl_ = mul128(jnp.zeros_like(r), r, jnp.int64(sh),
                          jnp.int64(sl))
        rem_h, rem_l = add128(rem_h, rem_l, rh_, rl_)
        rem_exp += step
        kk -= step
    # HALF_UP: round away from zero when remainder >= 5 * 10^(k-1).
    # (Comparing 2*remainder against 10^k would signed-wrap for k=38
    # remainders >= 2^126.)
    half = 5 * 10 ** (k - 1)
    mask = (1 << 64) - 1
    t_lo = half & mask
    t_hi = (half >> 64) & mask
    t_lo = t_lo - (1 << 64) if t_lo >= 1 << 63 else t_lo
    lt, _eq = cmp128(rem_h, rem_l, jnp.int64(t_hi), jnp.int64(t_lo))
    bump = (~lt).astype(I64)
    ah, al = add128(ah, al, jnp.zeros_like(h), bump)
    nh, nl = neg128(ah, al)
    return jnp.where(neg, nh, ah), jnp.where(neg, nl, al)


def div_pow10_trunc(h, l, k: int):
    """value / 10^k truncated toward zero (decimal → integer casts)."""
    if k == 0:
        return h, l
    neg = is_negative(h, l)
    ah, al = abs128(h, l)
    kk = k
    while kk > 0:
        step = min(kk, 9)
        ah, al, _r = _divmod_small(ah, al, 10 ** step)
        kk -= step
    nh, nl = neg128(ah, al)
    return jnp.where(neg, nh, ah), jnp.where(neg, nl, al)


def fits_precision(h, l, precision: int):
    """|value| < 10^precision (the declared-precision overflow check,
    reference: spark_check_overflow.rs)."""
    ah, al = abs128(h, l)
    bh, bl = _pow10_limbs(min(precision, MAX_PRECISION))
    lt, _ = cmp128(ah, al, jnp.int64(bh), jnp.int64(bl))
    return lt


# ---------------------------------------------------------------------------
# host conversion
# ---------------------------------------------------------------------------

def limbs_from_ints(values: list, cap: int) -> tuple[np.ndarray, np.ndarray,
                                                     np.ndarray]:
    """Python ints (scaled unscaled values; None = null) → limb arrays."""
    hi = np.zeros(cap, np.int64)
    lo = np.zeros(cap, np.int64)
    valid = np.zeros(cap, bool)
    mask = (1 << 64) - 1
    for i, v in enumerate(values):
        if v is None:
            continue
        u = v & ((1 << 128) - 1)           # two's complement 128
        l = u & mask
        h = (u >> 64) & mask
        lo[i] = l - (1 << 64) if l >= 1 << 63 else l
        hi[i] = h - (1 << 64) if h >= 1 << 63 else h
        valid[i] = True
    return hi, lo, valid


def ints_from_limbs(hi: np.ndarray, lo: np.ndarray,
                    valid: np.ndarray) -> list:
    """Limb arrays → python ints (None for nulls)."""
    out = []
    for h, l, ok in zip(hi.tolist(), lo.tolist(), valid.tolist()):
        if not ok:
            out.append(None)
            continue
        u = ((h & ((1 << 64) - 1)) << 64) | (l & ((1 << 64) - 1))
        if u >= 1 << 127:
            u -= 1 << 128
        out.append(u)
    return out


def to_float64(h, l):
    """Approximate float64 value of the 128-bit integer (for float-context
    arithmetic and casts)."""
    neg = is_negative(h, l)
    ah, al = abs128(h, l)
    lo_u = jnp.where(al < 0, al.astype(jnp.float64) + 2.0 ** 64,
                     al.astype(jnp.float64))
    mag = ah.astype(jnp.float64) * (2.0 ** 64) + lo_u
    return jnp.where(neg, -mag, mag)
