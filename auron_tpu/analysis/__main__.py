"""CLI: ``python -m auron_tpu.analysis``.

Exit codes: 0 = clean (no unbaselined violations, no parse errors),
1 = violations, 2 = usage/environment error (missing/garbage baseline).

    # the CI gate (what tests/test_zz_lint_gate.py runs)
    python -m auron_tpu.analysis --baseline tools/lint_baseline.json

    # freeze the current violation set (shrinking it is always safe;
    # growing it is a review conversation)
    python -m auron_tpu.analysis --update-baseline

    # machine-readable report (tools/lint_report.py input)
    python -m auron_tpu.analysis --baseline tools/lint_baseline.json --json
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from auron_tpu.analysis import core


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m auron_tpu.analysis", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*",
                    help="files/directories to analyze (default: the "
                         "repo tree — auron_tpu/, tools/, bench.py)")
    ap.add_argument("--baseline", default=None,
                    help="baseline JSON of grandfathered violations; "
                         "only NEW violations fail the run")
    ap.add_argument("--update-baseline", action="store_true",
                    help="write the current violation set to the "
                         "baseline path (default tools/lint_baseline."
                         "json) and exit 0")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the machine-readable report to stdout")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule-id subset (debugging)")
    ap.add_argument("--root", default=None,
                    help="repo root for relative paths / directory-"
                         "scoped rules (default: this checkout)")
    args = ap.parse_args(argv)

    root = os.path.abspath(args.root) if args.root else core.repo_root()
    targets = args.paths or None
    rule_ids = (args.rules.split(",") if args.rules else None)

    if args.update_baseline and (args.rules
                                 or (args.paths and not args.root)):
        # a subset run must never overwrite the whole-tree baseline:
        # freezing only GL007's (or one directory's) violations would
        # silently discard every other rule's frozen entries and the
        # next full gate run would report them all as NEW
        print("graftlint: refusing --update-baseline with --rules or "
              "explicit paths — the baseline freezes the WHOLE tree; "
              "run without a subset filter (paths are allowed together "
              "with --root for a self-contained tree)",
              file=sys.stderr)
        return 2

    result = core.analyze(targets, root=root, rule_ids=rule_ids)

    if args.update_baseline:
        path = args.baseline or core.default_baseline_path(root)
        data = core.save_baseline(path, result.violations)
        print(f"graftlint: baseline updated — {len(data['entries'])} "
              f"entries ({len(result.violations)} violations, "
              f"{result.suppressed} suppressed) -> {path}")
        return 0

    report = result.to_json()
    stale: list = []
    new = result.violations
    if args.baseline:
        try:
            baseline = core.load_baseline(args.baseline)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"graftlint: cannot load baseline: {e}",
                  file=sys.stderr)
            return 2
        new, old, stale = core.apply_baseline(result.violations, baseline)
        report["violations"] = [v.to_json() for v in new]
        report["grandfathered"] = len(old)
        report["stale_baseline_entries"] = stale
    report["new_violations"] = len(new)
    report["ok"] = not new and not result.parse_errors

    if args.as_json:
        print(json.dumps(report, indent=1))
    else:
        for v in new:
            print(v.render())
        for rel, msg in result.parse_errors:
            print(f"{rel}:0: parse error: {msg}")
        counts = ", ".join(f"{k}={n}" for k, n in result.by_rule().items())
        print(f"graftlint: {result.files_scanned} files, "
              f"{len(result.violations)} violations"
              + (f" ({counts})" if counts else "")
              + f", {result.suppressed} suppressed"
              + (f", {report.get('grandfathered', 0)} baselined, "
                 f"{len(new)} NEW" if args.baseline else ""))
        if stale:
            print(f"graftlint: {len(stale)} stale baseline entries "
                  f"(fixed code — prune with --update-baseline)")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
