"""The graftlint rule set: the runtime's cross-cutting contracts as AST
checks. Each rule encodes ONE invariant a past PR established and a
future PR could silently break; ANALYSIS.md documents the contracts in
prose. Scoping, heuristics and their limits are deliberate — every rule
errs toward *candidate* findings that the baseline freezes, never
toward silently passing a new violation of the real contract.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, Optional

from auron_tpu.analysis.core import FileContext, Project, Rule, rule

# directory scopes (repo-relative prefixes)
_RUNTIME_DIRS = ("auron_tpu/ops/", "auron_tpu/runtime/",
                 "auron_tpu/parallel/")
_TAXONOMY_DIRS = ("auron_tpu/runtime/", "auron_tpu/ops/",
                  "auron_tpu/fleet/")
_OPERATOR_DIRS = ("auron_tpu/ops/", "auron_tpu/parallel/",
                  "auron_tpu/io/", "auron_tpu/runtime/")


def _dotted(node: ast.AST) -> str:
    """``a.b.c`` for Name/Attribute chains, '' otherwise."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _contains_call(node: ast.AST, suffixes: tuple) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            d = _dotted(n.func)
            if d and (d.split(".")[-1] in suffixes):
                return True
    return False


# ---------------------------------------------------------------------------
# GL001 — sync discipline (PR 8's attribution invariant)
# ---------------------------------------------------------------------------

#: the sanctioned sync wrappers (obs/profile.py): waits routed through
#: them are credited as device time at the moved sync points
_SANCTIONED = ("timed_get", "device_fence")

#: call roots that mark a host-side value (skipped as candidates)
_HOST_FUNCS = frozenset((
    "len", "round", "min", "max", "sum", "abs", "ord", "hash", "id",
    "str", "repr", "int", "float", "bool", "divmod", "pow", "sorted",
    "time", "os", "math", "zlib", "json", "enumerate", "range",
))


@rule
class SyncDiscipline(Rule):
    """Device syncs in the execution packages must route through the
    profiler's sanctioned frames. PR 8 moved every per-batch sync to
    semantic boundaries (``profile.device_fence`` at materialization,
    ``profile.timed_get`` for control-scalar readbacks): a raw
    ``block_until_ready`` / ``jax.device_get`` / host conversion of a
    jax value both SERIALIZES the pipelined overlap and books the
    device wait into the wrong host bucket, so attribution stops
    summing to wall honestly. ``float()``/``int()``/``np.asarray`` on
    non-obviously-host values are reported as CANDIDATES (the baseline
    freezes today's ~230; a new one must justify itself)."""

    rule_id = "GL001"
    title = "sync-discipline"
    hint = ("route the readback through profile.timed_get(...) inside "
            "the operator's timer frame, or fence the semantic "
            "boundary with profile.device_fence(...); a provably "
            "host-only conversion may carry "
            "'# graft: disable=GL001 -- <why it is host-side>'")
    node_types = (ast.Attribute, ast.Call)
    dirs = _RUNTIME_DIRS

    def visit(self, node, ctx: FileContext) -> Iterable:
        if isinstance(node, ast.Attribute):
            if node.attr == "block_until_ready":
                yield self.violation(
                    ctx, node,
                    "raw block_until_ready outside a sanctioned "
                    "profile frame (PR 8 moved per-batch syncs to "
                    "device_fence/timed_get boundaries)")
            elif node.attr == "addressable_shards":
                yield self.violation(
                    ctx, node,
                    ".addressable_shards slices device state on the "
                    "host path — a hidden sync and a multihost "
                    "routing hazard (the reducer read path must stay "
                    "host-local or go through the RSS tier)")
            return
        # Calls
        func = node.func
        d = _dotted(func)
        leaf = d.split(".")[-1] if d else ""
        if leaf == "device_get":
            yield self.violation(
                ctx, node,
                "raw jax.device_get readback — the wait it absorbs "
                "books as host time; use profile.timed_get so the "
                "sync is credited as device wait")
            return
        if isinstance(func, ast.Name) and func.id in ("float", "int"):
            if len(node.args) != 1 or node.keywords:
                return
            arg = node.args[0]
            if self._host_side(arg):
                return
            yield self.violation(
                ctx, node,
                f"{func.id}() on a possibly device-resident value is "
                f"an implicit sync (candidate site)")
            return
        if leaf == "asarray" and d.split(".")[0] in ("np", "numpy"):
            if not node.args or self._host_side(node.args[0]):
                return
            yield self.violation(
                ctx, node,
                "np.asarray() on a possibly device-resident value is "
                "an implicit transfer+sync (candidate site)")

    @staticmethod
    def _host_side(arg: ast.AST) -> bool:
        """Conservatively true when the converted value is clearly a
        host value (literal, host-builtin result) or already routed
        through a sanctioned wrapper."""
        if isinstance(arg, (ast.Constant, ast.JoinedStr)):
            return True
        if _contains_call(arg, _SANCTIONED):
            return True
        if isinstance(arg, ast.Call):
            d = _dotted(arg.func)
            if d and (d.split(".")[0] in _HOST_FUNCS
                      or d.split(".")[-1] in _HOST_FUNCS):
                return True
        if isinstance(arg, ast.BinOp):
            return SyncDiscipline._host_side(arg.left) \
                and SyncDiscipline._host_side(arg.right)
        return False


# ---------------------------------------------------------------------------
# GL002 — donation safety (PR 3/10's retry-reuse contract)
# ---------------------------------------------------------------------------

@rule
class DonationSafety(Rule):
    """Buffer donation destroys its inputs, so every donation site must
    carry an explicit safety annotation: hashtable overflow retries
    re-run the step kernel on the SAME state+batch (PR 3), and the mesh
    exchange's quota escalation re-runs the stage program on the SAME
    inputs (PR 10) — donating there corrupts the retry. The annotation
    ``# graft: donation-ok -- <why the inputs are dead>`` (same line or
    the line above) states the argument; a site without one fails."""

    rule_id = "GL002"
    title = "donation-safety"
    hint = ("state why the donated inputs cannot be reused by any "
            "retry/escalation path with '# graft: donation-ok -- "
            "<reason>' on (or directly above) the call — or pass "
            "donate=False where a retry reuses inputs")
    node_types = (ast.Call,)

    def visit(self, node, ctx: FileContext) -> Iterable:
        for kw in node.keywords:
            if kw.arg not in ("donate", "donate_argnums"):
                continue
            # explicit non-donation is always safe
            v = kw.value
            if isinstance(v, ast.Constant) and not v.value:
                continue
            if isinstance(v, ast.Tuple) and not v.elts:
                continue
            if ctx.annotated("donation-ok", node.lineno):
                continue
            yield self.violation(
                ctx, node,
                f"donation site ({kw.arg}=...) without a "
                f"'# graft: donation-ok' annotation — overflow/"
                f"escalation retries that reuse inputs forbid "
                f"donation")
            return


# ---------------------------------------------------------------------------
# GL003 — trace-semantic knobs (PR 3's program-cache-key contract)
# ---------------------------------------------------------------------------

def _config_vocab():
    from auron_tpu import config as cfg
    keys = {o.key for o in cfg.options()}
    const_to_key = {}
    for name in dir(cfg):
        if not name.isupper():
            continue
        val = getattr(cfg, name)
        if isinstance(val, str) and val in keys:
            const_to_key[name] = val
    return keys, const_to_key, set(cfg.TRACE_SEMANTIC_KEYS)


_BUILDER_NAME = re.compile(r"(^build_kernel_fragment$|_kernel|_program"
                           r"|fragment)")


@rule
class TraceSemanticKnob(Rule):
    """A config knob read INSIDE kernel-builder code changes what the
    compiled program computes, so its value must ride every
    program-cache key — ``config.TRACE_SEMANTIC_KEYS`` feeds
    ``trace_salt()`` into runtime/programs.py for exactly this reason
    (the map-key-dedup precedent, PR 3). A knob read in a builder that
    is neither trace-semantic nor declared inert can serve a STALE
    compiled kernel after the knob flips."""

    rule_id = "GL003"
    title = "trace-semantic-knob"
    hint = ("add the key to config.TRACE_SEMANTIC_KEYS (it changes "
            "traced computation) or declare it inert with "
            "'# graft: inert-knob -- <why the traced program does not "
            "depend on it>'")
    node_types = (ast.Call,)

    def __init__(self):
        self._vocab = None

    def visit(self, node, ctx: FileContext) -> Iterable:
        if not (isinstance(node.func, ast.Attribute)
                and node.func.attr == "get" and node.args):
            return
        arg = node.args[0]
        key = None
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str) \
                and arg.value.startswith("auron."):
            key = arg.value
        elif isinstance(arg, ast.Attribute) or isinstance(arg, ast.Name):
            if self._vocab is None:
                self._vocab = _config_vocab()
            _, const_to_key, _ = self._vocab
            name = arg.attr if isinstance(arg, ast.Attribute) else arg.id
            key = const_to_key.get(name)
        if key is None:
            return
        fn = ctx.enclosing_function(node)
        if fn is None or not _BUILDER_NAME.search(fn.name):
            return
        if self._vocab is None:
            self._vocab = _config_vocab()
        _, _, salt_keys = self._vocab
        if key in salt_keys:
            return
        if ctx.annotated("inert-knob", node.lineno):
            return
        yield self.violation(
            ctx, node,
            f"config read of {key!r} inside kernel-builder "
            f"{fn.name!r} is not in config.TRACE_SEMANTIC_KEYS and "
            f"not declared inert — a flipped knob could serve a "
            f"stale compiled program")


# ---------------------------------------------------------------------------
# GL004 — error taxonomy (PR 4's classified-recovery contract)
# ---------------------------------------------------------------------------

@rule
class ErrorTaxonomy(Rule):
    """Runtime-path raises must be classified ``AuronError``s: the
    retry driver routes purely on ``errors.is_transient`` (PR 4 deleted
    the message-matching), so a bare ``raise RuntimeError`` gets the
    conservative default-retry treatment — retries+1 full recomputes of
    a deterministic failure — and a broad ``except Exception: pass``
    swallows classified verdicts the recovery plane needed to see."""

    rule_id = "GL004"
    title = "error-taxonomy"
    hint = ("raise a classified errors.AuronError subclass (double-"
            "inherit the builtin when legacy 'except' sites must keep "
            "working, the errors.py idiom); for a deliberate "
            "best-effort swallow, log or add '# graft: disable=GL004 "
            "-- <why swallowing is safe>'")
    node_types = (ast.Raise, ast.ExceptHandler)
    dirs = _TAXONOMY_DIRS

    def visit(self, node, ctx: FileContext) -> Iterable:
        if isinstance(node, ast.Raise):
            exc = node.exc
            name = ""
            if isinstance(exc, ast.Call):
                name = _dotted(exc.func)
            elif exc is not None:
                name = _dotted(exc)
            if name in ("RuntimeError", "Exception"):
                yield self.violation(
                    ctx, node,
                    f"bare 'raise {name}' in a runtime path — the "
                    f"retry driver routes on the errors.py taxonomy, "
                    f"not messages, and will blind-retry this")
            return
        # ExceptHandler: broad catch that silently swallows
        t = node.type
        broad = t is None or (isinstance(t, ast.Name)
                              and t.id in ("Exception", "BaseException"))
        if not broad:
            return
        body = node.body
        if all(isinstance(s, (ast.Pass, ast.Continue)) for s in body):
            yield self.violation(
                ctx, node,
                "broad 'except Exception' with a silent body swallows "
                "classified errors the recovery plane routes on")


# ---------------------------------------------------------------------------
# GL005 — knob-registry drift (config.py ↔ CONFIG.md ↔ use sites)
# ---------------------------------------------------------------------------

_CONFIG_MD_KEY = re.compile(r"^\|\s*`(auron\.[a-z0-9_.]+)`")


@rule
class KnobRegistryDrift(Rule):
    """Three-way consistency of the knob surface: every ``auron.*`` key
    read anywhere must be declared in config.py (an unknown key raises
    KeyError at runtime — at the user, not at CI); every declared key
    must appear in CONFIG.md and vice versa (the doc is generated —
    drift means someone hand-edited it or forgot to regenerate); and a
    declared knob nothing reads is a lie to the user (config.py's own
    declaration discipline)."""

    rule_id = "GL005"
    title = "knob-registry-drift"
    hint = ("declare new keys via config._opt, regenerate CONFIG.md "
            "(python -c \"from auron_tpu import config; "
            "open('CONFIG.md','w').write(config.generate_docs())\"), "
            "and delete knobs nothing reads")
    node_types = (ast.Call, ast.Attribute, ast.Name)

    def __init__(self):
        #: literal "auron.*" keys passed to .get/.set/.unset:
        #: [(rel, line, key)]
        self._literal_reads: list = []
        #: config-module constant names referenced outside config.py
        self._used_consts: set = set()
        #: literal keys seen ANYWHERE (string mention counts as a use
        #: for dead-knob purposes — tools reach knobs via env strings)
        self._literal_keys: set = set()

    def visit(self, node, ctx: FileContext) -> Iterable:
        in_config = ctx.rel == "auron_tpu/config.py"
        if isinstance(node, ast.Name):
            if not in_config and node.id.isupper():
                self._used_consts.add(node.id)
            return ()
        if isinstance(node, ast.Attribute):
            if not in_config and node.attr.isupper():
                self._used_consts.add(node.attr)
            return ()
        # Call: collect literal key reads through config-ish accessors
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in ("get", "set", "unset") \
                and node.args:
            a = node.args[0]
            if isinstance(a, ast.Constant) and isinstance(a.value, str) \
                    and a.value.startswith("auron."):
                self._literal_keys.add(a.value)
                if not in_config:
                    self._literal_reads.append(
                        (ctx.rel, node.lineno, a.value, ctx))
        return ()

    def finalize(self, project: Project) -> Iterable:
        import os

        from auron_tpu import config as cfg
        keys = {o.key for o in cfg.options()}
        _, const_to_key, _ = _config_vocab()
        key_to_const = {v: k for k, v in const_to_key.items()}

        # (a) literal reads of unknown keys
        for rel, line, key, ctx in self._literal_reads:
            if key not in keys:
                yield self.violation(
                    ctx, line,
                    f"config access of {key!r}, which is not declared "
                    f"in auron_tpu/config.py (KeyError at runtime)")

        # (b) config.py ↔ CONFIG.md key sets
        md_path = os.path.join(project.root, "CONFIG.md")
        md_keys: dict[str, int] = {}
        if os.path.exists(md_path):
            with open(md_path, encoding="utf-8") as f:
                for i, text in enumerate(f, start=1):
                    m = _CONFIG_MD_KEY.match(text)
                    if m:
                        md_keys[m.group(1)] = i
            for key in sorted(keys - set(md_keys)):
                yield Violation_md(
                    self, "CONFIG.md", 1,
                    f"declared knob {key!r} is missing from CONFIG.md "
                    f"— regenerate the doc")
            for key, line in sorted(md_keys.items()):
                if key not in keys:
                    yield Violation_md(
                        self, "CONFIG.md", line,
                        f"CONFIG.md documents {key!r}, which "
                        f"config.py no longer declares — regenerate "
                        f"the doc")
            if set(md_keys) == keys:
                # key sets agree: still fail on stale TEXT (a default
                # or doc string changed without regeneration)
                with open(md_path, encoding="utf-8") as f:
                    current = f.read()
                if current != cfg.generate_docs():
                    yield Violation_md(
                        self, "CONFIG.md", 1,
                        "CONFIG.md text differs from config."
                        "generate_docs() — a default or doc string "
                        "changed without regenerating")
        else:
            yield Violation_md(self, "CONFIG.md", 1,
                               "CONFIG.md is missing — regenerate it")

        # (c) dead knobs: declared but never referenced (by constant
        # name outside config.py, or by literal key anywhere)
        cfg_ctx = project.contexts.get("auron_tpu/config.py")
        if cfg_ctx is not None:
            for key in sorted(keys):
                const = key_to_const.get(key)
                if const and const in self._used_consts:
                    continue
                if key in self._literal_keys:
                    continue
                line = 1
                for i, text in enumerate(cfg_ctx.lines, start=1):
                    if f'"{key}"' in text:
                        line = i
                        break
                yield self.violation(
                    cfg_ctx, line,
                    f"declared knob {key!r} has no use site in the "
                    f"tree — an option nothing reads is a lie to the "
                    f"user (delete it, or land it with its feature)")


def Violation_md(r: Rule, file: str, line: int, message: str):
    """Violation on a non-Python surface (CONFIG.md has no AST ctx)."""
    from auron_tpu.analysis.core import Violation
    return Violation(file=file, line=line, rule=r.rule_id,
                     message=message, hint=r.hint, context="")


# ---------------------------------------------------------------------------
# GL006 — vocabulary drift (fault sites / trace categories)
# ---------------------------------------------------------------------------

_FAULT_FNS = frozenset(("maybe_fail", "maybe_hang", "maybe_cancel",
                        "maybe_corrupt", "fires"))
_TRACE_FNS = frozenset(("event", "complete_span", "category_enabled"))


@rule
class VocabularyDrift(Rule):
    """String literals at fault-plane and trace-plane call sites must
    belong to the documented vocabularies: an unknown fault site never
    fires (a chaos plan naming it is a silent no-op — faults.parse_plan
    validates plans, but the CODE side was unchecked), and an unknown
    trace category records events that ``auron.trace.events`` can never
    select and tools never aggregate."""

    rule_id = "GL006"
    title = "vocabulary-drift"
    hint = ("add the new site to runtime/faults.SITES (and its "
            "CONFIG.md doc) or the new category to obs/trace."
            "CATEGORIES before using it")
    node_types = (ast.Call,)

    def __init__(self):
        self._sites = self._kinds = self._cats = None

    def _load(self):
        if self._sites is None:
            from auron_tpu.obs import trace
            from auron_tpu.runtime import faults
            self._sites = set(faults.SITES)
            self._kinds = set(faults.KINDS)
            self._cats = set(trace.CATEGORIES)

    def visit(self, node, ctx: FileContext) -> Iterable:
        d = _dotted(node.func)
        if not d:
            return
        leaf = d.split(".")[-1]
        if leaf in _FAULT_FNS:
            # plain-named helpers ride on faults.* / direct import; a
            # same-named method on another object ("fires") must carry
            # a string that IS a site to be judged — non-literals skip
            if ctx.rel.endswith("runtime/faults.py"):
                return   # the plane's own implementation
            if not node.args:
                return
            a = node.args[0]
            if not (isinstance(a, ast.Constant)
                    and isinstance(a.value, str)):
                return
            self._load()
            # only judge dotted site-shaped strings when the callee is
            # not clearly the fault plane (avoids foreign .fires())
            base = d.split(".")[0]
            site_shaped = re.fullmatch(r"[a-z0-9_]+\.[a-z0-9_]+", a.value)
            if "fault" not in base and leaf == "fires" \
                    and not site_shaped:
                return
            if a.value not in self._sites:
                yield self.violation(
                    ctx, node,
                    f"fault site {a.value!r} is not in runtime/"
                    f"faults.SITES — it can never be armed by a "
                    f"chaos plan")
                return
            if leaf == "fires" and len(node.args) >= 2:
                k = node.args[1]
                if isinstance(k, ast.Constant) \
                        and isinstance(k.value, str) \
                        and k.value not in self._kinds:
                    yield self.violation(
                        ctx, node,
                        f"fault kind {k.value!r} is not in runtime/"
                        f"faults.KINDS")
            return
        if leaf in _TRACE_FNS:
            base = d.split(".")[0]
            if "trace" not in base:
                return   # threading.Event etc. — not the trace plane
            if ctx.rel.endswith("obs/trace.py"):
                return
            if not node.args:
                return
            a = node.args[0]
            if isinstance(a, ast.Constant) and isinstance(a.value, str):
                self._load()
                if a.value not in self._cats:
                    yield self.violation(
                        ctx, node,
                        f"trace category {a.value!r} is not in obs/"
                        f"trace.CATEGORIES — auron.trace.events can "
                        f"never select it and reports never "
                        f"aggregate it")


# ---------------------------------------------------------------------------
# GL007 — checkpoint coverage (PR 7's cooperative-lifecycle contract)
# ---------------------------------------------------------------------------

@rule
class CheckpointCoverage(Rule):
    """A batch-drive loop with no cooperative poll is invisible to the
    lifecycle plane: cancels/deadlines land only at the NEXT poll site,
    the stall watchdog sees no heartbeat, and injected lifecycle chaos
    (cancel.race / task.hang) gets no traffic. Every loop that drives a
    child operator stream (``for ... in <expr containing .execute(...)>``)
    must lexically contain a ``ctx.checkpoint(...)`` or
    ``check_cancelled()`` poll. Lexical check only: a loop that polls
    through a helper earns a suppression with the helper named."""

    rule_id = "GL007"
    title = "checkpoint-coverage"
    hint = ("poll ctx.checkpoint('<site>') inside the drive loop "
            "(heartbeat + lifecycle faults + cancel in one call); if "
            "the poll happens inside a called helper, suppress with "
            "'# graft: disable=GL007 -- polls via <helper>'")
    node_types = (ast.For,)
    dirs = _OPERATOR_DIRS

    def visit(self, node: ast.For, ctx: FileContext) -> Iterable:
        drives = any(
            isinstance(n, ast.Call)
            and isinstance(n.func, ast.Attribute)
            and n.func.attr == "execute"
            for n in ast.walk(node.iter))
        if not drives:
            return
        for stmt in node.body:
            for n in ast.walk(stmt):
                if isinstance(n, ast.Call) \
                        and isinstance(n.func, ast.Attribute) \
                        and n.func.attr in ("checkpoint",
                                            "check_cancelled"):
                    return
        yield self.violation(
            ctx, node,
            "batch-drive loop over a child .execute() stream with no "
            "ctx.checkpoint / check_cancelled poll site — cancels, "
            "deadlines and the stall watchdog cannot land here")


# ---------------------------------------------------------------------------
# GL008 — lock order (static deadlock detector for PR 9–14 concurrency)
# ---------------------------------------------------------------------------

_LOCKISH = re.compile(r"(lock|cond|mutex)", re.IGNORECASE)


@rule
class LockOrder(Rule):
    """The concurrency added since PR 9 (scheduler slots, memmgr
    accounting, program registry, journal appender, ops-server
    refcount) acquires locks through ``with`` statements. This rule
    builds the lexical acquisition graph — an edge A→B whenever a
    ``with`` holding lock A contains a ``with`` acquiring lock B — and
    fails on cycles: two code paths acquiring the same pair of locks in
    opposite orders is the canonical deadlock, and it is invisible to
    every test that doesn't hit the exact interleaving. Lock names are
    qualified by class (``QueryScheduler._cond``) or module; same-named
    locks on DIFFERENT classes are distinct nodes."""

    rule_id = "GL008"
    title = "lock-order"
    hint = ("acquire the two locks in one global order everywhere "
            "(document it where both are declared), or restructure so "
            "one side releases before taking the other")
    node_types = ()   # own traversal (needs the nesting stack)

    def __init__(self):
        #: directed edges {(a, b): (rel, line)} — first site wins
        self._edges: dict = {}

    def begin_file(self, ctx: FileContext) -> None:
        self._class_stack: list[str] = []
        self._walk(ctx.tree, [], ctx)

    def _lock_name(self, expr: ast.AST, ctx: FileContext) -> Optional[str]:
        try:
            text = ast.unparse(expr)
        except Exception:   # pragma: no cover - malformed expr
            return None
        if not _LOCKISH.search(text):
            return None
        # qualify: self._lock → <Class>._lock; module globals → module
        cls = self._class_stack[-1] if self._class_stack else None
        if text.startswith("self.") and cls:
            return f"{cls}.{text[5:]}"
        if "." not in text:
            mod = ctx.rel.rsplit("/", 1)[-1].removesuffix(".py")
            return f"{mod}:{text}"
        return text

    def _walk(self, node: ast.AST, held: list, ctx: FileContext) -> None:
        if isinstance(node, ast.ClassDef):
            self._class_stack.append(node.name)
            for child in ast.iter_child_nodes(node):
                self._walk(child, held, ctx)
            self._class_stack.pop()
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a fresh frame: locks held lexically OUTSIDE a def are not
            # held when the def later runs
            for child in ast.iter_child_nodes(node):
                self._walk(child, [], ctx)
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired = []
            for item in node.items:
                name = self._lock_name(item.context_expr, ctx)
                if name:
                    for h in held:
                        if h != name:
                            self._edges.setdefault(
                                (h, name), (ctx.rel, node.lineno))
                    acquired.append(name)
                    held = held + [name]
            for child in node.body:
                self._walk(child, held, ctx)
            return
        for child in ast.iter_child_nodes(node):
            self._walk(child, held, ctx)

    def finalize(self, project: Project) -> Iterable:
        graph: dict[str, list[str]] = {}
        for (a, b) in self._edges:
            graph.setdefault(a, []).append(b)
        # iterative three-color DFS; report each back edge's cycle once
        seen_cycles: set = set()
        color: dict[str, int] = {}   # 1 = on stack, 2 = done
        for start in sorted(graph):
            if color.get(start):
                continue
            stack = [(start, iter(graph.get(start, ())))]
            color[start] = 1
            path = [start]
            while stack:
                node, it = stack[-1]
                advanced = False
                for nxt in it:
                    if color.get(nxt) == 1:
                        i = path.index(nxt)
                        cycle = tuple(path[i:] + [nxt])
                        key = frozenset(cycle)
                        if key not in seen_cycles:
                            seen_cycles.add(key)
                            rel, line = self._edges[(node, nxt)]
                            from auron_tpu.analysis.core import Violation
                            yield Violation(
                                file=rel, line=line, rule=self.rule_id,
                                message=(
                                    "lock-order cycle: "
                                    + " -> ".join(cycle)
                                    + " — opposite-order acquisition "
                                      "is a latent deadlock"),
                                hint=self.hint, context="")
                    elif not color.get(nxt):
                        color[nxt] = 1
                        stack.append((nxt, iter(graph.get(nxt, ()))))
                        path.append(nxt)
                        advanced = True
                        break
                if not advanced:
                    color[node] = 2
                    stack.pop()
                    path.pop()
