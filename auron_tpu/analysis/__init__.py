"""graftlint: AST contract checker for the runtime's cross-cutting
invariants.

Fourteen PRs accreted contracts no type checker sees: device syncs must
route through ``profile.timed_get``/``device_fence`` frames, donation is
forbidden where a retry reuses inputs, trace-semantic knobs must ride
``config.trace_salt()``, runtime raises must be classified
``AuronError``s, fault-site / trace-category strings must match the
documented vocabularies, operator batch loops must poll
``ctx.checkpoint``, and lock acquisition must stay cycle-free. Each was
guarded only by chaos sweeps and regression tests that catch violations
AFTER they ship a wrong answer or a silent stall. This package enforces
them at CI time, the way the SystemML fusion-plan work (PAPERS.md,
1801.00829) and Flare (1703.08219) argue a native-execution engine must
enforce its structural invariants to evolve safely.

Entry points:

- ``python -m auron_tpu.analysis --baseline tools/lint_baseline.json``
  (the CI gate; ``--update-baseline`` freezes today's grandfathered
  violations, ``--json`` emits the machine-readable report)
- :func:`analyze` / :func:`run` for programmatic use
  (tests/test_zz_lint_gate.py, tools/perf_gate.py's lint arm)

The rule contracts, the suppression grammar
(``# graft: disable=<rule-id> -- <reason>``, reason mandatory) and the
baseline workflow are documented in ANALYSIS.md.
"""

from auron_tpu.analysis.core import (       # noqa: F401
    AnalysisResult,
    Violation,
    all_rules,
    analyze,
    apply_baseline,
    default_targets,
    load_baseline,
    repo_root,
    run,
    save_baseline,
)
