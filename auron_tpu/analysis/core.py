"""graftlint framework: rule registry, per-file visitor multiplexing,
suppressions and the frozen-violation baseline.

Design (the shape ANALYSIS.md documents):

- **One parse per file.** Every rule declares the AST node types it
  wants (``node_types``); the analyzer parses each file once, annotates
  parent links, and multiplexes each node to the rules registered for
  its type. Project-level rules (knob drift, lock order) accumulate
  state per file and emit from ``finalize``.
- **Structured violations.** Each :class:`Violation` carries
  ``file:line``, the rule id, a message, a fix hint, and ``context`` —
  the stripped source line, which is the violation's BASELINE IDENTITY:
  baselines key on ``(file, rule, context)`` so entries survive
  unrelated line-number drift but die with the offending code.
- **Suppression grammar.** ``# graft: disable=<rule-id>[,<id>...] --
  <reason>`` on the offending line suppresses those rules there;
  ``# graft: disable-file=<rule-id> -- <reason>`` anywhere in the file
  suppresses for the whole file. The reason is MANDATORY — a disable
  without one (or naming an unknown rule) is itself a violation
  (:data:`META_RULE` GL000), so every grandfathered exception carries
  its justification in the tree.
- **Frozen baseline.** ``tools/lint_baseline.json`` records today's
  grandfathered violations; the gate fails only on violations NOT in
  the baseline, so the checker could land with ~200 pre-existing
  candidate sites without a flag day while every NEW violation fails
  the PR that introduces it. ``--update-baseline`` regenerates it;
  stale entries (baselined code that no longer violates) are reported
  so the baseline shrinks monotonically.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Iterable, Iterator, Optional

#: rule id of the suppression-grammar meta rule (malformed/unknown
#: disables). Not suppressible — a broken suppression cannot excuse
#: itself.
META_RULE = "GL000"

#: the documented rule vocabulary (rules register themselves into this
#: at import; META_RULE is the framework's own)
_RULES: dict[str, type] = {}


def rule(cls):
    """Class decorator registering a rule by its ``rule_id``."""
    rid = cls.rule_id
    assert re.fullmatch(r"GL\d{3}", rid), f"bad rule id {rid!r}"
    assert rid not in _RULES, f"duplicate rule {rid}"
    _RULES[rid] = cls
    return cls


def all_rules() -> dict[str, type]:
    """{rule_id: rule class} — importing the rules module on demand so
    ``import auron_tpu.analysis`` stays cheap."""
    from auron_tpu.analysis import rules as _rules  # noqa: F401
    return dict(_RULES)


def known_rule_ids() -> set[str]:
    return set(all_rules()) | {META_RULE}


@dataclass(frozen=True)
class Violation:
    """One contract violation at ``file:line``."""

    file: str          # repo-relative posix path
    line: int
    rule: str          # GLnnn
    message: str
    hint: str = ""     # how to fix (the rule's standing advice)
    context: str = ""  # stripped source line — the baseline identity

    def key(self) -> tuple[str, str, str]:
        return (self.file, self.rule, self.context)

    def render(self) -> str:
        s = f"{self.file}:{self.line}: {self.rule}: {self.message}"
        if self.hint:
            s += f"\n    fix: {self.hint}"
        return s

    def to_json(self) -> dict:
        return {"file": self.file, "line": self.line, "rule": self.rule,
                "message": self.message, "hint": self.hint,
                "context": self.context}


class Rule:
    """Base rule. Subclasses set the class attributes and implement any
    of ``visit`` (per registered node), ``end_file`` (per file) and
    ``finalize`` (once, after every file) — each returns an iterable of
    :class:`Violation`. One instance lives per analysis run, so rules
    may accumulate cross-file state on ``self``."""

    rule_id: str = ""
    title: str = ""
    hint: str = ""
    #: AST node classes routed to ``visit`` (empty = none)
    node_types: tuple = ()
    #: repo-relative directory prefixes this rule applies to
    #: (None = every analyzed file)
    dirs: Optional[tuple] = None

    def applies(self, ctx: "FileContext") -> bool:
        if self.dirs is None:
            return True
        return any(ctx.rel.startswith(d) for d in self.dirs)

    def begin_file(self, ctx: "FileContext") -> None:
        pass

    def visit(self, node: ast.AST,
              ctx: "FileContext") -> Iterable[Violation]:
        return ()

    def end_file(self, ctx: "FileContext") -> Iterable[Violation]:
        return ()

    def finalize(self, project: "Project") -> Iterable[Violation]:
        return ()

    # -- helpers shared by rules ------------------------------------

    def violation(self, ctx: "FileContext", node_or_line,
                  message: str, hint: Optional[str] = None) -> Violation:
        line = getattr(node_or_line, "lineno", node_or_line)
        return Violation(
            file=ctx.rel, line=int(line), rule=self.rule_id,
            message=message,
            hint=self.hint if hint is None else hint,
            context=ctx.line_text(int(line)))


# ---------------------------------------------------------------------------
# suppression / annotation grammar
# ---------------------------------------------------------------------------

#: comment grammar: ``graft: disable=GL001[,GL004] -- reason`` (same
#: line) and ``graft: disable-file=GL007 -- reason`` (whole file),
#: each introduced by a hash
_SUPPRESS_RE = re.compile(
    r"#\s*graft:\s*(disable|disable-file)\s*=\s*"
    r"(?P<ids>[A-Za-z0-9_,\s]+?)"
    r"(?:\s*--\s*(?P<reason>.*))?$")

#: ``# graft: donation-ok -- reason`` / ``# graft: inert-knob -- reason``
#: — positive annotations rules consult (GL002/GL003); the reason is
#: mandatory like the disable grammar's.
_ANNOTATION_RE = re.compile(
    r"#\s*graft:\s*(?P<tag>donation-ok|inert-knob)\s*"
    r"(?:--\s*(?P<reason>.*))?$")


@dataclass
class _Suppressions:
    by_line: dict = field(default_factory=dict)      # line -> set(rule ids)
    file_wide: set = field(default_factory=set)      # rule ids
    annotations: dict = field(default_factory=dict)  # line -> set(tags)
    #: (line, message) pairs for malformed grammar → GL000
    malformed: list = field(default_factory=list)
    #: how many violations each suppression absorbed (the audit trail
    #: tools/lint_report.py prints) — keys (line, rule) / ("file", rule)
    used: dict = field(default_factory=dict)
    #: every well-formed disable directive as written:
    #: {line, scope: "line"|"file", rules: [..], reason}
    directives: list = field(default_factory=list)


def _comments(source: str) -> dict[int, str]:
    """{line: comment text} from real COMMENT tokens only — a
    ``# graft:`` inside a string literal or docstring is prose about
    the grammar, not a directive."""
    import io
    import tokenize
    out: dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                out[tok.start[0]] = tok.string
    except (tokenize.TokenError, IndentationError,
            SyntaxError):   # pragma: no cover - half-written file
        pass
    return out


def _parse_suppressions(source: str, known: set[str]) -> _Suppressions:
    sup = _Suppressions()
    for i, text in sorted(_comments(source).items()):
        if "graft:" not in text:
            continue
        m = _SUPPRESS_RE.search(text)
        if m:
            reason = (m.group("reason") or "").strip()
            ids = {s.strip() for s in m.group("ids").split(",") if s.strip()}
            if not reason:
                sup.malformed.append(
                    (i, "suppression without a reason — the grammar is "
                        "'# graft: disable=<rule-id> -- <reason>' and the "
                        "reason is mandatory"))
                continue
            unknown = sorted(ids - known)
            if unknown:
                sup.malformed.append(
                    (i, f"suppression names unknown rule id(s) "
                        f"{', '.join(unknown)}"))
                ids &= known
            if META_RULE in ids:
                sup.malformed.append(
                    (i, f"{META_RULE} (the suppression-grammar meta rule) "
                        f"cannot be suppressed"))
                ids.discard(META_RULE)
            if ids:
                sup.directives.append({
                    "line": i,
                    "scope": ("file" if m.group(1) == "disable-file"
                              else "line"),
                    "rules": sorted(ids), "reason": reason})
            if m.group(1) == "disable-file":
                sup.file_wide |= ids
            else:
                sup.by_line.setdefault(i, set()).update(ids)
            continue
        m = _ANNOTATION_RE.search(text)
        if m:
            reason = (m.group("reason") or "").strip()
            if not reason:
                sup.malformed.append(
                    (i, f"annotation '{m.group('tag')}' without a reason "
                        f"— '# graft: {m.group('tag')} -- <reason>'"))
                continue
            sup.annotations.setdefault(i, set()).add(m.group("tag"))
        elif re.search(r"#\s*graft:", text):
            sup.malformed.append(
                (i, "unrecognized '# graft:' directive (known: "
                    "disable=, disable-file=, donation-ok, inert-knob)"))
    return sup


# ---------------------------------------------------------------------------
# per-file context
# ---------------------------------------------------------------------------

class FileContext:
    """Everything the rules need about one parsed file."""

    def __init__(self, path: str, rel: str, source: str,
                 tree: ast.Module, known_rules: set[str]):
        self.path = path
        self.rel = rel
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self.suppressions = _parse_suppressions(source, known_rules)
        # parent links (one pass; rules use them for enclosure queries)
        for parent in ast.walk(tree):
            for child in ast.iter_child_nodes(parent):
                child._graft_parent = parent  # type: ignore[attr-defined]

    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def suppressed(self, rule_id: str, line: int) -> bool:
        """Directive lookup for a violation at ``line``: file-wide, the
        line itself, or a standalone directive in the contiguous
        comment block directly above — the same placement contract as
        ``annotated()``, so a long line's disable can sit above it."""
        sup = self.suppressions
        if rule_id in sup.file_wide:
            sup.used[("file", rule_id)] = \
                sup.used.get(("file", rule_id), 0) + 1
            return True
        i = line
        while i >= 1:
            if rule_id in sup.by_line.get(i, ()):
                sup.used[(i, rule_id)] = \
                    sup.used.get((i, rule_id), 0) + 1
                return True
            i -= 1
            if not self.line_text(i).startswith("#"):
                break
        return False

    def annotated(self, tag: str, line: int) -> bool:
        """Is annotation ``tag`` present on ``line`` or in the
        contiguous comment block directly above it? (The idiomatic spot
        is a comment above the call; wrapped reasons span lines.)"""
        ann = self.suppressions.annotations
        if tag in ann.get(line, ()):
            return True
        i = line - 1
        while i >= 1 and self.line_text(i).startswith("#"):
            if tag in ann.get(i, ()):
                return True
            i -= 1
        return False

    # -- AST enclosure helpers --------------------------------------

    def parents(self, node: ast.AST) -> Iterator[ast.AST]:
        while True:
            node = getattr(node, "_graft_parent", None)
            if node is None:
                return
            yield node

    def enclosing_function(self, node: ast.AST):
        for p in self.parents(node):
            if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return p
        return None

    def enclosing_class(self, node: ast.AST):
        for p in self.parents(node):
            if isinstance(p, ast.ClassDef):
                return p
        return None


# ---------------------------------------------------------------------------
# project: cross-file state for finalize-phase rules
# ---------------------------------------------------------------------------

class Project:
    """Carried through the run and handed to ``Rule.finalize``."""

    def __init__(self, root: str, files: list[str]):
        self.root = root
        self.files = files
        #: {rel: FileContext} — retained so finalize-phase violations
        #: still honor per-line suppressions in files that have one
        self.contexts: dict[str, FileContext] = {}

    def rel(self, path: str) -> str:
        return os.path.relpath(path, self.root).replace(os.sep, "/")


# ---------------------------------------------------------------------------
# file discovery
# ---------------------------------------------------------------------------

#: basenames / path fragments never analyzed (generated code, caches)
_EXCLUDE_PARTS = ("__pycache__",)
_EXCLUDE_FILES = ("auron_pb2.py",)


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def default_targets(root: Optional[str] = None) -> list[str]:
    """The analyzed tree: the package, the tools, and the top-level
    drivers. tests/ is deliberately excluded — fixtures seed violations
    on purpose; the gate lints the product, not its test fixtures."""
    root = root or repo_root()
    targets = [os.path.join(root, "auron_tpu"),
               os.path.join(root, "tools"),
               os.path.join(root, "bench.py"),
               os.path.join(root, "__graft_entry__.py")]
    return [t for t in targets if os.path.exists(t)]


def iter_python_files(targets: Iterable[str]) -> list[str]:
    out = []
    for target in targets:
        if os.path.isfile(target):
            if target.endswith(".py"):
                out.append(target)
            continue
        for dirpath, dirnames, filenames in os.walk(target):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in _EXCLUDE_PARTS)
            for fn in sorted(filenames):
                if fn.endswith(".py") and fn not in _EXCLUDE_FILES:
                    out.append(os.path.join(dirpath, fn))
    return out


# ---------------------------------------------------------------------------
# the analyzer
# ---------------------------------------------------------------------------

@dataclass
class AnalysisResult:
    violations: list        # post-suppression
    suppressed: int         # count absorbed by disable directives
    files_scanned: int
    parse_errors: list      # (rel, message)
    #: every disable directive as written, with its absorption count:
    #: [{file, line, scope, rules, reason, used}] — the audit surface
    #: (a used=0 directive suppresses nothing and deserves a look)
    suppression_inventory: list = field(default_factory=list)

    def by_rule(self) -> dict[str, int]:
        counts: dict[str, int] = {}
        for v in self.violations:
            counts[v.rule] = counts.get(v.rule, 0) + 1
        return dict(sorted(counts.items()))

    def to_json(self) -> dict:
        return {
            "files_scanned": self.files_scanned,
            "violations": [v.to_json() for v in self.violations],
            "suppressed": self.suppressed,
            "by_rule": self.by_rule(),
            "parse_errors": list(self.parse_errors),
            "suppression_inventory": list(self.suppression_inventory),
        }


def analyze(targets: Optional[Iterable[str]] = None,
            root: Optional[str] = None,
            rule_ids: Optional[Iterable[str]] = None) -> AnalysisResult:
    """Run the checker over ``targets`` (default: the repo tree).

    ``rule_ids`` narrows to a subset (tests exercise rules in
    isolation). The tree parses ONCE per file; every selected rule sees
    the same walk."""
    root = root or repo_root()
    targets = list(targets) if targets is not None \
        else default_targets(root)
    files = iter_python_files(targets)
    classes = all_rules()
    if rule_ids is not None:
        wanted = set(rule_ids)
        classes = {rid: c for rid, c in classes.items() if rid in wanted}
    rules = [cls() for _, cls in sorted(classes.items())]
    known = known_rule_ids()
    project = Project(root, files)

    violations: list[Violation] = []
    suppressed = 0
    parse_errors: list[tuple] = []

    def admit(ctx: FileContext, vs: Iterable[Violation]) -> None:
        nonlocal suppressed
        for v in vs:
            if ctx.suppressed(v.rule, v.line):
                suppressed += 1
            else:
                violations.append(v)

    for path in files:
        rel = project.rel(path)
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
            tree = ast.parse(source, filename=rel)
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            parse_errors.append((rel, f"{type(e).__name__}: {e}"))
            continue
        ctx = FileContext(path, rel, source, tree, known)
        project.contexts[rel] = ctx
        # suppression-grammar meta rule (not itself suppressible)
        for line, msg in ctx.suppressions.malformed:
            violations.append(Violation(
                file=rel, line=line, rule=META_RULE, message=msg,
                hint="grammar: '# graft: disable=<rule-id> -- <reason>' "
                     "(reason mandatory)",
                context=ctx.line_text(line)))
        active = [r for r in rules if r.applies(ctx)]
        for r in active:
            r.begin_file(ctx)
        dispatch: dict[type, list] = {}
        for r in active:
            for t in r.node_types:
                dispatch.setdefault(t, []).append(r)
        for node in ast.walk(tree):
            for r in dispatch.get(type(node), ()):
                admit(ctx, r.visit(node, ctx))
        for r in active:
            admit(ctx, r.end_file(ctx))

    for r in rules:
        # finalize-phase violations honor line suppressions when they
        # land in an analyzed file (dead-knob findings on config.py
        # declarations); findings on non-Python surfaces (CONFIG.md)
        # have no suppression channel — fix the doc instead
        for v in r.finalize(project):
            fctx = project.contexts.get(v.file)
            if fctx is not None and fctx.suppressed(v.rule, v.line):
                suppressed += 1
            else:
                violations.append(v)

    inventory = []
    for rel, ctx in sorted(project.contexts.items()):
        sup = ctx.suppressions
        for d in sup.directives:
            if d["scope"] == "file":
                used = sum(sup.used.get(("file", r), 0)
                           for r in d["rules"])
            else:
                used = sum(sup.used.get((d["line"], r), 0)
                           for r in d["rules"])
            inventory.append({"file": rel, "line": d["line"],
                              "scope": d["scope"], "rules": d["rules"],
                              "reason": d["reason"], "used": used})

    violations.sort(key=lambda v: (v.file, v.line, v.rule))
    return AnalysisResult(violations, suppressed, len(files),
                          parse_errors, inventory)


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------

BASELINE_VERSION = 1


def default_baseline_path(root: Optional[str] = None) -> str:
    return os.path.join(root or repo_root(), "tools",
                        "lint_baseline.json")


def load_baseline(path: str) -> dict:
    """Parse a baseline file; raises ValueError on a wrong schema (the
    gate must fail loudly on a garbage baseline, not pass vacuously)."""
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if not isinstance(data, dict) \
            or data.get("version") != BASELINE_VERSION \
            or not isinstance(data.get("entries"), list):
        raise ValueError(
            f"{path}: not a graftlint baseline "
            f"(want {{version: {BASELINE_VERSION}, entries: [...]}})")
    for e in data["entries"]:
        if not isinstance(e, dict) or "file" not in e or "rule" not in e:
            raise ValueError(f"{path}: malformed baseline entry {e!r}")
    return data


def save_baseline(path: str, violations: Iterable[Violation]) -> dict:
    """Freeze ``violations`` as the new baseline (sorted, counted by
    (file, rule, context) so unrelated line drift never dirties it)."""
    counts: dict[tuple, int] = {}
    for v in violations:
        counts[v.key()] = counts.get(v.key(), 0) + 1
    entries = [
        {"file": f, "rule": r, "context": c, "count": n}
        for (f, r, c), n in sorted(counts.items())]
    data = {"version": BASELINE_VERSION,
            "tool": "auron_tpu.analysis",
            "entries": entries}
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return data


def apply_baseline(violations: list, baseline: dict):
    """Split ``violations`` into (new, grandfathered) against the
    baseline, and report stale entries — frozen budget that matched
    nothing this run. A key frozen at count N whose sites were PARTLY
    fixed is stale too (``unmatched`` = leftover budget): leftover
    budget would silently grandfather future identical violations, so
    the report prompts pruning it with --update-baseline.

    Matching is by (file, rule, context) with per-key counts: a key
    frozen at count N absorbs at most N current violations, so ADDING
    an identical violation on a new line in the same file still fails
    the gate."""
    budget: dict[tuple, int] = {}
    for e in baseline.get("entries", ()):
        key = (e["file"], e["rule"], e.get("context", ""))
        budget[key] = budget.get(key, 0) + int(e.get("count", 1))
    new, grandfathered = [], []
    for v in violations:
        k = v.key()
        if budget.get(k, 0) > 0:
            budget[k] -= 1
            grandfathered.append(v)
        else:
            new.append(v)
    stale = [
        {"file": f, "rule": r, "context": c, "unmatched": n}
        for (f, r, c), n in sorted(budget.items()) if n > 0]
    return new, grandfathered, stale


def run(targets: Optional[Iterable[str]] = None,
        baseline_path: Optional[str] = None,
        root: Optional[str] = None) -> dict:
    """One-call gate for tests/tools: analyze, apply the baseline when
    given, and return the full machine-readable report."""
    result = analyze(targets, root=root)
    report = result.to_json()
    if baseline_path:
        baseline = load_baseline(baseline_path)
        new, old, stale = apply_baseline(result.violations, baseline)
        report["violations"] = [v.to_json() for v in new]
        report["new_violations"] = len(new)
        report["grandfathered"] = len(old)
        report["stale_baseline_entries"] = stale
    else:
        report["new_violations"] = len(result.violations)
        report["grandfathered"] = 0
        report["stale_baseline_entries"] = []
    report["ok"] = (report["new_violations"] == 0
                    and not report["parse_errors"])
    return report
