"""Performance forensics: host/device time attribution.

The [speed] ROADMAP item is a *measurement* problem before it is an
optimization problem: q01 CPU throughput decayed 276k → 108k rows/s
across three bench rounds with nothing pointing at WHERE the time went.
``elapsed_compute`` (ops/base.timer) honestly measures each operator's
wall — but Flare (PAPERS.md, 1703.08219) attributes exactly this class
of loss to host-side glue *around* the engine, and a single wall number
cannot separate the XLA execution from the python that feeds it.

This module splits every operator's wall into:

- ``elapsed_device`` — time spent waiting on the accelerator. The
  central program registry (runtime/programs.py) wraps every jitted
  program it hands out; each invocation times the async dispatch
  (call → return) and then ``block_until_ready`` on the outputs
  (return → results materialized). Kernels that bypass the registry
  (the dense grouped-agg module jits) still get a split through the
  ``timer.track`` fallback: the tracked-value registration marks the
  dispatch/device boundary and the timer's exit sync bounds the wait.
- ``elapsed_host_*`` — named host buckets for the remainder:
  ``dispatch`` (python glue until the async call returns: arg prep,
  cache lookups, jax dispatch), ``convert`` (arrow↔device transfers:
  scan decode waits, the executor's to_arrow materialization),
  ``serde`` (shuffle/spill frame pack/unpack + host slicing),
  ``iter`` (executor drive-loop bookkeeping between batches), and
  ``other`` (the unclassified residue, so per-timer attribution sums
  to the measured wall by construction).

Recording contract (same shape as obs/trace.py):

- disabled path: one cached config-epoch compare per timer / per
  program call — no frame allocation, no clock reads beyond what
  ``elapsed_compute`` already pays;
- enabled recording is thread-local (a frame STACK per thread, pushed
  by ops/base.timer) — kernel calls credit the innermost open frame,
  so nested/inclusive timers keep today's inclusive semantics and the
  residue lands in the inner operator's ``other``.

Beyond the per-op counters, each wrapped call feeds two process
histograms (``auron_dispatch_overhead_seconds`` /
``auron_device_call_seconds`` — the per-batch dispatch-overhead
p50/p95/p99 of the registry scrape) and, when the ``program`` trace
category records, a ``program.call`` span carrying the split so
tools/trace_report.py can print host/device columns. ``export_task``
appends one JSONL record per operator instance into ``auron.trace.dir``
(``profile_<trace>.jsonl``) — the input ``tools/hotspot_report.py``
ranks into its category×operator table.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Optional

#: host-bucket vocabulary (counter names are "elapsed_host_" + bucket)
HOST_BUCKETS = ("dispatch", "convert", "serde", "iter", "other")

#: finer-than-default histogram buckets (seconds): python dispatch glue
#: and single-batch device calls live in the 10µs–100ms range the
#: registry's 1ms-floor latency buckets cannot resolve
CALL_BUCKETS = (1e-5, 2.5e-5, 5e-5, 1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3,
                5e-3, 0.01, 0.025, 0.05, 0.1, 0.25, 1.0, 5.0)

#: (config epoch, enabled) verdict cache — the disabled hot path is one
#: int compare (the trace/faults pattern)
_CACHED: tuple[int, Optional[bool]] = (-1, None)

_TLS = threading.local()


def enabled() -> bool:
    global _CACHED
    from auron_tpu import config as cfg
    epoch, val = _CACHED
    if epoch == cfg.config_epoch() and val is not None:
        return val
    epoch = cfg.config_epoch()
    conf = cfg.get_config()
    # serial mode's attribution NEEDS the per-call sync point
    # (block_until_ready is what separates device wait from host glue),
    # so it must never override auron.metrics.device_sync=False — the
    # legacy maximum-throughput knob that trades metrics honesty for
    # async-dispatch overlap. Pipelined mode (auron.pipeline.enabled)
    # times asynchronously instead — dispatch per call, device at the
    # moved sync points (device_fence/timed_get) — so it keeps the
    # profiler on WITHOUT serializing anything: there is no per-call
    # block left to defeat the overlap.
    val = bool(conf.get(cfg.PROFILE_ENABLED)
               and (conf.get(cfg.METRICS_DEVICE_SYNC)
                    or conf.get(cfg.PIPELINE_ENABLED)))
    _CACHED = (epoch, val)
    return val


# ---------------------------------------------------------------------------
# frames: per-timer attribution scopes (thread-local stack)
# ---------------------------------------------------------------------------

class Frame:
    """One open timer scope's accumulators (nanoseconds)."""

    __slots__ = ("device", "dispatch", "convert", "serde", "iter",
                 "calls")

    def __init__(self):
        self.device = 0
        self.dispatch = 0
        self.convert = 0
        self.serde = 0
        self.iter = 0
        self.calls = 0


def _stack() -> list:
    st = getattr(_TLS, "stack", None)
    if st is None:
        st = []
        _TLS.stack = st
    return st


def push_frame() -> Optional[Frame]:
    """Open an attribution frame for a timer scope; None when profiling
    is off (the caller skips the pop entirely)."""
    if not enabled():
        return None
    f = Frame()
    _stack().append(f)
    return f


def pop_frame(frame: Frame, sink, wall_ns: int,
              track_offset_ns: Optional[int] = None,
              bucket: Optional[str] = None) -> None:
    """Close ``frame`` and flush its attribution into ``sink`` (the
    owning ops.base.MetricsSet).

    - wrapped program calls recorded their own dispatch/device split;
    - with NO wrapped call but a ``timer.track`` registration,
      ``track_offset_ns`` marks the dispatch→device boundary (the dense
      grouped-agg path, whose module-level jits bypass the registry);
    - with neither, a ``bucket`` hint classifies the whole wall (host
      sections: scan decode waits → convert, shuffle serde → serde);
    - the residue is ``other`` so the buckets sum to the wall.

    Only nonzero buckets materialize counters (metric snapshots stay
    small; EXPLAIN ANALYZE shows what actually happened, not the whole
    vocabulary)."""
    st = _stack()
    if st and st[-1] is frame:
        st.pop()
    else:   # pragma: no cover - unwound out of order (exception paths)
        try:
            st.remove(frame)
        except ValueError:
            pass
    device = frame.device
    dispatch = frame.dispatch
    convert = frame.convert
    serde = frame.serde
    iter_ns = frame.iter
    if frame.calls == 0:
        if track_offset_ns is not None:
            dispatch += max(track_offset_ns, 0)
            device += max(wall_ns - max(track_offset_ns, 0), 0)
        elif bucket is not None:
            if bucket == "convert":
                convert += wall_ns
            elif bucket == "serde":
                serde += wall_ns
            elif bucket == "iter":
                iter_ns += wall_ns
            else:
                dispatch += wall_ns
    other = wall_ns - (device + dispatch + convert + serde + iter_ns)
    if device:
        sink.counter("elapsed_device").add(device)
    if dispatch:
        sink.counter("elapsed_host_dispatch").add(dispatch)
    if convert:
        sink.counter("elapsed_host_convert").add(convert)
    if serde:
        sink.counter("elapsed_host_serde").add(serde)
    if iter_ns:
        sink.counter("elapsed_host_iter").add(iter_ns)
    if other > 0:
        sink.counter("elapsed_host_other").add(other)


def add_host(bucket: str, ns: int) -> None:
    """Credit ``ns`` host nanoseconds of ``bucket`` to the innermost
    open frame (no-op without one) — for host sections nested inside a
    compute timer."""
    st = getattr(_TLS, "stack", None)
    if not st:
        return
    f = st[-1]
    if bucket == "convert":
        f.convert += ns
    elif bucket == "serde":
        f.serde += ns
    elif bucket == "iter":
        f.iter += ns
    else:
        f.dispatch += ns


# ---------------------------------------------------------------------------
# program-call instrumentation (runtime/programs.py wraps through here)
# ---------------------------------------------------------------------------

def _block(out) -> None:
    """Wait for every array leaf of a program result. Per-leaf
    block_until_ready, tolerant of plugins where it raises (ops/base.
    _device_sync documents the tunneled-accelerator caveat)."""
    import jax
    for leaf in jax.tree_util.tree_leaves(out):
        block = getattr(leaf, "block_until_ready", None)
        if block is None:
            continue
        try:
            block()
        except Exception:   # pragma: no cover - plugin-dependent
            return


def on_call(dispatch_ns: int, device_ns: int, site: str) -> None:
    """One wrapped program invocation's split: credit the innermost
    frame, feed the registry histograms, and drop a ``program.call``
    span when that trace category records."""
    st = getattr(_TLS, "stack", None)
    if st:
        f = st[-1]
        f.dispatch += dispatch_ns
        f.device += device_ns
        f.calls += 1
    from auron_tpu.obs import registry as _registry
    if _registry.enabled():
        r = _registry.get_registry()
        r.histogram("auron_dispatch_overhead_seconds",
                    buckets=CALL_BUCKETS).observe(dispatch_ns * 1e-9)
        r.histogram("auron_device_call_seconds",
                    buckets=CALL_BUCKETS).observe(device_ns * 1e-9)
    from auron_tpu.obs import trace as _trace
    if _trace.category_enabled("program"):
        total = dispatch_ns + device_ns
        # start reconstructed from the durations: no clock reads beyond
        # the two the wrapper already took
        _trace.complete_span(
            "program", "program.call",
            _trace.tracer().now_ns() - total, total, site=site,
            dispatch_ms=round(dispatch_ns / 1e6, 4),
            device_ms=round(device_ns / 1e6, 4))


class ProfiledProgram:
    """Transparent callable proxy timing dispatch + device wait per
    invocation. Attribute access (``cache_info``-style introspection)
    passes through to the wrapped program."""

    __slots__ = ("_fn", "_site")

    def __init__(self, fn, site: str):
        object.__setattr__(self, "_fn", fn)
        object.__setattr__(self, "_site", site)

    def __call__(self, *args, **kwargs):
        import time
        t0 = time.perf_counter_ns()
        out = self._fn(*args, **kwargs)
        t1 = time.perf_counter_ns()
        from auron_tpu.runtime import pipeline
        if pipeline.enabled():
            # pipelined mode: the arrays stay in flight — batch N+1
            # dispatches while N computes. The device wait is measured
            # where execution actually synchronizes (device_fence /
            # timed_get at the semantic boundaries), so attribution
            # still sums to wall; per-call we record dispatch only.
            on_call(t1 - t0, 0, self._site)
        else:
            _block(out)
            on_call(t1 - t0, time.perf_counter_ns() - t1, self._site)
        return out

    def __getattr__(self, name):
        return getattr(object.__getattribute__(self, "_fn"), name)


def wrap_program(value, site: str):
    """The registry's return hook: wrap a callable program in the
    per-invocation timer when profiling is on; everything else (and the
    disabled path) passes through untouched."""
    if not callable(value) or not enabled():
        return value
    return ProfiledProgram(value, site)


# ---------------------------------------------------------------------------
# moved sync points (pipelined mode — runtime/pipeline.py)
# ---------------------------------------------------------------------------

def add_device(ns: int) -> None:
    """Credit ``ns`` device-wait nanoseconds to the innermost open
    frame (no-op without one) — the async twin of ``on_call``'s device
    half for waits measured at a moved sync point."""
    st = getattr(_TLS, "stack", None)
    if st:
        st[-1].device += ns


def device_fence(value, sink=None) -> int:
    """Pipelined mode's materialization point: block until every array
    leaf of ``value`` is ready and attribute the wait as device time —
    to the innermost open frame when one is recording, else to ``sink``
    (a MetricsSet) when given. Returns the wait in nanoseconds.

    Call this ONLY where execution semantically requires materialized
    results (the to_arrow export, sort collect, shuffle materialize):
    the whole point of pipelining is that nothing else waits."""
    import time
    t0 = time.perf_counter_ns()
    _block(value)
    ns = time.perf_counter_ns() - t0
    if not enabled():
        return ns
    st = getattr(_TLS, "stack", None)
    if st:
        st[-1].device += ns
    elif sink is not None:
        sink.counter("elapsed_device").add(ns)
    from auron_tpu.obs import registry as _registry
    if _registry.enabled():
        _registry.get_registry().histogram(
            "auron_device_call_seconds",
            buckets=CALL_BUCKETS).observe(ns * 1e-9)
    return ns


def timed_get(values):
    """``jax.device_get`` with the wait credited to the innermost open
    frame's device bucket — for the per-batch control-scalar readbacks
    (agg group counts, hashtable overflow flags, fused limit budgets)
    that ARE real sync points: under pipelined execution they carry the
    device wait the per-call block used to absorb, and attributing them
    as device keeps the host buckets honest."""
    import time

    import jax
    st = getattr(_TLS, "stack", None)
    if st is None or not st:
        return jax.device_get(values)
    t0 = time.perf_counter_ns()
    out = jax.device_get(values)
    st[-1].device += time.perf_counter_ns() - t0
    return out


# ---------------------------------------------------------------------------
# per-task export + aggregate views
# ---------------------------------------------------------------------------

def _lifecycle_query_id() -> str:
    try:
        from auron_tpu.runtime import lifecycle
        return lifecycle.current_query_id()
    except Exception:   # pragma: no cover - best-effort attribution
        return ""


def export_task(ctx, plan) -> None:
    """Append one JSONL record per operator instance of a finished task
    into ``auron.trace.dir`` (``profile_<trace>.jsonl``) — the
    tools/hotspot_report.py input. Best-effort like every observability
    sink; no-op unless profiling is on and a trace dir is configured."""
    if not enabled():
        return
    from auron_tpu import config as cfg
    trace_dir = cfg.get_config().get(cfg.TRACE_DIR)
    if not trace_dir:
        return
    from auron_tpu.obs import trace as _trace
    trace_id = _trace.tracer().current_trace
    path = os.path.join(trace_dir, f"profile_{trace_id:08d}.jsonl")
    try:
        os.makedirs(trace_dir, exist_ok=True)
        lines = []
        for (oid, suffix), (op, ms) in list(ctx.op_metrics.items()):
            snap = ms.snapshot()
            if not snap:
                continue
            lines.append(json.dumps({
                "task": ctx.task_id, "stage": ctx.stage_id,
                "partition": ctx.partition_id,
                # concurrent queries with tracing off share trace id 0
                # (one jsonl file): the query id keeps their records
                # attributable (cross-query safety audit)
                "query": _lifecycle_query_id(),
                "op": op.name + suffix, "repr": repr(op),
                "metrics": snap}))
        if lines:
            with open(path, "a") as f:
                f.write("\n".join(lines) + "\n")
    except Exception:   # pragma: no cover - observability is best-effort
        import logging
        logging.getLogger(__name__).exception(
            "profile export to %r failed", trace_dir)


def summarize_tree(node) -> dict:
    """Host/device rollup over a metric tree (obs/metric_tree.MetricNode)
    — the machine-readable profile section bench.py records and the
    EXPLAIN ANALYZE footer's source. Millisecond floats."""
    device = 0
    buckets = {b: 0 for b in HOST_BUCKETS}
    compute = 0
    for n in node.walk():
        device += n.metrics.get("elapsed_device", 0)
        compute += n.metrics.get("elapsed_compute", 0)
        for b in HOST_BUCKETS:
            buckets[b] += n.metrics.get("elapsed_host_" + b, 0)
    host = {b: round(v / 1e6, 3) for b, v in buckets.items() if v}
    return {
        "device_ms": round(device / 1e6, 3),
        "host_ms": round(sum(buckets.values()) / 1e6, 3),
        "host_buckets_ms": host,
        "elapsed_compute_ms": round(compute / 1e6, 3),
    }
