"""Post-mortem failure bundles: one self-contained diagnostic directory
per classified query failure.

When a production query is shed, misses its deadline, stalls out, loses
its mesh, or trips over a corrupt journal, the operator's question is
always the same: *what was the process doing in the seconds before?*
Every plane that can answer already exists — the flight recorder's ring,
the scheduler/memmgr/mesh stats, the probe and stall reports, the
metric tree — but each lives somewhere else and most are gone once the
process moves on. This module freezes them together at the unwind:

``bundle_<query_id>/``
    ``bundle.json``        manifest: schema, query id, outcome, error
    ``flight.jsonl``       flight-recorder dump (the failing query's
                           events with its neighbors interleaved — the
                           neighbor causing the pressure is evidence)
    ``explain.txt``        the query's plan tree WITH the metrics its
                           completed tasks mirrored (obs/metric_tree)
    ``metrics.prom``       registry exposition at failure time
    ``scheduler.json``     admission stats + live query table
    ``memmgr.json``        per-manager status (per-query ledgers)
    ``mesh.json``          mesh plane fault ledger (when armed)
    ``journal.json``       the query's journal state (when journaled)
    ``config.json``        resolved config snapshot + trace_salt
    ``probe_report.json``  last backend probe-ladder report
    ``stall_report_*.json``copied from auron.trace.dir (when present)

Triggering: ``maybe_write`` is called from the executor/serving unwind
(Session's admission scope, the serving handler) with the terminal
exception; only CLASSIFIED failures bundle — ``classify`` maps
MemoryExhausted, DeadlineExceeded, TaskStalled, MeshUnavailable and
JournalCorrupt/JournalInvalidated to an outcome tag and everything else
(plain cancels, admission sheds, unclassified crashes — tracebacks
already serve those) to None.

Retention: ``auron.bundle.max_bundles`` with oldest-first eviction, so
a crash loop can never fill the disk. Every artifact write is
best-effort and individually guarded — a failing diagnostic must never
shadow the query's own classified error.
"""

from __future__ import annotations

import glob
import json
import logging
import os
import shutil
import time
from typing import Optional

logger = logging.getLogger("auron_tpu.ops")

SCHEMA_VERSION = 1

#: outcome tag per bundle-eligible classified-failure class (order
#: matters: DeadlineExceeded IS-A QueryCancelled and MeshUnavailable
#: IS-A DeviceExecutionError — most-derived first)
_BUNDLE_CLASSES = (
    ("MemoryExhausted", "memory_exhausted"),
    ("DeadlineExceeded", "deadline"),
    ("TaskStalled", "stalled"),
    ("MeshUnavailable", "mesh_unavailable"),
    ("JournalCorrupt", "journal_corrupt"),
    ("JournalInvalidated", "journal_invalidated"),
)


def classify(exc) -> Optional[str]:
    """Outcome tag when ``exc`` is a bundle-eligible classified failure,
    else None (no bundle: plain cancels are the caller's verdict,
    admission sheds never held resources, unclassified crashes carry a
    traceback)."""
    if exc is None:
        return None
    from auron_tpu import errors
    for cls_name, tag in _BUNDLE_CLASSES:
        cls = getattr(errors, cls_name, None)
        if cls is not None and isinstance(exc, cls):
            return tag
    return None


def armed(config=None) -> bool:
    from auron_tpu import config as cfg
    conf = config if config is not None else cfg.get_config()
    return bool(conf.get(cfg.BUNDLE_ENABLED))


def bundle_dir(config=None) -> str:
    from auron_tpu import config as cfg
    conf = config if config is not None else cfg.get_config()
    d = conf.get(cfg.BUNDLE_DIR)
    if not d:
        import tempfile
        d = os.path.join(tempfile.gettempdir(), "auron-bundles")
    return d


def list_bundles(dir_path: str) -> list[str]:
    """Bundle directories under ``dir_path``, oldest first."""
    entries = [p for p in glob.glob(os.path.join(dir_path, "bundle_*"))
               if os.path.isdir(p)]
    entries.sort(key=lambda p: (os.path.getmtime(p), p))
    return entries


def maybe_write(exc, token=None, config=None, scheduler=None,
                mem_manager=None) -> Optional[str]:
    """Write one post-mortem bundle for a classified failure; returns
    the bundle path, or None when disarmed / not bundle-eligible.
    NEVER raises — the caller is an unwind path re-raising the query's
    own classified error."""
    try:
        if not armed(config):
            return None
        outcome = classify(exc)
        if outcome is None:
            return None
        return _write(exc, outcome, token=token, config=config,
                      scheduler=scheduler, mem_manager=mem_manager)
    except Exception:   # noqa: BLE001 — diagnostics must not shadow
        logger.exception("post-mortem bundle write failed")
        return None


def _write(exc, outcome: str, token=None, config=None, scheduler=None,
           mem_manager=None) -> str:
    root = bundle_dir(config)
    os.makedirs(root, exist_ok=True)
    qid = getattr(token, "query_id", "") or "unknown"
    name = f"bundle_{qid}"
    path = os.path.join(root, name)
    n = 2
    while os.path.exists(path):   # recycled id (cross-process dir)
        path = os.path.join(root, f"{name}_{n}")
        n += 1
    # stage on a dot-prefixed temp dir + rename: the eviction scan and
    # the chaos audit must never observe a half-written bundle
    tmp = os.path.join(root, f".{os.path.basename(path)}.part")
    shutil.rmtree(tmp, ignore_errors=True)
    os.makedirs(tmp)

    def art(filename: str, producer) -> None:
        """One guarded artifact: a failing collector costs its file,
        never the bundle."""
        try:
            body = producer()
            if body is None:
                return
            with open(os.path.join(tmp, filename), "w") as f:
                f.write(body)
        except Exception:   # noqa: BLE001
            logger.exception("bundle artifact %s failed", filename)

    art("bundle.json", lambda: json.dumps({
        "schema_version": SCHEMA_VERSION,
        "query_id": qid,
        "outcome": outcome,
        "error_type": type(exc).__name__,
        "error": str(exc)[:2000],
        "reason": getattr(token, "reason", None),
        "site": getattr(exc, "site", None),
        "tasks_done": getattr(token, "tasks_done", 0),
        "tasks_total": getattr(token, "tasks_total", 0),
        "created_wall": time.time(),
        "pid": os.getpid(),
    }, indent=2, default=str))
    art("flight.jsonl", _flight_dump)
    art("ledger.json", lambda: _ledger_json(token))
    art("explain.txt", lambda: _explain_text(token))
    art("metrics.prom", _metrics_text)
    art("scheduler.json", lambda: _scheduler_json(scheduler))
    art("memmgr.json", lambda: _memmgr_json(mem_manager))
    art("mesh.json", _mesh_json)
    art("journal.json", lambda: _journal_json(token))
    art("config.json", lambda: _config_json(config))
    art("probe_report.json", _probe_json)
    _copy_stall_reports(tmp, config)
    os.replace(tmp, path)
    _evict(root, config)
    try:
        from auron_tpu.obs import registry
        if registry.enabled():
            registry.get_registry().counter(
                "auron_bundles_written_total", outcome=outcome).inc()
    except Exception:   # pragma: no cover - telemetry best-effort
        pass
    logger.warning("post-mortem bundle written: %s (%s: %s)", path,
                   type(exc).__name__, str(exc)[:200])
    return path


def write_fleet_death(dead_name: str, dead_health, dead_queries,
                      router_stats, timeline: str,
                      config=None) -> Optional[str]:
    """Fleet failure bundle: one directory per liveness-confirmed
    replica death, written by the ROUTER (the only process that saw
    the whole story):

    ``bundle_fleet_death_<replica>/``
        ``bundle.json``            manifest (kind=fleet_death)
        ``routing_timeline.jsonl`` the router's flight ring — route /
                                   forward / death / failover events
        ``replica_health.json``    the dead replica's LAST scraped
                                   /healthz body (its final state)
        ``replica_queries.json``   its last /queries table
        ``router_stats.json``      router counters + fleet snapshot

    The survivor's recovery record (``failover.json``) is appended via
    :func:`add_artifact` once failover lands — recovery happens AFTER
    the death, so the bundle is sealed first. NEVER raises; returns
    the bundle path or None (disarmed / write failure)."""
    try:
        if not armed(config):
            return None
        root = bundle_dir(config)
        os.makedirs(root, exist_ok=True)
        safe = str(dead_name).replace(":", "_").replace("/", "_")
        name = f"bundle_fleet_death_{safe}"
        path = os.path.join(root, name)
        n = 2
        while os.path.exists(path):   # the same replica can die twice
            path = os.path.join(root, f"{name}_{n}")
            n += 1
        tmp = os.path.join(root, f".{os.path.basename(path)}.part")
        shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp)

        def art(filename: str, producer) -> None:
            try:
                body = producer()
                if body is None:
                    return
                with open(os.path.join(tmp, filename), "w") as f:
                    f.write(body)
            except Exception:   # noqa: BLE001
                logger.exception("bundle artifact %s failed", filename)

        art("bundle.json", lambda: json.dumps({
            "schema_version": SCHEMA_VERSION,
            "kind": "fleet_death",
            "replica": dead_name,
            "outcome": "replica_death",
            "created_wall": time.time(),
            "pid": os.getpid(),
        }, indent=2, default=str))
        art("routing_timeline.jsonl", lambda: timeline or None)
        art("replica_health.json",
            lambda: (json.dumps(dead_health, indent=2, default=str)
                     if dead_health else None))
        art("replica_queries.json",
            lambda: (json.dumps(dead_queries, indent=2, default=str)
                     if dead_queries else None))
        art("router_stats.json",
            lambda: json.dumps(router_stats, indent=2, default=str))
        os.replace(tmp, path)
        _evict(root, config)
        try:
            from auron_tpu.obs import registry
            if registry.enabled():
                registry.get_registry().counter(
                    "auron_bundles_written_total",
                    outcome="replica_death").inc()
        except Exception:   # pragma: no cover - telemetry best-effort
            pass
        logger.warning("fleet death bundle written: %s (replica %s)",
                       path, dead_name)
        return path
    except Exception:   # noqa: BLE001 — diagnostics must not shadow
        logger.exception("fleet death bundle write failed")
        return None


def add_artifact(path: str, filename: str, body: str) -> bool:
    """Append one artifact to an ALREADY-sealed bundle (the router's
    ``failover.json``: the survivor's recovery record lands after the
    death bundle was written). Best-effort, never raises."""
    try:
        if not path or not os.path.isdir(path):
            return False
        with open(os.path.join(path, filename), "w") as f:
            f.write(body)
        return True
    except Exception:   # noqa: BLE001
        logger.exception("bundle add_artifact %s failed", filename)
        return False


# -- artifact producers (each individually guarded by art()) ----------------

def _ledger_json(token) -> Optional[str]:
    """The failing query's cost ledger (serving stashes it on the
    cancel token at finalize — ``outcome=failed`` partial costs are
    exactly what a post-mortem wants)."""
    led = getattr(token, "cost_ledger", None)
    if not isinstance(led, dict):
        return None
    return json.dumps(led, indent=2, default=str)


def _flight_dump() -> str:
    from auron_tpu.obs import flight_recorder
    return flight_recorder.recorder().dump_jsonl()


def _explain_text(token) -> Optional[str]:
    tree = getattr(token, "plan_tree", None)
    if tree is None:
        return None
    from auron_tpu.obs import metric_tree as mt
    return mt.render(tree)


def _metrics_text() -> str:
    from auron_tpu.obs import registry
    return registry.get_registry().render_prometheus()


def _scheduler_json(scheduler) -> str:
    from auron_tpu.runtime import scheduler as sched_mod
    body = {"table": sched_mod.aggregate_query_table()}
    if scheduler is not None:
        body["stats"] = scheduler.stats()
    else:
        body["states"] = sched_mod.aggregate_states()
    return json.dumps(body, indent=2, default=str)


def _memmgr_json(mem_manager) -> Optional[str]:
    if mem_manager is not None:
        statuses = [mem_manager.status()]
    else:
        from auron_tpu.memmgr import manager as _mgr
        statuses = _mgr.aggregate_status()
    return json.dumps(statuses, indent=2, default=str)


def _mesh_json() -> Optional[str]:
    from auron_tpu.parallel import mesh as _mesh
    plane = _mesh.current_plane()
    if plane is None:
        return None
    return json.dumps(plane.stats(), indent=2, default=str)


def _journal_json(token) -> Optional[str]:
    jr = getattr(token, "journal", None)
    if jr is None:
        return None
    body = {}
    for attr in ("stem", "path", "scope", "num_partitions",
                 "query_id"):
        v = getattr(jr, attr, None)
        if v is not None:
            body[attr] = v
    try:
        from auron_tpu.runtime import journal as jrn
        body["stats"] = jrn.last_stats()
    except Exception:   # pragma: no cover - stats optional
        pass
    return json.dumps(body, indent=2, default=str)


def _config_json(config) -> str:
    from auron_tpu import config as cfg
    conf = config if config is not None else cfg.get_config()
    resolved = {}
    for opt in cfg.options():
        try:
            resolved[opt.key] = conf.get(opt.key)
        except Exception:   # pragma: no cover - env parse failure
            resolved[opt.key] = "<unresolvable>"
    return json.dumps({"resolved": resolved,
                       "trace_salt": list(cfg.trace_salt())},
                      indent=2, default=str)


def _probe_json() -> Optional[str]:
    from auron_tpu.runtime import watchdog
    report = watchdog.last_probe_report()
    if report is None:
        return None
    return report.to_json()


def _copy_stall_reports(tmp: str, config, limit: int = 8) -> None:
    """Copy recent stall reports from auron.trace.dir (the watchdog
    writes them there) — best-effort, bounded."""
    try:
        from auron_tpu import config as cfg
        conf = config if config is not None else cfg.get_config()
        tdir = conf.get(cfg.TRACE_DIR)
        if not tdir or not os.path.isdir(tdir):
            return
        reports = sorted(
            glob.glob(os.path.join(tdir, "stall_report_*.json")),
            key=os.path.getmtime)[-limit:]
        for p in reports:
            shutil.copy(p, os.path.join(tmp, os.path.basename(p)))
    except Exception:   # noqa: BLE001
        logger.exception("bundle stall-report copy failed")


def _evict(root: str, config) -> None:
    """Oldest-first retention: keep at most auron.bundle.max_bundles."""
    from auron_tpu import config as cfg
    conf = config if config is not None else cfg.get_config()
    keep = int(conf.get(cfg.BUNDLE_MAX_BUNDLES))
    if keep <= 0:
        return
    entries = list_bundles(root)
    for victim in entries[:-keep] if len(entries) > keep else []:
        shutil.rmtree(victim, ignore_errors=True)


def read_manifest(path: str) -> dict:
    """Load one bundle's manifest (tools/ops_report.py, chaos audit)."""
    with open(os.path.join(path, "bundle.json")) as f:
        return json.load(f)
