"""Always-on flight recorder: the last N structured events, every
thread, every category — armed even when tracing is off.

The trace plane (obs/trace.py) is opt-in and export-oriented: spans are
recorded only while ``auron.trace.enabled`` is on, and a query's
timeline leaves the process as a per-query file. Production failures do
not wait for tracing to be enabled — when a query is shed, stalled, or
crash-resumed, the seconds BEFORE the failure are exactly the data
nobody recorded. This module is the black box that closes that gap:

- **Tee at emit time.** The trace plane's emit functions
  (``trace.event`` / ``trace.complete_span`` / span exit) call
  :func:`tee` before their own enabled check, so every structured event
  the runtime ever emits — fault injections, retries, admission
  decisions, pressure rungs, demotions, stall verdicts — lands in the
  ring regardless of the tracing knobs. With tracing off the ring holds
  the control-plane events (spans are never timed on the disabled
  path); with tracing on it additionally holds the completed spans.

- **Bounded per-thread rings.** Each thread appends to its own
  ``collections.deque(maxlen=auron.flight.ring_events)`` — lock-free
  recording (the tracer's buffer pattern), O(1) memory, oldest events
  evicted first. The merged, time-ordered snapshot happens only at dump
  time (``/flight``, a post-mortem bundle).

- **Query attribution.** Every record carries the lifecycle plane's
  current query id, so a bundle can present the failing query's
  timeline with its neighbors' events interleaved — which is what a
  shed/stall post-mortem actually needs (the neighbor that caused the
  pressure is on the same timeline).

Overhead contract: the disarmed path costs one cached config-epoch
compare (the fault-plane pattern); the armed path is one thread-local
read plus a deque append, measured <2% by the bench three-arm A/B's
``norec`` arm (PERF.md "Ops plane"). ``auron.flight.{enabled,
ring_events}`` are deliberately NOT trace-semantic: flipping the
recorder never retraces a kernel.
"""

from __future__ import annotations

import json
import os
import threading
import time
import weakref
from collections import deque
from typing import NamedTuple, Optional

#: which fleet role this process plays ("client" default; the serving
#: CLI sets "replica", the router thread adopts "router") — stamped on
#: flight snapshots and trace exports so records merged across process
#: boundaries stay attributable. Lives here (not obs/trace.py) because
#: the trace plane imports this module at its top, never the reverse.
_ROLE = "client"


def set_role(role: str) -> None:
    global _ROLE
    _ROLE = str(role)


def get_role() -> str:
    return _ROLE


class _Settings(NamedTuple):
    enabled: bool
    ring: int


#: (config epoch, settings) — the disarmed check must cost one int
#: compare (same verdict-cache shape as obs/trace._CACHED)
_CACHED: tuple[int, Optional[_Settings]] = (-1, None)


def _settings() -> _Settings:
    global _CACHED
    from auron_tpu import config as cfg
    epoch, st = _CACHED
    if epoch == cfg.config_epoch() and st is not None:
        return st
    epoch = cfg.config_epoch()
    conf = cfg.get_config()
    st = _Settings(
        enabled=conf.get(cfg.FLIGHT_ENABLED),
        ring=max(int(conf.get(cfg.FLIGHT_RING_EVENTS)), 16),
    )
    _CACHED = (epoch, st)
    return st


def armed() -> bool:
    return _settings().enabled


class FlightRecorder:
    """Process flight recorder: per-thread bounded rings, merged on
    demand. Records are tuples ``(ts_ns, cat, name, query_id, dur_ns,
    tid, attrs)`` — the span vocabulary, flattened.

    Rings are held as ``(weakref-to-owning-thread, deque)`` pairs: a
    thread-per-connection serving process mints one ring per handler
    thread, so dead threads' rings are PRUNED when a new ring registers
    — their events fold into one shared bounded ``graveyard`` ring
    (a task thread that died moments before a failure holds exactly
    the evidence a post-mortem needs, so pruning preserves the recent
    tail instead of dropping it), and recorder memory stays bounded by
    the LIVE thread count plus one ring."""

    def __init__(self):
        self._lock = threading.Lock()
        self._rings: list[tuple] = []   # (thread weakref, deque)
        #: merged tail of dead threads' rings (bounded like any ring)
        self._graveyard: deque = deque(maxlen=4096)
        self._tls = threading.local()
        #: wall-clock epoch of the monotonic ts origin (dump metadata —
        #: lets a post-mortem reader print absolute timestamps)
        self.epoch_wall = time.time()
        self._t0 = time.perf_counter_ns()

    # -- recording (per-thread, lock-free) ----------------------------------

    def _prune_locked(self, maxlen: int) -> None:
        """Fold dead threads' rings into the graveyard (caller holds
        the lock). Runs only when a NEW ring registers, so the cost is
        bounded by thread creation, not by recording."""
        if self._graveyard.maxlen != maxlen:
            self._graveyard = deque(self._graveyard, maxlen=maxlen)
        alive = []
        for tref, ring in self._rings:
            t = tref()
            if t is not None and t.is_alive():
                alive.append((tref, ring))
            else:
                self._graveyard.extend(ring)
        self._rings = alive

    def _ring(self, maxlen: int) -> deque:
        ring = getattr(self._tls, "ring", None)
        if ring is None or ring.maxlen != maxlen:
            fresh: deque = deque(maxlen=maxlen)
            me = weakref.ref(threading.current_thread())
            with self._lock:
                if ring is not None:
                    # ring_events changed mid-flight: replace this
                    # thread's ring (keeping what fits) so the old one
                    # is neither leaked nor double-dumped
                    self._rings = [(r, d) for r, d in self._rings
                                   if d is not ring]
                    fresh.extend(list(ring)[-maxlen:])
                self._prune_locked(maxlen)
                self._rings.append((me, fresh))
            self._tls.ring = fresh
            ring = fresh
        return ring

    def now_ns(self) -> int:
        return time.perf_counter_ns() - self._t0

    def record(self, cat: str, name: str, attrs: dict, query_id: str,
               dur_ns: int = 0, ts_ns: Optional[int] = None) -> None:
        self._ring(_settings().ring).append(
            (ts_ns if ts_ns is not None else self.now_ns(), cat, name,
             query_id, dur_ns, threading.get_ident(), attrs))

    # -- merge / dump --------------------------------------------------------

    def snapshot(self, query_id: Optional[str] = None,
                 last: Optional[int] = None) -> list[dict]:
        """Merged, time-ordered view of every thread's ring. ``query_id``
        keeps only that query's records; ``last`` keeps the newest N
        after merging. Rings are appended lock-free by their owning
        threads, so the copy retries around a concurrent mutation."""
        with self._lock:
            rings = [d for _t, d in self._rings] + [self._graveyard]
        raw: list[tuple] = []
        for ring in rings:
            for _ in range(8):
                try:
                    raw.extend(list(ring))
                    break
                except RuntimeError:   # mutated during iteration: retry
                    continue
        if query_id is not None:
            raw = [r for r in raw if r[3] == query_id]
        raw.sort(key=lambda r: r[0])
        if last is not None and last > 0:
            raw = raw[-last:]
        wall0 = self.epoch_wall
        role, pid = get_role(), os.getpid()
        return [{"ts_us": r[0] / 1000.0,
                 "wall": round(wall0 + r[0] * 1e-9, 6),
                 "role": role, "pid": pid,
                 "cat": r[1], "name": r[2], "query": r[3],
                 "dur_us": r[4] / 1000.0, "tid": r[5],
                 "attrs": r[6]} for r in raw]

    def dump_jsonl(self, query_id: Optional[str] = None,
                   last: Optional[int] = None) -> str:
        """The ring as JSONL text (one event per line, timeline order)
        — the ``/flight`` endpoint's and the bundle's wire format."""
        out = []
        for rec in self.snapshot(query_id=query_id, last=last):
            try:
                out.append(json.dumps(rec, default=str))
            except (TypeError, ValueError):   # pragma: no cover
                out.append(json.dumps({**rec, "attrs": str(rec["attrs"])}))
        return "\n".join(out) + ("\n" if out else "")

    def reset(self) -> None:
        """Drop every buffered event (tests, chaos-run isolation)."""
        with self._lock:
            for _t, ring in self._rings:
                ring.clear()
            self._graveyard.clear()


_RECORDER = FlightRecorder()


def recorder() -> FlightRecorder:
    return _RECORDER


def reset() -> None:
    _RECORDER.reset()


#: memoized lifecycle accessor — tee runs on hot emit paths (per
#: program-cache hit), so the import lookup happens once per process
_CURRENT_QID = None


def tee(cat: str, name: str, attrs: dict, dur_ns: int = 0,
        ts_ns: Optional[int] = None) -> None:
    """The trace plane's emit-time tee (called BEFORE the tracing
    enabled check): record one structured event with the current
    query's id attached. Disarmed cost: one cached epoch compare."""
    if not _settings().enabled:
        return
    global _CURRENT_QID
    if _CURRENT_QID is None:
        try:
            from auron_tpu.runtime.lifecycle import current_query_id
            _CURRENT_QID = current_query_id
        except Exception:   # pragma: no cover - import cycle guard
            _CURRENT_QID = lambda: ""   # noqa: E731
    _RECORDER.record(cat, name, attrs, _CURRENT_QID(), dur_ns=dur_ns,
                     ts_ns=ts_ns)


def read_jsonl(path: str) -> list[dict]:
    """Load a flight dump back into records (tools/ops_report.py, the
    chaos bundle audit)."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out
