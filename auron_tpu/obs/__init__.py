"""Observability plane: span tracing, mirrored metric tree, process
metrics registry.

The reference's observability layer is load-bearing (SURVEY.md L9):
every native operator registers timers/counters in an
ExecutionPlanMetricsSet, task end mirrors them into Spark's SQLMetrics
tree by position (auron/src/metrics.rs, rt.rs:302-308), and pprof HTTP
endpoints expose process profiles. This package is that layer for the
TPU engine, split the same three ways:

- :mod:`auron_tpu.obs.trace` — Dapper-style query→stage→task→operator→
  event span timeline, recorded lock-free per thread and exported as
  Chrome-trace JSON (Perfetto-loadable) or a JSONL event log;
- :mod:`auron_tpu.obs.metric_tree` — the positional metric tree each
  PhysicalOp node mirrors into at finalize (EXPLAIN ANALYZE);
- :mod:`auron_tpu.obs.registry` — process-wide counters/gauges/
  histograms with a Prometheus text exposition (the pprof-endpoint
  analogue for scrapers).
"""
