"""Per-query cost ledger: one compact accounting record per query.

The fleet serves queries across process boundaries (client → router →
replica, possibly failing over), and every capacity decision — admission
weights, replica sizing, cache-vs-recompute — needs the same question
answered per query: *what did it cost?* The metric tree answers it
per-operator inside one process; this module folds the per-partition
``ExecutionRuntime.finalize()`` snapshots into ONE flat record at query
finalize:

- **device vs host split** — ``elapsed_compute`` summed into device
  seconds, the PR 6 host buckets (``elapsed_host_{dispatch,convert,
  serde,iter,other}``) summed per bucket;
- **data movement** — shuffle write/read seconds, live shuffle bytes,
  map-side combine rows in/out, mesh collective bytes, spill
  count/bytes, journal bytes reused by resume;
- **compile plane** — XLA compiles + seconds, program builds vs cache
  hits;
- **robustness** — retry/recovery counters (attempts, transient
  retries, corruption recomputes, watchdog fallbacks, injected faults);
- **serving identity** — rows, batches, partitions, cache hit,
  served_from, outcome, wall seconds.

The record rides the serving DONE frame (``cost_ledger`` key), is
retained in a bounded process ring (``record``/``recent`` — the
``AuronClient.stats()`` and STATS-frame surface), lands in failure
bundles as ``ledger.json``, and the router augments it with fleet
facts (``fleet.hops``/``spillovers``/``failover``) before replaying
DONE to the client. ``auron.ledger.enabled`` gates assembly; overhead
is gated < 2% by the perf-gate obs-fleet arm.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Iterable, Optional

LEDGER_VERSION = 1

#: the PR 6 profiler's host-bucket vocabulary (ops/base per-op timers)
HOST_BUCKETS = ("dispatch", "convert", "serde", "iter", "other")

#: snapshot keys that are nested dicts but NOT per-op metric sets
_NON_OP_KEYS = frozenset({"recovery", "mesh", "profile"})

_RECOVERY_KEYS = ("attempts", "transient_retries",
                  "corruption_recomputes", "watchdog_fallbacks",
                  "faults_injected")


def enabled(config=None) -> bool:
    from auron_tpu import config as cfg
    conf = config if config is not None else cfg.get_config()
    return bool(conf.get(cfg.LEDGER_ENABLED))


def build(snaps: Optional[Iterable[dict]], *, query_id: str = "",
          rows: int = 0, batches: int = 0, partitions: int = 0,
          wall_s: float = 0.0, cache_hit: bool = False,
          served_from: str = "", outcome: str = "ok") -> dict:
    """Fold per-partition ``finalize()`` snapshots into one ledger.

    Tolerant by contract: snapshots are observability output, so a
    missing counter, a partial snapshot from a failed partition, or an
    empty list all produce a valid (zeroed) ledger — assembly must
    never fail a finished query.
    """
    device_ns = 0
    host_ns = dict.fromkeys(HOST_BUCKETS, 0)
    shuffle_write_ns = shuffle_read_ns = 0
    shuffle_bytes = spill_bytes = spill_count = 0
    combine_in = combine_out = 0
    mesh_bytes = journal_reused = 0
    xla_compiles = program_builds = program_hits = 0
    compile_s = 0.0
    retries = dict.fromkeys(_RECOVERY_KEYS, 0)
    for snap in snaps or ():
        if not isinstance(snap, dict):
            continue
        xla_compiles += _i(snap.get("xla_compiles"))
        compile_s += _f(snap.get("xla_compile_seconds"))
        program_builds += _i(snap.get("program_builds"))
        program_hits += _i(snap.get("program_hits"))
        rec = snap.get("recovery")
        if isinstance(rec, dict):
            for k in _RECOVERY_KEYS:
                retries[k] += _i(rec.get(k))
        for op, vals in snap.items():
            if not isinstance(vals, dict) or op in _NON_OP_KEYS:
                continue
            device_ns += _i(vals.get("elapsed_compute"))
            for b in HOST_BUCKETS:
                host_ns[b] += _i(vals.get("elapsed_host_" + b))
            shuffle_write_ns += _i(vals.get("shuffle_write_total_time"))
            shuffle_read_ns += _i(vals.get("shuffle_read_total_time"))
            shuffle_bytes += _i(vals.get("shuffle_bytes_live"))
            spill_bytes += _i(vals.get("mem_spill_size"))
            spill_count += _i(vals.get("mem_spill_count"))
            combine_in += _i(vals.get("combine_rows_in"))
            combine_out += _i(vals.get("combine_rows_out"))
            mesh_bytes += _i(vals.get("mesh_bytes_moved"))
            journal_reused += _i(vals.get("journal_bytes_reused"))
    return {
        "version": LEDGER_VERSION,
        "query_id": str(query_id),
        "outcome": str(outcome),
        "wall_s": round(float(wall_s), 6),
        "device_s": round(device_ns * 1e-9, 6),
        "host_s": {b: round(v * 1e-9, 6) for b, v in host_ns.items()},
        "host_total_s": round(sum(host_ns.values()) * 1e-9, 6),
        "shuffle": {
            "write_s": round(shuffle_write_ns * 1e-9, 6),
            "read_s": round(shuffle_read_ns * 1e-9, 6),
            "bytes": shuffle_bytes,
            "combine_rows_in": combine_in,
            "combine_rows_out": combine_out,
        },
        "spill": {"count": spill_count, "bytes": spill_bytes},
        "mesh_bytes": mesh_bytes,
        "journal_bytes_reused": journal_reused,
        "compile": {
            "xla_compiles": xla_compiles,
            "seconds": round(compile_s, 4),
            "program_builds": program_builds,
            "program_hits": program_hits,
        },
        "rows": _i(rows),
        "batches": _i(batches),
        "partitions": _i(partitions),
        "cache_hit": bool(cache_hit),
        "served_from": str(served_from),
        "retries": retries,
        # the router fills these before replaying DONE to the client
        "fleet": {"hops": 0, "spillovers": 0, "failover": "",
                  "replica": ""},
    }


def augment_fleet(ledger, *, hops: Optional[int] = None,
                  spillovers: Optional[int] = None,
                  failover: Optional[str] = None,
                  replica: Optional[str] = None) -> dict:
    """Router-side fleet augmentation of a DONE-frame ledger — tolerant
    of a non-dict / ledger-less payload (propagation off on either
    side), returning the input unchanged in that case."""
    if not isinstance(ledger, dict):
        return ledger
    fleet = ledger.setdefault("fleet", {})
    if not isinstance(fleet, dict):   # foreign payload: do not fight it
        return ledger
    if hops is not None:
        fleet["hops"] = _i(hops)
    if spillovers is not None:
        fleet["spillovers"] = _i(spillovers)
    if failover is not None:
        fleet["failover"] = str(failover)
    if replica is not None:
        fleet["replica"] = str(replica)
    return ledger


def fold(ledgers: Iterable[dict]) -> dict:
    """Aggregate many ledgers into fleet-scale totals (load_report's
    capacity view): sums for seconds/bytes/rows/counters, a count, and
    how many were cache hits / failovers."""
    tot = {"queries": 0, "device_s": 0.0, "host_total_s": 0.0,
           "host_s": dict.fromkeys(HOST_BUCKETS, 0.0),
           "shuffle_bytes": 0, "spill_bytes": 0, "rows": 0,
           "cache_hits": 0, "retries": 0, "failovers": 0,
           "replica_hops": 0}
    for led in ledgers or ():
        if not isinstance(led, dict):
            continue
        tot["queries"] += 1
        tot["device_s"] += _f(led.get("device_s"))
        tot["host_total_s"] += _f(led.get("host_total_s"))
        host = led.get("host_s")
        if isinstance(host, dict):
            for b in HOST_BUCKETS:
                tot["host_s"][b] += _f(host.get(b))
        shuffle = led.get("shuffle")
        if isinstance(shuffle, dict):
            tot["shuffle_bytes"] += _i(shuffle.get("bytes"))
        spill = led.get("spill")
        if isinstance(spill, dict):
            tot["spill_bytes"] += _i(spill.get("bytes"))
        tot["rows"] += _i(led.get("rows"))
        tot["cache_hits"] += 1 if led.get("cache_hit") else 0
        rec = led.get("retries")
        if isinstance(rec, dict):
            tot["retries"] += _i(rec.get("transient_retries"))
        fleet = led.get("fleet")
        if isinstance(fleet, dict):
            tot["replica_hops"] += _i(fleet.get("hops"))
            tot["failovers"] += 1 if fleet.get("failover") else 0
    tot["device_s"] = round(tot["device_s"], 6)
    tot["host_total_s"] = round(tot["host_total_s"], 6)
    tot["host_s"] = {b: round(v, 6) for b, v in tot["host_s"].items()}
    return tot


# ---------------------------------------------------------------------------
# bounded process retention (the stats()/STATS-frame surface)
# ---------------------------------------------------------------------------

_LOCK = threading.Lock()
_RECENT: deque = deque(maxlen=64)


def record(ledger: dict) -> None:
    """Retain one finished ledger in the bounded process ring."""
    if isinstance(ledger, dict):
        with _LOCK:
            _RECENT.append(ledger)


def recent(n: Optional[int] = None) -> list[dict]:
    with _LOCK:
        items = list(_RECENT)
    return items[-n:] if n else items


def reset() -> None:
    """Drop retained ledgers (tests, chaos-run isolation)."""
    with _LOCK:
        _RECENT.clear()


def _i(v) -> int:
    try:
        return int(v or 0)
    except (TypeError, ValueError):
        return 0


def _f(v) -> float:
    try:
        return float(v or 0.0)
    except (TypeError, ValueError):
        return 0.0
