"""Process-wide metrics registry: counters, gauges, fixed-bucket
histograms, Prometheus text exposition.

Per-task metrics (ops/base.MetricsSet) die with their ExecContext; this
registry is the process-lifetime aggregate the scrape surface reads —
the role the reference's pprof/metrics HTTP endpoints play
(auron/src/http/mod.rs:25-108). The executor feeds it one observation
per finished task (gated by ``auron.metrics.registry``): task seconds,
retries, recovery counters, spill volume. ``render_prometheus`` emits
the standard text format and additionally collects live totals from the
runtime singletons (program-cache builds/hits per site, backend
compiles, injected faults, watchdog fallbacks) so a scrape needs no
separate wiring per subsystem.

Histograms are fixed-bucket (Prometheus-shaped: cumulative ``le``
buckets + ``_sum``/``_count``) with p50/p95/p99 estimation by linear
interpolation inside the bucket — exact enough for dashboards, O(1)
memory, no reservoir.

The exposition is trace_salt-aware: ``auron_info`` carries the current
``config.trace_salt()`` so a scraper can correlate metric shifts with
trace-semantic config flips (the same salt that partitions every
program-cache key, runtime/programs.py).
"""

from __future__ import annotations

import re
import threading
from typing import Optional

#: default latency buckets (seconds): 1ms .. 2min, roughly log-spaced
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0)


def _label_key(labels: dict) -> tuple:
    return tuple(sorted(labels.items()))


def escape_label(v) -> str:
    """Prometheus text-format label-value escaping (exposition format
    spec): backslash, double-quote and newline — in THAT order, or the
    escapes themselves get re-escaped."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_labels(labels: tuple) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{escape_label(v)}"' for k, v in labels)
    return "{" + inner + "}"


class Counter:
    """Monotonic counter."""

    def __init__(self, name: str, labels: tuple):
        self.name = name
        self.labels = labels
        self._lock = threading.Lock()
        self.value = 0.0

    def inc(self, v: float = 1.0) -> None:
        with self._lock:
            self.value += v

    def expose(self) -> list[str]:
        return [f"{self.name}{_fmt_labels(self.labels)} {self.value:g}"]


class Gauge:
    """Last-write-wins value."""

    def __init__(self, name: str, labels: tuple):
        self.name = name
        self.labels = labels
        self._lock = threading.Lock()
        self.value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self.value = float(v)

    def inc(self, v: float = 1.0) -> None:
        with self._lock:
            self.value += v

    def expose(self) -> list[str]:
        return [f"{self.name}{_fmt_labels(self.labels)} {self.value:g}"]


class Histogram:
    """Fixed cumulative-bucket histogram with percentile estimation."""

    def __init__(self, name: str, labels: tuple,
                 buckets: Optional[tuple] = None):
        self.name = name
        self.labels = labels
        self.buckets = tuple(sorted(buckets or DEFAULT_BUCKETS))
        self._lock = threading.Lock()
        #: per-bucket NON-cumulative counts; [-1] is the +Inf overflow
        self.counts = [0] * (len(self.buckets) + 1)
        self.sum = 0.0
        self.count = 0

    def observe(self, v: float) -> None:
        with self._lock:
            self.sum += v
            self.count += 1
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self.counts[i] += 1
                    return
            self.counts[-1] += 1

    def percentile(self, p: float) -> float:
        """Estimate the p-quantile (p in [0, 1]) by linear interpolation
        inside the bucket holding the target rank; the overflow bucket
        answers with the largest finite bound (a floor, honestly)."""
        with self._lock:
            total = self.count
            if total == 0:
                return 0.0
            rank = p * total
            cum = 0.0
            lo = 0.0
            for i, b in enumerate(self.buckets):
                c = self.counts[i]
                if cum + c >= rank and c > 0:
                    frac = (rank - cum) / c
                    return lo + (b - lo) * min(max(frac, 0.0), 1.0)
                cum += c
                lo = b
            return self.buckets[-1]

    def expose(self) -> list[str]:
        base = dict(self.labels)
        out = []
        cum = 0
        with self._lock:
            for i, b in enumerate(self.buckets):
                cum += self.counts[i]
                lab = _label_key(dict(base, le=f"{b:g}"))
                out.append(f"{self.name}_bucket{_fmt_labels(lab)} {cum}")
            cum += self.counts[-1]
            lab = _label_key(dict(base, le="+Inf"))
            out.append(f"{self.name}_bucket{_fmt_labels(lab)} {cum}")
            out.append(f"{self.name}_sum{_fmt_labels(self.labels)} "
                       f"{self.sum:g}")
            out.append(f"{self.name}_count{_fmt_labels(self.labels)} "
                       f"{self.count}")
        return out


class MetricsRegistry:
    """Name+labels → instrument store; one per process (get_registry)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._instruments: dict[tuple, object] = {}
        self._types: dict[str, str] = {}

    def _get(self, cls, typ: str, name: str, labels: dict, **kw):
        key = (name, _label_key(labels))
        with self._lock:
            prev = self._types.setdefault(name, typ)
            if prev != typ:
                raise TypeError(
                    f"metric {name!r} already registered as {prev}")
            inst = self._instruments.get(key)
            if inst is None:
                inst = cls(name, _label_key(labels), **kw)
                self._instruments[key] = inst
            return inst

    def counter(self, name: str, **labels) -> Counter:
        return self._get(Counter, "counter", name, labels)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get(Gauge, "gauge", name, labels)

    def histogram(self, name: str, buckets: Optional[tuple] = None,
                  **labels) -> Histogram:
        return self._get(Histogram, "histogram", name, labels,
                         buckets=buckets)

    def snapshot(self) -> dict:
        """{name{labels}: value | {sum, count, p50, p95, p99}}."""
        with self._lock:
            items = list(self._instruments.items())
        out = {}
        for (name, labels), inst in items:
            key = f"{name}{_fmt_labels(labels)}"
            if isinstance(inst, Histogram):
                out[key] = {"sum": inst.sum, "count": inst.count,
                            "p50": inst.percentile(0.50),
                            "p95": inst.percentile(0.95),
                            "p99": inst.percentile(0.99)}
            else:
                out[key] = inst.value
        return out

    def render_prometheus(self) -> str:
        """Prometheus text exposition: registered instruments plus live
        totals collected from the runtime singletons. Conformance
        contract (pinned by tests/test_metrics_registry.py): exactly one
        ``# HELP`` and one ``# TYPE`` line per metric family, emitted
        before the family's first sample; label values escaped; a
        histogram's ``+Inf`` bucket equals its ``_count``."""
        with self._lock:
            items = sorted(self._instruments.items(),
                           key=lambda kv: kv[0])
            types = dict(self._types)
        lines = []
        seen = set()
        for (name, _labels), inst in items:
            if name not in seen:
                lines.append(f"# HELP {name} {_help_text(name)}")
                lines.append(f"# TYPE {name} {types[name]}")
                seen.add(name)
            lines.extend(inst.expose())
        for name, typ, samples in _collect_runtime():
            if name in seen:   # registered instruments own the family
                continue
            seen.add(name)
            lines.append(f"# HELP {name} {_help_text(name)}")
            lines.append(f"# TYPE {name} {typ}")
            lines.extend(samples)
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        with self._lock:
            self._instruments.clear()
            self._types.clear()


#: HELP text per metric family — the exposition's one-HELP-per-family
#: conformance line; unknown families fall back to a generic string so
#: a new metric can never break a scrape by missing an entry here.
_HELP = {
    "auron_info": "Build/config identity (trace_salt label).",
    "auron_program_builds_total": "Program-cache builds per compile site.",
    "auron_program_hits_total": "Program-cache hits per compile site.",
    "auron_program_live": "Live compiled programs per compile site.",
    "auron_backend_compiles_total": "Raw XLA backend compiles.",
    "auron_backend_compile_seconds_total": "Seconds spent in XLA compiles.",
    "auron_faults_injected_total": "Chaos-plane fault injections.",
    "auron_watchdog_fallbacks_total": "Watchdog CPU fallbacks taken.",
    "auron_watchdog_stalls_total": "Task stalls flagged by the watchdog.",
    "auron_trace_dropped_spans": "Spans dropped past auron.trace.max_spans.",
    "auron_sched_running": "Queries running, per scheduler.",
    "auron_sched_queued": "Queries queued, per scheduler.",
    "auron_tasks_total": "Finished tasks observed by the registry.",
    "auron_task_seconds": "Per-task wall seconds.",
    "auron_task_retries_total": "Transient task retries.",
    "auron_corruption_recomputes_total":
        "Map recomputes after checksum mismatches.",
    "auron_spill_runs_total": "Spill runs written.",
    "auron_spill_bytes_total": "Bytes spilled.",
    "auron_output_rows_total": "Rows produced by finished tasks.",
    "auron_query_duration_seconds":
        "End-to-end per-query latency by outcome "
        "(ok|shed|cancelled|failed) — the SLO-burn source.",
    "auron_bundles_written_total": "Post-mortem bundles written.",
    "auron_flight_events": "Events currently buffered by the recorder.",
    "auron_ops_scrapes_total": "Ops-endpoint requests served, per path.",
    "auron_cache_hits_total": "Warm-path cache hits, per plane.",
    "auron_cache_misses_total": "Warm-path cache misses, per plane.",
    "auron_cache_evictions_total":
        "Warm-path cache evictions (capacity LRU + memmgr pressure).",
    "auron_cache_inserts_total": "Warm-path cache inserts.",
    "auron_cache_bytes": "Bytes held by the warm-path cache.",
    "auron_cache_entries": "Entries held by the warm-path cache.",
    "auron_aot_warmed": "Plans warmed by the last AOT startup pass.",
    "auron_aot_errors": "Errors in the last AOT startup pass.",
    "auron_fleet_routed_total":
        "Fleet router submissions routed, per replica and pick reason.",
    "auron_fleet_spillover_total":
        "Fleet router spill-over retries after a replica shed.",
    "auron_fleet_shed_total":
        "Fleet-wide sheds surfaced to the client (every replica shed).",
    "auron_fleet_failover_total":
        "Fleet failovers per replica and action (resume|reexecute).",
    "auron_fleet_failover_seconds":
        "Fleet failover latency: replica-death detect to recovery done.",
    "auron_fleet_replica_deaths_total":
        "Liveness-confirmed replica deaths recorded by the router.",
    "auron_fleet_guard_shared_total":
        "Failover re-executions answered from the single-flight guard.",
    "auron_fleet_errors_forwarded_total":
        "Replica ERROR frames the router forwarded to clients.",
    "auron_fleet_replica_up":
        "Replica reachability as seen by the router (1 up, 0 down).",
}


def _help_text(name: str) -> str:
    return _HELP.get(name, "auron runtime metric.")


def _collect_runtime() -> list[tuple]:
    """Live totals from the runtime singletons — collected at scrape
    time so subsystems need no push wiring. Best-effort: a missing
    module never fails the exposition. Returns ``(family name, type,
    [sample lines])`` so the renderer can keep the one-HELP/TYPE-per-
    family conformance contract."""
    fams: list[tuple] = []

    def lab(**labels) -> str:
        return _fmt_labels(_label_key(labels))

    try:
        from auron_tpu import config as cfg
        salt = ",".join(str(v) for v in cfg.trace_salt())
        fams.append(("auron_info", "gauge",
                     [f"auron_info{lab(trace_salt=salt)} 1"]))
    except Exception:
        pass
    try:
        from auron_tpu.runtime import programs
        builds, hits, live = [], [], []
        for site, st in sorted(programs.snapshot().items()):
            builds.append(f"auron_program_builds_total{lab(site=site)} "
                          f"{st['builds']}")
            hits.append(f"auron_program_hits_total{lab(site=site)} "
                        f"{st['hits']}")
            live.append(f"auron_program_live{lab(site=site)} "
                        f"{st['live']}")
        fams.append(("auron_program_builds_total", "counter", builds))
        fams.append(("auron_program_hits_total", "counter", hits))
        fams.append(("auron_program_live", "gauge", live))
    except Exception:
        pass
    try:
        from auron_tpu.utils import compile_stats
        snap = compile_stats.snapshot()
        fams.append(("auron_backend_compiles_total", "counter",
                     [f"auron_backend_compiles_total {snap.count}"]))
        fams.append(("auron_backend_compile_seconds_total", "counter",
                     [f"auron_backend_compile_seconds_total "
                      f"{snap.seconds:g}"]))
    except Exception:
        pass
    try:
        from auron_tpu.runtime import faults
        fams.append(("auron_faults_injected_total", "counter",
                     [f"auron_faults_injected_total {faults.totals()}"]))
    except Exception:
        pass
    try:
        from auron_tpu.runtime import watchdog
        fams.append(("auron_watchdog_fallbacks_total", "counter",
                     [f"auron_watchdog_fallbacks_total "
                      f"{watchdog.totals()}"]))
        fams.append(("auron_watchdog_stalls_total", "counter",
                     [f"auron_watchdog_stalls_total "
                      f"{watchdog.stall_totals()}"]))
    except Exception:
        pass
    try:
        from auron_tpu.obs import trace
        fams.append(("auron_trace_dropped_spans", "counter",
                     [f"auron_trace_dropped_spans "
                      f"{trace.tracer().dropped}"]))
    except Exception:
        pass
    try:
        # scheduler occupancy collected LIVE and summed by name across
        # every scheduler in the process: several Sessions share the
        # "session" name, and per-change gauge sets from each would
        # overwrite one another last-writer-wins
        from auron_tpu.runtime import scheduler
        states = scheduler.aggregate_states()
        if states:
            running, queued = [], []
            for name, st in sorted(states.items()):
                running.append(f"auron_sched_running"
                               f"{lab(scheduler=name)} {st['running']}")
                queued.append(f"auron_sched_queued"
                              f"{lab(scheduler=name)} {st['queued']}")
            fams.append(("auron_sched_running", "gauge", running))
            fams.append(("auron_sched_queued", "gauge", queued))
    except Exception:
        pass
    try:
        from auron_tpu.cache import result_cache as _rcache
        rc = _rcache.get_cache().stats()
        fams.append(("auron_cache_hits_total", "counter", [
            f"auron_cache_hits_total{lab(plane='result')} {rc['hits']}",
            f"auron_cache_hits_total{lab(plane='subplan')} "
            f"{rc['subplan_hits']}"]))
        fams.append(("auron_cache_misses_total", "counter", [
            f"auron_cache_misses_total{lab(plane='result')} "
            f"{rc['misses']}",
            f"auron_cache_misses_total{lab(plane='subplan')} "
            f"{rc['subplan_misses']}"]))
        fams.append(("auron_cache_evictions_total", "counter",
                     [f"auron_cache_evictions_total {rc['evictions']}"]))
        fams.append(("auron_cache_inserts_total", "counter",
                     [f"auron_cache_inserts_total {rc['inserts']}"]))
        fams.append(("auron_cache_bytes", "gauge",
                     [f"auron_cache_bytes {rc['bytes']}"]))
        fams.append(("auron_cache_entries", "gauge",
                     [f"auron_cache_entries {rc['entries']}"]))
    except Exception:
        pass
    try:
        from auron_tpu.cache import aot as _aot
        a = _aot.last_stats()
        fams.append(("auron_aot_warmed", "gauge",
                     [f"auron_aot_warmed {a['warmed']}"]))
        fams.append(("auron_aot_errors", "gauge",
                     [f"auron_aot_errors {len(a['errors'])}"]))
    except Exception:
        pass
    return fams


_REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _REGISTRY


#: (config epoch, enabled) verdict cache — per-task feeding checks this
_CACHED: tuple[int, Optional[bool]] = (-1, None)


def enabled() -> bool:
    global _CACHED
    from auron_tpu import config as cfg
    epoch, val = _CACHED
    if epoch == cfg.config_epoch() and val is not None:
        return val
    epoch = cfg.config_epoch()
    val = cfg.get_config().get(cfg.METRICS_REGISTRY)
    _CACHED = (epoch, val)
    return val


def observe_memmgr(status: dict) -> None:
    """Mirror one MemManager.status() snapshot onto registry gauges —
    called by the manager on every ``update_mem_used`` / spill decision
    (gated by auron.metrics.registry), so the HBM/DRAM tier pressure the
    paper's memory manager arbitrates is scrapeable live:

    - ``auron_memmgr_budget_bytes`` / ``auron_memmgr_used_bytes`` /
      ``auron_memmgr_consumers`` / ``auron_memmgr_fair_share_bytes``
    - ``auron_memmgr_spills_total`` / ``auron_memmgr_spilled_bytes_total``
      (monotonic manager totals, exposed last-write-wins so a scrape
      between managers never double-counts)
    - ``auron_memmgr_consumer_bytes{consumer=...}`` per registered
      consumer. A consumer absent from a later snapshot keeps its last
      value (gauges are last-write-wins, not reaped); cardinality is
      bounded by the set of consumer NAMES, which are stable per
      operator class, not per instance.
    """
    if not enabled():
        return
    r = _REGISTRY
    r.gauge("auron_memmgr_budget_bytes").set(status["total"])
    r.gauge("auron_memmgr_used_bytes").set(status["used"])
    r.gauge("auron_memmgr_consumers").set(status["num_consumers"])
    r.gauge("auron_memmgr_fair_share_bytes").set(
        status.get("fair_share", 0))
    r.gauge("auron_memmgr_spills_total").set(status["num_spills"])
    r.gauge("auron_memmgr_spilled_bytes_total").set(
        status["spilled_bytes"])
    for name, used in status.get("consumers", {}).items():
        r.gauge("auron_memmgr_consumer_bytes", consumer=name).set(used)


def observe_task(wall_s: float, snap: dict, output_rows: int = 0) -> None:
    """One finished task's observation: called by the retry driver with
    the task's metrics snapshot (gated by auron.metrics.registry)."""
    if not enabled():
        return
    r = _REGISTRY
    r.counter("auron_tasks_total").inc()
    r.histogram("auron_task_seconds").observe(wall_s)
    rec = snap.get("recovery") or {}
    r.counter("auron_task_retries_total").inc(
        rec.get("transient_retries", 0))
    r.counter("auron_corruption_recomputes_total").inc(
        rec.get("corruption_recomputes", 0))
    spill_count = spill_bytes = 0
    for vals in snap.values():
        if isinstance(vals, dict):
            spill_count += vals.get("mem_spill_count", 0)
            spill_bytes += vals.get("mem_spill_size", 0)
    r.counter("auron_spill_runs_total").inc(spill_count)
    r.counter("auron_spill_bytes_total").inc(spill_bytes)
    r.counter("auron_output_rows_total").inc(output_rows)


# ---------------------------------------------------------------------------
# per-query SLO surface (the ops plane's /metrics acceptance metric)
# ---------------------------------------------------------------------------

def classify_outcome(exc) -> str:
    """Map a query's terminal exception onto the
    ``auron_query_duration_seconds`` outcome vocabulary:

    - ``ok`` — no exception;
    - ``shed`` — the runtime refused/evicted the query to protect the
      process (MemoryExhausted, AdmissionRejected);
    - ``cancelled`` — the caller's verdict (QueryCancelled, including
      DeadlineExceeded: the budget was the caller's) or a serving
      task-kill (TaskCancelled);
    - ``failed`` — everything else.
    """
    if exc is None:
        return "ok"
    from auron_tpu import errors
    if isinstance(exc, (errors.MemoryExhausted, errors.AdmissionRejected)):
        return "shed"
    if isinstance(exc, errors.QueryCancelled):
        return "cancelled"
    if type(exc).__name__ in ("TaskCancelled", "_Cancelled"):
        return "cancelled"
    return "failed"


def observe_query(duration_s: float, outcome: str,
                  served_from: Optional[str] = None) -> None:
    """One top-level query's end-to-end latency observation, labelled by
    outcome — fed by Session's admission scope and the serving handler,
    so SLO burn is computable from ``/metrics`` alone (gated by
    auron.metrics.registry). ``served_from="cache"`` distinguishes
    warm-path answers (auron_tpu/cache) from executed ones, so cached
    hits can't silently flatter the executed-latency SLO."""
    if not enabled():
        return
    labels = {"outcome": outcome}
    if served_from:
        labels["served_from"] = served_from
    _REGISTRY.histogram("auron_query_duration_seconds",
                        **labels).observe(duration_s)


# ---------------------------------------------------------------------------
# strict text-format parser (conformance audit + ops-plane gates)
# ---------------------------------------------------------------------------

_NAME_RE = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
_SAMPLE_RE = re.compile(
    rf"^({_NAME_RE})(\{{.*\}})? "
    r"(-?(?:[0-9]*\.?[0-9]+(?:[eE][+-]?[0-9]+)?|Inf|NaN)|\+Inf)$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
_VALID_TYPES = ("counter", "gauge", "histogram", "summary", "untyped")


def _unescape_label(v: str) -> str:
    """Single left-to-right scan: sequential str.replace would corrupt
    values where an escaped backslash precedes an 'n' (``\\\\n`` must
    read as backslash+n, not newline)."""
    out = []
    i = 0
    while i < len(v):
        c = v[i]
        if c == "\\" and i + 1 < len(v):
            n = v[i + 1]
            if n == "n":
                out.append("\n")
                i += 2
                continue
            if n in ('"', "\\"):
                out.append(n)
                i += 2
                continue
        out.append(c)
        i += 1
    return "".join(out)


def _parse_labels(body: str) -> dict:
    """Strict ``{k="v",...}`` parse: every byte must be consumed by
    well-formed pairs (a malformed pair silently dropped is exactly the
    torn-table bug the audit exists to catch)."""
    inner = body[1:-1].rstrip(",")
    if not inner:
        return {}
    out = {}
    pos = 0
    while pos < len(inner):
        m = _LABEL_RE.match(inner, pos)
        if m is None:
            raise ValueError(f"malformed label pair at {inner[pos:]!r}")
        out[m.group(1)] = _unescape_label(m.group(2))
        pos = m.end()
        if pos < len(inner):
            if inner[pos] != ",":
                raise ValueError(f"expected ',' at {inner[pos:]!r}")
            pos += 1
    return out


def render_federated(local_text: str, replica_texts: list) -> str:
    """Fleet-scope /metrics: merge this process's exposition with each
    replica's scraped exposition, every replica sample re-labeled
    ``replica="rN"`` — the router's one-scrape-path contract.

    Both inputs and the output go through :func:`parse_prometheus`
    strictness: the local text is parsed STRICTLY (we rendered it — a
    violation is a bug), while an unparseable replica text (a replica
    dying mid-scrape, a version skew) drops THAT replica's samples
    rather than failing the whole federation. ``replica_texts`` is
    ``[(label, exposition_text), ...]``.

    The merged text is conformant by construction: one HELP/TYPE per
    family before its first sample, and every histogram series is
    distinguished by the ``replica`` label, so each keeps its own
    +Inf==_count invariant.
    """
    fams: dict[str, dict] = {}

    def fold(parsed: dict, label) -> None:
        for fam, info in parsed.items():
            ent = fams.get(fam)
            if ent is None:
                ent = fams[fam] = {"type": info["type"],
                                   "help": info["help"] or "",
                                   "samples": []}
            elif ent["type"] != info["type"]:
                continue   # version-skewed family: first writer owns it
            for name, labels, value in info["samples"]:
                if label is not None:
                    labels = dict(labels, replica=label)
                ent["samples"].append((name, labels, value))

    fold(parse_prometheus(local_text), None)
    for label, text in replica_texts:
        try:
            fold(parse_prometheus(text), label)
        except ValueError:
            continue
    lines = []
    for fam in sorted(fams):
        ent = fams[fam]
        lines.append(f"# HELP {fam} {ent['help'] or _help_text(fam)}")
        lines.append(f"# TYPE {fam} {ent['type']}")
        for name, labels, value in ent["samples"]:
            lines.append(
                f"{name}{_fmt_labels(_label_key(labels))} {value:g}")
    return "\n".join(lines) + "\n"


def parse_prometheus(text: str) -> dict:
    """STRICT Prometheus text-format parser — the conformance oracle the
    regression tests and the perf-gate ops arm scrape through. Raises
    ``ValueError`` on any violation of the contract render_prometheus
    promises:

    - every non-comment line is a well-formed sample (name, optional
      escaped label set, float value);
    - exactly one ``# HELP`` and one ``# TYPE`` per family, before the
      family's first sample;
    - every sample belongs to a declared family (histogram samples via
      their ``_bucket``/``_sum``/``_count`` suffixes);
    - per histogram series: the ``+Inf`` bucket exists, equals
      ``_count``, and bucket counts are monotone in ``le``.

    Returns ``{family: {"type", "help", "samples": [(name, labels,
    value)]}}``.
    """
    fams: dict[str, dict] = {}

    def family_of(name: str) -> Optional[str]:
        if name in fams:
            return name
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix):
                base = name[: -len(suffix)]
                if base in fams and fams[base]["type"] == "histogram":
                    return base
        return None

    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split(" ", 3)
            if len(parts) < 3 or parts[1] not in ("HELP", "TYPE"):
                raise ValueError(f"line {lineno}: malformed comment "
                                 f"{line!r}")
            kind, name = parts[1], parts[2]
            if not re.fullmatch(_NAME_RE, name):
                raise ValueError(f"line {lineno}: bad metric name "
                                 f"{name!r}")
            ent = fams.setdefault(
                name, {"type": None, "help": None, "samples": []})
            if kind == "HELP":
                if ent["help"] is not None:
                    raise ValueError(
                        f"line {lineno}: duplicate HELP for {name}")
                if ent["samples"]:
                    raise ValueError(
                        f"line {lineno}: HELP for {name} after samples")
                ent["help"] = parts[3] if len(parts) > 3 else ""
            else:
                if ent["type"] is not None:
                    raise ValueError(
                        f"line {lineno}: duplicate TYPE for {name}")
                if ent["samples"]:
                    raise ValueError(
                        f"line {lineno}: TYPE for {name} after samples")
                typ = parts[3].strip() if len(parts) > 3 else ""
                if typ not in _VALID_TYPES:
                    raise ValueError(
                        f"line {lineno}: invalid type {typ!r} for {name}")
                ent["type"] = typ
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"line {lineno}: malformed sample {line!r}")
        name, labels_body, raw = m.group(1), m.group(2), m.group(3)
        fam = family_of(name)
        if fam is None:
            raise ValueError(
                f"line {lineno}: sample {name!r} has no declared family")
        if fams[fam]["type"] is None:
            raise ValueError(
                f"line {lineno}: sample {name!r} before its TYPE")
        labels = _parse_labels(labels_body) if labels_body else {}
        value = float(raw.replace("+Inf", "inf").replace("Inf", "inf")
                      .replace("NaN", "nan"))
        fams[fam]["samples"].append((name, labels, value))
    for name, ent in fams.items():
        if ent["type"] is None:
            raise ValueError(f"family {name}: HELP without TYPE")
        if ent["help"] is None:
            raise ValueError(f"family {name}: TYPE without HELP")
        if ent["type"] == "histogram":
            _check_histogram(name, ent["samples"])
    return fams


def _check_histogram(fam: str, samples: list) -> None:
    """Per-series histogram invariants: +Inf bucket present and equal to
    _count; cumulative bucket counts monotone in le."""
    series: dict[tuple, dict] = {}
    for name, labels, value in samples:
        key = _label_key({k: v for k, v in labels.items() if k != "le"})
        ent = series.setdefault(key, {"buckets": [], "count": None})
        if name == fam + "_bucket":
            if "le" not in labels:
                raise ValueError(f"{fam}: bucket sample without le")
            ent["buckets"].append((float(labels["le"]
                                         .replace("+Inf", "inf")), value))
        elif name == fam + "_count":
            ent["count"] = value
    for key, ent in series.items():
        if ent["count"] is None and not ent["buckets"]:
            continue
        buckets = sorted(ent["buckets"])
        if not buckets or buckets[-1][0] != float("inf"):
            raise ValueError(f"{fam}{dict(key)}: no +Inf bucket")
        if ent["count"] is None:
            raise ValueError(f"{fam}{dict(key)}: buckets without _count")
        if buckets[-1][1] != ent["count"]:
            raise ValueError(
                f"{fam}{dict(key)}: +Inf bucket {buckets[-1][1]} != "
                f"_count {ent['count']}")
        prev = 0.0
        for le, v in buckets:
            if v < prev:
                raise ValueError(
                    f"{fam}{dict(key)}: bucket le={le} count {v} < "
                    f"previous {prev} (not cumulative)")
            prev = v
