"""Process-wide metrics registry: counters, gauges, fixed-bucket
histograms, Prometheus text exposition.

Per-task metrics (ops/base.MetricsSet) die with their ExecContext; this
registry is the process-lifetime aggregate the scrape surface reads —
the role the reference's pprof/metrics HTTP endpoints play
(auron/src/http/mod.rs:25-108). The executor feeds it one observation
per finished task (gated by ``auron.metrics.registry``): task seconds,
retries, recovery counters, spill volume. ``render_prometheus`` emits
the standard text format and additionally collects live totals from the
runtime singletons (program-cache builds/hits per site, backend
compiles, injected faults, watchdog fallbacks) so a scrape needs no
separate wiring per subsystem.

Histograms are fixed-bucket (Prometheus-shaped: cumulative ``le``
buckets + ``_sum``/``_count``) with p50/p95/p99 estimation by linear
interpolation inside the bucket — exact enough for dashboards, O(1)
memory, no reservoir.

The exposition is trace_salt-aware: ``auron_info`` carries the current
``config.trace_salt()`` so a scraper can correlate metric shifts with
trace-semantic config flips (the same salt that partitions every
program-cache key, runtime/programs.py).
"""

from __future__ import annotations

import threading
from typing import Optional

#: default latency buckets (seconds): 1ms .. 2min, roughly log-spaced
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0)


def _label_key(labels: dict) -> tuple:
    return tuple(sorted(labels.items()))


def _fmt_labels(labels: tuple) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in labels)
    return "{" + inner + "}"


class Counter:
    """Monotonic counter."""

    def __init__(self, name: str, labels: tuple):
        self.name = name
        self.labels = labels
        self._lock = threading.Lock()
        self.value = 0.0

    def inc(self, v: float = 1.0) -> None:
        with self._lock:
            self.value += v

    def expose(self) -> list[str]:
        return [f"{self.name}{_fmt_labels(self.labels)} {self.value:g}"]


class Gauge:
    """Last-write-wins value."""

    def __init__(self, name: str, labels: tuple):
        self.name = name
        self.labels = labels
        self._lock = threading.Lock()
        self.value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self.value = float(v)

    def inc(self, v: float = 1.0) -> None:
        with self._lock:
            self.value += v

    def expose(self) -> list[str]:
        return [f"{self.name}{_fmt_labels(self.labels)} {self.value:g}"]


class Histogram:
    """Fixed cumulative-bucket histogram with percentile estimation."""

    def __init__(self, name: str, labels: tuple,
                 buckets: Optional[tuple] = None):
        self.name = name
        self.labels = labels
        self.buckets = tuple(sorted(buckets or DEFAULT_BUCKETS))
        self._lock = threading.Lock()
        #: per-bucket NON-cumulative counts; [-1] is the +Inf overflow
        self.counts = [0] * (len(self.buckets) + 1)
        self.sum = 0.0
        self.count = 0

    def observe(self, v: float) -> None:
        with self._lock:
            self.sum += v
            self.count += 1
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self.counts[i] += 1
                    return
            self.counts[-1] += 1

    def percentile(self, p: float) -> float:
        """Estimate the p-quantile (p in [0, 1]) by linear interpolation
        inside the bucket holding the target rank; the overflow bucket
        answers with the largest finite bound (a floor, honestly)."""
        with self._lock:
            total = self.count
            if total == 0:
                return 0.0
            rank = p * total
            cum = 0.0
            lo = 0.0
            for i, b in enumerate(self.buckets):
                c = self.counts[i]
                if cum + c >= rank and c > 0:
                    frac = (rank - cum) / c
                    return lo + (b - lo) * min(max(frac, 0.0), 1.0)
                cum += c
                lo = b
            return self.buckets[-1]

    def expose(self) -> list[str]:
        base = dict(self.labels)
        out = []
        cum = 0
        with self._lock:
            for i, b in enumerate(self.buckets):
                cum += self.counts[i]
                lab = _label_key(dict(base, le=f"{b:g}"))
                out.append(f"{self.name}_bucket{_fmt_labels(lab)} {cum}")
            cum += self.counts[-1]
            lab = _label_key(dict(base, le="+Inf"))
            out.append(f"{self.name}_bucket{_fmt_labels(lab)} {cum}")
            out.append(f"{self.name}_sum{_fmt_labels(self.labels)} "
                       f"{self.sum:g}")
            out.append(f"{self.name}_count{_fmt_labels(self.labels)} "
                       f"{self.count}")
        return out


class MetricsRegistry:
    """Name+labels → instrument store; one per process (get_registry)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._instruments: dict[tuple, object] = {}
        self._types: dict[str, str] = {}

    def _get(self, cls, typ: str, name: str, labels: dict, **kw):
        key = (name, _label_key(labels))
        with self._lock:
            prev = self._types.setdefault(name, typ)
            if prev != typ:
                raise TypeError(
                    f"metric {name!r} already registered as {prev}")
            inst = self._instruments.get(key)
            if inst is None:
                inst = cls(name, _label_key(labels), **kw)
                self._instruments[key] = inst
            return inst

    def counter(self, name: str, **labels) -> Counter:
        return self._get(Counter, "counter", name, labels)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get(Gauge, "gauge", name, labels)

    def histogram(self, name: str, buckets: Optional[tuple] = None,
                  **labels) -> Histogram:
        return self._get(Histogram, "histogram", name, labels,
                         buckets=buckets)

    def snapshot(self) -> dict:
        """{name{labels}: value | {sum, count, p50, p95, p99}}."""
        with self._lock:
            items = list(self._instruments.items())
        out = {}
        for (name, labels), inst in items:
            key = f"{name}{_fmt_labels(labels)}"
            if isinstance(inst, Histogram):
                out[key] = {"sum": inst.sum, "count": inst.count,
                            "p50": inst.percentile(0.50),
                            "p95": inst.percentile(0.95),
                            "p99": inst.percentile(0.99)}
            else:
                out[key] = inst.value
        return out

    def render_prometheus(self) -> str:
        """Prometheus text exposition: registered instruments plus live
        totals collected from the runtime singletons."""
        with self._lock:
            items = sorted(self._instruments.items(),
                           key=lambda kv: kv[0])
            types = dict(self._types)
        lines = []
        seen_type = set()
        for (name, _labels), inst in items:
            if name not in seen_type:
                lines.append(f"# TYPE {name} {types[name]}")
                seen_type.add(name)
            lines.extend(inst.expose())
        lines.extend(_collect_runtime())
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        with self._lock:
            self._instruments.clear()
            self._types.clear()


def _collect_runtime() -> list[str]:
    """Live totals from the runtime singletons — collected at scrape
    time so subsystems need no push wiring. Best-effort: a missing
    module never fails the exposition."""
    lines = []
    try:
        from auron_tpu import config as cfg
        salt = ",".join(str(v) for v in cfg.trace_salt())
        lines.append("# TYPE auron_info gauge")
        lines.append(f'auron_info{{trace_salt="{salt}"}} 1')
    except Exception:
        pass
    try:
        from auron_tpu.runtime import programs
        lines.append("# TYPE auron_program_builds_total counter")
        lines.append("# TYPE auron_program_hits_total counter")
        lines.append("# TYPE auron_program_live gauge")
        for site, st in sorted(programs.snapshot().items()):
            lab = f'{{site="{site}"}}'
            lines.append(f"auron_program_builds_total{lab} {st['builds']}")
            lines.append(f"auron_program_hits_total{lab} {st['hits']}")
            lines.append(f"auron_program_live{lab} {st['live']}")
    except Exception:
        pass
    try:
        from auron_tpu.utils import compile_stats
        snap = compile_stats.snapshot()
        lines.append("# TYPE auron_backend_compiles_total counter")
        lines.append(f"auron_backend_compiles_total {snap.count}")
        lines.append("# TYPE auron_backend_compile_seconds_total counter")
        lines.append(f"auron_backend_compile_seconds_total "
                     f"{snap.seconds:g}")
    except Exception:
        pass
    try:
        from auron_tpu.runtime import faults
        lines.append("# TYPE auron_faults_injected_total counter")
        lines.append(f"auron_faults_injected_total {faults.totals()}")
    except Exception:
        pass
    try:
        from auron_tpu.runtime import watchdog
        lines.append("# TYPE auron_watchdog_fallbacks_total counter")
        lines.append(f"auron_watchdog_fallbacks_total {watchdog.totals()}")
        lines.append("# TYPE auron_watchdog_stalls_total counter")
        lines.append(f"auron_watchdog_stalls_total "
                     f"{watchdog.stall_totals()}")
    except Exception:
        pass
    try:
        from auron_tpu.obs import trace
        lines.append("# TYPE auron_trace_dropped_spans counter")
        lines.append(f"auron_trace_dropped_spans {trace.tracer().dropped}")
    except Exception:
        pass
    try:
        # scheduler occupancy collected LIVE and summed by name across
        # every scheduler in the process: several Sessions share the
        # "session" name, and per-change gauge sets from each would
        # overwrite one another last-writer-wins
        from auron_tpu.runtime import scheduler
        states = scheduler.aggregate_states()
        if states:
            lines.append("# TYPE auron_sched_running gauge")
            lines.append("# TYPE auron_sched_queued gauge")
            for name, st in sorted(states.items()):
                lab = f'{{scheduler="{name}"}}'
                lines.append(f"auron_sched_running{lab} {st['running']}")
                lines.append(f"auron_sched_queued{lab} {st['queued']}")
    except Exception:
        pass
    return lines


_REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _REGISTRY


#: (config epoch, enabled) verdict cache — per-task feeding checks this
_CACHED: tuple[int, Optional[bool]] = (-1, None)


def enabled() -> bool:
    global _CACHED
    from auron_tpu import config as cfg
    epoch, val = _CACHED
    if epoch == cfg.config_epoch() and val is not None:
        return val
    epoch = cfg.config_epoch()
    val = cfg.get_config().get(cfg.METRICS_REGISTRY)
    _CACHED = (epoch, val)
    return val


def observe_memmgr(status: dict) -> None:
    """Mirror one MemManager.status() snapshot onto registry gauges —
    called by the manager on every ``update_mem_used`` / spill decision
    (gated by auron.metrics.registry), so the HBM/DRAM tier pressure the
    paper's memory manager arbitrates is scrapeable live:

    - ``auron_memmgr_budget_bytes`` / ``auron_memmgr_used_bytes`` /
      ``auron_memmgr_consumers`` / ``auron_memmgr_fair_share_bytes``
    - ``auron_memmgr_spills_total`` / ``auron_memmgr_spilled_bytes_total``
      (monotonic manager totals, exposed last-write-wins so a scrape
      between managers never double-counts)
    - ``auron_memmgr_consumer_bytes{consumer=...}`` per registered
      consumer. A consumer absent from a later snapshot keeps its last
      value (gauges are last-write-wins, not reaped); cardinality is
      bounded by the set of consumer NAMES, which are stable per
      operator class, not per instance.
    """
    if not enabled():
        return
    r = _REGISTRY
    r.gauge("auron_memmgr_budget_bytes").set(status["total"])
    r.gauge("auron_memmgr_used_bytes").set(status["used"])
    r.gauge("auron_memmgr_consumers").set(status["num_consumers"])
    r.gauge("auron_memmgr_fair_share_bytes").set(
        status.get("fair_share", 0))
    r.gauge("auron_memmgr_spills_total").set(status["num_spills"])
    r.gauge("auron_memmgr_spilled_bytes_total").set(
        status["spilled_bytes"])
    for name, used in status.get("consumers", {}).items():
        r.gauge("auron_memmgr_consumer_bytes", consumer=name).set(used)


def observe_task(wall_s: float, snap: dict, output_rows: int = 0) -> None:
    """One finished task's observation: called by the retry driver with
    the task's metrics snapshot (gated by auron.metrics.registry)."""
    if not enabled():
        return
    r = _REGISTRY
    r.counter("auron_tasks_total").inc()
    r.histogram("auron_task_seconds").observe(wall_s)
    rec = snap.get("recovery") or {}
    r.counter("auron_task_retries_total").inc(
        rec.get("transient_retries", 0))
    r.counter("auron_corruption_recomputes_total").inc(
        rec.get("corruption_recomputes", 0))
    spill_count = spill_bytes = 0
    for vals in snap.values():
        if isinstance(vals, dict):
            spill_count += vals.get("mem_spill_count", 0)
            spill_bytes += vals.get("mem_spill_size", 0)
    r.counter("auron_spill_runs_total").inc(spill_count)
    r.counter("auron_spill_bytes_total").inc(spill_bytes)
    r.counter("auron_output_rows_total").inc(output_rows)
