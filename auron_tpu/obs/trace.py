"""Span plane: the engine's query→stage→task→operator→event timeline.

Dapper-shaped tracing for the runtime (PAPERS.md distributed-tracing
line): every recovery- or latency-relevant boundary opens a *span*
(named, categorized, attributed, nested via a per-thread stack) or drops
a zero-duration *event*. What the reference gets from pprof HTTP
endpoints plus log archaeology — "what happened when" across retries,
shuffle fetches, spills, compiles and watchdog decisions — is here one
timeline, exportable two ways:

- Chrome-trace JSON (``export_chrome``): the ``{"traceEvents": [...]}``
  format Perfetto / chrome://tracing load directly;
- JSONL (``export_jsonl``): one span per line for programmatic
  consumption (``tools/trace_report.py``).

Recording contract (the <2% overhead budget, PERF.md):

- **disabled hot path**: one cached config-epoch compare (the
  fault-plane pattern, runtime/faults.py) — no lock, no dict lookup;
- **enabled recording is lock-free**: each thread appends to its own
  buffer (registered once under the tracer lock); merge happens only at
  export/snapshot time. The ``auron.trace.max_spans`` cap is enforced
  with the same lock-freedom, so it is approximate by design.

Span identity is stable and deterministic per process: monotonic
counters assign trace ids (one per top-level query scope) and span ids
(global), never wall-clock or randomness, so two runs of the same
single-threaded pipeline number their spans identically.

Config surface: ``auron.trace.{enabled,dir,events,max_spans}``
(config.py). The knobs are deliberately NOT trace-semantic in the
program-cache sense (config.TRACE_SEMANTIC_KEYS): flipping tracing must
never retrace a kernel.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from typing import NamedTuple, Optional

from auron_tpu.obs import flight_recorder as _flight
from auron_tpu.obs.flight_recorder import get_role, set_role  # noqa: F401

#: span categories (the auron.trace.events allowlist vocabulary).
#: The ``mesh`` category carries the SPMD plane's routing AND fault
#: domain: ``exchange.route`` (per-exchange routing decision),
#: ``mesh.gang`` (gang-door occupancy), ``exchange.demote`` (mid-query
#: route demotion with reason/recompute cost), ``mesh.straggler``
#: (round slower than straggler_factor × rolling p50) and
#: ``mesh.quarantine`` (device retired from future submeshes) —
#: tools/mesh_report.py prints all of them.
#: The ``cache`` category is the warm-path serving plane
#: (auron_tpu/cache): ``cache.hit`` / ``cache.miss`` / ``cache.store``
#: / ``cache.evict`` on the result/subplan cache and ``aot.warm``
#: spans around each ahead-of-time plan warming at Session init.
#: The ``fleet`` category is the cross-process serving plane:
#: ``fleet.submit`` (client-side conversation span), ``fleet.adopt``
#: (a process adopting an inbound wire trace context — carries
#: remote_parent/remote_role/remote_pid, the stitch tool's cross-
#: process link), ``fleet.route`` (router routing decision) and
#: ``fleet.forward`` (router hop span around one replica
#: conversation; failover shows as a second hop to the survivor).
CATEGORIES = ("query", "task", "program", "shuffle", "spill", "fault",
              "watchdog", "memory", "sched", "mesh", "journal", "cache",
              "fleet")

_SPAN_IDS = itertools.count(1)     # next() is GIL-atomic
_TRACE_IDS = itertools.count(1)


class _Settings(NamedTuple):
    enabled: bool
    dir: str
    events: Optional[frozenset]    # None = every category
    max_spans: int
    propagate: bool


#: (config epoch, settings) — the disabled check must cost one int
#: compare (same verdict-cache shape as runtime/faults._CACHED)
_CACHED: tuple[int, Optional[_Settings]] = (-1, None)


def _settings() -> _Settings:
    global _CACHED
    from auron_tpu import config as cfg
    epoch, st = _CACHED
    if epoch == cfg.config_epoch() and st is not None:
        return st
    # read the epoch BEFORE the values: a concurrent set() bumps it
    # after we read, so a stale cache entry misses on the next call
    epoch = cfg.config_epoch()
    conf = cfg.get_config()
    ev = conf.get(cfg.TRACE_EVENTS)
    cats = frozenset(c.strip() for c in ev.split(",") if c.strip())
    st = _Settings(
        enabled=conf.get(cfg.TRACE_ENABLED),
        dir=conf.get(cfg.TRACE_DIR),
        events=cats or None,
        max_spans=conf.get(cfg.TRACE_MAX_SPANS),
        propagate=conf.get(cfg.TRACE_PROPAGATE),
    )
    _CACHED = (epoch, st)
    return st


class Span:
    """One finished span (events are zero-duration spans)."""

    __slots__ = ("trace_id", "span_id", "parent_id", "cat", "name",
                 "ts_ns", "dur_ns", "tid", "attrs")

    def __init__(self, trace_id, span_id, parent_id, cat, name, ts_ns,
                 dur_ns, tid, attrs):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.cat = cat
        self.name = name
        self.ts_ns = ts_ns
        self.dur_ns = dur_ns
        self.tid = tid
        self.attrs = attrs

    def to_dict(self) -> dict:
        return {"trace": self.trace_id, "span": self.span_id,
                "parent": self.parent_id, "cat": self.cat,
                "name": self.name, "ts_us": self.ts_ns / 1000.0,
                "dur_us": self.dur_ns / 1000.0, "tid": self.tid,
                "attrs": self.attrs}

    @classmethod
    def from_dict(cls, d: dict) -> "Span":
        return cls(d["trace"], d["span"], d["parent"], d["cat"],
                   d["name"], round(d["ts_us"] * 1000.0),
                   round(d["dur_us"] * 1000.0), d["tid"],
                   d.get("attrs") or {})


class Tracer:
    """Process tracer: per-thread lock-free buffers, merged on demand."""

    def __init__(self):
        self._lock = threading.Lock()
        self._buffers: list[list[Span]] = []
        self._tls = threading.local()
        #: approximate buffered-span count (lock-free increments)
        self._count = 0
        self.dropped = 0
        #: wall-clock epoch of the monotonic ts origin (JSONL metadata)
        self.epoch_wall = time.time()
        self._t0 = time.perf_counter_ns()

    # -- recording (per-thread, lock-free) ----------------------------------

    def _buf(self) -> list:
        buf = getattr(self._tls, "buf", None)
        if buf is None:
            buf = []
            with self._lock:
                self._buffers.append(buf)
            self._tls.buf = buf
        return buf

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = []
            self._tls.stack = st
        return st

    @property
    def current_trace(self) -> int:
        return getattr(self._tls, "trace", 0)

    def set_trace(self, trace_id: int) -> None:
        self._tls.trace = trace_id

    def now_ns(self) -> int:
        return time.perf_counter_ns() - self._t0

    def record(self, span: Span, max_spans: int) -> None:
        sink = getattr(self._tls, "sink", None)
        if sink is not None:
            # adopted wire trace (wire_scope): stream the span straight
            # to its per-role file instead of buffering — the dead
            # replica's partial spans survive a SIGKILL, replica memory
            # stays flat without drop(), and a router thread sharing
            # the client's process never double-exports into the
            # client's buffered trace
            sink.write(span)
            return
        if self._count >= max_spans:
            self.dropped += 1
            return
        self._buf().append(span)
        self._count += 1

    # -- merge / export ------------------------------------------------------

    def spans(self, trace_id: Optional[int] = None) -> list[Span]:
        """Merged snapshot of every thread's buffer, timeline-ordered."""
        with self._lock:
            buffers = list(self._buffers)
        out: list[Span] = []
        for buf in buffers:
            out.extend(buf[:len(buf)])   # len() pins a consistent prefix
        if trace_id is not None:
            out = [s for s in out if s.trace_id == trace_id]
        out.sort(key=lambda s: (s.ts_ns, s.span_id))
        return out

    def drop(self, trace_id: int) -> None:
        """Forget one trace's spans (post-export memory bound)."""
        with self._lock:
            buffers = list(self._buffers)
        for buf in buffers:
            n = len(buf)   # pin: the owning thread may append concurrently
            kept = [s for s in buf[:n] if s.trace_id != trace_id]
            if len(kept) != n:
                buf[:n] = kept
                self._count -= n - len(kept)

    def reset(self) -> None:
        with self._lock:
            for buf in self._buffers:
                del buf[:]
            self._count = 0
            self.dropped = 0


_TRACER = Tracer()


def tracer() -> Tracer:
    return _TRACER


def enabled() -> bool:
    return _settings().enabled


def category_enabled(cat: str) -> bool:
    """True when spans of ``cat`` would actually record — tracing on
    AND the category not excluded by auron.trace.events. Hot paths that
    pay per-item clock reads purely to feed a span should gate on this,
    not on :func:`enabled` alone."""
    st = _settings()
    return st.enabled and (st.events is None or cat in st.events)


def reset() -> None:
    """Drop every buffered span (tests, chaos-run isolation)."""
    _TRACER.reset()


# ---------------------------------------------------------------------------
# recording API
# ---------------------------------------------------------------------------

class _Noop:
    """Disabled-path span: a shared, attribute-tolerant no-op."""

    __slots__ = ()
    span_id = 0

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        return self


_NOOP = _Noop()


class _SpanCM:
    __slots__ = ("cat", "name", "attrs", "span_id", "_parent", "_t0",
                 "_max")

    def __init__(self, cat, name, attrs, max_spans):
        self.cat = cat
        self.name = name
        self.attrs = attrs
        self._max = max_spans

    def set(self, **attrs):
        """Attach attributes discovered mid-span (bytes read, rows...)."""
        self.attrs.update(attrs)
        return self

    def __enter__(self):
        tr = _TRACER
        stack = tr._stack()
        self._parent = stack[-1] if stack else 0
        self.span_id = next(_SPAN_IDS)
        stack.append(self.span_id)
        self._t0 = tr.now_ns()
        return self

    def __exit__(self, exc_type, exc, tb):
        tr = _TRACER
        stack = tr._stack()
        # pop by identity, not position: spans held open across
        # generator yields (shuffle.fetch, spill.read wrap streams) can
        # exit out of LIFO order when a consumer interleaves two
        # streams — a positional pop would strand the dead id on the
        # stack forever, misparenting every later span on the thread
        if stack and stack[-1] == self.span_id:
            stack.pop()
        else:
            try:
                stack.remove(self.span_id)
            except ValueError:
                pass
        if exc_type is not None:
            self.attrs.setdefault("error", exc_type.__name__)
        t0 = self._t0
        dur = tr.now_ns() - t0
        # flight-recorder tee (obs/flight_recorder): completed spans
        # join the always-on ring — attrs are final here (error set)
        _flight.tee(self.cat, self.name, self.attrs, dur_ns=dur)
        tr.record(Span(tr.current_trace, self.span_id, self._parent,
                       self.cat, self.name, t0, dur,
                       threading.get_ident(), self.attrs), self._max)
        return False


def span(cat: str, name: str, **attrs):
    """Open a span (context manager). Disabled / filtered categories
    return a shared no-op whose cost is the settings check."""
    st = _settings()
    if not st.enabled or (st.events is not None and cat not in st.events):
        return _NOOP
    return _SpanCM(cat, name, attrs, st.max_spans)


def event(cat: str, name: str, **attrs) -> None:
    """Record a zero-duration span at the current stack position.

    Tees into the always-on flight recorder BEFORE the enabled check:
    structured events (fault injections, retries, sheds, admission
    decisions) stay reconstructable even with tracing off — the
    black-box contract (obs/flight_recorder.py)."""
    _flight.tee(cat, name, attrs)
    st = _settings()
    if not st.enabled or (st.events is not None and cat not in st.events):
        return
    tr = _TRACER
    stack = tr._stack()
    tr.record(Span(tr.current_trace, next(_SPAN_IDS),
                   stack[-1] if stack else 0, cat, name, tr.now_ns(), 0,
                   threading.get_ident(), attrs), st.max_spans)


def complete_span(cat: str, name: str, start_ns: int, dur_ns: int,
                  **attrs) -> None:
    """Record an already-finished span with explicit timing — for work
    accumulated across a GENERATOR's production segments (shuffle reads,
    spill reads). Holding a ``span()`` context open across yields would
    (a) time the consumer's compute while the generator is suspended and
    (b) keep the span on the per-thread stack so every consumer-side
    span misparents under it; measuring each ``next()`` segment and
    recording once at exhaustion reports only the producer's own cost.
    Parent is the CURRENT stack top (the consumer driving the
    generator), never the span itself."""
    _flight.tee(cat, name, attrs, dur_ns=dur_ns)
    st = _settings()
    if not st.enabled or (st.events is not None and cat not in st.events):
        return
    tr = _TRACER
    stack = tr._stack()
    tr.record(Span(tr.current_trace, next(_SPAN_IDS),
                   stack[-1] if stack else 0, cat, name, start_ns,
                   dur_ns, threading.get_ident(), attrs), st.max_spans)


def stream_spanned(cat: str, name: str, it, time_counter=None, **attrs):
    """Yield ``it``'s items, timing ONLY the production segments (each
    ``next()``), and record ONE completed span at exhaustion or
    abandonment (:func:`complete_span` explains why a span must never
    stay open across yields). ``time_counter`` — an ops.base Metric —
    additionally accrues the produced nanoseconds even when tracing is
    off, for host metrics (``shuffle_read_total_time``) that ride the
    same clock. With the category off/filtered and no counter, this
    degrades to plain iteration: zero per-item overhead."""
    record = category_enabled(cat)
    if not record and time_counter is None:
        yield from it
        return
    tr = _TRACER
    it = iter(it)
    start = tr.now_ns()
    produced_ns = 0
    n = 0
    try:
        while True:
            t0 = tr.now_ns()
            try:
                item = next(it)
            except StopIteration:
                produced_ns += tr.now_ns() - t0
                break
            produced_ns += tr.now_ns() - t0
            n += 1
            yield item
    finally:
        if time_counter is not None:
            time_counter.add(produced_ns)
        if record:
            complete_span(cat, name, start, produced_ns, items=n,
                          **attrs)


class _QueryScope:
    """Top-level query scope: assigns the trace id, opens the root
    ``query.execute`` span, and exports/drops the trace when the
    OUTERMOST scope exits (nested Session.execute calls — host-fn
    children, scalar subqueries — join the enclosing trace)."""

    __slots__ = ("trace_id", "_span", "_outermost", "_entered",
                 "_label")

    def __init__(self, label: str):
        self._label = label
        self.trace_id = 0
        self._span = _NOOP
        self._outermost = False
        self._entered = False

    def __enter__(self):
        st = _settings()
        if not st.enabled:
            return self
        self._entered = True
        tr = _TRACER
        depth = getattr(tr._tls, "query_depth", 0)
        tr._tls.query_depth = depth + 1
        if depth == 0:
            self.trace_id = next(_TRACE_IDS)
            tr.set_trace(self.trace_id)
            self._outermost = True
        else:
            self.trace_id = tr.current_trace
        # the span itself may be a no-op (the 'query' category can be
        # filtered by auron.trace.events) — scope bookkeeping must not
        # depend on it, or depth would leak and the trace never export
        self._span = span("query", "query.execute", label=self._label)
        self._span.__enter__()
        return self

    def __exit__(self, exc_type, exc, tb):
        self._span.__exit__(exc_type, exc, tb)
        if not self._entered:
            return False
        tr = _TRACER
        tr._tls.query_depth = max(getattr(tr._tls, "query_depth", 1) - 1,
                                  0)
        if self._outermost:
            # leave no stale trace id on the thread: spans recorded
            # BETWEEN queries (session init, watchdog probes) must not
            # tag themselves onto an already-exported trace
            tr.set_trace(0)
            st = _settings()
            if st.dir:
                # best-effort like every observability sink: an
                # unwritable trace dir must never discard the query
                # result computed inside the scope (or shadow the
                # query's own exception)
                try:
                    export_trace_dir(st.dir, self.trace_id)
                except Exception:
                    import logging
                    logging.getLogger(__name__).exception(
                        "trace export to %r failed", st.dir)
                finally:
                    tr.drop(self.trace_id)
        return False


def query_scope(label: str = "") -> _QueryScope:
    return _QueryScope(label)


# ---------------------------------------------------------------------------
# cross-process propagation (the serving wire protocol's TRACE frame)
# ---------------------------------------------------------------------------

def _span_line(s: Span, role: Optional[str] = None,
               pid: Optional[int] = None) -> dict:
    """One exported JSONL record: the span dict plus the cross-process
    alignment keys (role, pid, epoch wall-clock) the stitch tool needs
    — monotonic-only timestamps cannot be ordered across processes."""
    d = s.to_dict()
    d["role"] = role if role is not None else get_role()
    d["pid"] = pid if pid is not None else os.getpid()
    d["wall"] = round(_TRACER.epoch_wall + s.ts_ns * 1e-9, 6)
    return d


class _SpanSink:
    """Streaming per-role JSONL sink for one adopted wire trace
    (thread-local, installed by :class:`_WireScope`): every span the
    thread records is appended and flushed immediately, best-effort —
    a SIGKILLed replica leaves its partial spans on disk."""

    __slots__ = ("role", "pid", "_f")

    def __init__(self, path: str, role: str):
        self.role = role
        self.pid = os.getpid()
        self._f = open(path, "a")

    def write(self, s: Span) -> None:
        try:
            self._f.write(
                json.dumps(_span_line(s, self.role, self.pid),
                           default=str) + "\n")
            self._f.flush()
        except Exception:   # pragma: no cover - best-effort sink
            pass

    def close(self) -> None:
        try:
            self._f.close()
        except Exception:   # pragma: no cover
            pass


def wire_context() -> Optional[dict]:
    """The current thread's trace context for the wire (the TRACE
    frame payload): trace id, parent span id (current stack top) and
    the sender's role/pid. ``None`` when propagation or tracing is off
    or no trace is active — callers send no frame in that case, so the
    disabled wire is byte-identical to before."""
    st = _settings()
    if not st.enabled or not st.propagate:
        return None
    tr = _TRACER
    t = tr.current_trace
    if not t:
        return None
    stack = tr._stack()
    # inside an adopted wire scope the thread speaks AS that role (an
    # in-process router forwarding from a client process must stamp
    # role=router, or the stitcher resolves the parent span against
    # the wrong process group)
    role = getattr(tr._tls, "wire_role", None) or get_role()
    return {"trace": t, "parent": stack[-1] if stack else 0,
            "role": role, "pid": os.getpid()}


class _WireScope:
    """Adopt an inbound wire trace context on this thread: take the
    remote trace id, pretend an outer query scope is open (so a nested
    ``query_scope`` JOINS the trace instead of minting a new id and
    exporting it), open a ``fleet.adopt`` span carrying the remote
    parent/role/pid (span ids are per-process counters, so the
    cross-process parent link must travel as attributes — the stitch
    tool resolves it), and, when ``auron.trace.dir`` is set, stream
    this thread's spans straight to ``trace_<id>_<role><pid>.jsonl``."""

    __slots__ = ("trace_id", "_ctx", "_role", "_span", "_saved",
                 "_sink", "_entered")

    def __init__(self, ctx: Optional[dict], role: Optional[str]):
        self._ctx = ctx if isinstance(ctx, dict) else None
        self._role = role
        self.trace_id = 0
        self._span = _NOOP
        self._sink = None
        self._entered = False

    def __enter__(self):
        st = _settings()
        try:
            trace_id = int((self._ctx or {}).get("trace") or 0)
        except (TypeError, ValueError):
            trace_id = 0
        if not st.enabled or not st.propagate or trace_id <= 0:
            return self
        tr = _TRACER
        tls = tr._tls
        self._entered = True
        self.trace_id = trace_id
        self._saved = (tr.current_trace,
                       getattr(tls, "query_depth", 0),
                       getattr(tls, "sink", None),
                       getattr(tls, "wire_role", None))
        tr.set_trace(trace_id)
        tls.query_depth = self._saved[1] + 1
        role = self._role or get_role()
        tls.wire_role = role
        if st.dir:
            try:
                os.makedirs(st.dir, exist_ok=True)
                path = os.path.join(
                    st.dir,
                    f"trace_{trace_id:08d}_{role}{os.getpid()}.jsonl")
                tls.sink = _SpanSink(path, role)
                self._sink = tls.sink
            except Exception:   # unwritable dir: record to the buffer
                tls.sink = self._saved[2]
        ctx = self._ctx or {}
        self._span = span("fleet", "fleet.adopt", role=role,
                          remote_parent=ctx.get("parent") or 0,
                          remote_role=ctx.get("role") or "",
                          remote_pid=ctx.get("pid") or 0)
        self._span.__enter__()
        return self

    def __exit__(self, exc_type, exc, tb):
        if not self._entered:
            return False
        # close the adopt span BEFORE restoring the sink: its record
        # must land in the adopted trace's file, not the local buffer
        self._span.__exit__(exc_type, exc, tb)
        tr = _TRACER
        tls = tr._tls
        tr.set_trace(self._saved[0])
        tls.query_depth = self._saved[1]
        tls.sink = self._saved[2]
        tls.wire_role = self._saved[3]
        if self._sink is not None:
            self._sink.close()
        return False


def wire_scope(ctx: Optional[dict], role: Optional[str] = None) -> _WireScope:
    """Adopt ``ctx`` (a :func:`wire_context` dict off the wire) for the
    duration of the scope. A ``None``/invalid context, tracing off, or
    propagation off all degrade to a no-op scope."""
    return _WireScope(ctx, role)


# ---------------------------------------------------------------------------
# exporters
# ---------------------------------------------------------------------------

def chrome_trace(spans: list[Span]) -> dict:
    """Chrome-trace JSON object (Perfetto / chrome://tracing loadable):
    complete ('ph': 'X') events with microsecond ts/dur."""
    pid = os.getpid()
    events = []
    for s in spans:
        events.append({
            "name": s.name, "cat": s.cat, "ph": "X",
            "ts": s.ts_ns / 1000.0, "dur": s.dur_ns / 1000.0,
            "pid": pid, "tid": s.tid,
            "args": dict(s.attrs, trace=s.trace_id, span=s.span_id,
                         parent=s.parent_id),
        })
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"dropped_spans": _TRACER.dropped,
                          "epoch_wall": _TRACER.epoch_wall}}


def export_chrome(path: str, trace_id: Optional[int] = None,
                  spans: Optional[list] = None) -> int:
    """Write a Chrome-trace JSON file; returns the span count.
    ``spans`` skips the merge for callers that already snapshotted."""
    if spans is None:
        spans = _TRACER.spans(trace_id)
    tmp = path + ".part"
    with open(tmp, "w") as f:
        json.dump(chrome_trace(spans), f)
    os.replace(tmp, path)
    return len(spans)


def export_jsonl(path: str, trace_id: Optional[int] = None,
                 spans: Optional[list] = None) -> int:
    """Write the JSONL event log (one span per line, timeline order);
    returns the span count. ``spans`` as in :func:`export_chrome`."""
    if spans is None:
        spans = _TRACER.spans(trace_id)
    tmp = path + ".part"
    with open(tmp, "w") as f:
        for s in spans:
            f.write(json.dumps(_span_line(s), default=str) + "\n")
    os.replace(tmp, path)
    return len(spans)


def read_jsonl(path: str) -> list[Span]:
    """Load a JSONL event log back into Span records (trace_report).
    Malformed lines are skipped — a SIGKILLed process's streamed sink
    file may end mid-write, and the intact prefix is the evidence."""
    return [Span.from_dict(d) for d in read_jsonl_raw(path)]


def read_jsonl_raw(path: str) -> list[dict]:
    """The JSONL event log as raw dicts, keeping the cross-process keys
    (role/pid/wall) that :class:`Span` does not model — the stitch
    renderer's loader. Skips malformed/truncated lines like
    :func:`read_jsonl`."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                d = json.loads(line)
            except ValueError:
                continue
            if isinstance(d, dict) and "span" in d:
                out.append(d)
    return out


def export_trace_dir(trace_dir: str, trace_id: int) -> tuple[str, str]:
    """Per-query export into ``auron.trace.dir``: Chrome trace + JSONL,
    named by trace id. Returns the two paths."""
    os.makedirs(trace_dir, exist_ok=True)
    chrome = os.path.join(trace_dir, f"trace_{trace_id:08d}.json")
    jsonl = os.path.join(trace_dir, f"trace_{trace_id:08d}.jsonl")
    spans = _TRACER.spans(trace_id)   # one merge+sort for both files
    export_chrome(chrome, spans=spans)
    export_jsonl(jsonl, spans=spans)
    return chrome, jsonl
