"""Span plane: the engine's query→stage→task→operator→event timeline.

Dapper-shaped tracing for the runtime (PAPERS.md distributed-tracing
line): every recovery- or latency-relevant boundary opens a *span*
(named, categorized, attributed, nested via a per-thread stack) or drops
a zero-duration *event*. What the reference gets from pprof HTTP
endpoints plus log archaeology — "what happened when" across retries,
shuffle fetches, spills, compiles and watchdog decisions — is here one
timeline, exportable two ways:

- Chrome-trace JSON (``export_chrome``): the ``{"traceEvents": [...]}``
  format Perfetto / chrome://tracing load directly;
- JSONL (``export_jsonl``): one span per line for programmatic
  consumption (``tools/trace_report.py``).

Recording contract (the <2% overhead budget, PERF.md):

- **disabled hot path**: one cached config-epoch compare (the
  fault-plane pattern, runtime/faults.py) — no lock, no dict lookup;
- **enabled recording is lock-free**: each thread appends to its own
  buffer (registered once under the tracer lock); merge happens only at
  export/snapshot time. The ``auron.trace.max_spans`` cap is enforced
  with the same lock-freedom, so it is approximate by design.

Span identity is stable and deterministic per process: monotonic
counters assign trace ids (one per top-level query scope) and span ids
(global), never wall-clock or randomness, so two runs of the same
single-threaded pipeline number their spans identically.

Config surface: ``auron.trace.{enabled,dir,events,max_spans}``
(config.py). The knobs are deliberately NOT trace-semantic in the
program-cache sense (config.TRACE_SEMANTIC_KEYS): flipping tracing must
never retrace a kernel.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from typing import NamedTuple, Optional

from auron_tpu.obs import flight_recorder as _flight

#: span categories (the auron.trace.events allowlist vocabulary).
#: The ``mesh`` category carries the SPMD plane's routing AND fault
#: domain: ``exchange.route`` (per-exchange routing decision),
#: ``mesh.gang`` (gang-door occupancy), ``exchange.demote`` (mid-query
#: route demotion with reason/recompute cost), ``mesh.straggler``
#: (round slower than straggler_factor × rolling p50) and
#: ``mesh.quarantine`` (device retired from future submeshes) —
#: tools/mesh_report.py prints all of them.
#: The ``cache`` category is the warm-path serving plane
#: (auron_tpu/cache): ``cache.hit`` / ``cache.miss`` / ``cache.store``
#: / ``cache.evict`` on the result/subplan cache and ``aot.warm``
#: spans around each ahead-of-time plan warming at Session init.
CATEGORIES = ("query", "task", "program", "shuffle", "spill", "fault",
              "watchdog", "memory", "sched", "mesh", "journal", "cache")

_SPAN_IDS = itertools.count(1)     # next() is GIL-atomic
_TRACE_IDS = itertools.count(1)


class _Settings(NamedTuple):
    enabled: bool
    dir: str
    events: Optional[frozenset]    # None = every category
    max_spans: int


#: (config epoch, settings) — the disabled check must cost one int
#: compare (same verdict-cache shape as runtime/faults._CACHED)
_CACHED: tuple[int, Optional[_Settings]] = (-1, None)


def _settings() -> _Settings:
    global _CACHED
    from auron_tpu import config as cfg
    epoch, st = _CACHED
    if epoch == cfg.config_epoch() and st is not None:
        return st
    # read the epoch BEFORE the values: a concurrent set() bumps it
    # after we read, so a stale cache entry misses on the next call
    epoch = cfg.config_epoch()
    conf = cfg.get_config()
    ev = conf.get(cfg.TRACE_EVENTS)
    cats = frozenset(c.strip() for c in ev.split(",") if c.strip())
    st = _Settings(
        enabled=conf.get(cfg.TRACE_ENABLED),
        dir=conf.get(cfg.TRACE_DIR),
        events=cats or None,
        max_spans=conf.get(cfg.TRACE_MAX_SPANS),
    )
    _CACHED = (epoch, st)
    return st


class Span:
    """One finished span (events are zero-duration spans)."""

    __slots__ = ("trace_id", "span_id", "parent_id", "cat", "name",
                 "ts_ns", "dur_ns", "tid", "attrs")

    def __init__(self, trace_id, span_id, parent_id, cat, name, ts_ns,
                 dur_ns, tid, attrs):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.cat = cat
        self.name = name
        self.ts_ns = ts_ns
        self.dur_ns = dur_ns
        self.tid = tid
        self.attrs = attrs

    def to_dict(self) -> dict:
        return {"trace": self.trace_id, "span": self.span_id,
                "parent": self.parent_id, "cat": self.cat,
                "name": self.name, "ts_us": self.ts_ns / 1000.0,
                "dur_us": self.dur_ns / 1000.0, "tid": self.tid,
                "attrs": self.attrs}

    @classmethod
    def from_dict(cls, d: dict) -> "Span":
        return cls(d["trace"], d["span"], d["parent"], d["cat"],
                   d["name"], round(d["ts_us"] * 1000.0),
                   round(d["dur_us"] * 1000.0), d["tid"],
                   d.get("attrs") or {})


class Tracer:
    """Process tracer: per-thread lock-free buffers, merged on demand."""

    def __init__(self):
        self._lock = threading.Lock()
        self._buffers: list[list[Span]] = []
        self._tls = threading.local()
        #: approximate buffered-span count (lock-free increments)
        self._count = 0
        self.dropped = 0
        #: wall-clock epoch of the monotonic ts origin (JSONL metadata)
        self.epoch_wall = time.time()
        self._t0 = time.perf_counter_ns()

    # -- recording (per-thread, lock-free) ----------------------------------

    def _buf(self) -> list:
        buf = getattr(self._tls, "buf", None)
        if buf is None:
            buf = []
            with self._lock:
                self._buffers.append(buf)
            self._tls.buf = buf
        return buf

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = []
            self._tls.stack = st
        return st

    @property
    def current_trace(self) -> int:
        return getattr(self._tls, "trace", 0)

    def set_trace(self, trace_id: int) -> None:
        self._tls.trace = trace_id

    def now_ns(self) -> int:
        return time.perf_counter_ns() - self._t0

    def record(self, span: Span, max_spans: int) -> None:
        if self._count >= max_spans:
            self.dropped += 1
            return
        self._buf().append(span)
        self._count += 1

    # -- merge / export ------------------------------------------------------

    def spans(self, trace_id: Optional[int] = None) -> list[Span]:
        """Merged snapshot of every thread's buffer, timeline-ordered."""
        with self._lock:
            buffers = list(self._buffers)
        out: list[Span] = []
        for buf in buffers:
            out.extend(buf[:len(buf)])   # len() pins a consistent prefix
        if trace_id is not None:
            out = [s for s in out if s.trace_id == trace_id]
        out.sort(key=lambda s: (s.ts_ns, s.span_id))
        return out

    def drop(self, trace_id: int) -> None:
        """Forget one trace's spans (post-export memory bound)."""
        with self._lock:
            buffers = list(self._buffers)
        for buf in buffers:
            n = len(buf)   # pin: the owning thread may append concurrently
            kept = [s for s in buf[:n] if s.trace_id != trace_id]
            if len(kept) != n:
                buf[:n] = kept
                self._count -= n - len(kept)

    def reset(self) -> None:
        with self._lock:
            for buf in self._buffers:
                del buf[:]
            self._count = 0
            self.dropped = 0


_TRACER = Tracer()


def tracer() -> Tracer:
    return _TRACER


def enabled() -> bool:
    return _settings().enabled


def category_enabled(cat: str) -> bool:
    """True when spans of ``cat`` would actually record — tracing on
    AND the category not excluded by auron.trace.events. Hot paths that
    pay per-item clock reads purely to feed a span should gate on this,
    not on :func:`enabled` alone."""
    st = _settings()
    return st.enabled and (st.events is None or cat in st.events)


def reset() -> None:
    """Drop every buffered span (tests, chaos-run isolation)."""
    _TRACER.reset()


# ---------------------------------------------------------------------------
# recording API
# ---------------------------------------------------------------------------

class _Noop:
    """Disabled-path span: a shared, attribute-tolerant no-op."""

    __slots__ = ()
    span_id = 0

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        return self


_NOOP = _Noop()


class _SpanCM:
    __slots__ = ("cat", "name", "attrs", "span_id", "_parent", "_t0",
                 "_max")

    def __init__(self, cat, name, attrs, max_spans):
        self.cat = cat
        self.name = name
        self.attrs = attrs
        self._max = max_spans

    def set(self, **attrs):
        """Attach attributes discovered mid-span (bytes read, rows...)."""
        self.attrs.update(attrs)
        return self

    def __enter__(self):
        tr = _TRACER
        stack = tr._stack()
        self._parent = stack[-1] if stack else 0
        self.span_id = next(_SPAN_IDS)
        stack.append(self.span_id)
        self._t0 = tr.now_ns()
        return self

    def __exit__(self, exc_type, exc, tb):
        tr = _TRACER
        stack = tr._stack()
        # pop by identity, not position: spans held open across
        # generator yields (shuffle.fetch, spill.read wrap streams) can
        # exit out of LIFO order when a consumer interleaves two
        # streams — a positional pop would strand the dead id on the
        # stack forever, misparenting every later span on the thread
        if stack and stack[-1] == self.span_id:
            stack.pop()
        else:
            try:
                stack.remove(self.span_id)
            except ValueError:
                pass
        if exc_type is not None:
            self.attrs.setdefault("error", exc_type.__name__)
        t0 = self._t0
        dur = tr.now_ns() - t0
        # flight-recorder tee (obs/flight_recorder): completed spans
        # join the always-on ring — attrs are final here (error set)
        _flight.tee(self.cat, self.name, self.attrs, dur_ns=dur)
        tr.record(Span(tr.current_trace, self.span_id, self._parent,
                       self.cat, self.name, t0, dur,
                       threading.get_ident(), self.attrs), self._max)
        return False


def span(cat: str, name: str, **attrs):
    """Open a span (context manager). Disabled / filtered categories
    return a shared no-op whose cost is the settings check."""
    st = _settings()
    if not st.enabled or (st.events is not None and cat not in st.events):
        return _NOOP
    return _SpanCM(cat, name, attrs, st.max_spans)


def event(cat: str, name: str, **attrs) -> None:
    """Record a zero-duration span at the current stack position.

    Tees into the always-on flight recorder BEFORE the enabled check:
    structured events (fault injections, retries, sheds, admission
    decisions) stay reconstructable even with tracing off — the
    black-box contract (obs/flight_recorder.py)."""
    _flight.tee(cat, name, attrs)
    st = _settings()
    if not st.enabled or (st.events is not None and cat not in st.events):
        return
    tr = _TRACER
    stack = tr._stack()
    tr.record(Span(tr.current_trace, next(_SPAN_IDS),
                   stack[-1] if stack else 0, cat, name, tr.now_ns(), 0,
                   threading.get_ident(), attrs), st.max_spans)


def complete_span(cat: str, name: str, start_ns: int, dur_ns: int,
                  **attrs) -> None:
    """Record an already-finished span with explicit timing — for work
    accumulated across a GENERATOR's production segments (shuffle reads,
    spill reads). Holding a ``span()`` context open across yields would
    (a) time the consumer's compute while the generator is suspended and
    (b) keep the span on the per-thread stack so every consumer-side
    span misparents under it; measuring each ``next()`` segment and
    recording once at exhaustion reports only the producer's own cost.
    Parent is the CURRENT stack top (the consumer driving the
    generator), never the span itself."""
    _flight.tee(cat, name, attrs, dur_ns=dur_ns)
    st = _settings()
    if not st.enabled or (st.events is not None and cat not in st.events):
        return
    tr = _TRACER
    stack = tr._stack()
    tr.record(Span(tr.current_trace, next(_SPAN_IDS),
                   stack[-1] if stack else 0, cat, name, start_ns,
                   dur_ns, threading.get_ident(), attrs), st.max_spans)


def stream_spanned(cat: str, name: str, it, time_counter=None, **attrs):
    """Yield ``it``'s items, timing ONLY the production segments (each
    ``next()``), and record ONE completed span at exhaustion or
    abandonment (:func:`complete_span` explains why a span must never
    stay open across yields). ``time_counter`` — an ops.base Metric —
    additionally accrues the produced nanoseconds even when tracing is
    off, for host metrics (``shuffle_read_total_time``) that ride the
    same clock. With the category off/filtered and no counter, this
    degrades to plain iteration: zero per-item overhead."""
    record = category_enabled(cat)
    if not record and time_counter is None:
        yield from it
        return
    tr = _TRACER
    it = iter(it)
    start = tr.now_ns()
    produced_ns = 0
    n = 0
    try:
        while True:
            t0 = tr.now_ns()
            try:
                item = next(it)
            except StopIteration:
                produced_ns += tr.now_ns() - t0
                break
            produced_ns += tr.now_ns() - t0
            n += 1
            yield item
    finally:
        if time_counter is not None:
            time_counter.add(produced_ns)
        if record:
            complete_span(cat, name, start, produced_ns, items=n,
                          **attrs)


class _QueryScope:
    """Top-level query scope: assigns the trace id, opens the root
    ``query.execute`` span, and exports/drops the trace when the
    OUTERMOST scope exits (nested Session.execute calls — host-fn
    children, scalar subqueries — join the enclosing trace)."""

    __slots__ = ("trace_id", "_span", "_outermost", "_entered",
                 "_label")

    def __init__(self, label: str):
        self._label = label
        self.trace_id = 0
        self._span = _NOOP
        self._outermost = False
        self._entered = False

    def __enter__(self):
        st = _settings()
        if not st.enabled:
            return self
        self._entered = True
        tr = _TRACER
        depth = getattr(tr._tls, "query_depth", 0)
        tr._tls.query_depth = depth + 1
        if depth == 0:
            self.trace_id = next(_TRACE_IDS)
            tr.set_trace(self.trace_id)
            self._outermost = True
        else:
            self.trace_id = tr.current_trace
        # the span itself may be a no-op (the 'query' category can be
        # filtered by auron.trace.events) — scope bookkeeping must not
        # depend on it, or depth would leak and the trace never export
        self._span = span("query", "query.execute", label=self._label)
        self._span.__enter__()
        return self

    def __exit__(self, exc_type, exc, tb):
        self._span.__exit__(exc_type, exc, tb)
        if not self._entered:
            return False
        tr = _TRACER
        tr._tls.query_depth = max(getattr(tr._tls, "query_depth", 1) - 1,
                                  0)
        if self._outermost:
            # leave no stale trace id on the thread: spans recorded
            # BETWEEN queries (session init, watchdog probes) must not
            # tag themselves onto an already-exported trace
            tr.set_trace(0)
            st = _settings()
            if st.dir:
                # best-effort like every observability sink: an
                # unwritable trace dir must never discard the query
                # result computed inside the scope (or shadow the
                # query's own exception)
                try:
                    export_trace_dir(st.dir, self.trace_id)
                except Exception:
                    import logging
                    logging.getLogger(__name__).exception(
                        "trace export to %r failed", st.dir)
                finally:
                    tr.drop(self.trace_id)
        return False


def query_scope(label: str = "") -> _QueryScope:
    return _QueryScope(label)


# ---------------------------------------------------------------------------
# exporters
# ---------------------------------------------------------------------------

def chrome_trace(spans: list[Span]) -> dict:
    """Chrome-trace JSON object (Perfetto / chrome://tracing loadable):
    complete ('ph': 'X') events with microsecond ts/dur."""
    pid = os.getpid()
    events = []
    for s in spans:
        events.append({
            "name": s.name, "cat": s.cat, "ph": "X",
            "ts": s.ts_ns / 1000.0, "dur": s.dur_ns / 1000.0,
            "pid": pid, "tid": s.tid,
            "args": dict(s.attrs, trace=s.trace_id, span=s.span_id,
                         parent=s.parent_id),
        })
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"dropped_spans": _TRACER.dropped,
                          "epoch_wall": _TRACER.epoch_wall}}


def export_chrome(path: str, trace_id: Optional[int] = None,
                  spans: Optional[list] = None) -> int:
    """Write a Chrome-trace JSON file; returns the span count.
    ``spans`` skips the merge for callers that already snapshotted."""
    if spans is None:
        spans = _TRACER.spans(trace_id)
    tmp = path + ".part"
    with open(tmp, "w") as f:
        json.dump(chrome_trace(spans), f)
    os.replace(tmp, path)
    return len(spans)


def export_jsonl(path: str, trace_id: Optional[int] = None,
                 spans: Optional[list] = None) -> int:
    """Write the JSONL event log (one span per line, timeline order);
    returns the span count. ``spans`` as in :func:`export_chrome`."""
    if spans is None:
        spans = _TRACER.spans(trace_id)
    tmp = path + ".part"
    with open(tmp, "w") as f:
        for s in spans:
            f.write(json.dumps(s.to_dict()) + "\n")
    os.replace(tmp, path)
    return len(spans)


def read_jsonl(path: str) -> list[Span]:
    """Load a JSONL event log back into Span records (trace_report)."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(Span.from_dict(json.loads(line)))
    return out


def export_trace_dir(trace_dir: str, trace_id: int) -> tuple[str, str]:
    """Per-query export into ``auron.trace.dir``: Chrome trace + JSONL,
    named by trace id. Returns the two paths."""
    os.makedirs(trace_dir, exist_ok=True)
    chrome = os.path.join(trace_dir, f"trace_{trace_id:08d}.json")
    jsonl = os.path.join(trace_dir, f"trace_{trace_id:08d}.jsonl")
    spans = _TRACER.spans(trace_id)   # one merge+sort for both files
    export_chrome(chrome, spans=spans)
    export_jsonl(jsonl, spans=spans)
    return chrome, jsonl
