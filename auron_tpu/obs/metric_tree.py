"""Mirrored metric tree: per-plan-node metrics, EXPLAIN ANALYZE.

The reference walks the native plan tree on task end and copies each
operator's metric values onto the matching Spark SQLMetrics node *by
position* (``update_metric_node``, auron/src/rt.rs:302-308) — the plan
the user sees in the UI is annotated with what actually happened. Here
the host plan IS the PhysicalOp tree, so the mirror is: build a
``MetricNode`` tree positionally congruent with the plan
(:func:`build_tree`), then after each finished task fold that task's
per-op metric sets into the nodes (:func:`mirror` — ExecContext records
a *per-instance* MetricsSet for every op that reported metrics, see
ops/base.ExecContext.metrics_for). Values accumulate across tasks/
partitions, exactly like SQLMetrics sum over Spark tasks.

Canonical metric names follow the reference (NativeHelper.scala:170-238):
``elapsed_compute`` (ns), ``output_rows``, ``output_batches``,
``mem_spill_count``/``mem_spill_size``, ``shuffle_write_total_time``/
``shuffle_read_total_time``, plus this engine's dispatch-decision
counters (``dispatch_hashtable``, ``dispatch_sort``, ...).

``render`` produces the EXPLAIN ANALYZE text
(DataFrame.explain(analyze=True), tools/explain_report.py).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

#: display order: the canonical trio first, then everything sorted
_CANONICAL = ("output_rows", "output_batches", "elapsed_compute")


@dataclass
class MetricNode:
    """One plan node's mirrored metrics (positionally congruent with the
    PhysicalOp tree it was built from)."""

    name: str
    op_repr: str
    metrics: dict = field(default_factory=dict)
    children: list = field(default_factory=list)

    def add(self, values: dict) -> None:
        for k, v in values.items():
            self.metrics[k] = self.metrics.get(k, 0) + v

    def walk(self):
        yield self
        for c in self.children:
            yield from c.walk()


def build_tree(op) -> MetricNode:
    """A MetricNode tree positionally mirroring ``op``'s plan tree."""
    return MetricNode(op.name, repr(op),
                      children=[build_tree(c) for c in op.children])


def mirror(node: MetricNode, op, ctx) -> None:
    """Fold one finished task's per-op metric sets into the tree — the
    positional walk of the reference's update_metric_node. ``node`` must
    have been built from this exact ``op`` tree (same positions)."""
    for ms in ctx.op_metric_sets(op):
        node.add(ms.snapshot())
    for child_node, child_op in zip(node.children, op.children):
        mirror(child_node, child_op, ctx)


def _fmt_value(name: str, v) -> str:
    # the engine's naming contract: every ``elapsed_*`` counter
    # (elapsed_compute, the profiler's elapsed_device / elapsed_host_*)
    # and every ``*_time`` counter (io_time, shuffle_*_total_time) is a
    # nanosecond wall, rendered as milliseconds
    if name.startswith("elapsed_") or name.endswith("_time"):
        return f"{v / 1e6:.1f}ms"
    if name.endswith("_size") or name.endswith("_bytes"):
        if v >= 1 << 20:
            return f"{v / (1 << 20):.1f}MiB"
        if v >= 1 << 10:
            return f"{v / (1 << 10):.1f}KiB"
    return str(v)


def _annotation(metrics: dict) -> str:
    if not metrics:
        return ""
    names = [n for n in _CANONICAL if n in metrics]
    names += sorted(n for n in metrics if n not in _CANONICAL)
    parts = [f"{n}={_fmt_value(n, metrics[n])}" for n in names]
    return "  [" + ", ".join(parts) + "]"


def render(node: MetricNode, indent: int = 0) -> str:
    """EXPLAIN ANALYZE text: the plan tree annotated per node."""
    s = "  " * indent + node.op_repr + _annotation(node.metrics) + "\n"
    for c in node.children:
        s += render(c, indent + 1)
    return s


def totals(node: MetricNode) -> dict:
    """Aggregate view over the whole tree (report footers): summed
    elapsed_compute/output_rows plus node count.

    ``elapsed_compute_ms`` is a sum of PER-NODE values, and pass-through
    nodes (limits, exchange reads, scans feeding a pipeline) time their
    producer's ``next()`` INCLUSIVELY (ops/base.count_output
    ``timed=True``) — so the sum exceeds wall time whenever such nodes
    stack; treat it as attribution weight, not a wall-clock figure."""
    elapsed = rows = nodes = 0
    for n in node.walk():
        nodes += 1
        elapsed += n.metrics.get("elapsed_compute", 0)
        rows += n.metrics.get("output_rows", 0)
    return {"nodes": nodes, "elapsed_compute_ms": round(elapsed / 1e6, 3),
            "output_rows": rows}


def explain_analyze(plan, num_partitions: int = 1, mem_manager=None,
                    config=None, cancel_token=None
                    ) -> tuple[MetricNode, "object"]:
    """Run every partition of ``plan`` with a mirrored metric tree and
    return (tree, collected pyarrow table) — the engine of
    DataFrame.explain(analyze=True) and tools/explain_report.py.
    ``cancel_token`` threads the query's lifecycle/scheduler identity
    through, so an analyzed run is admitted, cancellable and
    attributed exactly like a normal one."""
    from auron_tpu.runtime.executor import collect
    tree = build_tree(plan)
    table = collect(plan, num_partitions=num_partitions,
                    mem_manager=mem_manager, config=config,
                    metric_tree=tree, cancel_token=cancel_token)
    return tree, table
