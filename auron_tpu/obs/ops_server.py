"""Ops plane: the live in-process telemetry HTTP endpoint.

The reference engine ships a runtime HTTP service for live metrics and
profiling (pprof flamegraph + heap endpoints, auron/src/http/mod.rs:
25-108; plus a Spark UI tab). Our stack had every data plane — the
process registry, the scheduler, the memmgr ledger, the mesh fault
domain, the flight recorder — but only as per-query file exports or
in-process snapshots. This module is the scrape surface that makes a
LIVE process operable:

- ``GET /metrics``  — the registry's Prometheus text exposition
  (``obs/registry.render_prometheus``), conformance-pinned;
- ``GET /healthz``  — ok-vs-degraded verdict assembled from the last
  probe-ladder report, watchdog fallback/stall counters, scheduler
  occupancy, memmgr pressure and the mesh plane's quarantine ledger;
- ``GET /queries``  — the live query table (id, running|queued, wall so
  far, tasks done/total, per-query memory vs quota, program-cache
  hits) across every scheduler in the process;
- ``GET /flight``   — the always-on flight recorder's ring as JSONL
  (``?query=<id>`` filters, ``?last=N`` tails).

One server per process, REFCOUNTED: every Session (and AuronServer)
built while ``auron.ops.enabled`` is on acquires it; the last close
releases and stops it. ``auron.ops.port`` 0 binds an ephemeral port,
logged at startup and surfaced as ``Session.ops_address`` / the
AuronServer ``ops_port`` stat. Handlers are read-only and best-effort:
a scrape can never mutate engine state, and a failing collector answers
500 instead of wedging the socket.
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

logger = logging.getLogger("auron_tpu.ops")


# ---------------------------------------------------------------------------
# collectors (read-only views over the process's planes)
# ---------------------------------------------------------------------------

def health() -> dict:
    """The /healthz body: per-plane state plus an overall verdict.
    ``degraded`` (not dead — the process is still serving) when the
    accelerator probe failed, a watchdog CPU fallback was taken, mesh
    devices sit in quarantine, or a memmgr runs past 90% of budget."""
    reasons: list[str] = []
    out: dict = {"status": "ok"}
    try:
        from auron_tpu.runtime import watchdog
        probe = watchdog.last_probe_report()
        out["probe"] = probe.to_dict() if probe is not None else None
        if probe is not None and not probe.ok:
            reasons.append(f"probe_failed:{probe.summary()}")
        wd = watchdog.stats()
        out["watchdog"] = wd
        if wd.get("fallbacks"):
            reasons.append("watchdog_cpu_fallback")
    except Exception:   # pragma: no cover - collectors best-effort
        out["watchdog"] = None
    try:
        from auron_tpu.runtime import scheduler
        out["scheduler"] = scheduler.aggregate_states()
    except Exception:   # pragma: no cover
        out["scheduler"] = None
    try:
        from auron_tpu.memmgr import manager as _mgr
        statuses = _mgr.aggregate_status()
        out["memmgr"] = statuses
        for st in statuses:
            if st["total"] > 0 and st["used"] / st["total"] > 0.9:
                reasons.append(
                    f"memory_pressure:{st['used']}/{st['total']}")
    except Exception:   # pragma: no cover
        out["memmgr"] = None
    try:
        from auron_tpu.parallel import mesh as _mesh
        plane = _mesh.current_plane()
        if plane is not None:
            st = plane.stats()
            out["mesh"] = st
            if st.get("quarantined"):
                reasons.append(
                    f"mesh_quarantined:{st['quarantined']}")
        else:
            out["mesh"] = None
    except Exception:   # pragma: no cover
        out["mesh"] = None
    if reasons:
        out["status"] = "degraded"
        out["reasons"] = reasons
    return out


def queries() -> dict:
    """The /queries body: live table + per-scheduler admission stats
    (the same table the serving STATS frame answers)."""
    from auron_tpu.runtime import scheduler
    table = scheduler.aggregate_query_table()
    admission: dict = {}
    for s in list(scheduler._SCHEDULERS):
        st = s.stats()
        ent = admission.setdefault(
            s.name, {"admitted": 0, "rejected": 0, "dequeued": 0})
        for k in ent:
            ent[k] += st[k]
    out = {"queries": table, "admission": admission}
    try:
        from auron_tpu.cache import aot as _aot
        from auron_tpu.cache import result_cache as _rcache
        out["cache"] = _rcache.get_cache().stats()
        out["aot"] = _aot.last_stats()
        # warm inventory for the fleet router's affinity routing: the
        # plan fingerprints this process can serve from its result
        # cache without executing anything
        out["warm_plan_fps"] = _rcache.get_cache().warm_plan_fps()
    except Exception:   # pragma: no cover - cache plane optional
        pass
    try:
        from auron_tpu import config as _cfg
        from auron_tpu.runtime import journal as _jrn
        jdir = _cfg.get_config().get(_cfg.JOURNAL_DIR)
        if jdir:
            # failover inventory: which journaled queries under the
            # (fleet-shared) journal dir could a survivor RESUME
            out["resume_inventory"] = _jrn.resume_inventory(jdir)
    except Exception:   # pragma: no cover - journal plane optional
        pass
    return out


# ---------------------------------------------------------------------------
# HTTP plumbing
# ---------------------------------------------------------------------------

class _OpsHandler(BaseHTTPRequestHandler):
    #: stop http.server from logging every scrape to stderr
    def log_message(self, fmt, *args):   # noqa: D102 - stdlib override
        pass

    def _reply(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _reply_json(self, obj, code: int = 200) -> None:
        self._reply(code, json.dumps(obj, indent=2,
                                     default=str).encode(),
                    "application/json")

    def do_GET(self):   # noqa: N802 - stdlib casing
        url = urlparse(self.path)
        q = parse_qs(url.query)
        try:
            self._route(url.path.rstrip("/") or "/", q)
        except BrokenPipeError:   # pragma: no cover - client went away
            pass
        except Exception as e:   # noqa: BLE001 — scrape must not wedge
            logger.exception("ops endpoint %s failed", self.path)
            try:
                self._reply(500, f"{type(e).__name__}: {e}".encode(),
                            "text/plain; charset=utf-8")
            except OSError:   # pragma: no cover
                pass

    def _route(self, path: str, q: dict) -> None:
        self._count(path)
        if path == "/metrics":
            from auron_tpu.obs import registry
            body = registry.get_registry().render_prometheus().encode()
            self._reply(200, body,
                        "text/plain; version=0.0.4; charset=utf-8")
        elif path == "/healthz":
            # degraded still answers 200 (the process IS serving —
            # degraded-vs-ok is the body's verdict, not liveness)
            self._reply_json(health())
        elif path == "/queries":
            self._reply_json(queries())
        elif path == "/flight":
            from auron_tpu.obs import flight_recorder
            query_id = (q.get("query") or [None])[0]
            last = q.get("last")
            body = flight_recorder.recorder().dump_jsonl(
                query_id=query_id,
                last=int(last[0]) if last else None).encode()
            self._reply(200, body, "application/x-ndjson")
        elif path == "/":
            self._reply_json({
                "service": "auron ops endpoint",
                "endpoints": ["/metrics", "/healthz", "/queries",
                              "/flight"]})
        else:
            self._reply(404, f"no such endpoint {path!r}\n".encode(),
                        "text/plain; charset=utf-8")

    #: the fixed label vocabulary of the scrape counter — unknown
    #: paths bucket under "other", or a port scanner looping over
    #: unique URLs would mint one counter instrument per URL (the
    #: classic Prometheus cardinality leak)
    _KNOWN_PATHS = frozenset(
        ("/metrics", "/healthz", "/queries", "/flight", "/"))

    @classmethod
    def _count(cls, path: str) -> None:
        try:
            from auron_tpu.obs import registry
            if registry.enabled():
                label = path if path in cls._KNOWN_PATHS else "other"
                registry.get_registry().counter(
                    "auron_ops_scrapes_total", path=label).inc()
        except Exception:   # pragma: no cover - telemetry best-effort
            pass


class OpsServer:
    """One process's ops endpoint (ThreadingHTTPServer on a daemon
    thread). ``address`` is the BOUND (host, port) — the ephemeral-port
    discovery surface.

    ``handler_cls`` swaps the route table (the fleet router serves its
    federated views through the same plumbing); ``context`` is exposed
    to handlers as ``self.server.context``."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 handler_cls=None, context=None):
        self._httpd = ThreadingHTTPServer((host, port),
                                          handler_cls or _OpsHandler)
        self._httpd.daemon_threads = True
        self._httpd.context = context
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> tuple:
        return self._httpd.server_address[:2]

    @property
    def port(self) -> int:
        return self.address[1]

    def start(self) -> "OpsServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="auron-ops-server")
        self._thread.start()
        logger.info("ops endpoint listening on http://%s:%d "
                    "(/metrics /healthz /queries /flight)",
                    *self.address)
        return self

    def stop(self) -> None:
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except Exception:   # pragma: no cover - teardown best-effort
            logger.exception("ops endpoint shutdown failed")
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


# ---------------------------------------------------------------------------
# process-wide refcounted singleton (Session / AuronServer lifecycle)
# ---------------------------------------------------------------------------

_LOCK = threading.Lock()
_SERVER: Optional[OpsServer] = None
_REFS = 0


def ensure_started(config=None) -> Optional[OpsServer]:
    """Acquire the process ops endpoint when ``auron.ops.enabled`` is
    on (None otherwise): the first acquirer binds and starts it —
    ``auron.ops.port``, 0 = ephemeral — and every acquirer must pair
    with one :func:`release`. Idempotent across Sessions: they share
    the one server."""
    from auron_tpu import config as cfg
    conf = config if config is not None else cfg.get_config()
    if not conf.get(cfg.OPS_ENABLED):
        return None
    global _SERVER, _REFS
    with _LOCK:
        if _SERVER is None:
            try:
                _SERVER = OpsServer(
                    port=int(conf.get(cfg.OPS_PORT))).start()
            except OSError:
                # a taken fixed port must not fail Session construction
                # — the ops plane is observability, never availability
                logger.exception("could not bind the ops endpoint")
                return None
        _REFS += 1
        return _SERVER


def release() -> None:
    """Drop one acquisition; the last release stops the server (the
    Session.close() clean-shutdown contract)."""
    global _SERVER, _REFS
    with _LOCK:
        if _REFS == 0:
            return
        _REFS -= 1
        if _REFS > 0 or _SERVER is None:
            return
        server, _SERVER = _SERVER, None
    server.stop()


def current() -> Optional[OpsServer]:
    with _LOCK:
        return _SERVER
