"""Hash-table-backed group state for the general aggregation path.

``HashAggState`` is the open-addressing replacement for the sort path's
incremental (batch-sort + searchsorted-merge) state: every batch runs ONE
fused program — hash keys, insert (vectorized probe rounds), scatter the
batch's accumulator contributions into the owning slots — and the O(S)
state pass disappears entirely (the table IS the state; nothing re-sorts
per batch). This is the reference AggTable's update loop
(datafusion-ext-plans/src/agg/agg_table.rs:68-356) with the row-at-a-time
probe replaced by ``hashtable.core``'s lock-step rounds.

Growth keeps the ``auron.agg.initial_capacity`` power-of-two re-bucketing
discipline: when an insert overflows its probe-round budget or occupancy
crosses ``auron.hashtable.load_factor``, the table doubles and re-inserts
itself (one program; keys re-place positionally, accumulators follow
their slots). Pathological repeat overflow — adversarial hash collisions,
not load — raises ``HashTableOverflow``, which the operator catches to
fall back to the sort path mid-stream without losing state.

``to_sorted_table()`` exports the slots as the agg path's canonical
hash-sorted 5-tuple ``(keys, accs, num_groups, cap, hashes)`` — occupied
slots sorted by hash ascending, dead slots carrying the shared sentinel
last — so emit, spill (``memmgr`` bucket spills rely on the hash-sorted
run invariant), and the partial-skip decision reuse the existing
machinery unchanged, and hash-vs-sort results stay bit-identical down to
group output order.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from auron_tpu.hashtable import core
from auron_tpu.runtime.programs import program_cache
from auron_tpu.utils.shapes import next_pow2

#: absolute slot-capacity ceiling: growth genuinely fixes load-bound and
#: tail-bound overflow (doubling halves chain lengths), so only
#: collision-pathological inputs keep overflowing — they hit this wall
#: and fall back to the sort path
_MAX_CAPACITY = 1 << 26


class HashTableOverflow(Exception):
    """Insert could not place every key within the probe-round budget at
    any sane capacity; the caller falls back to the sort path."""


def _hashes(keys, cap: int) -> jax.Array:
    from auron_tpu.ops import hashing
    h = hashing.xxhash64_columns(list(keys), cap).view(jnp.uint64)
    return core.remap_hashes(h)


@program_cache("hashtable.agg_step", maxsize=128)
def _agg_step_kernel(key_meta: tuple, acc_meta: tuple, n: int, cap: int,
                     rounds: int):
    """One fused program per (key codec, acc layout, batch/table shape):
    hash + insert + store winners + scatter accumulator contributions."""

    @jax.jit
    def kernel(th, tw, store, accs, auxs, keys, contribs, live, ord_base):
        h = _hashes(keys, n)
        w = core.key_words(keys, key_meta)
        claims, slot, resolved = core.insert_loop(th, tw, h, w, live,
                                                  rounds)
        th2, tw2 = core.table_install(th, tw, h, w, claims)
        store2 = core.store_install(store, keys, key_meta, claims)
        accs2, auxs2 = core.agg_update(accs, auxs, acc_meta, slot,
                                       resolved, contribs, ord_base)
        n_new = jnp.sum(core.batch_owned(claims).astype(jnp.int32))
        overflow = jnp.any(live & ~resolved)
        return th2, tw2, store2, accs2, auxs2, n_new, overflow

    return kernel


@program_cache("hashtable.agg_grow", maxsize=64)
def _grow_kernel(key_meta: tuple, acc_meta: tuple, old_cap: int,
                 new_cap: int, rounds: int):
    """Re-bucket: re-insert every occupied slot into an empty table of
    ``new_cap`` (stored hashes reused; equality words recomputed from the
    stored original values) and move accumulators to their new slots."""
    W = core.total_words(key_meta)

    @jax.jit
    def kernel(th, store, accs, auxs):
        occupied = th != core.EMPTY
        cols = core.store_columns(store, key_meta)
        w = core.key_words(cols, key_meta)
        nth = jnp.full(new_cap, core.EMPTY, jnp.uint64)
        ntw = jnp.zeros((new_cap, W), jnp.uint64)
        claims, _slot, resolved = core.insert_loop(nth, ntw, th, w,
                                                   occupied, rounds)
        nth, ntw = core.table_install(nth, ntw, th, w, claims)
        nstore = core.store_install(
            core.empty_store(key_meta, new_cap), cols, key_meta, claims)
        # accumulators follow their keys: each batch-won new slot gathers
        # the old slot's acc through claims (claims[new] = old slot id)
        won = core.batch_owned(claims)
        cw = jnp.clip(claims, 0, old_cap - 1)
        naccs, nauxs = [], []
        for (kind, dt), acc, aux in zip(acc_meta, accs, auxs):
            neutral = core.neutral_like(kind, jnp.dtype(dt))
            naccs.append(jnp.where(won, acc[cw], neutral))
            nauxs.append(jnp.where(won, aux[cw], core.ORD_NONE)
                         if kind == "first" else None)
        return (nth, ntw, nstore, tuple(naccs), tuple(nauxs),
                jnp.any(occupied & ~resolved))

    return kernel


@program_cache("hashtable.agg_export", maxsize=64)
def _export_kernel(key_meta: tuple, acc_meta: tuple, cap: int):
    """Slots → the hash-sorted group-table layout (dead slots last under
    the shared sentinel): the handoff that keeps emit/spill/merge
    invariants — and output group order — identical to the sort path."""
    from auron_tpu.columnar.batch import gather_column

    @jax.jit
    def kernel(th, store, accs):
        occupied = th != core.EMPTY
        ng = jnp.sum(occupied.astype(jnp.int32))
        perm = jnp.argsort(th, stable=True)     # EMPTY is max: dead last
        out_valid = jnp.arange(cap, dtype=jnp.int32) < ng
        cols = tuple(gather_column(c, perm, out_valid)
                     for c in core.store_columns(store, key_meta))
        accs_out = tuple(a[perm] for a in accs)
        return cols, accs_out, ng, th[perm]

    return kernel


def _pad_string_keys(keys, target_meta: tuple):
    """Pad narrower batch string columns up to the store's width bucket
    (zero padding keeps words and hashes unchanged)."""
    from auron_tpu.columnar.batch import StringColumn
    out = []
    for c, m in zip(keys, target_meta):
        if m[0] == "str" and c.width < m[1]:
            c = StringColumn(
                jnp.pad(c.chars, ((0, 0), (0, m[1] - c.width))),
                c.lens, c.validity)
        out.append(c)
    return tuple(out)


class HashAggState:
    """Mutable per-execution group state: the device table + slot-indexed
    accumulators, with host-driven growth. ``kinds`` is the flat
    device-reduce-kind list (ops/agg._device_kinds order)."""

    def __init__(self, kinds, initial_capacity: int = 4096,
                 load_factor: float = 0.5, max_probe_rounds: int = 64):
        self.kinds = tuple(kinds)
        self.cap = max(16, next_pow2(initial_capacity))
        self.load_factor = float(load_factor)
        self.rounds = int(max_probe_rounds)
        self.count = 0          # occupied slots (host mirror)
        self.rows_seen = 0      # global row ordinal base for 'first'
        self.key_meta = None    # set lazily on the first update
        self.acc_meta = None
        self.th = self.tw = self.store = self.accs = self.auxs = None

    # -- sizing --------------------------------------------------------------

    @property
    def built(self) -> bool:
        return self.key_meta is not None

    def nbytes(self) -> int:
        if not self.built:
            return 0
        total = self.th.nbytes + self.tw.nbytes
        for s in self.store:
            total += sum(a.nbytes for a in s)
        total += sum(a.nbytes for a in self.accs)
        total += sum(a.nbytes for a in self.auxs if a is not None)
        return total

    # -- state transitions ---------------------------------------------------

    def _init_arrays(self, keys, contribs) -> None:
        self.key_meta = core.key_meta(keys)
        self.acc_meta = tuple(
            (kind, str(np.dtype(v.dtype)))
            for kind, v in zip(self.kinds, contribs))
        W = core.total_words(self.key_meta)
        self.th = jnp.full(self.cap, core.EMPTY, jnp.uint64)
        self.tw = jnp.zeros((self.cap, W), jnp.uint64)
        self.store = core.empty_store(self.key_meta, self.cap)
        self.accs, self.auxs = core.init_accs(self.acc_meta, self.cap)

    def _unify_widths(self, keys):
        """Reconcile per-batch string width buckets with the store's: pad
        the narrower side (a wider batch widens the store, rebuilding the
        word matrix with zero blocks in the new char-word positions)."""
        meta = core.key_meta(keys)
        if meta == self.key_meta:
            return keys
        widen = core.string_width_drift(meta, self.key_meta)
        if widen:
            self.tw, self.store, self.key_meta = core.widen_string_store(
                self.tw, self.store, self.key_meta, widen)
        return _pad_string_keys(keys, self.key_meta)

    def _grow(self) -> None:
        new_cap = self.cap * 2
        while True:
            if new_cap > _MAX_CAPACITY:
                raise HashTableOverflow(
                    f"hash table stuck at {self.count} keys despite "
                    f"capacity {new_cap} (probe rounds {self.rounds})")
            kern = _grow_kernel(self.key_meta, self.acc_meta, self.cap,
                                new_cap, self.rounds)
            nth, ntw, nstore, naccs, nauxs, ovf = kern(
                self.th, self.store, self.accs, self.auxs)
            if bool(jax.device_get(ovf)):
                new_cap *= 2
                continue
            self.th, self.tw, self.store = nth, ntw, nstore
            self.accs, self.auxs = naccs, nauxs
            self.cap = new_cap
            return

    def update(self, keys, contribs, live) -> None:
        """Fold one batch (group-key columns + per-row accumulator
        contributions + live mask) into the table. One fused program plus
        one batched scalar readback — the same per-batch host-RTT budget
        as the sort path's group-count readback."""
        keys = tuple(keys)
        contribs = tuple(contribs)
        if not self.built:
            self._init_arrays(keys, contribs)
        keys = self._unify_widths(keys)
        n = int(live.shape[0])
        ord_base = jnp.asarray(self.rows_seen, jnp.int64)
        while True:
            kern = _agg_step_kernel(self.key_meta, self.acc_meta, n,
                                    self.cap, self.rounds)
            th, tw, store, accs, auxs, n_new, overflow = kern(
                self.th, self.tw, self.store, self.accs, self.auxs,
                keys, contribs, live, ord_base)
            # this readback is the per-batch sync point (pipelined mode
            # attributes the wait as device time). NOTE the donation
            # sweep deliberately skips the step/grow kernels: the
            # overflow-retry protocol re-runs them with the SAME state
            # and batch inputs, which donation would have invalidated.
            from auron_tpu.obs import profile as _profile
            n_new_h, ovf = _profile.timed_get([n_new, overflow])
            if not bool(ovf):
                self.th, self.tw, self.store = th, tw, store
                self.accs, self.auxs = accs, auxs
                self.count += int(n_new_h)
                self.rows_seen += n
                if self.count > self.load_factor * self.cap:
                    try:
                        self._grow()
                    except HashTableOverflow:
                        # the batch is already committed — raising here
                        # would double-count it when the caller falls
                        # back and re-merges. Results stay correct at
                        # high load; a later insert that genuinely
                        # cannot place surfaces the overflow PRE-commit.
                        pass
                return
            # round budget exhausted: discard this attempt (the committed
            # state is untouched), re-bucket, retry the whole batch
            self._grow()

    def to_sorted_table(self):
        """The canonical hash-sorted 5-tuple (keys, accs, num_groups,
        cap, hashes) — or None when nothing was ever inserted."""
        if not self.built:
            return None
        kern = _export_kernel(self.key_meta, self.acc_meta, self.cap)
        cols, accs, ng, h = kern(self.th, self.store, self.accs)
        return (cols, accs, ng, self.cap, h)


# ---------------------------------------------------------------------------
# single-shot traced form (flagship kernel / microbench)
# ---------------------------------------------------------------------------

def grouped_agg_once(keys, contribs, kinds, live, capacity: int,
                     max_rounds: int = 128, full_rounds: int = 1):
    """Fully traced one-batch hash aggregation: build + update + export
    in one program (no host growth loop — callers size ``capacity`` at
    >= 2x the possible distinct-key count). Returns (key_cols, accs,
    num_groups, group_valid) in SLOT order (no export sort — this is the
    cheap single-program form the bench and microbench measure); rows
    the round budget could not place are dropped (callers pick a budget
    that makes this impossible for their key distribution)."""
    keys = tuple(keys)
    meta = core.key_meta(keys)
    n = live.shape[0]
    W = core.total_words(meta)
    h = _hashes(keys, n)
    w = core.key_words(keys, meta)
    th = jnp.full(capacity, core.EMPTY, jnp.uint64)
    tw = jnp.zeros((capacity, W), jnp.uint64)
    claims, slot, resolved = core.insert_loop(th, tw, h, w, live,
                                              max_rounds, full_rounds,
                                              tail_frac=8)
    store = core.store_install(core.empty_store(meta, capacity), keys,
                               meta, claims)
    acc_meta = tuple((k, str(np.dtype(v.dtype)))
                     for k, v in zip(kinds, contribs))
    accs, auxs = core.init_accs(acc_meta, capacity)
    accs, _auxs = core.agg_update(accs, auxs, acc_meta, slot, resolved,
                                  contribs, jnp.int64(0))
    won = core.batch_owned(claims)
    ng = jnp.sum(won.astype(jnp.int32))
    return core.store_columns(store, meta), accs, ng, won
