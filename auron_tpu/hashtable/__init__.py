"""Device-resident vectorized hash table (the engine's missing data
structure: reference AggExec/JoinHashMap are open-addressing tables,
agg_table.rs:68-356 + join_hash_map.rs:44-365).

Three public operations, all built from JAX primitives and traceable
into any jit program:

- ``build``  — insert key columns, get stable slot ids
  (``DeviceHashTable.insert`` / the traced ``core.insert_loop``);
- ``probe``  — lookup-only (``DeviceHashTable.probe``, and the
  hash-join candidate index ``build_join_index``/``JoinHashIndex``);
- ``agg_update`` — slot-indexed accumulator scatters
  (``core.agg_update``; fused per-batch into ``HashAggState.update``).

Every compile site registers with the central program-cache registry
(runtime/programs.py): hashtable.agg_step / agg_grow / agg_export /
build / probe / grow / join_index — visible in tools/compile_report.py
and bounded by ``auron.max_live_programs``.
"""

from auron_tpu.hashtable.agg import (HashAggState, HashTableOverflow,
                                     grouped_agg_once)
from auron_tpu.hashtable.core import SUPPORTED_KINDS
from auron_tpu.hashtable.table import (DeviceHashTable, JoinHashIndex,
                                       build_join_index)

__all__ = [
    "DeviceHashTable", "HashAggState", "HashTableOverflow",
    "JoinHashIndex", "SUPPORTED_KINDS", "build_join_index",
    "grouped_agg_once",
]
