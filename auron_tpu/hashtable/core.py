"""Device-resident open-addressing hash table — traced building blocks.

The reference's AggExec and JoinHashMap are open-addressing tables probed
row-at-a-time (reference: datafusion-ext-plans/src/agg/agg_table.rs:68-356,
joins/join_hash_map.rs:44-365). A sequential probe chain is hostile to a
vector machine, but the probe LOOP itself vectorizes — with one twist that
makes it fast on an XLA backend: random scatters are the expensive
primitive (two orders of magnitude over gathers on the CPU mesh), so the
insert is shaped to spend exactly ONE scatter per round and none on
installs.

**Claim-owner rounds (scatter-claim + gather-verify).** Every unresolved
row probes its cursor slot in lock-step. Rows at unowned slots race
through a single scatter-min of their row id (the claim); then EVERY row
gathers the slot's owner and verifies key equality against the owner's
words — so duplicates resolve in the same round their winner claims, and
rows that hit a different key advance their cursor (double hashing: an
odd, hash-derived step keeps probe chains logarithmic). The claims array
itself becomes the table update: after the loop, slot contents (hash,
words, stored key values) are pure GATHERS of each slot's winning row.

**Compacted tail.** Round one resolves the overwhelming mass of rows;
survivors are collision chains. Rather than paying full-width rounds for
a shrinking set, the loop compacts unresolved rows once — a packed
``jnp.sort`` of (resolved-bit | row-id), ~7x cheaper than argsort — and
finishes them in narrow rounds over a bounded tail buffer. Rows the tail
cannot hold (or that exhaust the round budget) report as unresolved and
the caller grows the table and retries, the same power-of-two
re-bucketing discipline as the sort path's capacity growth.

The **key codec** encodes group/join keys of primitive, string, and
decimal128 columns into canonical uint64 words — NULL rows as a zeroed
word vector under a 0 validity word (null == null, as group keys
require), floats through ``hashing.canonicalize_float`` (-0.0 == 0.0,
one NaN) — so equality is an exact word compare, while the slot-indexed
**store** keeps each key's ORIGINAL column values (first-occurrence
bits, because claim winners are minimum row ids and duplicates probe in
lock-step) for emit: the same representative the sort path's stable
sort picks, bit-for-bit.

``agg_update`` scatters accumulator contributions into their owning
slots for the reassociation-exact reduce kinds (sum/min/max/or/first) —
the replacement for sort + segment-reduce on the general-agg hot path.

Sentinel discipline: an empty slot holds ``EMPTY`` (the sort path's
``_HASH_SENTINEL``); real hashes equal to it are remapped to
``EMPTY - 1`` before insert AND probe, so occupancy stays decidable and
exported tables keep dead slots sorted last, preserving the hash-sorted
state invariant the agg spill/merge machinery relies on.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from auron_tpu.columnar.batch import PrimitiveColumn, StringColumn

#: empty-slot sentinel — deliberately the agg path's _HASH_SENTINEL so
#: exported tables drop into the existing hash-sorted state contract.
#: numpy scalar: a module-level jnp constant would force jax backend init
#: at import time (see ops/hashing.py).
EMPTY = np.uint64(0xFFFFFFFFFFFFFFFF)

#: claims-array sentinels: a slot is unowned, owned by a pre-existing
#: table entry, or owned by batch row id >= 0
UNOWNED = np.int32(0x7FFFFFFF)
PREOWNED = np.int32(-1)

#: reduce kinds ``agg_update`` scatters exactly (bit-identical to the
#: sort path's segment reduction for any update order): integer adds are
#: associative, min/max/or are order-free, and ``first`` resolves through
#: a deterministic global row ordinal. Float sums are structurally
#: supported but reassociate — the dispatch policy keeps them off the
#: hash path unless auron.hashtable.backend=hash forces them.
SUPPORTED_KINDS = frozenset({"sum", "min", "max", "or", "first"})


def remap_hashes(h: jax.Array) -> jax.Array:
    """uint64 hashes with the (astronomically unlikely) EMPTY value moved
    to EMPTY-1, so it can never masquerade as an empty slot."""
    return jnp.where(h == EMPTY, jnp.uint64(EMPTY - np.uint64(1)), h)


# ---------------------------------------------------------------------------
# key codec
# ---------------------------------------------------------------------------

def key_meta(cols) -> tuple:
    """Static per-column codec descriptor — part of every program-cache
    key, and enough to rebuild an empty store. Raises NotImplementedError
    for column shapes without a word encoding (nested types); the
    dispatch policy routes those to the sort path before kernels build.
    """
    from auron_tpu.columnar.decimal128 import Decimal128Column
    meta = []
    for c in cols:
        if isinstance(c, StringColumn):
            meta.append(("str", int(c.width)))
        elif isinstance(c, Decimal128Column):
            meta.append(("dec",))
        elif isinstance(c, PrimitiveColumn):
            meta.append(("prim", str(np.dtype(c.data.dtype))))
        else:
            raise NotImplementedError(
                f"hashtable keys of {type(c).__name__} are not supported")
    return tuple(meta)


def words_per_column(meta_entry) -> int:
    kind = meta_entry[0]
    if kind == "prim":
        return 2                        # validity, canonical value
    if kind == "dec":
        return 3                        # validity, hi, lo
    # string: validity, length, ceil(width / 8) char words (widths are
    # bucketed to multiples of 8 — utils/shapes.bucket_string_width)
    return 2 + (meta_entry[1] + 7) // 8


def total_words(meta: tuple) -> int:
    return sum(words_per_column(m) for m in meta)


def _prim_word(col: PrimitiveColumn) -> jax.Array:
    """One canonical uint64 word per row for a primitive column."""
    from auron_tpu.ops.hashing import _f64_bits, canonicalize_float
    d = col.data
    if d.dtype == jnp.dtype(jnp.float64):
        lo, hi = _f64_bits(d)           # canonicalizes; TPU-safe bitcast
        return lo.astype(jnp.uint64) | (hi.astype(jnp.uint64) << 32)
    if d.dtype == jnp.dtype(jnp.float32):
        return canonicalize_float(d).view(jnp.uint32).astype(jnp.uint64)
    if d.dtype == jnp.bool_:
        return d.astype(jnp.uint64)
    return d.astype(jnp.int64).view(jnp.uint64)


def key_words(cols, meta: tuple) -> jax.Array:
    """uint64[n, W] canonical equality words (zeroed where invalid, so
    null keys equal each other and nothing else)."""
    ws = []
    for c, m in zip(cols, meta):
        valid = c.validity
        ws.append(valid.astype(jnp.uint64))
        zero = jnp.uint64(0)
        if m[0] == "prim":
            ws.append(jnp.where(valid, _prim_word(c), zero))
        elif m[0] == "dec":
            ws.append(jnp.where(valid, c.hi.view(jnp.uint64), zero))
            ws.append(jnp.where(valid, c.lo.view(jnp.uint64), zero))
        else:
            width = m[1]
            ws.append(jnp.where(valid, c.lens.astype(jnp.uint64), zero))
            n = c.chars.shape[0]
            padded = c.chars if width % 8 == 0 else jnp.pad(
                c.chars, ((0, 0), (0, 8 - width % 8)))
            # bytes at/after lens must not contribute (producers pad with
            # zeros, but masking here makes equality contractual)
            in_len = (jnp.arange(padded.shape[1], dtype=jnp.int32)[None, :]
                      < c.lens[:, None]) & valid[:, None]
            b = jnp.where(in_len, padded, 0).astype(jnp.uint64)
            b = b.reshape(n, -1, 8)
            shifts = (jnp.arange(8, dtype=jnp.uint64) * 8)[None, None, :]
            w64 = jnp.sum(b << shifts, axis=2)          # [n, width/8] LE
            ws.extend(w64[:, i] for i in range(w64.shape[1]))
    return jnp.stack(ws, axis=1)


def empty_store(meta: tuple, cap: int) -> tuple:
    """Slot-indexed original-value storage: one tuple of arrays per key
    column (the emit-side complement of the equality words)."""
    store = []
    for m in meta:
        if m[0] == "prim":
            store.append((jnp.zeros(cap, jnp.dtype(m[1])),
                          jnp.zeros(cap, bool)))
        elif m[0] == "dec":
            store.append((jnp.zeros(cap, jnp.int64),
                          jnp.zeros(cap, jnp.int64),
                          jnp.zeros(cap, bool)))
        else:
            store.append((jnp.zeros((cap, m[1]), jnp.uint8),
                          jnp.zeros(cap, jnp.int32),
                          jnp.zeros(cap, bool)))
    return tuple(store)


def _col_arrays(col, m) -> tuple:
    if m[0] == "prim":
        return (col.data, col.validity)
    if m[0] == "dec":
        return (col.hi, col.lo, col.validity)
    return (col.chars, col.lens, col.validity)


def store_columns(store: tuple, meta: tuple) -> tuple:
    """Rebuild key Column objects from a store (slot-indexed)."""
    from auron_tpu.columnar.decimal128 import Decimal128Column
    cols = []
    for s, m in zip(store, meta):
        if m[0] == "prim":
            cols.append(PrimitiveColumn(s[0], s[1]))
        elif m[0] == "dec":
            cols.append(Decimal128Column(s[0], s[1], s[2]))
        else:
            cols.append(StringColumn(s[0], s[1], s[2]))
    return tuple(cols)


def widen_string_store(tw, store: tuple, meta: tuple,
                       new_widths: dict) -> tuple:
    """Grow string columns' width buckets in place: pad stored chars and
    splice zero char-words into the word matrix at each widened column's
    segment (zero padding leaves hashes and the words of every stored
    key unchanged). Returns (tw, store, meta)."""
    cap = tw.shape[0]
    blocks, out_meta, out_store = [], [], []
    off = 0
    for i, m in enumerate(meta):
        w = words_per_column(m)
        seg = tw[:, off:off + w]
        s = store[i]
        if i in new_widths:
            nw = new_widths[i]
            pad_words = (nw - m[1]) // 8
            seg = jnp.concatenate(
                [seg, jnp.zeros((cap, pad_words), jnp.uint64)], axis=1)
            s = (jnp.pad(s[0], ((0, 0), (0, nw - m[1]))), s[1], s[2])
            m = ("str", nw)
        blocks.append(seg)
        out_meta.append(m)
        out_store.append(s)
        off += w
    return (jnp.concatenate(blocks, axis=1), tuple(out_store),
            tuple(out_meta))


def string_width_drift(batch_meta: tuple, table_meta: tuple) -> dict:
    """{column index: new width} for batch string columns wider than the
    table's store; asserts every other shape aspect is stable."""
    widen = {}
    for i, (bm, sm) in enumerate(zip(batch_meta, table_meta)):
        if bm[0] != sm[0] or (bm[0] != "str" and bm != sm):
            raise AssertionError(
                f"hashtable key column {i} changed shape mid-stream: "
                f"{sm} -> {bm}")
        if bm[0] == "str" and bm[1] > sm[1]:
            widen[i] = bm[1]
    return widen


# ---------------------------------------------------------------------------
# install-by-gather (the claims array IS the update)
# ---------------------------------------------------------------------------

def batch_owned(claims: jax.Array) -> jax.Array:
    """bool[cap]: slots claimed by this batch (vs empty / pre-existing)."""
    return (claims != UNOWNED) & (claims != PREOWNED)


def table_install(table_h, table_w, h, w, claims):
    """Fold a finished claims map into (hashes, words): batch-won slots
    gather their winner's hash/words — no scatter touches the table."""
    won = batch_owned(claims)
    cw = jnp.clip(claims, 0, h.shape[0] - 1)
    th = jnp.where(won, h[cw], table_h)
    tw = jnp.where(won[:, None], w[cw], table_w)
    return th, tw


def store_install(store: tuple, cols, meta: tuple, claims) -> tuple:
    """Gather winners' ORIGINAL key values into batch-won slots."""
    won = batch_owned(claims)
    cw = jnp.clip(claims, 0, cols[0].validity.shape[0] - 1)
    out = []
    for s, c, m in zip(store, cols, meta):
        arrs = []
        for old, val in zip(s, _col_arrays(c, m)):
            sel = won if old.ndim == 1 else won[:, None]
            arrs.append(jnp.where(sel, val[cw], old))
        out.append(tuple(arrs))
    return tuple(out)


# ---------------------------------------------------------------------------
# probe loops
# ---------------------------------------------------------------------------

def _probe_base_step(h: jax.Array, cap: int):
    """(base slot, odd step) per row — double hashing over a power-of-two
    table: an odd step is coprime with 2^k, so every row's probe sequence
    visits all slots."""
    mask = jnp.uint64(cap - 1)
    base = (h & mask).astype(jnp.int32)
    step = (((h >> 32) & mask) | jnp.uint64(1)).astype(jnp.int32)
    return base, step


def _claim_round(claims, unresolved, pos, slot, rids, hh, ww, step,
                 table_h, table_w, h_all, w_all, cap: int):
    """One scatter-claim + gather-verify round over an arbitrary row
    subset (full batch or compacted tail). ``rids`` index into the full
    batch arrays ``h_all``/``w_all`` (owner equality gathers)."""
    n = h_all.shape[0]
    owner_pre = claims[pos]
    claimant = unresolved & (owner_pre == UNOWNED)
    cpos = jnp.where(claimant, pos, cap)
    claims = claims.at[cpos].min(rids, mode="drop")
    owner = claims[pos]
    ow = jnp.clip(owner, 0, n - 1)
    by_batch = batch_owned(owner)
    own_h = jnp.where(by_batch, h_all[ow], table_h[pos])
    own_w = jnp.where(by_batch[:, None], w_all[ow], table_w[pos])
    match = (owner != UNOWNED) & (own_h == hh) & \
        jnp.all(own_w == ww, axis=1)
    resolved = unresolved & match
    slot = jnp.where(resolved, pos, slot)
    unresolved = unresolved & ~resolved
    pos = jnp.where(unresolved, (pos + step) & jnp.int32(cap - 1), pos)
    return claims, unresolved, pos, slot


def _tail_capacity(n: int, tail_frac: int) -> int:
    """Static tail-buffer size: generous enough that only genuinely
    pathological chains overflow it (caller grows and retries)."""
    return n if n <= 4096 else max(4096, n // tail_frac)


def insert_loop(table_h: jax.Array, table_w: jax.Array, h: jax.Array,
                w: jax.Array, live: jax.Array, max_rounds: int,
                full_rounds: int = 2, tail_frac: int = 4):
    """Vectorized open-addressing insert.

    ``full_rounds`` claim rounds run at batch width (round one resolves
    the bulk: winners claim, duplicates verify against the winner in the
    same round); survivors compact once via a packed sort and finish in
    narrow rounds over a ``n/4`` tail buffer, early-exiting as soon as
    every row is resolved.

    Returns (claims[cap] int32, slot[n] int32, resolved[n] bool). Slot
    contents derive from ``claims`` by gather (``table_install`` /
    ``store_install``). ``live & ~resolved`` rows exhausted the round
    budget or overflowed the tail buffer — the caller re-buckets and
    retries (or falls back).
    """
    n = h.shape[0]
    cap = table_h.shape[0]
    # never place a key deeper than lookups are allowed to walk: a probe
    # with the same max_rounds must always be able to find it
    full_rounds = max(1, min(full_rounds, max_rounds))
    base, step = _probe_base_step(h, cap)
    rid = jnp.arange(n, dtype=jnp.int32)
    # pre-existing entries own their slots before the batch arrives
    claims = jnp.where(table_h != EMPTY, PREOWNED, UNOWNED)

    unresolved, pos, slot = live, base, jnp.zeros(n, jnp.int32)
    for _ in range(full_rounds):
        claims, unresolved, pos, slot = _claim_round(
            claims, unresolved, pos, slot, rid, h, w, step,
            table_h, table_w, h, w, cap)

    T = _tail_capacity(n, tail_frac)
    # compact survivors: resolved/dead rows sort behind the live
    # unresolved ones (packed sort ~7x cheaper than argsort)
    packed = (jnp.where(unresolved, jnp.uint64(0), jnp.uint64(1)) << 32) \
        | rid.astype(jnp.uint64)
    srt = jnp.sort(packed)[:T]
    t_rid = (srt & jnp.uint64(0xFFFFFFFF)).astype(jnp.int32)
    t_live = (srt >> 32) == 0
    t_h, t_w = h[t_rid], w[t_rid]
    t_pos, t_step = pos[t_rid], step[t_rid]

    def cond(st):
        return (st[0] < max_rounds) & jnp.any(st[1])

    def body(st):
        r, t_unres, t_pos, t_slot, claims = st
        claims, t_unres, t_pos, t_slot = _claim_round(
            claims, t_unres, t_pos, t_slot, t_rid, t_h, t_w, t_step,
            table_h, table_w, h, w, cap)
        return r + 1, t_unres, t_pos, t_slot, claims

    init = (jnp.int32(full_rounds), t_live, t_pos,
            jnp.zeros(T, jnp.int32), claims)
    _r, t_unres, _tp, t_slot, claims = lax.while_loop(cond, body, init)

    done = t_live & ~t_unres
    wb = jnp.where(done, t_rid, n)
    slot = slot.at[wb].set(t_slot, mode="drop")
    resolved = (~unresolved & live).at[wb].set(True, mode="drop") & live
    # rows that did not fit the tail buffer stay unresolved
    return claims, slot, resolved


def probe_loop(table_h: jax.Array, table_w: jax.Array, h: jax.Array,
               w: jax.Array, live: jax.Array, max_rounds: int):
    """Lookup-only probe (joins, distinct-membership): walks the same
    double-hashed sequence as ``insert_loop``; an empty slot proves
    absence (open addressing never deletes). Scatter-free — every round
    is gathers and compares. Returns (slot, found)."""
    cap = table_h.shape[0]
    base, step = _probe_base_step(h, cap)
    cmask = jnp.int32(cap - 1)

    def cond(st):
        return (st[0] < max_rounds) & jnp.any(st[1])

    def body(st):
        r, unresolved, pos, slot, found = st
        slot_h = table_h[pos]
        occupied = slot_h != EMPTY
        match = occupied & (slot_h == h) & \
            jnp.all(table_w[pos] == w, axis=1)
        hit = unresolved & match
        slot = jnp.where(hit, pos, slot)
        # keep walking only past occupied non-matching slots
        unresolved = unresolved & occupied & ~match
        pos = jnp.where(unresolved, (pos + step) & cmask, pos)
        return r + 1, unresolved, pos, slot, found | hit

    init = (jnp.int32(0), live, base, jnp.zeros(h.shape[0], jnp.int32),
            jnp.zeros(h.shape[0], bool))
    _r, _u, _p, slot, found = lax.while_loop(cond, body, init)
    return slot, found


def probe_hash_index(table_h: jax.Array, h: jax.Array, live: jax.Array,
                     max_rounds: int):
    """Degenerate probe for tables keyed on the 64-bit hash alone (the
    join candidate index): equality IS the hash compare, no words."""
    w = jnp.zeros((h.shape[0], 0), jnp.uint64)
    return probe_loop(table_h, jnp.zeros((table_h.shape[0], 0),
                                         jnp.uint64), h, w, live,
                      max_rounds)


# ---------------------------------------------------------------------------
# slot-indexed accumulator update
# ---------------------------------------------------------------------------

def neutral_like(kind: str, dtype):
    """Neutral element of a reduce kind for acc-array initialization."""
    if kind == "sum":
        return jnp.zeros((), dtype)
    if kind == "min":
        if jnp.issubdtype(dtype, jnp.floating):
            return jnp.asarray(jnp.inf, dtype)
        return jnp.asarray(jnp.iinfo(dtype).max, dtype)
    if kind == "max":
        if jnp.issubdtype(dtype, jnp.floating):
            return jnp.asarray(-jnp.inf, dtype)
        return jnp.asarray(jnp.iinfo(dtype).min, dtype)
    if kind == "or":
        return jnp.zeros((), jnp.bool_)
    if kind == "first":
        return jnp.zeros((), dtype)
    raise ValueError(kind)


#: ordinal sentinel for first-kind aux arrays (no row yet)
ORD_NONE = np.int64(0x7FFFFFFFFFFFFFFF)


def init_accs(acc_meta: tuple, cap: int):
    """(accs, auxs): neutral acc array per (kind, dtype); first-kind accs
    get a parallel int64 ordinal array (global first-row tracking)."""
    accs, auxs = [], []
    for kind, dt in acc_meta:
        accs.append(jnp.full(cap, neutral_like(kind, jnp.dtype(dt))))
        auxs.append(jnp.full(cap, ORD_NONE, jnp.int64)
                    if kind == "first" else None)
    return tuple(accs), tuple(auxs)


def agg_update(accs: tuple, auxs: tuple, acc_meta: tuple,
               slot: jax.Array, mask: jax.Array, contribs: tuple,
               ord_base) -> tuple:
    """Fold one batch's per-row contributions into slot-indexed
    accumulators. ``mask`` selects resolved live rows; ``ord_base`` is
    the global row ordinal of this batch's first row (device scalar),
    which makes ``first`` deterministic across batches: the accumulator
    keeps the value at the minimum ordinal — first batch, first row —
    matching the sort path's merge preference for earlier state."""
    cap = accs[0].shape[0] if accs else 0
    pos = jnp.where(mask, slot, cap)
    n = slot.shape[0]
    out_accs, out_auxs = [], []
    for (kind, _dt), acc, aux, v in zip(acc_meta, accs, auxs, contribs):
        if kind == "sum":
            out_accs.append(acc.at[pos].add(
                jnp.where(mask, v, jnp.zeros((), v.dtype)), mode="drop"))
            out_auxs.append(None)
        elif kind in ("min", "max"):
            # contributions already carry the reduce neutral where the
            # row's value is invalid (ops/agg._contributions)
            upd = acc.at[pos]
            out_accs.append((upd.min if kind == "min" else upd.max)(
                v, mode="drop"))
            out_auxs.append(None)
        elif kind == "or":
            hits = jnp.zeros(cap, jnp.int32).at[pos].add(
                v.astype(jnp.int32), mode="drop")
            out_accs.append(acc | (hits > 0))
            out_auxs.append(None)
        elif kind == "first":
            ordinal = ord_base + jnp.arange(n, dtype=jnp.int64)
            ordinal = jnp.where(mask, ordinal, ORD_NONE)
            new_aux = aux.at[pos].min(ordinal, mode="drop")
            # row ordinals are unique, so exactly one row writes per slot
            setter = mask & (ordinal == new_aux[slot])
            out_accs.append(acc.at[jnp.where(setter, slot, cap)].set(
                v, mode="drop"))
            out_auxs.append(new_aux)
        else:
            raise ValueError(kind)
    return tuple(out_accs), tuple(out_auxs)
