"""DeviceHashTable — the stateful build/probe surface, plus the
hash-join candidate index.

``DeviceHashTable`` is the key→slot map alone (no accumulators): build
inserts key columns and returns stable slot ids, probe is lookup-only.
Distinct/dedup and join-membership shapes use it directly; the general
aggregation path uses the fused ``HashAggState`` instead (one program
per batch including the accumulator scatters).

``build_join_index`` packages the hash-join specialization: the build
side is already sorted by 64-bit key hash (ops/joins._BuildSide), so
candidate lookup only needs ``probe hash → (run start, run length)``.
The index keys slots on the hash value itself (equality = one compare,
no words) and stores the run bounds as slot payloads; a probe becomes
O(probe rounds) gathers instead of the two O(log B) searchsorted
passes, and returns the EXACT (lo, count) pairs searchsorted would —
downstream expand + exact-key verification consume them unchanged, so
join results are bit-identical with the index on or off.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from auron_tpu.hashtable import core
from auron_tpu.runtime.programs import program_cache
from auron_tpu.utils.shapes import next_pow2


@program_cache("hashtable.build", maxsize=128)
def _build_kernel(key_meta: tuple, n: int, cap: int, rounds: int):
    @jax.jit
    def kernel(th, tw, store, keys, live):
        from auron_tpu.hashtable.agg import _hashes
        h = _hashes(keys, n)
        w = core.key_words(keys, key_meta)
        claims, slot, resolved = core.insert_loop(th, tw, h, w, live,
                                                  rounds)
        th2, tw2 = core.table_install(th, tw, h, w, claims)
        store2 = core.store_install(store, keys, key_meta, claims)
        rid = jnp.arange(n, dtype=jnp.int32)
        is_new = resolved & (claims[slot] == rid)
        n_new = jnp.sum(core.batch_owned(claims).astype(jnp.int32))
        return (th2, tw2, store2, slot, is_new, n_new,
                jnp.any(live & ~resolved))

    return kernel


@program_cache("hashtable.probe", maxsize=128)
def _probe_kernel(key_meta: tuple, n: int, cap: int, rounds: int):
    @jax.jit
    def kernel(th, tw, keys, live):
        from auron_tpu.hashtable.agg import _hashes
        h = _hashes(keys, n)
        w = core.key_words(keys, key_meta)
        return core.probe_loop(th, tw, h, w, live, rounds)

    return kernel


@program_cache("hashtable.grow", maxsize=64)
def _table_grow_kernel(key_meta: tuple, old_cap: int, new_cap: int,
                       rounds: int):
    W = core.total_words(key_meta)

    @jax.jit
    def kernel(th, store):
        occupied = th != core.EMPTY
        cols = core.store_columns(store, key_meta)
        w = core.key_words(cols, key_meta)
        nth = jnp.full(new_cap, core.EMPTY, jnp.uint64)
        ntw = jnp.zeros((new_cap, W), jnp.uint64)
        claims, slot, resolved = core.insert_loop(nth, ntw, th, w,
                                                  occupied, rounds)
        nth, ntw = core.table_install(nth, ntw, th, w, claims)
        nstore = core.store_install(
            core.empty_store(key_meta, new_cap), cols, key_meta, claims)
        return nth, ntw, nstore, slot, jnp.any(occupied & ~resolved)

    return kernel


class DeviceHashTable:
    """Key → slot-id map over canonical-word key equality (null == null,
    NaN == NaN, -0.0 == 0.0). ``insert`` returns per-row slot ids and an
    is-new mask; slot ids are stable until a growth re-bucket, which
    reports the old→new slot remap to the caller."""

    def __init__(self, initial_capacity: int = 4096,
                 load_factor: float = 0.5, max_probe_rounds: int = 64):
        self.cap = max(16, next_pow2(initial_capacity))
        self.load_factor = float(load_factor)
        self.rounds = int(max_probe_rounds)
        self.count = 0
        self.key_meta = None
        self.th = self.tw = self.store = None
        #: (old_cap, new_slot_of_old[old_cap], occupied[old_cap]) of the
        #: most recent growth — callers with slot-indexed side state
        #: consume and clear it
        self.last_remap = None

    def _init_arrays(self, keys) -> None:
        self.key_meta = core.key_meta(keys)
        W = core.total_words(self.key_meta)
        self.th = jnp.full(self.cap, core.EMPTY, jnp.uint64)
        self.tw = jnp.zeros((self.cap, W), jnp.uint64)
        self.store = core.empty_store(self.key_meta, self.cap)

    def _grow(self) -> None:
        from auron_tpu.hashtable.agg import (_MAX_CAPACITY,
                                             HashTableOverflow)
        new_cap = self.cap * 2
        while True:
            if new_cap > _MAX_CAPACITY:
                raise HashTableOverflow(
                    f"hash table stuck at {self.count} keys at capacity "
                    f"{new_cap}")
            kern = _table_grow_kernel(self.key_meta, self.cap, new_cap,
                                      self.rounds)
            nth, ntw, nstore, slot, ovf = kern(self.th, self.store)
            if bool(jax.device_get(ovf)):
                new_cap *= 2
                continue
            self.last_remap = (self.cap, slot, self.th != core.EMPTY)
            self.th, self.tw, self.store = nth, ntw, nstore
            self.cap = new_cap
            return

    def _unify_widths(self, keys):
        from auron_tpu.hashtable.agg import _pad_string_keys
        meta = core.key_meta(keys)
        if meta != self.key_meta:
            widen = core.string_width_drift(meta, self.key_meta)
            if widen:
                self.tw, self.store, self.key_meta = \
                    core.widen_string_store(self.tw, self.store,
                                            self.key_meta, widen)
        return _pad_string_keys(keys, self.key_meta)

    def insert(self, keys, live):
        """Insert live rows' keys; returns (slot[n], is_new[n])."""
        keys = tuple(keys)
        if self.key_meta is None:
            self._init_arrays(keys)
        keys = self._unify_widths(keys)
        n = int(live.shape[0])
        while True:
            kern = _build_kernel(self.key_meta, n, self.cap, self.rounds)
            th, tw, store, slot, is_new, n_new, ovf = kern(
                self.th, self.tw, self.store, keys, live)
            n_new_h, ovf_h = jax.device_get([n_new, ovf])
            if not bool(ovf_h):
                self.th, self.tw, self.store = th, tw, store
                self.count += int(n_new_h)
                if self.count > self.load_factor * self.cap:
                    self._grow()
                return slot, is_new
            self._grow()

    def probe(self, keys, live):
        """Lookup-only: (slot[n], found[n]); probe keys WIDER than the
        store's width bucket widen it first (a wider probe key can still
        equal a stored narrower one)."""
        if self.key_meta is None:
            n = int(live.shape[0])
            return jnp.zeros(n, jnp.int32), jnp.zeros(n, bool)
        keys = self._unify_widths(tuple(keys))
        n = int(live.shape[0])
        kern = _probe_kernel(self.key_meta, n, self.cap, self.rounds)
        return kern(self.th, self.tw, keys, live)

    def keys_columns(self) -> tuple:
        """Slot-indexed original key values (emit side)."""
        return core.store_columns(self.store, self.key_meta)


# ---------------------------------------------------------------------------
# hash-join candidate index
# ---------------------------------------------------------------------------

@program_cache("hashtable.join_index", maxsize=128)
def _join_index_kernel(cap: int, table_cap: int, rounds: int):
    """Hash-run index over a hash-SORTED build column: one slot per
    distinct 64-bit hash, payload = (run start, run length)."""

    @jax.jit
    def kernel(h_sorted):
        idx = jnp.arange(cap, dtype=jnp.int32)
        first = jnp.concatenate(
            [jnp.ones(1, bool), h_sorted[1:] != h_sorted[:-1]])
        run_id = jnp.cumsum(first.astype(jnp.int32)) - 1
        run_lo = jax.ops.segment_min(idx, run_id, num_segments=cap)
        run_hi = jax.ops.segment_max(idx, run_id, num_segments=cap)
        lo_row = run_lo[run_id]
        cnt_row = (run_hi - run_lo + 1)[run_id]
        th = jnp.full(table_cap, core.EMPTY, jnp.uint64)
        tw = jnp.zeros((table_cap, 0), jnp.uint64)
        w = jnp.zeros((cap, 0), jnp.uint64)     # hash IS the key
        claims, _slot, resolved = core.insert_loop(th, tw, h_sorted, w,
                                                   first, rounds)
        won = core.batch_owned(claims)
        cw = jnp.clip(claims, 0, cap - 1)
        th = jnp.where(won, h_sorted[cw], th)
        lo_arr = jnp.where(won, lo_row[cw], 0)
        cnt_arr = jnp.where(won, cnt_row[cw], 0)
        # a real build hash equal to the empty sentinel would be
        # indistinguishable from an empty slot — the host disables the
        # index for that build side (searchsorted handles it exactly)
        bad = jnp.any(h_sorted == core.EMPTY) | \
            jnp.any(first & ~resolved)
        return th, lo_arr, cnt_arr, bad

    return kernel


#: build sides larger than this keep the searchsorted candidate search
#: (the index would double their device footprint for a log-factor win
#: that large builds don't feel)
MAX_INDEX_BUILD_ROWS = 1 << 22


class JoinHashIndex:
    """Immutable probe-side index: hash → (lo, count) into the sorted
    build table. ``lookup`` is traced (usable inside fused probe
    programs)."""

    __slots__ = ("th", "lo", "cnt", "rounds", "capacity")

    def __init__(self, th, lo, cnt, rounds: int):
        self.th = th
        self.lo = lo
        self.cnt = cnt
        self.rounds = rounds
        self.capacity = int(th.shape[0])

    def lookup(self, h: jax.Array):
        """(lo[n], counts[n]) for probe hashes — the searchsorted
        contract: count 0 (lo 0) where the hash is absent."""
        live = h != core.EMPTY     # null/dead probe rows never match
        slot, found = core.probe_hash_index(self.th, h, live,
                                            self.rounds)
        lo = jnp.where(found, self.lo[slot], 0)
        counts = jnp.where(found, self.cnt[slot], 0)
        return lo, counts


def build_join_index(h_sorted: jax.Array,
                     max_probe_rounds: int = 64):
    """Index a hash-sorted build column; returns a JoinHashIndex, or
    None when the build side is too large or its hashes collide with the
    empty sentinel (callers keep the exact searchsorted path)."""
    cap = int(h_sorted.shape[0])
    if cap > MAX_INDEX_BUILD_ROWS:
        return None
    table_cap = max(16, next_pow2(cap) * 2)
    kern = _join_index_kernel(cap, table_cap, max_probe_rounds)
    th, lo, cnt, bad = kern(h_sorted)
    if bool(jax.device_get(bad)):
        return None
    return JoinHashIndex(th, lo, cnt, max_probe_rounds)
