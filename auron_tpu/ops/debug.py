"""Debug operator: logs batches flowing through (reference:
datafusion-ext-plans/src/debug_exec.rs)."""

from __future__ import annotations

import logging
from typing import Iterator

from auron_tpu.columnar.arrow_bridge import to_arrow
from auron_tpu.columnar.schema import Schema
from auron_tpu.ops.base import ExecContext, PhysicalOp, count_output

logger = logging.getLogger("auron_tpu.debug")


class DebugOp(PhysicalOp):
    name = "debug"

    def __init__(self, child: PhysicalOp, label: str = "",
                 max_preview_rows: int = 5):
        self.child = child
        self.label = label
        self.max_preview_rows = max_preview_rows

    @property
    def children(self):
        return [self.child]

    def schema(self) -> Schema:
        return self.child.schema()

    def execute(self, partition: int, ctx: ExecContext) -> Iterator:
        metrics = ctx.metrics_for(self)
        schema = self.child.schema()

        def stream():
            enabled = logger.isEnabledFor(logging.INFO)
            for i, batch in enumerate(self.child.execute(partition, ctx)):
                if enabled:
                    n = int(batch.num_rows)
                    preview = ""
                    if n and self.max_preview_rows:
                        rb = to_arrow(batch, schema)
                        preview = rb.slice(0, self.max_preview_rows).to_pydict()
                    logger.info("[debug%s] partition=%d batch=%d rows=%d "
                                "capacity=%d %s",
                                f" {self.label}" if self.label else "",
                                partition, i, n, batch.capacity, preview)
                yield batch

        return count_output(stream(), metrics, timed=True)

    def __repr__(self):
        return f"DebugOp[{self.label}]"
