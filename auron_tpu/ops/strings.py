"""Device string kernels over fixed-width byte matrices.

All operate on (chars uint8[n, w], lens int32[n]) — the padded layout from
auron_tpu.columnar.batch.StringColumn. Zero padding makes plain byte-wise
comparison coincide with lexicographic ordering (0 sorts below every byte, so
a proper prefix sorts first), which turns string sort keys into integer
columns the MXU-era sort networks can chew on.

Covers the string surface of the reference's expression/function layer
(reference: datafusion-ext-exprs/src/string_{starts_with,ends_with,
contains}.rs, datafusion-ext-functions/src/spark_strings.rs).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from auron_tpu.columnar.batch import StringColumn


def literal_to_device(s: bytes | str, width: int) -> tuple[np.ndarray, int]:
    """Encode a literal to a zero-padded row of the given width."""
    b = s.encode() if isinstance(s, str) else s
    if len(b) > width:
        # longer than any possible column value of this width
        return np.zeros(width, np.uint8), len(b)
    out = np.zeros(width, np.uint8)
    out[: len(b)] = np.frombuffer(b, np.uint8)
    return out, len(b)


def _be_words(chars: jax.Array) -> jax.Array:
    """Pack bytes into big-endian uint32 words [n, ceil(w/4)] so word-wise
    integer comparison == lexicographic byte comparison."""
    n, w = chars.shape
    pad = (-w) % 4
    if pad:
        chars = jnp.pad(chars, ((0, 0), (0, pad)))
    u = chars.astype(jnp.uint32).reshape(n, -1, 4)
    return (u[:, :, 0] << 24) | (u[:, :, 1] << 16) | (u[:, :, 2] << 8) | u[:, :, 3]


def compare(a_chars, a_lens, b_chars, b_lens):
    """Three-way compare: returns (lt, eq) bool[n] per lexicographic byte
    order. Zero padding means lens only matter for the eq tie-break when one
    is a strict prefix — handled for free because padding is 0."""
    wa = _be_words(a_chars)
    wb = _be_words(b_chars)
    k = max(wa.shape[1], wb.shape[1])
    if wa.shape[1] < k:
        wa = jnp.pad(wa, ((0, 0), (0, k - wa.shape[1])))
    if wb.shape[1] < k:
        wb = jnp.pad(wb, ((0, 0), (0, k - wb.shape[1])))
    lt = jnp.zeros(wa.shape[0], bool)
    eq = jnp.ones(wa.shape[0], bool)
    for i in range(k):
        lt = lt | (eq & (wa[:, i] < wb[:, i]))
        eq = eq & (wa[:, i] == wb[:, i])
    # equal padded bytes but different lengths cannot happen with 0-padding
    # unless values contain NUL bytes; SQL strings here never do.
    return lt, eq & (a_lens == b_lens)


def sort_key_words(col: StringColumn, max_words: int | None = None) -> jax.Array:
    """uint32[n, k] big-endian words usable as a compound sort key."""
    w = _be_words(col.chars)
    if max_words is not None and w.shape[1] > max_words:
        w = w[:, :max_words]
    return w


def starts_with(chars, lens, prefix: bytes) -> jax.Array:
    n, w = chars.shape
    if len(prefix) == 0:
        return jnp.ones(n, bool)
    if len(prefix) > w:
        return jnp.zeros(n, bool)
    lit = jnp.asarray(np.frombuffer(prefix, np.uint8))
    match = jnp.all(chars[:, : len(prefix)] == lit[None, :], axis=1)
    return match & (lens >= len(prefix))


def ends_with(chars, lens, suffix: bytes) -> jax.Array:
    n, w = chars.shape
    m = len(suffix)
    if m == 0:
        return jnp.ones(n, bool)
    if m > w:
        return jnp.zeros(n, bool)
    lit = jnp.asarray(np.frombuffer(suffix, np.uint8))
    # gather the last m bytes of each row
    start = jnp.maximum(lens - m, 0)
    idx = start[:, None] + jnp.arange(m)[None, :]
    tail = jnp.take_along_axis(chars, jnp.clip(idx, 0, w - 1), axis=1)
    return jnp.all(tail == lit[None, :], axis=1) & (lens >= m)


def contains(chars, lens, infix: bytes) -> jax.Array:
    n, w = chars.shape
    m = len(infix)
    if m == 0:
        return jnp.ones(n, bool)
    if m > w:
        return jnp.zeros(n, bool)
    lit = jnp.asarray(np.frombuffer(infix, np.uint8))
    # windows: for each start s in [0, w-m], all(chars[:, s:s+m] == lit)
    hits = jnp.zeros(n, bool)
    for s in range(w - m + 1):
        win_ok = jnp.all(chars[:, s: s + m] == lit[None, :], axis=1)
        hits = hits | (win_ok & (s + m <= lens))
    return hits


def substring(col: StringColumn, start: jax.Array, length: jax.Array) -> StringColumn:
    """1-based SQL substring with Spark semantics (negative start counts from
    the end; reference: spark_strings.rs string_substring)."""
    chars, lens = col.chars, col.lens
    n, w = chars.shape
    start = jnp.asarray(start, jnp.int32)
    length = jnp.maximum(jnp.asarray(length, jnp.int32), 0)
    # Spark UTF8String.substringSQL: start>0 → start-1; start==0 → 0;
    # start<0 → len+start UNCLAMPED — the window end is start+length
    # *before* clamping, so substring('hello', -10, 2) is '' (the window
    # [-5,-3) misses the string entirely), not 'he'
    raw = jnp.where(start > 0, start - 1,
                    jnp.where(start == 0, 0, lens + start))
    # end in int64: Spark's 2-arg substring passes length=Int.MaxValue,
    # which would wrap int32 raw+length and empty the result
    end = jnp.clip(raw.astype(jnp.int64) + length.astype(jnp.int64),
                   0, lens.astype(jnp.int64)).astype(jnp.int32)
    zero_based = jnp.clip(raw, 0, lens)
    out_len = jnp.maximum(end - zero_based, 0)
    idx = zero_based[:, None] + jnp.arange(w, dtype=jnp.int32)[None, :]
    gathered = jnp.take_along_axis(chars, jnp.clip(idx, 0, w - 1), axis=1)
    mask = jnp.arange(w, dtype=jnp.int32)[None, :] < out_len[:, None]
    return StringColumn(jnp.where(mask, gathered, 0).astype(jnp.uint8),
                        out_len, col.validity)


def concat(cols: list[StringColumn], out_width: int) -> StringColumn:
    """Concatenate string columns row-wise (null if any null — Spark concat)."""
    n = cols[0].capacity
    out = jnp.zeros((n, out_width), jnp.uint8)
    pos = jnp.zeros(n, jnp.int32)
    for c in cols:
        w = c.width
        # scatter c.chars rows at offset pos
        tgt = pos[:, None] + jnp.arange(w, dtype=jnp.int32)[None, :]
        valid = jnp.arange(w, dtype=jnp.int32)[None, :] < c.lens[:, None]
        tgt = jnp.where(valid, tgt, out_width)  # out-of-range drops
        rows = jnp.broadcast_to(jnp.arange(n)[:, None], (n, w))
        out = out.at[rows.reshape(-1), jnp.clip(tgt, 0, out_width).reshape(-1)].max(
            jnp.where(valid, c.chars, 0).reshape(-1), mode="drop")
        pos = pos + c.lens
    validity = cols[0].validity
    for c in cols[1:]:
        validity = validity & c.validity
    return StringColumn(out, jnp.where(validity, pos, 0), validity)


def upper(col: StringColumn) -> StringColumn:
    c = col.chars
    is_lower = (c >= ord("a")) & (c <= ord("z"))
    return StringColumn(jnp.where(is_lower, c - 32, c).astype(jnp.uint8),
                        col.lens, col.validity)


def lower(col: StringColumn) -> StringColumn:
    c = col.chars
    is_upper = (c >= ord("A")) & (c <= ord("Z"))
    return StringColumn(jnp.where(is_upper, c + 32, c).astype(jnp.uint8),
                        col.lens, col.validity)


def trim(col: StringColumn, left: bool = True, right: bool = True) -> StringColumn:
    chars, lens = col.chars, col.lens
    n, w = chars.shape
    pos = jnp.arange(w, dtype=jnp.int32)[None, :]
    in_str = pos < lens[:, None]
    is_space = (chars == ord(" ")) & in_str
    if right:
        nonspace_idx = jnp.where(~is_space & in_str, pos, -1)
        last_nonspace = jnp.max(nonspace_idx, axis=1)  # -1 if all spaces
        new_len = last_nonspace + 1
    else:
        new_len = lens
    if left:
        lead = jnp.where(~is_space & in_str, pos, w)
        first_nonspace = jnp.min(lead, axis=1)
        first_nonspace = jnp.minimum(first_nonspace, new_len)
        idx = first_nonspace[:, None] + pos
        chars = jnp.take_along_axis(chars, jnp.clip(idx, 0, w - 1), axis=1)
        new_len = new_len - first_nonspace
    mask = pos < new_len[:, None]
    return StringColumn(jnp.where(mask, chars, 0).astype(jnp.uint8),
                        jnp.maximum(new_len, 0), col.validity)
