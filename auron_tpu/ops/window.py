"""Window operator.

Reference: datafusion-ext-plans/src/window_exec.rs + window/processors/*
(rank, row_number, dense_rank, lead/lag, nth_value, percent_rank, cume_dist,
agg-over-window) and the window-group-limit pushdown (auron.proto:590-593).

TPU design: the reference streams rows through per-partition processor state
(a sequential scan). Sequential row processing is hostile to a vector
machine, so here the whole operator is one data-parallel kernel over the
sorted partition:

  sort by (partition keys, order keys)           — reuses the sort kernels
  → segment-boundary flags via neighbor equality  — one vector compare
  → every window function is a closed-form gather / segmented scan over
    positions (row_number = pos - seg_start + 1, rank via cummax of
    order-boundary positions, running aggs via segmented prefix scans with
    jax.lax.associative_scan, lead/lag via shifted gathers)

Aggregates use Spark's default frame semantics: with ORDER BY, RANGE
UNBOUNDED PRECEDING..CURRENT ROW (peer rows share the value at their tie
group's end); without ORDER BY, the whole partition.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterator, Optional

import jax
import jax.numpy as jnp

from auron_tpu.columnar.batch import (DeviceBatch, PrimitiveColumn,
                                      StringColumn, gather_batch)
from auron_tpu.columnar.schema import DataType, Field, Schema
from auron_tpu.exprs import ir
from auron_tpu.exprs.eval import (EvalContext, TypedValue, evaluate,
                                  infer_dtype)
from auron_tpu.ops.base import ExecContext, PhysicalOp, count_output, timer
from auron_tpu.ops.sort import _concat_all, sort_permutation
from auron_tpu.runtime.programs import program_cache

RANK_LIKE = ("row_number", "rank", "dense_rank", "percent_rank",
             "cume_dist", "ntile")
OFFSET_FNS = ("lead", "lag", "nth_value", "first_value", "last_value")
AGG_FNS = ("sum", "count", "count_star", "avg", "min", "max")


@dataclass(frozen=True)
class WindowFunctionSpec:
    kind: str                      # rank_like | offset | agg
    fn: str
    arg: Optional[ir.Expr] = None
    offset: int = 1                # lead/lag distance, nth n, ntile buckets
    default: object = None         # lead/lag default value
    #: ROWS BETWEEN (lo, hi) relative offsets for 'agg' functions
    #: (lo=-1, hi=1 is 1 PRECEDING..1 FOLLOWING); None = Spark's default
    #: frame. Supported for sum/count/count_star/avg (prefix-sum
    #: invertible); min/max over sliding frames fail fast.
    frame: Optional[tuple] = None

    def __post_init__(self):
        if self.kind == "rank_like":
            assert self.fn in RANK_LIKE, self.fn
        elif self.kind == "offset":
            assert self.fn in OFFSET_FNS, self.fn
        elif self.kind == "agg":
            assert self.fn in AGG_FNS, self.fn
        else:
            raise ValueError(self.kind)
        if self.frame is not None:
            if self.kind != "agg" or self.fn in ("min", "max"):
                raise NotImplementedError(
                    "ROWS frames are supported for sum/count/avg window "
                    "aggregates only (min/max need non-invertible sliding "
                    "state)")
            lo, hi = self.frame
            assert lo <= hi, self.frame


# ---------------------------------------------------------------------------
# segment machinery
# ---------------------------------------------------------------------------

def _col_neq_prev(col) -> jax.Array:
    """bool[cap]: row i differs from row i-1 (null-aware, NaN == NaN,
    struct fieldwise; row 0 => True)."""
    from auron_tpu.ops.hashing import adjacent_eq
    return jnp.concatenate([jnp.ones(1, bool), ~adjacent_eq(col)])


def _segmented_cummax_pos(flags: jax.Array) -> jax.Array:
    """For each row, the last position <= i where flags was True."""
    cap = flags.shape[0]
    pos = jnp.arange(cap, dtype=jnp.int32)
    return jax.lax.cummax(jnp.where(flags, pos, -1))


def _segmented_scan(values, seg_new: jax.Array, combine):
    """Inclusive segmented prefix scan: resets at seg_new."""
    def op(a, b):
        fa, va = a
        fb, vb = b
        return (fa | fb, jnp.where(fb, vb, combine(va, vb)))

    _, out = jax.lax.associative_scan(op, (seg_new, values))
    return out


def _segmented_scan128(h, l, seg_new: jax.Array, combine128):
    """Segmented inclusive scan over two-limb (hi, lo) values; combine128
    takes (ah, al, bh, bl) -> (h, l) and must be associative (add128 and
    the cmp128-select min/max are)."""
    def op(a, b):
        fa, ha, la = a
        fb, hb, lb = b
        ch, cl = combine128(ha, la, hb, lb)
        return (fa | fb,
                jnp.where(fb, hb, ch), jnp.where(fb, lb, cl))

    _, oh, ol = jax.lax.associative_scan(op, (seg_new, h, l))
    return oh, ol


# ---------------------------------------------------------------------------
# kernel
# ---------------------------------------------------------------------------

def _result_field(spec: WindowFunctionSpec, name: str,
                  in_schema: Schema) -> Field:
    if spec.kind == "rank_like":
        if spec.fn in ("percent_rank", "cume_dist"):
            return Field(name, DataType.FLOAT64, False)
        return Field(name, DataType.INT64, False)
    if spec.kind == "offset":
        dt, p, s = infer_dtype(spec.arg, in_schema)
        return Field(name, dt, True, p, s)
    # agg
    if spec.fn in ("count", "count_star"):
        return Field(name, DataType.INT64, False)
    dt, p, s = infer_dtype(spec.arg, in_schema)
    if spec.fn == "avg":
        if dt == DataType.DECIMAL:
            if p + 4 > 18:
                # matches AggOp: avg past 18 digits promotes to the wide
                # representation with Spark's bounded(p+4, s+4) type
                from auron_tpu.ops.agg import decimal_avg_result
                p, s = decimal_avg_result(p, s)
            else:
                p, s = _decimal_avg_type(p, s)
        elif dt != DataType.FLOAT64:
            dt = DataType.FLOAT64
    if spec.fn == "sum" and dt == DataType.DECIMAL:
        # Spark sum headroom for narrow AND wide inputs: sum(decimal(p,s))
        # is decimal(p+10, s) capped at the 128-bit 38; narrow inputs with
        # p+10 > 18 promote to the two-limb representation (AggOp parity)
        p = min(p + 10, 38)
    if spec.fn == "sum" and dt.is_integer:
        dt = DataType.INT64   # kernel accumulates int64 (Spark: sum → long)
    return Field(name, dt, True, p, s)


def _decimal_half_up_div(total, count, shift: int):
    """Scaled-int decimal average: (total * shift) / count rounded
    HALF_UP away from zero (Spark Decimal.divide); quotient/remainder
    form keeps the intermediate within one 10^delta shift of the sum.
    Shared by the default-frame and ROWS-frame window avg paths."""
    num = total * shift
    safe = jnp.maximum(count, 1)
    a = jnp.abs(num)
    q0 = a // safe
    rem = a - q0 * safe
    q = q0 + (2 * rem >= safe)
    return jnp.where(num < 0, -q, q)


def _decimal_avg_type(p: int, s: int) -> tuple[int, int]:
    """Spark: avg(decimal(p,s)) -> decimal(p+4, s+4). This engine's
    decimals are int64-scaled, so precision caps at 18 and the scale is
    clamped to the capped precision (sums whose scaled value would exceed
    int64 are an accepted engine limitation, as for sum)."""
    p = p or 18
    s = s or 0
    np_ = min(p + 4, 18)
    return np_, min(s + 4, np_)


@program_cache("ops.window.window", maxsize=128)
def _window_kernel(partition_exprs: tuple, order_by: tuple, fn_specs: tuple,
                   in_schema: Schema, capacity: int, group_limit):
    n_funcs = len(fn_specs)

    @jax.jit
    def kernel(batch: DeviceBatch):
        ectx = EvalContext(memo={})
        pcols = [evaluate(e, batch, in_schema, ectx).col
                 for e in partition_exprs]
        ocols = [evaluate(o.expr, batch, in_schema, ectx).col
                 for o in order_by]
        key_cols = pcols + ocols
        orders = ([(True, True)] * len(pcols) +
                  [(o.ascending, o.nulls_first) for o in order_by])
        if key_cols:
            perm = sort_permutation(batch, key_cols, orders)
        else:
            perm = jnp.arange(batch.capacity, dtype=jnp.int32)
        sbatch = gather_batch(batch, perm, batch.num_rows)
        cap = sbatch.capacity
        live = sbatch.row_mask()
        pos = jnp.arange(cap, dtype=jnp.int32)
        n = sbatch.num_rows

        def sorted_col(c):
            from auron_tpu.columnar.batch import StructColumn
            from auron_tpu.columnar.decimal128 import Decimal128Column
            if isinstance(c, StringColumn):
                return StringColumn(c.chars[perm], c.lens[perm],
                                    c.validity[perm])
            if isinstance(c, Decimal128Column):
                return Decimal128Column(c.hi[perm], c.lo[perm],
                                        c.validity[perm])
            if isinstance(c, StructColumn):
                return StructColumn(tuple(sorted_col(ch)
                                          for ch in c.children),
                                    c.validity[perm])
            return PrimitiveColumn(c.data[perm], c.validity[perm])

        spcols = [sorted_col(c) for c in pcols]
        socols = [sorted_col(c) for c in ocols]

        # partition segment boundaries
        if spcols:
            seg_new = jnp.zeros(cap, bool)
            for c in spcols:
                seg_new = seg_new | _col_neq_prev(c)
            seg_new = seg_new.at[0].set(True)
        else:
            seg_new = jnp.zeros(cap, bool).at[0].set(True)
        # order-key (peer group) boundaries
        tie_new = seg_new
        for c in socols:
            tie_new = tie_new | _col_neq_prev(c)

        seg_start = _segmented_cummax_pos(seg_new)
        seg_id = jnp.cumsum(seg_new.astype(jnp.int32)) - 1
        # end of each row's segment: last live row with same seg_id, via
        # scatter-max of positions
        seg_end = jax.ops.segment_max(
            jnp.where(live, pos, -1), jnp.clip(seg_id, 0, cap - 1),
            num_segments=cap)
        seg_end_row = seg_end[jnp.clip(seg_id, 0, cap - 1)]
        npart = (seg_end_row - seg_start + 1).astype(jnp.int64)

        # peer (tie) group end: last row with same (segment, order keys)
        tie_id = jnp.cumsum(tie_new.astype(jnp.int32)) - 1
        tie_end = jax.ops.segment_max(
            jnp.where(live, pos, -1), jnp.clip(tie_id, 0, cap - 1),
            num_segments=cap)
        tie_end_row = tie_end[jnp.clip(tie_id, 0, cap - 1)]

        row_number = (pos - seg_start + 1).astype(jnp.int64)
        rank = (_segmented_cummax_pos(tie_new) - seg_start + 1).astype(jnp.int64)
        dense_rank = _segmented_scan(
            tie_new.astype(jnp.int64), seg_new, jnp.add)

        out_cols = []
        for spec in fn_specs:
            if spec.kind == "rank_like":
                if spec.fn == "row_number":
                    data = row_number
                elif spec.fn == "rank":
                    data = rank
                elif spec.fn == "dense_rank":
                    data = dense_rank
                elif spec.fn == "percent_rank":
                    data = jnp.where(npart > 1,
                                     (rank - 1).astype(jnp.float64)
                                     / jnp.maximum(npart - 1, 1), 0.0)
                elif spec.fn == "cume_dist":
                    data = (tie_end_row - seg_start + 1).astype(jnp.float64) \
                        / jnp.maximum(npart, 1)
                elif spec.fn == "ntile":
                    k = spec.offset
                    q, r = npart // k, npart % k
                    rn0 = row_number - 1
                    cutoff = (q + 1) * r
                    in_big = rn0 < cutoff
                    data = jnp.where(
                        in_big, rn0 // jnp.maximum(q + 1, 1) + 1,
                        r + (rn0 - cutoff) // jnp.maximum(q, 1) + 1)
                out_cols.append(PrimitiveColumn(data, live))
                continue

            v = evaluate(spec.arg, sbatch, in_schema, ectx) \
                if spec.arg is not None else None

            if spec.kind == "offset":
                col = v.col
                if spec.fn in ("lead", "lag"):
                    delta = spec.offset if spec.fn == "lead" else -spec.offset
                    src = pos + delta
                    in_seg = (src >= seg_start) & (src <= seg_end_row)
                    src_c = jnp.clip(src, 0, cap - 1)
                elif spec.fn == "first_value":
                    src_c, in_seg = seg_start, live
                elif spec.fn == "last_value":
                    # default frame: up to current peer group end
                    src_c = tie_end_row if order_by else seg_end_row
                    in_seg = live
                else:  # nth_value (frame-clipped like last_value)
                    src = seg_start + (spec.offset - 1)
                    bound = tie_end_row if order_by else seg_end_row
                    in_seg = (src <= bound) & live
                    src_c = jnp.clip(src, 0, cap - 1)
                from auron_tpu.columnar.decimal128 import Decimal128Column
                if isinstance(col, Decimal128Column):
                    if spec.default is not None:
                        raise NotImplementedError(
                            "lead/lag default over decimal(p>18)")
                    out_cols.append(Decimal128Column(
                        col.hi[src_c], col.lo[src_c],
                        col.validity[src_c] & in_seg & live))
                    continue
                if isinstance(col, StringColumn):
                    chars = col.chars[src_c]
                    lens = jnp.where(in_seg, col.lens[src_c], 0)
                    valid = col.validity[src_c] & in_seg & live
                    if spec.default is not None and spec.fn in ("lead", "lag"):
                        db = str(spec.default).encode()[:col.width]
                        drow = jnp.zeros(col.width, jnp.uint8).at[
                            :len(db)].set(jnp.asarray(list(db), jnp.uint8))
                        chars = jnp.where(in_seg[:, None], chars, drow[None, :])
                        lens = jnp.where(in_seg, lens, len(db))
                        valid = jnp.where(in_seg, valid, live)
                    out = StringColumn(chars, lens, valid)
                else:
                    data = col.data[src_c]
                    valid = col.validity[src_c] & in_seg & live
                    if spec.default is not None and spec.fn in ("lead", "lag"):
                        data = jnp.where(in_seg, data,
                                         jnp.asarray(spec.default, data.dtype))
                        valid = jnp.where(in_seg, valid, live)
                    out = PrimitiveColumn(data, valid)
                out_cols.append(out)
                continue

            if spec.frame is not None:
                # ROWS BETWEEN lo..hi: windowed segmented sums via prefix
                # differences — sum[i] = P[b] - P[a-1] with a/b clamped
                # into the row's segment (reference: the frame-bounded agg
                # processors in window/processors/agg.rs). Sums whose
                # declared type exceeds 18 digits (wide input, or narrow
                # promoted by the p+10 headroom) run the scan in 128-bit
                # limbs; framed avg over those still fails fast.
                from auron_tpu.columnar.decimal128 import Decimal128Column
                if v is not None and spec.fn == "avg":
                    _dt0, _p0, _s0 = infer_dtype(spec.arg, in_schema)
                    if isinstance(v.col, Decimal128Column) or (
                            _dt0 == DataType.DECIMAL and _p0 + 4 > 18):
                        raise NotImplementedError(
                            "ROWS frames over avg(decimal(p>14)): the "
                            "framed HALF_UP division runs on the int64 "
                            "path only")
                lo_off, hi_off = spec.frame

                # shared frame index math: prefix rows at the window's
                # inclusive end (bi) and exclusive start (ai, valid only
                # when has_lo), empty = window outside the segment
                a = pos + lo_off
                b = pos + hi_off
                f_empty = (a > seg_end_row) | (b < seg_start)
                a_c = jnp.clip(a, seg_start, seg_end_row)
                b_c = jnp.clip(b, seg_start, seg_end_row)
                f_bi = jnp.clip(b_c, 0, cap - 1)
                f_ai = jnp.clip(a_c - 1, 0, cap - 1)
                f_has_lo = a_c > seg_start

                def frame_window(prefix):
                    lo_v = jnp.where(f_has_lo, prefix[f_ai], 0)
                    return jnp.where(f_empty, 0, prefix[f_bi] - lo_v)

                if spec.fn == "count_star":
                    # one scan: the count prefix IS the value prefix here
                    p_cnt = _segmented_scan(live.astype(jnp.int64),
                                            seg_new, jnp.add)
                    out_cols.append(
                        PrimitiveColumn(frame_window(p_cnt), live))
                    continue
                vv = v.validity & live
                p_cnt = _segmented_scan(vv.astype(jnp.int64), seg_new,
                                        jnp.add)
                wcnt = frame_window(p_cnt)
                if spec.fn == "count":
                    out_cols.append(PrimitiveColumn(wcnt, live))
                    continue
                dt_in, _p, in_s = infer_dtype(spec.arg, in_schema)
                if spec.fn == "sum" and dt_in == DataType.DECIMAL \
                        and (_p + 10 > 18
                             or isinstance(v.col, Decimal128Column)):
                    # wide-typed frame sum: exact 128-bit prefix scan +
                    # limb-pair prefix differences, overflow-nulled at the
                    # declared precision like the running-window path (an
                    # int64 scan here can silently wrap inside a frame)
                    from auron_tpu.columnar import decimal128 as d128
                    if isinstance(v.col, Decimal128Column):
                        s_hi, s_lo = v.col.hi, v.col.lo
                    else:
                        s_hi, s_lo = d128.from_int64(
                            v.col.data.astype(jnp.int64))
                    ph, pl = _segmented_scan128(
                        jnp.where(vv, s_hi, 0), jnp.where(vv, s_lo, 0),
                        seg_new, d128.add128)
                    lh = jnp.where(f_has_lo, ph[f_ai], 0)
                    ll = jnp.where(f_has_lo, pl[f_ai], 0)
                    rh, rl = d128.sub128(ph[f_bi], pl[f_bi], lh, ll)
                    ok = ((wcnt > 0) & live & ~f_empty
                          & d128.fits_precision(rh, rl, min(_p + 10, 38)))
                    out_cols.append(Decimal128Column(rh, rl, ok))
                    continue
                vals = jnp.where(vv, v.col.data, 0)
                if jnp.issubdtype(vals.dtype, jnp.integer):
                    vals = vals.astype(jnp.int64)
                p_sum = _segmented_scan(vals, seg_new, jnp.add)
                wsum = frame_window(p_sum)
                if spec.fn == "avg":
                    if dt_in == DataType.DECIMAL:
                        _rp, rs = _decimal_avg_type(_p, in_s)
                        wsum = _decimal_half_up_div(
                            wsum, wcnt, 10 ** (rs - (in_s or 0)))
                    else:
                        wsum = wsum.astype(jnp.float64) \
                            / jnp.maximum(wcnt, 1)
                out_cols.append(PrimitiveColumn(wsum, (wcnt > 0) & live))
                continue

            # agg over window — two-limb decimal(p>18) values run the
            # same segmented scans in 128-bit limb arithmetic
            from auron_tpu.columnar.decimal128 import Decimal128Column
            if (v is not None and spec.fn in ("avg", "sum")
                    and not isinstance(v.col, Decimal128Column)):
                _dt, _p, _s = infer_dtype(spec.arg, in_schema)
                headroom = 4 if spec.fn == "avg" else 10
                if _dt == DataType.DECIMAL and _p + headroom > 18:
                    # same wide promotion as AggOp: window avg of
                    # decimal(15..18,s) returns Spark's decimal(p+4,s+4),
                    # window sum of decimal(9..18,s) decimal(p+10,s)
                    from auron_tpu.columnar import decimal128 as d128
                    _h, _l = d128.from_int64(v.col.data.astype(jnp.int64))
                    v = TypedValue(Decimal128Column(_h, _l, v.validity),
                                   DataType.DECIMAL, _p, _s)
            if v is not None and isinstance(v.col, Decimal128Column) \
                    and spec.fn != "count":
                from auron_tpu.columnar import decimal128 as d128
                from auron_tpu.ops.agg import _DEC_NEUTRAL
                vv = v.validity & live
                hi, lo = v.col.hi, v.col.lo
                has = _segmented_scan(vv.astype(jnp.int64), seg_new,
                                      jnp.add)
                if spec.fn in ("sum", "avg"):
                    rh, rl = _segmented_scan128(
                        jnp.where(vv, hi, 0), jnp.where(vv, lo, 0),
                        seg_new, d128.add128)
                else:   # min / max
                    nh, nl = _DEC_NEUTRAL[f"d{spec.fn}"]
                    def pick(ah, al, bh, bl, _mx=(spec.fn == "max")):
                        lt, _ = d128.cmp128(ah, al, bh, bl)
                        take_a = (~lt) if _mx else lt
                        return (jnp.where(take_a, ah, bh),
                                jnp.where(take_a, al, bl))
                    rh, rl = _segmented_scan128(
                        jnp.where(vv, hi, nh), jnp.where(vv, lo, nl),
                        seg_new, pick)
                end = tie_end_row if order_by else seg_end_row
                end_c = jnp.clip(end, 0, cap - 1)
                rh, rl, has_e = rh[end_c], rl[end_c], has[end_c]
                ok = has_e > 0
                if spec.fn == "sum":
                    # running sums past the declared precision null, like
                    # AggOp's wide sum (Spark non-ANSI overflow)
                    _dt, _p, _s = infer_dtype(spec.arg, in_schema)
                    ok = ok & d128.fits_precision(rh, rl, min(_p + 10, 38))
                if spec.fn == "avg":
                    _dt, _p, in_s = infer_dtype(spec.arg, in_schema)
                    from auron_tpu.ops.agg import decimal_avg_result
                    _rp, rs = decimal_avg_result(_p, in_s)
                    rh, rl, fits = d128.avg_pow10_div_half_up(
                        rh, rl, jnp.maximum(has_e, 1), rs - in_s)
                    ok = ok & fits
                out_cols.append(Decimal128Column(rh, rl, ok & live))
                continue
            if spec.fn == "count_star":
                run = _segmented_scan(live.astype(jnp.int64), seg_new, jnp.add)
                valid = live
            elif spec.fn == "count":
                run = _segmented_scan((v.validity & live).astype(jnp.int64),
                                      seg_new, jnp.add)
                valid = live
            elif spec.fn in ("sum", "avg"):
                vals = jnp.where(v.validity & live, v.col.data, 0)
                if jnp.issubdtype(vals.dtype, jnp.integer):
                    vals = vals.astype(jnp.int64)
                run = _segmented_scan(vals, seg_new, jnp.add)
                has = _segmented_scan((v.validity & live).astype(jnp.int64),
                                      seg_new, jnp.add)
                if spec.fn == "avg":
                    dt_in, _p, in_s = infer_dtype(spec.arg, in_schema)
                    if dt_in == DataType.DECIMAL:
                        # scaled-int divide at the (clamped) s+4 result
                        # scale (shared HALF_UP helper)
                        _rp, rs = _decimal_avg_type(_p, in_s)
                        run = _decimal_half_up_div(
                            run, has, 10 ** (rs - (in_s or 0)))
                    else:
                        run = run.astype(jnp.float64) / jnp.maximum(has, 1)
                valid = has > 0
            else:  # min / max
                big = jnp.asarray(
                    jnp.finfo(v.col.data.dtype).max
                    if jnp.issubdtype(v.col.data.dtype, jnp.floating)
                    else jnp.iinfo(v.col.data.dtype).max, v.col.data.dtype)
                neutral = big if spec.fn == "min" else (
                    -big if jnp.issubdtype(v.col.data.dtype, jnp.floating)
                    else jnp.asarray(
                        jnp.iinfo(v.col.data.dtype).min, v.col.data.dtype))
                vals = jnp.where(v.validity & live, v.col.data, neutral)
                run = _segmented_scan(
                    vals, seg_new,
                    jnp.minimum if spec.fn == "min" else jnp.maximum)
                has = _segmented_scan((v.validity & live).astype(jnp.int64),
                                      seg_new, jnp.add)
                valid = has > 0
            if order_by:
                # peers share the value at their tie group's end
                run = run[jnp.clip(tie_end_row, 0, cap - 1)]
                valid = valid[jnp.clip(tie_end_row, 0, cap - 1)] & live
            else:
                run = run[jnp.clip(seg_end_row, 0, cap - 1)]
                valid = valid[jnp.clip(seg_end_row, 0, cap - 1)] & live
            out_cols.append(PrimitiveColumn(run, valid))

        result = DeviceBatch(tuple(sbatch.columns) + tuple(out_cols), n)
        if group_limit is not None:
            from auron_tpu.columnar.batch import compact
            keep = (rank <= group_limit) & live
            result = compact(result, keep)
        return result

    return kernel


# ---------------------------------------------------------------------------
# operator
# ---------------------------------------------------------------------------

class WindowOp(PhysicalOp):
    name = "window"

    def __init__(self, child: PhysicalOp, partition_by: list[ir.Expr],
                 order_by: list[ir.SortOrder],
                 functions: list[WindowFunctionSpec],
                 output_names: Optional[list[str]] = None,
                 group_limit: Optional[int] = None):
        self.child = child
        self.partition_by = tuple(partition_by)
        self.order_by = tuple(order_by)
        self.functions = tuple(functions)
        self.group_limit = group_limit
        names = output_names or [f"w{i}" for i in range(len(functions))]
        self.output_names = list(names)
        in_schema = child.schema()
        extra = [_result_field(spec, n, in_schema)
                 for spec, n in zip(self.functions, names)]
        self._schema = Schema(tuple(in_schema.fields) + tuple(extra))

    @property
    def children(self):
        return [self.child]

    def schema(self) -> Schema:
        return self._schema

    def execute(self, partition: int, ctx: ExecContext) -> Iterator[DeviceBatch]:
        metrics = ctx.metrics_for(self)
        elapsed = metrics.counter("elapsed_compute")
        in_schema = self.child.schema()
        _sync = ctx.device_sync

        def stream():
            batches = list(self.child.execute(partition, ctx))
            if not batches:
                return
            with timer(elapsed, sync=_sync) as t:
                merged = _concat_all(batches) if len(batches) > 1 else batches[0]
                kern = _window_kernel(self.partition_by, self.order_by,
                                      self.functions, in_schema,
                                      merged.capacity, self.group_limit)
                out = t.track(kern(merged))
            yield out

        return count_output(stream(), metrics)

    def __repr__(self):
        fns = ",".join(s.fn for s in self.functions)
        return (f"WindowOp[{fns} partition_by={len(self.partition_by)} "
                f"order_by={len(self.order_by)}]")
