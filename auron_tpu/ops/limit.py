"""Limit / union / coalesce-batches / empty / rename operators.

reference: datafusion-ext-plans/src/limit_exec.rs, union_exec.rs,
coalesce_batches_exec.rs, empty_partitions_exec.rs, rename_columns_exec.rs.
"""

from __future__ import annotations

from typing import Iterator

import jax.numpy as jnp

from auron_tpu.columnar.batch import DeviceBatch, concat_batches, resize
from auron_tpu.columnar.schema import Schema
from auron_tpu.ops.base import ExecContext, PhysicalOp, count_output
from auron_tpu.utils.shapes import bucket_rows


class LimitOp(PhysicalOp):
    name = "limit"
    fusable = True
    owns_output = "inherit"   # yields the child's batches (truncated)

    def __init__(self, child: PhysicalOp, limit: int):
        self.child = child
        self.limit = limit

    @property
    def children(self):
        return [self.child]

    def schema(self) -> Schema:
        return self.child.schema()

    def build_kernel_fragment(self):
        """Limit-within-batch as a carry: the remaining-row budget lives
        in the member's int64 carry slot, truncation is a num_rows
        rewrite (no data movement), and the host polls the slot to stop
        pulling the child — see FusedStageOp.execute."""
        from auron_tpu.ops.fused import KernelFragment

        def apply(batch, partition_id, carry):
            n = jnp.asarray(batch.num_rows, jnp.int64)
            take = jnp.minimum(n, jnp.maximum(carry, 0))
            out = DeviceBatch(batch.columns, take.astype(jnp.int32))
            return (out,), carry - take

        return KernelFragment(key=("limit", self.limit), apply=apply,
                              init_carry=self.limit, is_limit=True)

    def execute(self, partition: int, ctx: ExecContext) -> Iterator[DeviceBatch]:
        metrics = ctx.metrics_for(self)

        def stream():
            remaining = self.limit
            for batch in self.child.execute(partition, ctx):
                if remaining <= 0:
                    break
                n = int(batch.num_rows)
                if n <= remaining:
                    remaining -= n
                    yield batch
                else:
                    yield DeviceBatch(batch.columns,
                                      jnp.asarray(remaining, jnp.int32))
                    remaining = 0
                    break

        return count_output(stream(), metrics, timed=True)

    def __repr__(self):
        return f"LimitOp[{self.limit}]"


class UnionOp(PhysicalOp):
    """UNION ALL: chains children streams (reference maps each input to a
    distinct partition set; single-stream chain is equivalent per-partition)."""

    name = "union"
    owns_output = "inherit"

    def __init__(self, inputs: list[PhysicalOp]):
        self.inputs = inputs

    @property
    def children(self):
        return list(self.inputs)

    def schema(self) -> Schema:
        return self.inputs[0].schema()

    def execute(self, partition: int, ctx: ExecContext) -> Iterator[DeviceBatch]:
        metrics = ctx.metrics_for(self)

        def stream():
            for child in self.inputs:
                yield from child.execute(partition, ctx)

        return count_output(stream(), metrics, timed=True)


class CoalesceBatchesOp(PhysicalOp):
    """Merge small batches up to a target row count so downstream kernels run
    at full occupancy (reference: coalesce_batches_exec.rs; the reference's
    ExecutionContext also coalesces on output, execution_context.rs:146-233)."""

    name = "coalesce_batches"
    owns_output = "inherit"   # big batches pass through unchanged

    def __init__(self, child: PhysicalOp, target_rows: int):
        self.child = child
        self.target_rows = target_rows

    @property
    def children(self):
        return [self.child]

    def schema(self) -> Schema:
        return self.child.schema()

    def execute(self, partition: int, ctx: ExecContext) -> Iterator[DeviceBatch]:
        metrics = ctx.metrics_for(self)
        target_cap = bucket_rows(self.target_rows)

        def stream():
            acc = None
            acc_rows = 0
            for batch in self.child.execute(partition, ctx):
                n = int(batch.num_rows)
                if n == 0:
                    continue
                if n >= self.target_rows and acc is None:
                    yield batch
                    continue
                if acc is None:
                    acc = resize(batch, target_cap)
                    acc_rows = n
                else:
                    grown = concat_batches(acc, batch)
                    acc = resize(grown, max(target_cap, grown.capacity)) \
                        if grown.capacity > target_cap else grown
                    acc_rows += n
                if acc_rows >= self.target_rows:
                    yield acc
                    acc = None
                    acc_rows = 0
            if acc is not None and acc_rows > 0:
                yield acc

        return count_output(stream(), metrics, timed=True)


class EmptyPartitionsOp(PhysicalOp):
    """Produces N empty partitions (reference: empty_partitions_exec.rs)."""

    name = "empty_partitions"

    def __init__(self, schema: Schema, num_partitions: int):
        self._schema = schema
        self.num_partitions = num_partitions

    @property
    def children(self):
        return []

    def schema(self) -> Schema:
        return self._schema

    def execute(self, partition: int, ctx: ExecContext) -> Iterator[DeviceBatch]:
        return iter(())


class RenameColumnsOp(PhysicalOp):
    """Schema-only rename (reference: rename_columns_exec.rs)."""

    name = "rename_columns"
    fusable = True
    owns_output = "inherit"

    def build_kernel_fragment(self):
        """Identity fragment: fusion chains cross renames for free."""
        from auron_tpu.ops.fused import KernelFragment
        return KernelFragment(key=("rename",),
                              apply=lambda batch, pid, carry:
                              ((batch,), carry))

    def __init__(self, child: PhysicalOp, names: list[str]):
        self.child = child
        self.names = list(names)
        base = child.schema()
        self._schema = Schema(tuple(f.with_name(n) for f, n in zip(base, self.names)))

    @property
    def children(self):
        return [self.child]

    def schema(self) -> Schema:
        return self._schema

    def execute(self, partition: int, ctx: ExecContext) -> Iterator[DeviceBatch]:
        return self.child.execute(partition, ctx)
