"""Hash aggregation, TPU-style.

The reference's AggExec is an open-addressing hash table with sorted-bucket
spills (reference: datafusion-ext-plans/src/agg/agg_table.rs:68-356). Open
addressing is sequential probing — hostile to a vector machine — so this
engine keeps the same *contract* (streaming partial/final agg with a bounded
in-memory group state) but replaces the probe loop with sort-based grouping,
which XLA lowers to parallel bitonic-class sorts on the VPU:

  per input batch:
    state_rows ++ input_rows → xxhash64(group keys)
    → stable sort by hash → null-aware neighbor-equality boundaries
    → segment-reduce accumulators → new state (groups sorted by hash)

Group count exceeding the state capacity triggers a host-side capacity
re-bucket (rerun of the pure merge kernel at the next power of two), the
shape-static analogue of the reference's table growth; hash-ordered state
also gives the sorted-run invariant its bucket spills rely on.

Aggregate set: sum/count/avg/min/max/first/first_ignores_null (reference:
datafusion-ext-plans/src/agg/*.rs). Accumulators are flat device columns —
the AccColumn idea (reference: agg/acc.rs) without the row-format detour.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache, partial
from typing import Iterator, Optional

import numpy as np

import jax
import jax.numpy as jnp

from auron_tpu.columnar.batch import (DeviceBatch, PrimitiveColumn, StringColumn,
                                      concat_columns, gather_column)
from auron_tpu.columnar.schema import DataType, Field, Schema
from auron_tpu.exprs import ir
from auron_tpu.exprs.eval import EvalContext, TypedValue, evaluate, infer_dtype
from auron_tpu.ops import hashing
from auron_tpu.ops.base import ExecContext, PhysicalOp, count_output, timer
from auron_tpu.utils.shapes import bucket_rows

# ---------------------------------------------------------------------------
# accumulator specs
# ---------------------------------------------------------------------------

_SUM_DTYPE = {
    DataType.INT8: DataType.INT64, DataType.INT16: DataType.INT64,
    DataType.INT32: DataType.INT64, DataType.INT64: DataType.INT64,
    DataType.FLOAT32: DataType.FLOAT64, DataType.FLOAT64: DataType.FLOAT64,
    DataType.DECIMAL: DataType.DECIMAL,
}

_JNPT = {
    DataType.INT64: jnp.int64, DataType.FLOAT64: jnp.float64,
    DataType.DECIMAL: jnp.int64, DataType.INT32: jnp.int32,
    DataType.FLOAT32: jnp.float32, DataType.BOOL: jnp.bool_,
    DataType.INT8: jnp.int8, DataType.INT16: jnp.int16,
    DataType.DATE32: jnp.int32, DataType.TIMESTAMP_US: jnp.int64,
}


@dataclass(frozen=True)
class AccSpec:
    """How one aggregate maps to flat state columns.

    state_fields: (name, dtype, reduce_kind) per state column.
    reduce kinds: sum | min | max | or | first (first = value at the
    first-ordered valid row of the group).
    """
    fn: str
    state_fields: tuple
    result: tuple  # (dtype, precision, scale)


def make_acc_spec(agg: ir.AggFunction, in_schema: Schema, mode: str) -> AccSpec:
    fn = agg.fn
    if fn in ("count", "count_star"):
        return AccSpec(fn, (("count", DataType.INT64, "sum"),),
                       (DataType.INT64, 0, 0))
    dt, p, s = infer_dtype(agg.arg, in_schema)
    if fn == "sum":
        sdt = _SUM_DTYPE[dt]
        sp, ss = (min(p + 10, 18), s) if sdt == DataType.DECIMAL else (0, 0)
        return AccSpec(fn, (("sum", sdt, "sum"), ("has", DataType.BOOL, "or")),
                       (sdt, sp, ss))
    if fn == "avg":
        sdt = _SUM_DTYPE[dt]
        res = (DataType.FLOAT64, 0, 0)
        return AccSpec(fn, (("sum", sdt, "sum"), ("count", DataType.INT64, "sum")),
                       res)
    if fn in ("min", "max"):
        return AccSpec(fn, (("val", dt, fn), ("has", DataType.BOOL, "or")),
                       (dt, p, s))
    if fn in ("first", "first_ignores_null"):
        return AccSpec(fn, (("val", dt, "first"), ("has", DataType.BOOL, "or")),
                       (dt, p, s))
    raise NotImplementedError(f"aggregate function {fn}")


# neutral elements per reduce kind
def _neutral(kind: str, dtype):
    if kind == "sum":
        return jnp.asarray(0, dtype)
    if kind == "min":
        if jnp.issubdtype(dtype, jnp.floating):
            return jnp.asarray(jnp.inf, dtype)
        return jnp.asarray(jnp.iinfo(dtype).max, dtype)
    if kind == "max":
        if jnp.issubdtype(dtype, jnp.floating):
            return jnp.asarray(-jnp.inf, dtype)
        return jnp.asarray(jnp.iinfo(dtype).min, dtype)
    if kind == "or":
        return jnp.asarray(False, jnp.bool_)
    raise ValueError(kind)


# ---------------------------------------------------------------------------
# merge kernel
# ---------------------------------------------------------------------------

def _keys_equal_prev(sorted_keys, live):
    """eq[i] = keys[i] == keys[i-1] (null == null true; eq[0] = False)."""
    eq = jnp.ones_like(live)
    for col in sorted_keys:
        if isinstance(col, StringColumn):
            same_chars = jnp.all(col.chars[1:] == col.chars[:-1], axis=1)
            same = same_chars & (col.lens[1:] == col.lens[:-1])
        else:
            same = col.data[1:] == col.data[:-1]
        both_valid = col.validity[1:] & col.validity[:-1]
        both_null = ~col.validity[1:] & ~col.validity[:-1]
        same = (both_valid & same) | both_null
        eq = eq & jnp.concatenate([jnp.zeros(1, bool), same])
    return eq


@lru_cache(maxsize=256)
def _merge_kernel(n_keys: int, acc_meta: tuple, out_cap: int):
    """Builds the jitted merge: (concat'd keys, accs, live) → state of
    capacity out_cap. acc_meta: tuple of (dtype_enum_value, kind) per state
    column."""

    @jax.jit
    def kernel(keys, accs, live):
        cap = live.shape[0]
        h = hashing.xxhash64_columns(list(keys), cap).view(jnp.uint64)
        # dead rows to the end
        h = jnp.where(live, h, jnp.uint64(0xFFFFFFFFFFFFFFFF))
        perm = jnp.argsort(h, stable=True)
        live_s = live[perm]
        keys_s = tuple(gather_column(c, perm, jnp.ones(cap, bool)) for c in keys)
        h_s = h[perm]

        same_hash = jnp.concatenate(
            [jnp.zeros(1, bool), h_s[1:] == h_s[:-1]])
        same_keys = _keys_equal_prev(keys_s, live_s)
        boundary = live_s & ~(same_hash & same_keys)
        gid = jnp.cumsum(boundary.astype(jnp.int32)) - 1
        gid = jnp.maximum(gid, 0)
        num_groups = jnp.sum(boundary.astype(jnp.int32))

        # first sorted row of each group → representative for keys
        rep = jax.ops.segment_min(
            jnp.where(live_s, jnp.arange(cap, dtype=jnp.int32), cap),
            gid, num_segments=out_cap)
        rep = jnp.clip(rep, 0, cap - 1)
        out_valid = jnp.arange(out_cap, dtype=jnp.int32) < num_groups
        new_keys = tuple(gather_column(c, rep, out_valid) for c in keys_s)

        new_accs = []
        for (dt_val, kind), acc in zip(acc_meta, accs):
            acc_s = acc[perm]
            if kind == "first":
                # value at first sorted valid row; pair-reduce via segment_min
                # over (order, value-index)
                first_idx = jax.ops.segment_min(
                    jnp.where(live_s, jnp.arange(cap, dtype=jnp.int32), cap),
                    gid, num_segments=out_cap)
                first_idx = jnp.clip(first_idx, 0, cap - 1)
                new_accs.append(acc_s[first_idx])
                continue
            neutral = _neutral(kind, acc.dtype)
            masked = jnp.where(live_s, acc_s, neutral)
            if kind == "sum":
                red = jax.ops.segment_sum(masked, gid, num_segments=out_cap)
            elif kind == "min":
                red = jax.ops.segment_min(masked, gid, num_segments=out_cap)
            elif kind == "max":
                red = jax.ops.segment_max(masked, gid, num_segments=out_cap)
            elif kind == "or":
                red = jax.ops.segment_max(masked.astype(jnp.int8), gid,
                                          num_segments=out_cap).astype(jnp.bool_)
            else:
                raise ValueError(kind)
            new_accs.append(red)
        return new_keys, tuple(new_accs), num_groups

    return kernel


# ---------------------------------------------------------------------------
# the operator
# ---------------------------------------------------------------------------

def _state_nbytes(state) -> int:
    """Device bytes of an accumulator state, from array metadata only."""
    from auron_tpu.columnar.batch import column_nbytes
    keys, accs, _num_groups, _cap = state
    return (sum(column_nbytes(k) for k in keys)
            + sum(a.nbytes for a in accs))


class _AggSpillConsumer:
    """MemConsumer for AggOp: owns the accumulator state between merges.

    The operator checks the state out with ``take_state`` before each merge
    and checks the merged result back in with ``observe``. While checked
    out, an externally-triggered spill (another consumer's update picking
    this one as victim) must refuse — serializing a state the operator is
    about to fold new rows into would double-count every group on emit."""

    FRAME_ROWS = 1 << 16

    def __init__(self, op: "AggOp", mem_manager, metrics):
        import threading
        self.op = op
        self.mem = mem_manager
        self.metrics = metrics
        self.consumer_name = f"agg-{id(op):x}"
        self.state = None
        self.spills = []
        self._lock = threading.RLock()
        self._merging = False
        mem_manager.register_consumer(self)

    def take_state(self):
        with self._lock:
            self._merging = True
            state, self.state = self.state, None
            return state

    def observe(self, state):
        """Check the merged state back in; may spill it synchronously (the
        requester-side trigger). Returns the state the operator should
        continue with (None right after a spill)."""
        with self._lock:
            self.state = state
            self._merging = False
        if state is not None:
            self.mem.update_mem_used(self, _state_nbytes(state))
        with self._lock:
            return self.state

    def mem_used(self) -> int:
        with self._lock:
            return 0 if self.state is None else _state_nbytes(self.state)

    def spill(self) -> int:
        from auron_tpu.columnar.serde import (batch_to_host,
                                              serialize_host_batch,
                                              slice_host_batch)
        with self._lock:
            if self.state is None or self._merging:
                return 0
            state, self.state = self.state, None
        state_batch = self.op._state_batch(state)
        freed = _state_nbytes(state)
        n = int(state_batch.num_rows)
        host = batch_to_host(state_batch, n)
        spill = self.mem.spill_manager.new_spill()
        for lo in range(0, max(n, 1), self.FRAME_ROWS):
            hi = min(lo + self.FRAME_ROWS, n)
            spill.write_frame(
                serialize_host_batch(slice_host_batch(host, lo, hi)))
        with self._lock:
            self.spills.append(spill.finish())
        self.metrics.counter("mem_spill_count").add(1)
        self.metrics.counter("mem_spill_size").add(freed)
        return freed

    def read_spilled_states(self):
        from auron_tpu.columnar.serde import (deserialize_host_batch,
                                              host_to_batch)
        from auron_tpu.utils.shapes import bucket_rows
        for spill in self.spills:
            for frame in spill.frames():
                host, _ = deserialize_host_batch(frame)
                if host.num_rows:
                    yield host_to_batch(host, bucket_rows(host.num_rows))

    def close(self) -> None:
        self.mem.unregister_consumer(self)
        for s in self.spills:
            s.release()
        self.spills = []


class AggOp(PhysicalOp):
    """mode: 'partial' emits (keys..., state...); 'final' consumes state
    columns; 'complete' does full agg in one op (reference: AggMode,
    agg/agg_ctx.rs)."""

    name = "agg"

    def __init__(self, child: PhysicalOp, group_exprs: list[ir.Expr],
                 aggs: list[ir.AggFunction], mode: str = "complete",
                 group_names: Optional[list[str]] = None,
                 agg_names: Optional[list[str]] = None,
                 initial_capacity: int = 4096):
        assert mode in ("partial", "final", "complete")
        self.child = child
        self.group_exprs = tuple(group_exprs)
        self.aggs = tuple(aggs)
        self.mode = mode
        self.initial_capacity = initial_capacity
        in_schema = child.schema()

        if mode == "final":
            # input layout: group cols ++ flattened state cols, as produced
            # by a partial AggOp with the same aggs
            n_keys = len(group_exprs)
            self.specs = []
            idx = n_keys
            for a in aggs:
                # state fields of the partial side
                spec = make_acc_spec_from_partial(a, in_schema, idx)
                self.specs.append(spec)
                idx += len(spec.state_fields)
        else:
            self.specs = [make_acc_spec(a, in_schema, mode) for a in aggs]

        self.group_names = list(group_names or
                                [f"k{i}" for i in range(len(group_exprs))])
        self.agg_names = list(agg_names or [f"a{i}" for i in range(len(aggs))])

        key_fields = []
        for e, n in zip(self.group_exprs, self.group_names):
            dt, p, s = infer_dtype(e, in_schema)
            key_fields.append(Field(n, dt, True, p, s))

        if mode == "partial":
            state_fields = []
            for spec, an in zip(self.specs, self.agg_names):
                for (fname, fdt, _kind) in spec.state_fields:
                    prec, sc = (spec.result[1], spec.result[2]) \
                        if fdt == DataType.DECIMAL else (0, 0)
                    state_fields.append(Field(f"{an}#{fname}", fdt, True, prec, sc))
            self._schema = Schema(tuple(key_fields + state_fields))
        else:
            out_fields = [Field(n, spec.result[0], True, spec.result[1], spec.result[2])
                          for spec, n in zip(self.specs, self.agg_names)]
            self._schema = Schema(tuple(key_fields + out_fields))

    @property
    def children(self):
        return [self.child]

    def schema(self) -> Schema:
        return self._schema

    # -- input row → state contributions -----------------------------------
    def _contributions(self, batch: DeviceBatch, in_schema: Schema,
                       ctx: EvalContext):
        """Evaluate group keys and per-row initial accumulator columns."""
        keys = tuple(evaluate(e, batch, in_schema, ctx).col
                     for e in self.group_exprs)
        accs = []
        live = batch.row_mask()
        if self.mode == "final":
            # state columns come in as-is
            idx = len(self.group_exprs)
            for spec in self.specs:
                for k, (fname, fdt, kind) in enumerate(spec.state_fields):
                    col = batch.columns[idx]
                    data = col.data
                    if fname == "has":
                        data = data.astype(jnp.bool_) & col.validity
                    elif kind in ("min", "max") or kind == "first":
                        data = data  # validity handled via 'has'
                    accs.append(data)
                    idx += 1
            return keys, accs, live

        for agg, spec in zip(self.aggs, self.specs):
            if agg.fn in ("count", "count_star"):
                if agg.arg is None:
                    c = live.astype(jnp.int64)
                else:
                    v = evaluate(agg.arg, batch, in_schema, ctx)
                    c = (v.validity & live).astype(jnp.int64)
                accs.append(c)
                continue
            v = evaluate(agg.arg, batch, in_schema, ctx)
            valid = v.validity & live
            if isinstance(v.col, StringColumn):
                raise NotImplementedError(f"{agg.fn} over strings")
            for fname, fdt, kind in spec.state_fields:
                if fname == "has":
                    accs.append(valid)
                elif fname == "count":
                    accs.append(valid.astype(jnp.int64))
                elif kind == "sum":
                    jdt = _JNPT[fdt]
                    accs.append(jnp.where(valid, v.data, 0).astype(jdt))
                elif kind in ("min", "max"):
                    neutral = _neutral(kind, v.data.dtype)
                    accs.append(jnp.where(valid, v.data, neutral))
                elif kind == "first":
                    accs.append(v.data)
                else:
                    raise ValueError(kind)
        return keys, accs, live

    # -- merge driver -------------------------------------------------------
    def _merge(self, state, keys, accs, live, elapsed):
        """state: None | (keys, accs, num_groups, capacity). Returns updated
        state, growing capacity buckets when groups overflow."""
        acc_meta = tuple((0, kind) for spec in self.specs
                         for (_n, _dt, kind) in spec.state_fields)
        if state is None:
            cat_keys, cat_accs, cat_live = keys, tuple(accs), live
        else:
            s_keys, s_accs, s_n, s_cap = state
            s_live = jnp.arange(s_cap, dtype=jnp.int32) < s_n
            cat_keys = tuple(concat_columns(a, b) for a, b in zip(s_keys, keys))
            cat_accs = tuple(jnp.concatenate([a, b])
                             for a, b in zip(s_accs, accs))
            cat_live = jnp.concatenate([s_live, live])

        out_cap = self.initial_capacity if state is None else state[3]
        while True:
            kern = _merge_kernel(len(cat_keys), acc_meta, out_cap)
            with timer(elapsed):
                new_keys, new_accs, num_groups = kern(cat_keys, cat_accs, cat_live)
            ng = int(num_groups)
            if ng <= out_cap:
                return (new_keys, new_accs, num_groups, out_cap)
            out_cap = bucket_rows(ng)

    # -- finalize → output batch -------------------------------------------
    def _emit(self, state, in_schema: Schema) -> DeviceBatch:
        keys, accs, num_groups, cap = state
        out_cols = list(keys)
        valid = jnp.arange(cap, dtype=jnp.int32) < num_groups

        if self.mode == "partial":
            i = 0
            for spec in self.specs:
                for (fname, fdt, kind) in spec.state_fields:
                    data = accs[i]
                    if data.dtype == jnp.bool_ and fname != "has":
                        data = data.astype(jnp.bool_)
                    out_cols.append(PrimitiveColumn(
                        data, valid))
                    i += 1
            return DeviceBatch(tuple(out_cols), num_groups)

        # final/complete: finalize each agg
        i = 0
        for spec in self.specs:
            n_state = len(spec.state_fields)
            state_vals = accs[i: i + n_state]
            i += n_state
            fn = spec.fn
            if fn in ("count", "count_star"):
                out_cols.append(PrimitiveColumn(state_vals[0], valid))
            elif fn == "sum":
                s, has = state_vals
                out_cols.append(PrimitiveColumn(s, valid & has))
            elif fn == "avg":
                s, cnt = state_vals
                res_dt = spec.result[0]
                safe = jnp.maximum(cnt, 1)
                if res_dt == DataType.FLOAT64:
                    avg = s.astype(jnp.float64) / safe
                else:
                    avg = s / safe
                out_cols.append(PrimitiveColumn(avg, valid & (cnt > 0)))
            elif fn in ("min", "max", "first", "first_ignores_null"):
                v, has = state_vals
                out_cols.append(PrimitiveColumn(v, valid & has))
            else:
                raise NotImplementedError(fn)
        return DeviceBatch(tuple(out_cols), num_groups)

    # -- spill support ------------------------------------------------------
    # The reference spills the in-mem hash table as sorted buckets and
    # merges with a radix queue on output (agg/agg_table.rs:68-356). Here
    # the spilled unit is the whole accumulator table as a partial-layout
    # batch; on emit, spilled tables re-enter the same device merge kernel —
    # associativity of the accumulators makes re-merging exact.

    def _state_batch(self, state) -> DeviceBatch:
        keys, accs, num_groups, cap = state
        valid = jnp.arange(cap, dtype=jnp.int32) < num_groups
        cols = list(keys) + [PrimitiveColumn(a, valid) for a in accs]
        return DeviceBatch(tuple(cols), num_groups)

    def _state_contributions(self, batch: DeviceBatch):
        n_keys = len(self.group_exprs)
        keys = tuple(batch.columns[:n_keys])
        live = batch.row_mask()
        accs = []
        idx = n_keys
        for spec in self.specs:
            for (fname, _fdt, _kind) in spec.state_fields:
                col = batch.columns[idx]
                data = col.data
                if fname == "has":
                    data = data.astype(jnp.bool_) & col.validity
                accs.append(data)
                idx += 1
        return keys, accs, live

    def execute(self, partition: int, ctx: ExecContext) -> Iterator[DeviceBatch]:
        metrics = ctx.metrics_for(self.name)
        elapsed = metrics.counter("elapsed_compute")
        in_schema = self.child.schema()
        ectx = EvalContext(partition_id=partition)
        mem = ctx.mem_manager
        spillable = mem is not None and getattr(mem, "spill_manager", None) is not None

        def stream():
            consumer = _AggSpillConsumer(self, mem, metrics) if spillable else None
            state = None
            try:
                for batch in self.child.execute(partition, ctx):
                    keys, accs, live = self._contributions(batch, in_schema, ectx)
                    if consumer is not None:
                        # state lives in the consumer between merges so an
                        # external victim spill can take it atomically
                        state = consumer.take_state()
                    state = self._merge(state, keys, accs, live, elapsed)
                    if consumer is not None:
                        state = consumer.observe(state)
                if consumer is not None:
                    # re-take: locks out external spills for the final merge
                    # (consumer.state is the source of truth, the local var
                    # may have been spilled away since the last observe)
                    state = consumer.take_state()
                    for spilled in consumer.read_spilled_states():
                        keys, accs, live = self._state_contributions(spilled)
                        state = self._merge(state, keys, accs, live, elapsed)
                if state is None:
                    if not self.group_exprs and self.mode in ("final", "complete"):
                        # global agg over empty input: one row of neutral results
                        yield self._empty_global()
                    return
                yield self._emit(state, in_schema)
            finally:
                if consumer is not None:
                    consumer.close()

        return count_output(stream(), metrics)

    def _empty_global(self) -> DeviceBatch:
        cols = []
        for spec in self.specs:
            dt = spec.result[0]
            jdt = _JNPT[dt]
            if spec.fn in ("count", "count_star"):
                cols.append(PrimitiveColumn(jnp.zeros(1, jnp.int64),
                                            jnp.ones(1, bool)))
            else:
                cols.append(PrimitiveColumn(jnp.zeros(1, jdt),
                                            jnp.zeros(1, bool)))
        return DeviceBatch(tuple(cols), jnp.asarray(1, jnp.int32))

    def __repr__(self):
        fns = ",".join(a.fn for a in self.aggs)
        return f"AggOp[{self.mode}: {len(self.group_exprs)} keys; {fns}]"


def make_acc_spec_from_partial(agg: ir.AggFunction, in_schema: Schema,
                               start_idx: int) -> AccSpec:
    """Spec for the final side: state dtypes read from the partial schema."""
    fn = agg.fn
    if fn in ("count", "count_star"):
        return AccSpec(fn, (("count", DataType.INT64, "sum"),),
                       (DataType.INT64, 0, 0))
    f0 = in_schema[start_idx]
    if fn == "sum":
        return AccSpec(fn, (("sum", f0.dtype, "sum"), ("has", DataType.BOOL, "or")),
                       (f0.dtype, f0.precision, f0.scale))
    if fn == "avg":
        return AccSpec(fn, (("sum", f0.dtype, "sum"), ("count", DataType.INT64, "sum")),
                       (DataType.FLOAT64, 0, 0))
    if fn in ("min", "max"):
        return AccSpec(fn, (("val", f0.dtype, fn), ("has", DataType.BOOL, "or")),
                       (f0.dtype, f0.precision, f0.scale))
    if fn in ("first", "first_ignores_null"):
        return AccSpec(fn, (("val", f0.dtype, "first"), ("has", DataType.BOOL, "or")),
                       (f0.dtype, f0.precision, f0.scale))
    raise NotImplementedError(fn)
