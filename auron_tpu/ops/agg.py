"""Hash aggregation, TPU-style.

The reference's AggExec is an open-addressing hash table with sorted-bucket
spills (reference: datafusion-ext-plans/src/agg/agg_table.rs:68-356). Open
addressing is sequential probing — hostile to a vector machine — so this
engine keeps the same *contract* (streaming partial/final agg with a bounded
in-memory group state) but replaces the probe loop with sort-based grouping,
which XLA lowers to parallel bitonic-class sorts on the VPU:

  per input batch:
    state_rows ++ input_rows → xxhash64(group keys)
    → stable sort by hash → null-aware neighbor-equality boundaries
    → segment-reduce accumulators → new state (groups sorted by hash)

Group count exceeding the state capacity triggers a host-side capacity
re-bucket (rerun of the pure merge kernel at the next power of two), the
shape-static analogue of the reference's table growth; hash-ordered state
also gives the sorted-run invariant its bucket spills rely on.

Aggregate set: sum/count/avg/min/max/first/first_ignores_null (reference:
datafusion-ext-plans/src/agg/*.rs). Accumulators are flat device columns —
the AccColumn idea (reference: agg/acc.rs) without the row-format detour.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Iterator, Optional

import numpy as np

import jax
import jax.numpy as jnp

from auron_tpu.columnar.batch import (DeviceBatch, PrimitiveColumn, StringColumn,
                                      gather_column, unify_column_widths)
from auron_tpu.columnar.schema import DataType, Field, Schema
from auron_tpu.exprs import ir
from auron_tpu.exprs.eval import EvalContext, TypedValue, evaluate, infer_dtype
from auron_tpu.ops import hashing
from auron_tpu.ops.base import ExecContext, PhysicalOp, count_output, timer
from auron_tpu.utils.shapes import bucket_rows
from auron_tpu.runtime.programs import program_cache

# ---------------------------------------------------------------------------
# accumulator specs
# ---------------------------------------------------------------------------

_SUM_DTYPE = {
    DataType.INT8: DataType.INT64, DataType.INT16: DataType.INT64,
    DataType.INT32: DataType.INT64, DataType.INT64: DataType.INT64,
    DataType.FLOAT32: DataType.FLOAT64, DataType.FLOAT64: DataType.FLOAT64,
    DataType.DECIMAL: DataType.DECIMAL,
}

_JNPT = {
    DataType.INT64: jnp.int64, DataType.FLOAT64: jnp.float64,
    DataType.DECIMAL: jnp.int64, DataType.INT32: jnp.int32,
    DataType.FLOAT32: jnp.float32, DataType.BOOL: jnp.bool_,
    DataType.INT8: jnp.int8, DataType.INT16: jnp.int16,
    DataType.DATE32: jnp.int32, DataType.TIMESTAMP_US: jnp.int64,
}


@dataclass(frozen=True)
class AccSpec:
    """How one aggregate maps to flat state columns.

    state_fields: (name, dtype, reduce_kind) per state column.
    reduce kinds: sum | min | max | or | first (first = value at the
    first-ordered valid row of the group) run on device inside the merge
    kernel; collect_list/collect_set carry a padded list accumulator
    (values[cap, E], lens[cap]) through the same kernel; bloom / udaf are
    host-side states (kind marks the field, no device accumulator).
    """
    fn: str
    state_fields: tuple
    result: tuple  # (dtype, precision, scale)
    elem: Optional[DataType] = None  # list element dtype (collect_*)
    #: per-state-field (precision, scale) for DECIMAL state columns whose
    #: type differs from the result type (avg's sum accumulates at the
    #: INPUT scale; the result-scale shift happens inside the finalizing
    #: division); None = use the result's (p, s)
    state_ps: Optional[tuple] = None


#: reduce kinds whose state is accumulated host-side, not in the kernel
HOST_KINDS = ("bloom", "udaf")

#: reduce kinds over string values; their accumulator is a 3-tuple
#: (chars[cap, W] uint8, lens[cap] int32, valid[cap] bool) and reduction
#: runs on order-preserving uint64 words (the sort operator's order-word
#: normalization, ops/sort.py order_words) instead of segment min/max
_STR_KINDS = ("smin", "smax", "sfirst", "sfirst_ign")


#: reduce kinds over two-limb decimal(p>18) values; their accumulator is a
#: pair (hi[cap], lo[cap]) of int64 limb arrays reduced with carry-exact
#: 128-bit arithmetic inside the merge kernel (reference handles these as
#: Arrow Decimal128 in its AccColumn: datafusion-ext-plans/src/agg/acc.rs +
#: sum.rs; here the i128 is two int64 limbs, columnar/decimal128.py)
_DEC_KINDS = ("dsum", "dmin", "dmax", "dfirst")

#: collect kinds over two-limb decimal(p>18) values; their accumulator is
#: (hi[cap, E], lo[cap, E], lens[cap]) — the padded-list accumulator with
#: limb-pair payloads. State/wire columns ride the MapColumn carrier
#: (hi→keys, lo→values), the same offsets-over-pairs reuse as entry lists
_DCOLLECT = ("dcollect_list", "dcollect_set")

#: limb-pair neutral elements as plain python ints (module-level jnp
#: constants would force backend init at import time — see ops/hashing.py).
#: dmin's neutral is +2^127-1 (hi=INT64_MAX, lo=all-ones), dmax's is
#: -2^127; real decimals are bounded by 10^38 < 2^127 so neither collides
_DEC_NEUTRAL = {"dmin": (0x7FFFFFFFFFFFFFFF, -1),
                "dmax": (-0x8000000000000000, 0)}


def decimal_avg_result(p: int, s: int) -> tuple[int, int]:
    """Spark avg(decimal(p,s)) → DecimalType.bounded(p+4, s+4): each bound
    clamps at 38 independently (avg(decimal(38,18)) is decimal(38,22)) —
    NOT the adjustPrecisionScale scale-reduction binary arithmetic uses."""
    return min(p + 4, 38), min(s + 4, 38)


def make_acc_spec(agg: ir.AggFunction, in_schema: Schema, mode: str) -> AccSpec:
    fn = agg.fn
    if agg.distinct:
        # DISTINCT state rides the collect_set accumulator: the merge
        # kernel already dedupes per group, so count/sum/avg finalize
        # straight off the set (reference models distinct the same
        # "expand to set then aggregate" way); min/max/first are
        # distinct-invariant and keep their plain state
        if fn in ("count", "sum", "avg"):
            dt, p, s = infer_dtype(agg.arg, in_schema)
            if dt in (DataType.STRING, DataType.LIST):
                raise NotImplementedError(f"{fn} DISTINCT over {dt.value}")
            if dt == DataType.DECIMAL and p > 18:
                raise NotImplementedError(
                    f"{fn} DISTINCT over decimal(p={p}>18): the set "
                    "accumulator is single-word; cast the arg first")
            res = {"count": (DataType.INT64, 0, 0),
                   "sum": (_SUM_DTYPE[dt], 0, 0),
                   "avg": (DataType.FLOAT64, 0, 0)}[fn]
            return AccSpec(f"{fn}_distinct",
                           (("set", dt, "collect_set"),), res, elem=dt)
        if fn not in ("min", "max", "first", "first_ignores_null",
                      "collect_set"):
            raise NotImplementedError(f"{fn} DISTINCT")
    if fn in ("count", "count_star"):
        return AccSpec(fn, (("count", DataType.INT64, "sum"),),
                       (DataType.INT64, 0, 0))
    if fn in ("bloom_filter",) or fn.startswith("udaf:"):
        # host-side accumulators read single-word device columns; keep the
        # plan-time fail-fast for two-limb args (the old all-fn guard)
        if agg.arg is not None:
            _dt, _p, _s = infer_dtype(agg.arg, in_schema)
            if _dt == DataType.DECIMAL and _p > 18:
                raise NotImplementedError(
                    f"{fn} over decimal(p={_p}>18): cast the arg to "
                    "decimal(<=18) or double first")
    if fn == "bloom_filter":
        # host-built runtime filter (reference: agg/bloom_filter.rs);
        # result/state travel as base64 of the Spark wire format
        return AccSpec(fn, (("bloom", DataType.STRING, "bloom"),),
                       (DataType.STRING, 0, 0))
    if fn.startswith("udaf:"):
        from auron_tpu.exprs.udf import lookup_udaf
        udaf = lookup_udaf(fn[5:])
        rdt = getattr(udaf, "dtype", DataType.FLOAT64)
        rp = getattr(udaf, "precision", 0)
        rs = getattr(udaf, "scale", 0)
        return AccSpec(fn, (("udaf", DataType.STRING, "udaf"),), (rdt, rp, rs))
    dt, p, s = infer_dtype(agg.arg, in_schema)
    wide = dt == DataType.DECIMAL and p > 18
    if fn == "sum":
        if dt == DataType.DECIMAL and p + 10 > 18:
            # Spark: sum(decimal(p,s)) → decimal(min(p+10,38), s). Narrow
            # inputs with p in 9..18 promote to the two-limb
            # representation with the Spark type (DecimalType.bounded, as
            # the avg branch); wide sums past 2^127 wrap before the 10^38
            # fits-check can see them — same accepted limitation as the
            # narrow path's int64 sums
            return AccSpec(fn, (("sum", DataType.DECIMAL, "dsum"),
                                ("has", DataType.BOOL, "or")),
                           (DataType.DECIMAL, min(p + 10, 38), s))
        sdt = _SUM_DTYPE[dt]
        sp, ss = (min(p + 10, 18), s) if sdt == DataType.DECIMAL else (0, 0)
        return AccSpec(fn, (("sum", sdt, "sum"), ("has", DataType.BOOL, "or")),
                       (sdt, sp, ss))
    if fn == "avg":
        if dt == DataType.DECIMAL:
            # Spark: avg(decimal(p,s)) → decimal(p+4, s+4) (precision cap
            # 38 wide / 18 narrow). The sum accumulates at the INPUT
            # scale; the finalizer shifts to the result scale inside the
            # division (q*10^k + round(r*10^k/count)) so only genuinely
            # overflowing totals wrap the representation
            if wide or p + 4 > 18:
                # Spark promotes past 18 digits: avg(decimal(16,2)) is
                # decimal(20,6) — narrow inputs with p in 15..18 route
                # through the two-limb representation for the result
                rp, rs = decimal_avg_result(p, s)
                sp, kind = min(p + 10, 38), "dsum"
            else:
                rp = p + 4
                rs = min(s + 4, rp)
                sp, kind = min(p + 10, 18), "sum"
            # the count field's (otherwise unused) precision/scale slots
            # carry the RESULT (p, s) so a final-mode op rebuilt from the
            # partial schema recovers the exact Spark avg type — the
            # capped sum-state type alone is not invertible
            return AccSpec(fn, (("sum", DataType.DECIMAL, kind),
                                ("count", DataType.INT64, "sum")),
                           (DataType.DECIMAL, rp, rs),
                           state_ps=((sp, s), (rp, rs)))
        sdt = _SUM_DTYPE[dt]
        res = (DataType.FLOAT64, 0, 0)
        return AccSpec(fn, (("sum", sdt, "sum"), ("count", DataType.INT64, "sum")),
                       res)
    if fn in ("min", "max"):
        if dt == DataType.STRING:
            # single state field; validity rides inside the string acc
            # tuple (chars, lens, valid) — see _reduce_sorted's _STR_KINDS
            return AccSpec(fn, (("val", DataType.STRING, f"s{fn}"),),
                           (dt, p, s))
        if wide:
            return AccSpec(fn, (("val", DataType.DECIMAL, f"d{fn}"),
                                ("has", DataType.BOOL, "or")), (dt, p, s))
        return AccSpec(fn, (("val", dt, fn), ("has", DataType.BOOL, "or")),
                       (dt, p, s))
    if fn in ("first", "first_ignores_null"):
        if dt == DataType.STRING:
            kind = "sfirst_ign" if fn == "first_ignores_null" else "sfirst"
            return AccSpec(fn, (("val", DataType.STRING, kind),), (dt, p, s))
        kind = "dfirst" if wide else "first"
        return AccSpec(fn, (("val", dt, kind), ("has", DataType.BOOL, "or")),
                       (dt, p, s))
    if fn in ("collect_list", "collect_set"):
        if dt in (DataType.STRING, DataType.LIST):
            raise NotImplementedError(f"{fn} over {dt.value}")
        if wide:
            # two-limb elements: the (p, s) of the ELEMENT type rides the
            # result's precision/scale slots (a LIST result has no other
            # use for them) so serde/arrow can rebuild decimal128 values
            return AccSpec(fn, (("list", dt, f"d{fn}"),),
                           (DataType.LIST, p, s), elem=dt)
        # narrow decimal elements carry their (p, s) the same way so the
        # arrow boundary renders list<decimal(p,s)>, not raw scaled ints
        return AccSpec(fn, (("list", dt, fn),), (DataType.LIST, p, s),
                       elem=dt)
    raise NotImplementedError(f"aggregate function {fn}")


def _device_fields(spec: AccSpec) -> tuple:
    """State fields accumulated on device (everything but bloom/udaf)."""
    return tuple(f for f in spec.state_fields if f[2] not in HOST_KINDS)


def _list_column_from_acc(acc, validity):
    """(values[cap, E], lens[cap]) list accumulator → ListColumn (all
    elements below lens are valid: collect_* skip nulls on input)."""
    from auron_tpu.columnar.batch import ListColumn
    vals, lens = acc
    ev = (jnp.arange(vals.shape[1], dtype=jnp.int32)[None, :]
          < lens[:, None])
    return ListColumn(vals, ev, lens, validity)


def _map_carrier_from_dacc(acc, validity):
    """(hi[cap, E], lo[cap, E], lens[cap]) dcollect accumulator → the
    MapColumn carrier used for list<decimal128> state/output columns
    (hi→keys, lo→values; all in-range elements valid — collect skips
    nulls on input)."""
    from auron_tpu.columnar.batch import MapColumn
    hi, lo, lens = acc
    ev = (jnp.arange(hi.shape[1], dtype=jnp.int32)[None, :]
          < lens[:, None])
    return MapColumn(hi, lo, ev, lens, validity)


def _unify_acc_pair(accs_a: tuple, accs_b: tuple) -> tuple[tuple, tuple]:
    """Pad the trailing (element-count / char-width) dimension of paired
    tuple accumulators so state and batch sides can merge shape-to-shape."""
    def _pad2d(t, e):
        # every 2-D member widens (limb-pair lists carry TWO matrices;
        # strings carry one char matrix); 1-D lens/validity stay as-is
        return tuple(jnp.pad(x, ((0, 0), (0, e - x.shape[1])))
                     if x.ndim == 2 and x.shape[1] < e else x for x in t)

    out_a, out_b = [], []
    for a, b in zip(accs_a, accs_b):
        if isinstance(a, tuple) and a[0].ndim == 2:   # list/string accs;
            # decimal limb pairs are 1-D and width-free
            e = max(a[0].shape[1], b[0].shape[1])
            a = _pad2d(a, e)
            b = _pad2d(b, e)
        out_a.append(a)
        out_b.append(b)
    return tuple(out_a), tuple(out_b)


# neutral elements per reduce kind
def _neutral(kind: str, dtype):
    if kind == "sum":
        return jnp.asarray(0, dtype)
    if kind == "min":
        if jnp.issubdtype(dtype, jnp.floating):
            return jnp.asarray(jnp.inf, dtype)
        return jnp.asarray(jnp.iinfo(dtype).max, dtype)
    if kind == "max":
        if jnp.issubdtype(dtype, jnp.floating):
            return jnp.asarray(-jnp.inf, dtype)
        return jnp.asarray(jnp.iinfo(dtype).min, dtype)
    if kind == "or":
        return jnp.asarray(False, jnp.bool_)
    raise ValueError(kind)


# ---------------------------------------------------------------------------
# merge kernel
# ---------------------------------------------------------------------------

def _keys_equal_prev(sorted_keys, live):
    """eq[i] = keys[i] == keys[i-1] (null == null true, NaN == NaN,
    struct fieldwise; eq[0] = False)."""
    from auron_tpu.ops.hashing import adjacent_eq
    eq = jnp.ones_like(live)
    for col in sorted_keys:
        eq = eq & jnp.concatenate([jnp.zeros(1, bool), adjacent_eq(col)])
    return eq


#: dead rows / invalid state slots carry this hash so they sort last; the
#: (astronomically unlikely) real hash equal to it is still correct — such
#: rows group among themselves via the exact key compare
_HASH_SENTINEL = np.uint64(0xFFFFFFFFFFFFFFFF)


def _gather_acc(acc, perm):
    if isinstance(acc, tuple):
        return tuple(x[perm] for x in acc)
    return acc[perm]


def _reduce_sorted(keys_s, accs_s, live_s, h_s, acc_meta, out_cap):
    """Group + reduce rows that are ALREADY sorted by (dead-last, hash
    asc). Shared by the batch-reduce and state-merge kernels. Returns
    (new_keys, new_accs, h_out, num_groups, needed_elems); outputs stay
    hash-sorted (reps are increasing), which is the state invariant the
    merge-by-searchsorted path relies on."""
    cap = live_s.shape[0]
    same_hash = jnp.concatenate(
        [jnp.zeros(1, bool), h_s[1:] == h_s[:-1]])
    same_keys = _keys_equal_prev(keys_s, live_s)
    boundary = live_s & ~(same_hash & same_keys)
    gid = jnp.cumsum(boundary.astype(jnp.int32)) - 1
    gid = jnp.maximum(gid, 0)
    num_groups = jnp.sum(boundary.astype(jnp.int32))

    # first sorted row of each group → representative for keys
    rep = jax.ops.segment_min(
        jnp.where(live_s, jnp.arange(cap, dtype=jnp.int32), cap),
        gid, num_segments=out_cap)
    rep = jnp.clip(rep, 0, cap - 1)
    out_valid = jnp.arange(out_cap, dtype=jnp.int32) < num_groups
    new_keys = tuple(gather_column(c, rep, out_valid) for c in keys_s)
    h_out = jnp.where(out_valid, h_s[rep], _HASH_SENTINEL)

    new_accs = []
    needed_elems = []
    for (kind, out_elems), acc in zip(acc_meta, accs_s):
        if kind in ("collect_list", "collect_set") or kind in _DCOLLECT:
            # acc = (vals[cap, in_E], lens) — or limb pairs
            # (hi[cap, in_E], lo[cap, in_E], lens) for the dcollect kinds;
            # the offsets/scatter logic is per-matrix and shared
            *mats, lens_in = acc
            in_e = mats[0].shape[1]
            lens_s = jnp.where(live_s, lens_in, 0)
            # within-group exclusive element offset: global exclusive
            # cumsum minus the group's base (cumsum at its first row)
            cum = jnp.cumsum(lens_s)
            excl = cum - lens_s
            base = excl[rep]          # [out_cap]
            start = excl - base[gid]
            j = jnp.arange(in_e, dtype=jnp.int32)[None, :]
            flat = gid[:, None] * out_elems + start[:, None] + j
            ok = (live_s[:, None] & (j < lens_s[:, None])
                  & ((start[:, None] + j) < out_elems))
            flat = jnp.where(ok, flat, out_cap * out_elems)

            def scatter(m_s, _flat=flat):
                buf = jnp.zeros((out_cap * out_elems,), m_s.dtype).at[
                    _flat.reshape(-1)].set(m_s.reshape(-1), mode="drop")
                return buf.reshape(out_cap, out_elems)

            out_mats = [scatter(m) for m in mats]
            glens_raw = jax.ops.segment_sum(lens_s, gid,
                                            num_segments=out_cap)
            needed_elems.append(jnp.max(glens_raw))
            glens = jnp.minimum(glens_raw, out_elems)
            if kind in ("collect_set", "dcollect_set"):
                # per-group dedupe, sort-based so memory stays
                # O(cap * E): row-wise lexsort by (is_pad, value...) pushes
                # padding last and groups equal values adjacently; keep
                # first-of-run, compact left. Set order is unspecified
                # (as in Spark), so reordering is free. Limb pairs sort
                # and compare on (hi, lo) jointly.
                jj = jnp.arange(out_elems, dtype=jnp.int32)
                pad = jj[None, :] >= glens[:, None]
                sorted_ops = jax.lax.sort(
                    (pad, *out_mats), dimension=1,
                    num_keys=1 + len(out_mats))
                s_pad, *s_mats = sorted_ops
                neq = s_mats[0][:, 1:] != s_mats[0][:, :-1]
                for m in s_mats[1:]:
                    neq = neq | (m[:, 1:] != m[:, :-1])
                keep = ~s_pad & jnp.concatenate(
                    [jnp.ones((out_cap, 1), bool), neq], axis=1)
                pos = jnp.cumsum(keep, axis=1) - 1
                row = jnp.arange(out_cap, dtype=jnp.int32)[:, None]
                flat2 = jnp.where(keep, row * out_elems + pos,
                                  out_cap * out_elems)
                out_mats = [scatter(m, flat2) for m in s_mats]
                glens = jnp.sum(keep, axis=1).astype(jnp.int32)
            new_accs.append((*out_mats, glens))
            continue
        if kind in _STR_KINDS:
            chars_s, lens_s, v = acc   # already sorted components
            v_s = v & live_s
            idx = jnp.arange(cap, dtype=jnp.int32)
            if kind in ("sfirst", "sfirst_ign"):
                # representative row per group: first sorted live row
                # (sfirst) or first sorted VALID row (sfirst_ign)
                cand = jnp.where(
                    v_s if kind == "sfirst_ign" else live_s, idx, cap)
                raw = jax.ops.segment_min(cand, gid,
                                          num_segments=out_cap)
                fi = jnp.clip(raw, 0, cap - 1)
                # raw == cap means NO qualifying row (all-null group in
                # sfirst_ign): the clipped index then points at an
                # unrelated row whose validity must not leak through
                res_valid = v_s[fi] & (raw < cap) & out_valid
                new_accs.append((chars_s[fi], lens_s[fi], res_valid))
                continue
            # smin/smax: string order reduces on the sort operator's
            # order-preserving words — rank every row by value with one
            # multi-word argsort, then segment_min of ranks picks each
            # group's winner (reference handles all Arrow types in its
            # AccColumn instead: datafusion-ext-plans/src/agg/acc.rs)
            from auron_tpu.ops.sort import order_words
            col_s = StringColumn(chars_s, lens_s, v_s)
            words = order_words(col_s, ascending=(kind == "smin"),
                                nulls_first=False)
            lw = lens_s.astype(jnp.uint64)  # tiebreak embedded NULs
            words.append(lw if kind == "smin" else ~lw)
            lead = jnp.where(v_s, jnp.uint64(0), jnp.uint64(1))
            vperm = idx
            for w in reversed([lead] + words):
                vperm = vperm[jnp.argsort(w[vperm], stable=True)]
            rank = jnp.zeros(cap, jnp.int32).at[vperm].set(idx)
            winner_rank = jax.ops.segment_min(
                jnp.where(v_s, rank, cap), gid, num_segments=out_cap)
            win = vperm[jnp.clip(winner_rank, 0, cap - 1)]
            has = jax.ops.segment_max(
                v_s.astype(jnp.int8), gid,
                num_segments=out_cap).astype(jnp.bool_)
            new_accs.append((chars_s[win], lens_s[win],
                             has & out_valid))
            continue
        if kind in _DEC_KINDS:
            h_acc, l_acc = acc     # int64 limb pair, already sorted
            if kind == "dsum":
                # carry-exact segmented 128-bit sum: split the unsigned low
                # limb into 32-bit halves, segment-sum each as int64 (a
                # half-sum of cap<=2^31 rows stays < 2^63), recombine with
                # explicit carries. Two's-complement makes the signed total
                # exact mod 2^128 (columnar/decimal128.py add128 contract)
                m32 = 0xFFFFFFFF
                lo_lo = jnp.where(live_s, l_acc & m32, 0)
                lo_hi = jnp.where(live_s, (l_acc >> 32) & m32, 0)
                hi_m = jnp.where(live_s, h_acc, 0)
                s_ll = jax.ops.segment_sum(lo_lo, gid, num_segments=out_cap)
                s_lh = jax.ops.segment_sum(lo_hi, gid, num_segments=out_cap)
                s_h = jax.ops.segment_sum(hi_m, gid, num_segments=out_cap)
                mid = (s_ll >> 32) + s_lh          # both non-negative
                out_lo = (s_ll & m32) | (mid << 32)
                out_hi = s_h + (mid >> 32)
                new_accs.append((out_hi, out_lo))
            elif kind in ("dmin", "dmax"):
                # lexicographic two-pass: signed compare on the high limb,
                # then unsigned compare (sign-flip trick) on the low limb
                # among rows tied at the group's winning high limb
                nh, nl = _DEC_NEUTRAL[kind]
                seg = jax.ops.segment_min if kind == "dmin" \
                    else jax.ops.segment_max
                mh = seg(jnp.where(live_s, h_acc, nh), gid,
                         num_segments=out_cap)
                tied = live_s & (h_acc == mh[gid])
                sign = -0x8000000000000000
                lx = jnp.where(tied, l_acc ^ sign,
                               0x7FFFFFFFFFFFFFFF if kind == "dmin"
                               else sign)
                ml = seg(lx, gid, num_segments=out_cap) ^ sign
                new_accs.append((mh, ml))
            else:   # dfirst: limb pair at the first sorted live row
                fi = jax.ops.segment_min(
                    jnp.where(live_s, jnp.arange(cap, dtype=jnp.int32),
                              cap), gid, num_segments=out_cap)
                fi = jnp.clip(fi, 0, cap - 1)
                new_accs.append((h_acc[fi], l_acc[fi]))
            continue
        acc_s = acc
        if kind == "first":
            # value at first sorted valid row; pair-reduce via segment_min
            # over (order, value-index)
            first_idx = jax.ops.segment_min(
                jnp.where(live_s, jnp.arange(cap, dtype=jnp.int32), cap),
                gid, num_segments=out_cap)
            first_idx = jnp.clip(first_idx, 0, cap - 1)
            new_accs.append(acc_s[first_idx])
            continue
        neutral = _neutral(kind, acc.dtype)
        masked = jnp.where(live_s, acc_s, neutral)
        if kind == "sum":
            red = jax.ops.segment_sum(masked, gid, num_segments=out_cap)
        elif kind == "min":
            red = jax.ops.segment_min(masked, gid, num_segments=out_cap)
        elif kind == "max":
            red = jax.ops.segment_max(masked, gid, num_segments=out_cap)
        elif kind == "or":
            red = jax.ops.segment_max(masked.astype(jnp.int8), gid,
                                      num_segments=out_cap).astype(jnp.bool_)
        else:
            raise ValueError(kind)
        new_accs.append(red)
    return new_keys, tuple(new_accs), h_out, num_groups, tuple(needed_elems)


@program_cache("ops.agg.batch_reduce", maxsize=256)
def _batch_reduce_kernel(n_keys: int, acc_meta: tuple, cap: int,
                         donate: bool = False):
    """(keys, accs, live) of one batch → its own group table, hash-sorted.
    One O(B log B) sort of the BATCH only — the state is never re-sorted
    (it merges by binary search in _state_merge_kernel). acc_meta: tuple
    of (kind, out_elems) per state column. Returns (keys, accs, hashes,
    num_groups, needed_elems). ``donate`` hands the batch's key/acc/live
    buffers to XLA — they are dead after the reduce when the child owns
    its batches and no collect kind can force the caller's growth retry
    (callers gate on exactly that; programs.jit keeps donation off the
    advisory CPU backend)."""
    from auron_tpu.runtime import programs

    def kernel(keys, accs, live):
        h = hashing.xxhash64_columns(list(keys), cap).view(jnp.uint64)
        h = jnp.where(live, h, _HASH_SENTINEL)  # dead rows to the end
        perm = jnp.argsort(h, stable=True)
        live_s = live[perm]
        keys_s = tuple(gather_column(c, perm, jnp.ones(cap, bool))
                       for c in keys)
        accs_s = tuple(_gather_acc(a, perm) for a in accs)
        return _reduce_sorted(keys_s, accs_s, live_s, h[perm], acc_meta, cap)

    # graft: donation-ok -- per-batch contribution temporaries;
    # collect kinds/aliased leaves force donate=False upstream
    return programs.jit(kernel,
                        donate_argnums=(0, 1, 2) if donate else ())


def _scatter_acc(a_s, a_b, pos_s, pos_b, m: int):
    """Merge two acc entries (state + batch groups) by scattering both to
    their merged positions."""
    if isinstance(a_s, tuple):
        out = []
        for xs, xb in zip(a_s, a_b):
            buf = jnp.zeros((m,) + xs.shape[1:], xs.dtype)
            buf = buf.at[pos_s].set(xs).at[pos_b].set(xb)
            out.append(buf)
        return tuple(out)
    buf = jnp.zeros((m,) + a_s.shape[1:], a_s.dtype)
    return buf.at[pos_s].set(a_s).at[pos_b].set(a_b)


@program_cache("ops.agg.state_merge", maxsize=256)
def _state_merge_kernel(n_keys: int, acc_meta: tuple, cap_s: int,
                        cap_b: int, out_cap: int):
    """Fold a hash-sorted batch group table into the hash-sorted state
    WITHOUT re-sorting the state: merge positions come from two
    searchsorted calls (O(B log S + S)), then one scatter interleaves both
    sides and the shared reduce folds duplicate groups. This is the
    incremental-update contract of the reference's AggTable (reference:
    datafusion-ext-plans/src/agg/agg_table.rs:68-356) with the
    open-addressing probe replaced by the sorted-merge primitive."""

    @jax.jit
    def kernel(keys_s, accs_s, h_s, n_s, keys_b, accs_b, h_b, n_b):
        live_s = jnp.arange(cap_s, dtype=jnp.int32) < n_s
        live_b = jnp.arange(cap_b, dtype=jnp.int32) < n_b
        # dead slots on both sides hold _HASH_SENTINEL (state invariant +
        # batch-reduce output), so they merge to the tail; side='left' for
        # state vs 'right' for batch keeps state rows first on hash ties
        # (so 'first' semantics prefer earlier batches) and makes the
        # combined position map a permutation of [0, cap_s + cap_b)
        pos_s = (jnp.arange(cap_s, dtype=jnp.int32)
                 + jnp.searchsorted(h_b, h_s, side="left").astype(jnp.int32))
        pos_b = (jnp.arange(cap_b, dtype=jnp.int32)
                 + jnp.searchsorted(h_s, h_b, side="right").astype(jnp.int32))
        m = cap_s + cap_b

        def scatter2(xs, xb):
            buf = jnp.zeros((m,) + xs.shape[1:], xs.dtype)
            return buf.at[pos_s].set(xs).at[pos_b].set(xb)

        def scatter_col(a, b):
            if isinstance(a, StringColumn):
                return StringColumn(scatter2(a.chars, b.chars),
                                    scatter2(a.lens, b.lens),
                                    scatter2(a.validity, b.validity))
            from auron_tpu.columnar.decimal128 import Decimal128Column
            if isinstance(a, Decimal128Column):
                return Decimal128Column(scatter2(a.hi, b.hi),
                                        scatter2(a.lo, b.lo),
                                        scatter2(a.validity, b.validity))
            from auron_tpu.columnar.batch import ListColumn, StructColumn
            if isinstance(a, ListColumn):
                return ListColumn(scatter2(a.values, b.values),
                                  scatter2(a.elem_valid, b.elem_valid),
                                  scatter2(a.lens, b.lens),
                                  scatter2(a.validity, b.validity))
            if isinstance(a, StructColumn):
                return StructColumn(
                    tuple(scatter_col(ca, cb)
                          for ca, cb in zip(a.children, b.children)),
                    scatter2(a.validity, b.validity))
            return PrimitiveColumn(scatter2(a.data, b.data),
                                   scatter2(a.validity, b.validity))

        keys_m = tuple(scatter_col(a, b) for a, b in zip(keys_s, keys_b))
        accs_m = tuple(_scatter_acc(a, b, pos_s, pos_b, m)
                       for a, b in zip(accs_s, accs_b))
        h_m = scatter2(h_s, h_b)
        live_m = scatter2(live_s, live_b)
        return _reduce_sorted(keys_m, accs_m, live_m, h_m, acc_meta, out_cap)

    return kernel


# ---------------------------------------------------------------------------
# the operator
# ---------------------------------------------------------------------------

def _table_nbytes(tbl) -> int:
    from auron_tpu.columnar.batch import column_nbytes
    keys, accs, _num_groups, _cap, hashes = tbl
    return (sum(column_nbytes(k) for k in keys)
            + hashes.nbytes
            + sum(sum(x.nbytes for x in a) if isinstance(a, tuple)
                  else a.nbytes for a in accs))


def _lvl_nbytes(lvl) -> int:
    from auron_tpu.hashtable import HashAggState
    if isinstance(lvl, HashAggState):
        return lvl.nbytes()
    return _table_nbytes(lvl)


def _state_nbytes(state) -> int:
    """Device bytes of a (main, hot) accumulator state — or a
    hash-table-backed state level — from array metadata only."""
    if state is None:
        return 0
    return sum(_lvl_nbytes(lvl) for lvl in state if lvl is not None)


#: single shared NaN object so NaN group keys rendezvous in host dicts
_CANONICAL_NAN = float("nan")


def _column_pyvalues(col, n: int) -> list:
    """First n rows of a column as python values (None where invalid);
    struct rows become tuples of child values (hashable → usable as
    host-dict keys)."""
    from auron_tpu.columnar.batch import StructColumn
    if isinstance(col, StructColumn):
        kids = [_column_pyvalues(ch, n) for ch in col.children]
        val = np.asarray(col.validity[:n])
        return [tuple(k[i] for k in kids) if val[i] else None
                for i in range(n)]
    if isinstance(col, StringColumn):
        chars = np.asarray(col.chars[:n])
        lens = np.asarray(col.lens[:n])
        val = np.asarray(col.validity[:n])
        return [bytes(chars[i, :lens[i]]).decode("utf-8", "surrogateescape")
                if val[i] else None for i in range(n)]
    data = np.asarray(col.data[:n])
    val = np.asarray(col.validity[:n])
    return [data[i].item() if val[i] else None for i in range(n)]


def _key_tuples_host(key_cols, n: int) -> list[tuple]:
    """Group-key tuples for the first n state rows (host python values) —
    the rendezvous between device group state and host-side (udaf)
    accumulators, which are keyed by value."""
    if not key_cols:
        return [() for _ in range(n)]
    per_col = [_column_pyvalues(c, n) for c in key_cols]

    def canon(x):
        # keys only (NOT aggregate inputs — Spark's NormalizeNaNAndZero
        # applies to group/join/window keys alone): one shared NaN object
        # so NaN keys rendezvous in host dicts via identity; -0.0 → 0.0
        if isinstance(x, float):
            if x != x:
                return _CANONICAL_NAN
            if x == 0.0:
                return 0.0
        return x

    return [tuple(canon(c[i]) for c in per_col) for i in range(n)]


def _host_string_column(values: list, cap: int) -> StringColumn:
    """Build a device StringColumn from python str/None values."""
    from auron_tpu.utils.shapes import bucket_string_width
    enc = [None if v is None else v.encode() for v in values]
    width = bucket_string_width(max([len(b) for b in enc if b is not None],
                                    default=1) or 1)
    chars = np.zeros((cap, width), np.uint8)
    lens = np.zeros(cap, np.int32)
    val = np.zeros(cap, bool)
    for i, b in enumerate(enc):
        if b is None:
            continue
        chars[i, :len(b)] = np.frombuffer(b, np.uint8)
        lens[i] = len(b)
        val[i] = True
    return StringColumn(jnp.asarray(chars), jnp.asarray(lens),
                        jnp.asarray(val))


def _contribution_columns(group_exprs, mode: str, aggs, specs,
                          batch: DeviceBatch, in_schema: Schema,
                          ctx: EvalContext):
    """Evaluate group keys and per-row initial accumulator columns.

    Module-level (plan data in, columns out) so traced closures can use
    it without capturing the AggOp — the combine fold's stage closure
    (``build_combine_stage``) lands in the process-wide split-program
    cache, where a captured op would pin its whole subtree (including
    any broadcast build buffers below it) for the cache's lifetime."""
    keys = tuple(evaluate(e, batch, in_schema, ctx).col
                 for e in group_exprs)
    accs = []
    live = batch.row_mask()
    if mode == "final":
        # state columns come in as-is
        idx = len(group_exprs)
        for spec in specs:
            for k, (fname, fdt, kind) in enumerate(spec.state_fields):
                col = batch.columns[idx]
                if kind in HOST_KINDS:
                    idx += 1      # merged host-side (_HostAggState)
                    continue
                if kind in ("collect_list", "collect_set"):
                    accs.append((col.values,
                                 jnp.where(col.validity, col.lens, 0)))
                    idx += 1
                    continue
                if kind in _DCOLLECT:
                    accs.append((col.keys, col.values,
                                 jnp.where(col.validity, col.lens, 0)))
                    idx += 1
                    continue
                if kind in _STR_KINDS:
                    accs.append((col.chars, col.lens, col.validity))
                    idx += 1
                    continue
                if kind in _DEC_KINDS:
                    # limb pair; invalid state rows already hold the
                    # reduce-neutral (partial emit / passthrough
                    # neutralized them), so no re-masking needed
                    accs.append((col.hi, col.lo))
                    idx += 1
                    continue
                data = col.data
                if fname == "has":
                    data = data.astype(jnp.bool_) & col.validity
                elif kind in ("min", "max") or kind == "first":
                    data = data  # validity handled via 'has'
                accs.append(data)
                idx += 1
        return keys, accs, live

    for agg, spec in zip(aggs, specs):
        if spec.state_fields and spec.state_fields[0][2] in HOST_KINDS:
            continue              # accumulated host-side
        if spec.state_fields[0][2] in ("collect_list", "collect_set"):
            # collect_* and the DISTINCT aggs share the padded-list
            # accumulator (one-element list per valid row; len 0
            # where null: Spark collect_*/distinct skip nulls)
            v = evaluate(agg.arg, batch, in_schema, ctx)
            if not isinstance(v.col, PrimitiveColumn):
                raise NotImplementedError(f"{agg.fn} over non-primitives")
            valid = v.validity & live
            accs.append((v.col.data[:, None], valid.astype(jnp.int32)))
            continue
        if spec.state_fields[0][2] in _DCOLLECT:
            from auron_tpu.columnar.decimal128 import Decimal128Column
            v = evaluate(agg.arg, batch, in_schema, ctx)
            if not isinstance(v.col, Decimal128Column):
                raise NotImplementedError(
                    f"{agg.fn}: expected two-limb decimal input")
            valid = v.validity & live
            accs.append((v.col.hi[:, None], v.col.lo[:, None],
                         valid.astype(jnp.int32)))
            continue
        if agg.fn in ("count", "count_star"):
            if agg.arg is None:
                c = live.astype(jnp.int64)
            else:
                v = evaluate(agg.arg, batch, in_schema, ctx)
                c = (v.validity & live).astype(jnp.int64)
            accs.append(c)
            continue
        v = evaluate(agg.arg, batch, in_schema, ctx)
        valid = v.validity & live
        if isinstance(v.col, StringColumn):
            if spec.state_fields[0][2] in _STR_KINDS:
                accs.append((v.col.chars, v.col.lens, valid))
                continue
            raise NotImplementedError(f"{agg.fn} over strings")
        from auron_tpu.columnar.decimal128 import Decimal128Column
        needs_limbs = any(k in _DEC_KINDS
                          for _f, _d, k in spec.state_fields)
        if isinstance(v.col, Decimal128Column) or needs_limbs:
            if isinstance(v.col, Decimal128Column):
                hi, lo = v.col.hi, v.col.lo
            else:
                # narrow decimal input promoted to two limbs: avg
                # with p+4>18 accumulates/returns wide (Spark
                # DecimalType.bounded promotion past 18 digits)
                from auron_tpu.columnar import decimal128 as d128
                hi, lo = d128.from_int64(v.col.data.astype(jnp.int64))
            for fname, fdt, kind in spec.state_fields:
                if fname == "has":
                    accs.append(valid)
                elif fname == "count":
                    accs.append(valid.astype(jnp.int64))
                elif kind == "dsum":
                    accs.append((jnp.where(valid, hi, 0),
                                 jnp.where(valid, lo, 0)))
                elif kind in ("dmin", "dmax"):
                    nh, nl = _DEC_NEUTRAL[kind]
                    accs.append((jnp.where(valid, hi, nh),
                                 jnp.where(valid, lo, nl)))
                elif kind == "dfirst":
                    accs.append((hi, lo))
                else:
                    raise ValueError(kind)
            continue
        for fname, fdt, kind in spec.state_fields:
            if fname == "has":
                accs.append(valid)
            elif fname == "count":
                accs.append(valid.astype(jnp.int64))
            elif kind == "sum":
                jdt = _JNPT[fdt]
                accs.append(jnp.where(valid, v.data, 0).astype(jdt))
            elif kind in ("min", "max"):
                neutral = _neutral(kind, v.data.dtype)
                accs.append(jnp.where(valid, v.data, neutral))
            elif kind == "first":
                accs.append(v.data)
            else:
                raise ValueError(kind)
    return keys, accs, live


def _passthrough_state_batch(keys, accs, live, num_rows) -> DeviceBatch:
    """One input batch re-expressed in partial-state layout without
    merging — each row is its own group (adaptive partial-agg
    skipping, reference: agg/agg_ctx.rs:63-196). Module-level for the
    same no-captured-op rule as ``_contribution_columns``."""
    cols = list(keys)
    for a in accs:
        if isinstance(a, tuple) and len(a) == 3:
            cols.append(StringColumn(a[0], a[1], a[2]))
        elif isinstance(a, tuple) and a[0].ndim == 1:
            from auron_tpu.columnar.decimal128 import Decimal128Column
            cols.append(Decimal128Column(a[0], a[1], live))
        elif isinstance(a, tuple):
            cols.append(_list_column_from_acc(a, live))
        else:
            cols.append(PrimitiveColumn(a, live))
    return DeviceBatch(tuple(cols), num_rows)


class _HostAggState:
    """Host-side accumulation for bloom_filter and host-UDAF aggregates.

    The reference routes these through its JVM fallback (reference:
    datafusion-ext-plans/src/agg/spark_udaf_wrapper.rs:52-380 — per-group
    JVM buffer rows with update/merge/eval/spill entry points) and builds
    runtime bloom filters natively (agg/bloom_filter.rs). Here both are
    host-python escape hatches: udaf buffers live in a dict keyed by group
    key values (the value-keyed analogue of the wrapper's index caches),
    bloom filters accumulate via the vectorized SparkBloomFilter builder.
    State travels between partial/final stages as base64 inside STRING
    columns.

    Round 3: the buffer dict is spill-managed — it registers with the
    memory manager (size estimated from a sampled pickled buffer), and
    under pressure the whole dict serializes to tiered storage (the
    wrapper's spill/unspill entry points, spark_udaf_wrapper.rs:52-380);
    spilled states fold back in via udaf.merge before emit. Per-batch
    updates are bucketed per group so a UDAF exposing a vectorized
    ``update_batch(buf, values)`` hook is called once per group, not once
    per row.
    """

    consumer_name = "host-agg"

    def __init__(self, op: "AggOp", in_schema: Schema, mem=None,
                 metrics=None):
        self.op = op
        self.in_schema = in_schema
        self.mem = mem
        self.metrics = metrics
        self.entries: dict[int, list] = {}
        self.spills = []
        import threading
        self._buf_size_sample = 64
        self._sampled_at = 0     # group count at last buffer-size sample
        self._emitting = False   # spill() refuses once emit has begun
        #: guards the buffer dicts against an externally-triggered victim
        #: spill landing mid-update (same role as the device consumer's
        #: refuse-while-merging protocol)
        self._lock = threading.RLock()
        for si, (agg, spec) in enumerate(zip(op.aggs, op.specs)):
            if spec.fn == "bloom_filter":
                from auron_tpu.exprs.bloom import SparkBloomFilter
                if op.group_exprs:
                    raise NotImplementedError(
                        "bloom_filter aggregate with group keys")
                items = agg.expected_items or 100_000
                self.entries[si] = ["bloom", SparkBloomFilter.create(
                    items, agg.fpp or 0.03)]
            elif spec.fn.startswith("udaf:"):
                from auron_tpu.exprs.udf import lookup_udaf
                self.entries[si] = ["udaf", lookup_udaf(spec.fn[5:]), {}]
        self._spillable = (
            mem is not None
            and getattr(mem, "spill_manager", None) is not None
            and any(e[0] == "udaf" for e in self.entries.values()))
        if self._spillable:
            self.consumer_name = f"host-agg-{id(op):x}"
            mem.register_consumer(self)

    def empty(self) -> bool:
        return not self.entries

    def has_bloom(self) -> bool:
        return any(e[0] == "bloom" for e in self.entries.values())

    # -- MemConsumer ---------------------------------------------------------

    def _n_buffers(self) -> int:
        return sum(len(e[2]) for e in self.entries.values()
                   if e[0] == "udaf")

    def mem_used(self) -> int:
        # per-buffer estimate from a sampled pickle + dict/key overhead
        return self._n_buffers() * (self._buf_size_sample + 96)

    def _account(self) -> None:
        if self._spillable:
            self.mem.update_mem_used(self, self.mem_used())

    def spill(self) -> int:
        """Serialize every UDAF buffer dict to tiered storage and clear.
        Refuses during emit — the restored dict is being read — and takes
        the state lock so a victim spill can't snapshot-and-clear a dict
        another thread's update() is mutating."""
        import pickle
        with self._lock:
            if not self._spillable or self._n_buffers() == 0 \
                    or self._emitting:
                return 0
            freed = self.mem_used()
            payload = {si: list(e[2].items())
                       for si, e in self.entries.items()
                       if e[0] == "udaf"}
            for e in self.entries.values():
                if e[0] == "udaf":
                    e[2].clear()
        spill = self.mem.spill_manager.new_spill()
        spill.write_frame(pickle.dumps(payload))
        self.spills.append(spill.finish())
        if self.metrics is not None:
            self.metrics.counter("mem_spill_count").add(1)
            self.metrics.counter("mem_spill_size").add(freed)
        self.mem.update_mem_used(self, 0)
        return freed

    def restore_spills(self) -> None:
        """Fold spilled buffer dicts back in (udaf.merge) before emit;
        latches the emit phase, which blocks further spills of this
        state."""
        import pickle
        with self._lock:
            self._emitting = True
        if not self.spills:
            return
        spills, self.spills = self.spills, []
        for sp in spills:
            for frame in sp.frames():
                payload = pickle.loads(frame)
                for si, items in payload.items():
                    ent = self.entries.get(si)
                    if ent is None or ent[0] != "udaf":
                        continue
                    _, udaf, bufs = ent
                    for kt, buf in items:
                        old = bufs.get(kt)
                        bufs[kt] = buf if old is None \
                            else udaf.merge(old, buf)
            sp.release()

    def close(self) -> None:
        if self._spillable:
            self.mem.unregister_consumer(self)
        for sp in self.spills:
            sp.release()
        self.spills = []

    # -- update (partial / complete input rows) -----------------------------

    def update(self, batch: DeviceBatch, ectx: EvalContext) -> None:
        if not self.entries:
            return
        with self._lock:
            self._update_locked(batch, ectx)

    def _update_locked(self, batch: DeviceBatch, ectx: EvalContext) -> None:
        n = int(batch.num_rows)
        key_tuples = None
        for si, ent in self.entries.items():
            agg = self.op.aggs[si]
            v = evaluate(agg.arg, batch, self.in_schema, ectx)
            if ent[0] == "bloom":
                data = np.asarray(v.col.data[:n])
                valid = np.asarray((v.validity & batch.row_mask())[:n])
                ent[1].put_longs(data[valid].astype(np.int64))
            else:
                _, udaf, bufs = ent
                if key_tuples is None:
                    key_cols = [evaluate(e, batch, self.in_schema, ectx).col
                                for e in self.op.group_exprs]
                    key_tuples = _key_tuples_host(key_cols, n)
                vals = _column_pyvalues(v.col.with_validity(
                    v.validity & batch.row_mask()), n)
                # bucket rows by group: one update(_batch) call per group
                from collections import defaultdict
                per_group: dict = defaultdict(list)
                for i in range(n):
                    per_group[key_tuples[i]].append(vals[i])
                update_batch = getattr(udaf, "update_batch", None)
                for kt, group_vals in per_group.items():
                    buf = bufs.get(kt)
                    if buf is None:
                        buf = udaf.zero()
                    if update_batch is not None:
                        bufs[kt] = update_batch(buf, group_vals)
                    else:
                        for gv in group_vals:
                            buf = udaf.update(buf, gv)
                        bufs[kt] = buf
        self._sample_buf_size()
        self._account()

    def _sample_buf_size(self) -> None:
        # re-sample only when the group count doubles: pickling a large
        # accumulator every batch would make the hot path O(buffer bytes)
        import pickle
        n = self._n_buffers()
        if n < max(self._sampled_at * 2, 1):
            return
        self._sampled_at = n
        for e in self.entries.values():
            if e[0] == "udaf" and e[2]:
                buf = next(iter(e[2].values()))
                try:
                    self._buf_size_sample = max(
                        self._buf_size_sample, len(pickle.dumps(buf)))
                except Exception:   # graft: disable=GL004 -- size sampling is advisory; an unpicklable UDAF buffer must not fail the query
                    pass
                break

    # -- merge (final-mode input rows carry serialized states) --------------

    def merge_partial(self, batch: DeviceBatch) -> None:
        if not self.entries:
            return
        with self._lock:
            self._merge_partial_locked(batch)

    def _merge_partial_locked(self, batch: DeviceBatch) -> None:
        import base64
        import pickle
        n = int(batch.num_rows)
        n_keys = len(self.op.group_exprs)
        key_tuples = _key_tuples_host(batch.columns[:n_keys], n)
        # state column index per spec in the partial layout
        idx = n_keys
        col_of = {}
        for si, spec in enumerate(self.op.specs):
            col_of[si] = idx
            idx += len(spec.state_fields)
        for si, ent in self.entries.items():
            col = batch.columns[col_of[si]]
            states = _column_pyvalues(col, n)
            if ent[0] == "bloom":
                from auron_tpu.exprs.bloom import SparkBloomFilter
                for s in states:
                    if s:
                        ent[1].merge(SparkBloomFilter.deserialize(
                            base64.b64decode(s)))
            else:
                _, udaf, bufs = ent
                for i, s in enumerate(states):
                    if s is None:
                        continue
                    buf = pickle.loads(base64.b64decode(s))
                    kt = key_tuples[i]
                    old = bufs.get(kt)
                    bufs[kt] = buf if old is None else udaf.merge(old, buf)
        self._sample_buf_size()
        self._account()

    # -- emit ----------------------------------------------------------------

    def result_column(self, si: int, key_tuples: list[tuple], ng: int,
                      cap: int, partial: bool):
        import base64
        import pickle
        self.restore_spills()
        ent = self.entries[si]
        if ent[0] == "bloom":
            blob = base64.b64encode(ent[1].serialize()).decode()
            vals = [blob if i < ng else None for i in range(min(ng, 1))]
            vals += [None] * (cap - len(vals))
            return _host_string_column(vals[:cap], cap)
        _, udaf, bufs = ent
        out = []
        for i in range(ng):
            buf = bufs.get(key_tuples[i])
            if partial:
                out.append(None if buf is None
                           else base64.b64encode(pickle.dumps(buf)).decode())
            else:
                # missing buffer = no input rows reached the UDAF (empty
                # global input): Spark evaluates the initial buffer
                out.append(udaf.eval(udaf.zero() if buf is None else buf))
        out += [None] * (cap - ng)
        if partial:
            return _host_string_column(out, cap)
        spec = self.op.specs[si]
        jdt = _JNPT[spec.result[0]]
        data = np.zeros(cap, np.dtype(jnp.dtype(jdt)))
        valid = np.zeros(cap, bool)
        for i, v in enumerate(out[:cap]):
            if v is not None:
                data[i] = v
                valid[i] = True
        return PrimitiveColumn(jnp.asarray(data), jnp.asarray(valid))


class _AggSpillConsumer:
    """MemConsumer for AggOp: owns the accumulator state between merges.

    The operator checks the state out with ``take_state`` before each merge
    and checks the merged result back in with ``observe``. While checked
    out, an externally-triggered spill (another consumer's update picking
    this one as victim) must refuse — serializing a state the operator is
    about to fold new rows into would double-count every group on emit."""

    def __init__(self, op: "AggOp", mem_manager, metrics, conf=None):
        import threading
        from auron_tpu import config as cfg
        self.op = op
        self.mem = mem_manager
        self.metrics = metrics
        conf = conf or cfg.get_config()
        self.frame_rows = conf.get(cfg.SPILL_FRAME_ROWS)
        self.codec_level = conf.get(cfg.SPILL_CODEC_LEVEL)
        self.consumer_name = f"agg-{id(op):x}"
        self.state = None
        self.spills = []
        #: groups written to spill runs so far — feeds the partial-skip
        #: cardinality estimate (spilled keys are otherwise invisible at
        #: the decision point); an upper bound, since a key can appear in
        #: several runs
        self.spilled_groups = 0
        self._lock = threading.RLock()
        self._merging = False
        mem_manager.register_consumer(self)

    def take_state(self):
        with self._lock:
            self._merging = True
            state, self.state = self.state, None
            return state

    def observe(self, state):
        """Check the merged state back in; may spill it synchronously (the
        requester-side trigger). Returns the state the operator should
        continue with (None right after a spill). A None state still
        reports (as zero) so dropping the state — e.g. the partial-skip
        switchover — clears this consumer's accounted usage instead of
        leaving stale pressure on the manager."""
        with self._lock:
            self.state = state
            self._merging = False
        self.mem.update_mem_used(self, _state_nbytes(state))
        with self._lock:
            return self.state

    def mem_used(self) -> int:
        with self._lock:
            return 0 if self.state is None else _state_nbytes(self.state)

    def spill(self) -> int:
        from auron_tpu.columnar.serde import (batch_to_host,
                                              serialize_host_batch,
                                              slice_host_batch)
        with self._lock:
            if self.state is None or self._merging:
                return 0
            state, self.state = self.state, None
        freed = _state_nbytes(state)
        # each level of the (main, hot) state spills as its own run; the
        # restore path re-merges them, so level boundaries are free
        spill = self.mem.spill_manager.new_spill()
        for lvl in state:
            if lvl is None:
                continue
            state_batch = self.op._state_batch(lvl)
            n = int(state_batch.num_rows)
            if n == 0:
                continue
            self.spilled_groups += n
            host = batch_to_host(state_batch, n)
            for lo in range(0, n, self.frame_rows):
                hi = min(lo + self.frame_rows, n)
                spill.write_frame(
                    serialize_host_batch(slice_host_batch(host, lo, hi),
                                         codec_level=self.codec_level))
        # an all-empty state yields an empty (frameless) spill — restore
        # simply yields nothing for it
        with self._lock:
            self.spills.append(spill.finish())
        self.metrics.counter("mem_spill_count").add(1)
        self.metrics.counter("mem_spill_size").add(freed)
        return freed

    @staticmethod
    def _restored_batches(spill):
        from auron_tpu.columnar.serde import (deserialize_host_batch,
                                              host_to_batch)
        from auron_tpu.utils.shapes import bucket_rows
        for frame in spill.frames():
            host, _ = deserialize_host_batch(frame)
            if host.num_rows:
                yield host_to_batch(host, bucket_rows(host.num_rows))

    def read_spilled_states(self):
        for spill in self.spills:
            yield from self._restored_batches(spill)

    def drain_spilled_states(self):
        """read_spilled_states, then release + clear — used when the
        operator folds spilled runs back in mid-stream (partial-agg skip
        switchover) rather than at close."""
        with self._lock:
            spills, self.spills = self.spills, []
        for spill in spills:
            yield from self._restored_batches(spill)
            spill.release()

    def close(self) -> None:
        self.mem.unregister_consumer(self)
        for s in self.spills:
            s.release()
        self.spills = []


class _HashPathCtl:
    """Per-execution hash-path control: the dispatch decision's knobs
    plus the mid-stream fallback latch (pathological probe overflow
    disables the hash path for the rest of the stream)."""

    __slots__ = ("load_factor", "max_probe_rounds", "metrics", "disabled")

    def __init__(self, decision, metrics):
        self.load_factor = decision.load_factor
        self.max_probe_rounds = decision.max_probe_rounds
        self.metrics = metrics
        self.disabled = False


class AggOp(PhysicalOp):
    """mode: 'partial' emits (keys..., state...); 'final' consumes state
    columns; 'complete' does full agg in one op (reference: AggMode,
    agg/agg_ctx.rs)."""

    name = "agg"

    def __init__(self, child: PhysicalOp, group_exprs: list[ir.Expr],
                 aggs: list[ir.AggFunction], mode: str = "complete",
                 group_names: Optional[list[str]] = None,
                 agg_names: Optional[list[str]] = None,
                 initial_capacity: int = 4096,
                 key_domain: Optional[int] = None):
        assert mode in ("partial", "final", "complete")
        self.child = child
        self.group_exprs = tuple(group_exprs)
        self.aggs = tuple(aggs)
        self.mode = mode
        self.initial_capacity = initial_capacity
        #: exclusive upper bound on the (non-negative, non-null) group
        #: key when the planner can prove one from table stats; feeds
        #: the dense-kernel dispatch (auron_tpu/kernels). The bound is a
        #: plan-time promise, verified at runtime: out-of-range or NULL
        #: keys fail the task with a deterministic ValueError.
        self.key_domain = key_domain
        #: SPMD layout (parallel/mesh.buffer_spec): a partial agg's
        #: state rows shard on the batch dim — they are exactly what a
        #: mesh-routed exchange moves through the all-to-all (the
        #: map-side-combine-before-exchange shape)
        self.mesh_buffer_kind = "agg_partial" if mode == "partial" else None
        in_schema = child.schema()

        if mode == "final":
            # input layout: group cols ++ flattened state cols, as produced
            # by a partial AggOp with the same aggs
            n_keys = len(group_exprs)
            self.specs = []
            idx = n_keys
            for a in aggs:
                # state fields of the partial side
                spec = make_acc_spec_from_partial(a, in_schema, idx)
                self.specs.append(spec)
                idx += len(spec.state_fields)
        else:
            self.specs = [make_acc_spec(a, in_schema, mode) for a in aggs]

        self.group_names = list(group_names or
                                [f"k{i}" for i in range(len(group_exprs))])
        self.agg_names = list(agg_names or [f"a{i}" for i in range(len(aggs))])

        key_fields = []
        for e, n in zip(self.group_exprs, self.group_names):
            # nested-aware: struct group keys keep their children metadata
            # through the output/partial schema (serde needs it)
            from auron_tpu.exprs.eval import infer_field
            key_fields.append(infer_field(e, in_schema, n))

        if mode == "partial":
            state_fields = []
            for spec, an in zip(self.specs, self.agg_names):
                for fi, (fname, fdt, kind) in enumerate(spec.state_fields):
                    if kind in ("collect_list", "collect_set") \
                            or kind in _DCOLLECT:
                        # element (p, s) riding the LIST slots covers
                        # decimal elements (0/0 for everything else)
                        state_fields.append(Field(
                            f"{an}#{fname}", DataType.LIST, True,
                            spec.result[1], spec.result[2],
                            elem=spec.elem))
                        continue
                    if spec.state_ps is not None:
                        prec, sc = spec.state_ps[fi]
                    elif fdt == DataType.DECIMAL:
                        prec, sc = spec.result[1], spec.result[2]
                    else:
                        prec, sc = 0, 0
                    state_fields.append(Field(f"{an}#{fname}", fdt, True, prec, sc))
            self._schema = Schema(tuple(key_fields + state_fields))
        else:
            out_fields = [Field(n, spec.result[0], True, spec.result[1],
                                spec.result[2], elem=spec.elem)
                          for spec, n in zip(self.specs, self.agg_names)]
            self._schema = Schema(tuple(key_fields + out_fields))

    @property
    def children(self):
        return [self.child]

    def schema(self) -> Schema:
        return self._schema

    # -- input row → state contributions -----------------------------------
    def _contributions(self, batch: DeviceBatch, in_schema: Schema,
                       ctx: EvalContext):
        """Evaluate group keys and per-row initial accumulator columns."""
        return _contribution_columns(self.group_exprs, self.mode, self.aggs,
                                     self.specs, batch, in_schema, ctx)

    # -- merge driver -------------------------------------------------------
    #
    # Two-kernel incremental update (the sorted analogue of the reference
    # AggTable's probe-update, agg_table.rs:68-356):
    #   1. _batch_reduce_kernel sorts and reduces ONLY the incoming batch
    #      (O(B log B)) into a hash-sorted group table;
    #   2. _state_merge_kernel folds that table into the hash-sorted state
    #      by searchsorted + scatter (O(B log S + S)) — the state is never
    #      re-sorted and its hashes are computed exactly once.

    def _collect_elems(self, accs) -> list[int]:
        from auron_tpu.utils.shapes import next_pow2
        # list accumulators are (values[cap, E], lens[cap]) — or limb-pair
        # (hi[cap, E], lo[cap, E], lens[cap]) for dcollect; string accs
        # are also 3-tuples but their [1] (lens) is 1-D, and decimal limb
        # pairs are 2-tuples of 1-D arrays with no element width
        def elems(a):
            if not isinstance(a, tuple) or a[0].ndim != 2:
                return 0
            if len(a) == 2 or (len(a) == 3 and a[1].ndim == 2):
                return max(4, next_pow2(a[0].shape[1]))
            return 0
        return [elems(a) for a in accs]

    def _grow_check(self, kinds, out_elems, ng, out_cap, needed):
        """Shared capacity/element-overflow check; mutates out_elems.
        Returns (ok, new_out_cap)."""
        from auron_tpu.utils.shapes import next_pow2
        ok = ng <= out_cap
        ni = 0
        for i, k in enumerate(kinds):
            if k in ("collect_list", "collect_set") or k in _DCOLLECT:
                nd = int(needed[ni])
                ni += 1
                if nd > out_elems[i]:
                    ok = False
                    out_elems[i] = max(4, next_pow2(nd))
        return ok, (bucket_rows(ng) if ng > out_cap else out_cap)

    def _shrink_table(self, tbl, ng: int):
        """Slice a group table down to its occupancy bucket. Live groups
        are a hash-sorted prefix, so shrinking is a plain slice; keeps
        small-cardinality states from carrying batch-sized buffers through
        every subsequent merge."""
        keys, accs, n, cap, h = tbl
        new_cap = max(bucket_rows(max(ng, 1)), self.initial_capacity)
        if new_cap >= cap:
            return tbl

        def slice_col(c):
            if isinstance(c, StringColumn):
                return StringColumn(c.chars[:new_cap], c.lens[:new_cap],
                                    c.validity[:new_cap])
            from auron_tpu.columnar.batch import ListColumn, StructColumn
            from auron_tpu.columnar.decimal128 import Decimal128Column
            if isinstance(c, ListColumn):
                return ListColumn(c.values[:new_cap], c.elem_valid[:new_cap],
                                  c.lens[:new_cap], c.validity[:new_cap])
            if isinstance(c, Decimal128Column):
                return Decimal128Column(c.hi[:new_cap], c.lo[:new_cap],
                                        c.validity[:new_cap])
            if isinstance(c, StructColumn):
                return StructColumn(tuple(slice_col(ch) for ch in c.children),
                                    c.validity[:new_cap])
            return PrimitiveColumn(c.data[:new_cap], c.validity[:new_cap])

        keys2 = tuple(slice_col(c) for c in keys)
        accs2 = tuple(tuple(x[:new_cap] for x in a) if isinstance(a, tuple)
                      else a[:new_cap] for a in accs)
        return (keys2, accs2, n, new_cap, h[:new_cap])

    def _reduce_batch(self, keys, accs, live, elapsed, donate=False):
        """Step 1: one batch → its hash-sorted group table. ``donate``
        (the owned-batch donation sweep) hands the contribution buffers
        to XLA; callers may only pass it when the batch is owned, no
        collect kind can grow elements (the retry below reuses the
        inputs), and no two contribution leaves alias one buffer."""
        kinds = [kind for spec in self.specs
                 for (_n, _dt, kind) in _device_fields(spec)]
        cap_b = live.shape[0]
        out_elems = self._collect_elems(accs)
        if donate:
            # duplicate donated buffers are illegal: sum(x) + avg(x)
            # evaluate to the SAME column object twice
            leaves = jax.tree_util.tree_leaves((tuple(keys), tuple(accs),
                                                live))
            if len({id(x) for x in leaves}) != len(leaves):
                donate = False
        while True:
            meta = tuple(zip(kinds, out_elems))
            kern = _batch_reduce_kernel(len(keys), meta, cap_b, donate)
            with timer(elapsed) as t:
                bk, ba, bh, bn, needed = kern(tuple(keys), tuple(accs),
                                              live)
                # one batched round trip for every control scalar — on
                # tunneled accelerators each separate int() readback costs
                # a full RTT, and the readback doubles as the device sync
                # (under pipelining it IS the sync point: attributed as
                # device wait, obs/profile.timed_get)
                from auron_tpu.obs import profile as _profile
                ng, needed_h = _profile.timed_get([bn, needed])
                ng = int(ng)
            ok, _cap = self._grow_check(kinds, out_elems, ng, cap_b,
                                        needed_h)
            if ok:
                return self._shrink_table((bk, ba, bn, cap_b, bh), ng)

    def _merge_tables(self, s, b, elapsed):
        """Fold group table ``b`` into group table ``s`` (both hash-sorted
        5-tuples) via the searchsorted merge kernel, growing capacity /
        element buckets as needed."""
        kinds = [kind for spec in self.specs
                 for (_n, _dt, kind) in _device_fields(spec)]
        s_keys, s_accs, s_n, s_cap, s_h = s
        bk, ba, bn, cap_b, bh = b
        # string/list columns may land in different width buckets per
        # batch (and per restored spill run) — unify before the merge
        unified = [unify_column_widths([a, c]) for a, c in zip(s_keys, bk)]
        s_keys = tuple(p[0] for p in unified)
        bk = tuple(p[1] for p in unified)
        s_accs, ba = _unify_acc_pair(s_accs, ba)

        out_cap = max(s_cap, self.initial_capacity)
        out_elems = self._collect_elems(s_accs)
        while True:
            meta = tuple(zip(kinds, out_elems))
            kern = _state_merge_kernel(len(s_keys), meta, s_cap, cap_b,
                                       out_cap)
            with timer(elapsed) as t:
                new_keys, new_accs, h_out, num_groups, needed = kern(
                    s_keys, s_accs, s_h, s_n, bk, ba, bh, bn)
                from auron_tpu.obs import profile as _profile
                ng, needed_h = _profile.timed_get([num_groups, needed])
                ng = int(ng)
            ok, out_cap = self._grow_check(kinds, out_elems, ng, out_cap,
                                           needed_h)
            if ok:
                return self._shrink_table(
                    (new_keys, new_accs, num_groups, out_cap, h_out), ng)

    #: hot table folds into main once it has grown this many times the
    #: batch capacity — bounds the amortized main-merge cost to
    #: O(S / _HOT_FACTOR) per batch (LSM-style two-level state)
    _HOT_FACTOR = 8

    def _hash_dispatch(self, ctx: ExecContext):
        """Consult the general-path grouping policy (hashtable vs sort,
        kernels/dispatch.select_hash_agg)."""
        from auron_tpu.exprs.eval import infer_field
        from auron_tpu.kernels import dispatch as kdispatch
        in_schema = self.child.schema()
        key_dts = tuple(infer_field(e, in_schema, "k").dtype
                        for e in self.group_exprs)
        has_float_sum = any(
            kind == "sum" and fdt in (DataType.FLOAT32, DataType.FLOAT64)
            for spec in self.specs
            for (_f, fdt, kind) in _device_fields(spec))
        return kdispatch.select_hash_agg(
            key_dtypes=key_dts, acc_kinds=tuple(self._device_kinds()),
            has_float_sum=has_float_sum, conf=ctx.conf,
            metrics=ctx.metrics_for("kernels"))

    def _merge_hash(self, state, keys, accs, live, elapsed, ht):
        """Hash-table update: the batch folds into the device table in
        one fused program (no per-batch state sort/merge). A sorted
        (tbl, None) state — the partial-skip decision's compaction, or a
        drained spill fold — re-enters the table as group-partial
        contributions (the same associativity the sorted merge relies
        on). Pathological probe overflow latches the sort path for the
        rest of the stream, salvaging the table as a sorted state."""
        from auron_tpu.hashtable import HashAggState, HashTableOverflow
        if state is not None and isinstance(state[0], HashAggState):
            hs = state[0]
            pending = [(keys, accs, live)]
        else:
            hs = HashAggState(
                self._device_kinds(),
                initial_capacity=self.initial_capacity,
                load_factor=ht.load_factor,
                max_probe_rounds=ht.max_probe_rounds)
            pending = [self._state_contributions(self._state_batch(lvl))
                       for lvl in (state or ()) if lvl is not None]
            pending.append((keys, accs, live))
        for i, (k2, a2, l2) in enumerate(pending):
            try:
                with timer(elapsed):    # update syncs via its readback
                    hs.update(k2, a2, l2)
            except HashTableOverflow:
                # fall back mid-stream: export whatever the table holds
                # (updates are transactional — the failed batch is NOT
                # in it) and push it plus the unconsumed contributions
                # through the sort path
                ht.disabled = True
                ht.metrics.counter("hashtable_overflow_fallback").add(1)
                tbl = hs.to_sorted_table()
                sorted_state = None if tbl is None else \
                    (self._shrink_table(tbl, hs.count), None)
                for (k3, a3, l3) in pending[i:]:
                    sorted_state = self._merge_sorted(
                        sorted_state, k3, a3, l3, elapsed)
                return sorted_state
        return (hs,)

    def _merge(self, state, keys, accs, live, elapsed, ht=None,
               donate=False):
        if ht is not None and not ht.disabled:
            # the hash step's overflow-retry protocol reuses its inputs
            # (PERF.md 'Pipelined execution'): no donation on this path
            return self._merge_hash(state, keys, accs, live, elapsed, ht)
        # graft: donation-ok -- sorted path only: the hash branch
        # above latched off (its overflow retry reuses inputs)
        return self._merge_sorted(state, keys, accs, live, elapsed,
                                  donate=donate)

    def _donate_contributions(self, ctx: ExecContext) -> bool:
        """Owned-batch donation gate for the per-batch reduce: the child
        must own its batches (dead after the reduce) and no collect kind
        may be present — collect-element growth retries the reduce with
        the same inputs, which donation would have invalidated."""
        from auron_tpu.ops.base import yields_owned_batches
        if not yields_owned_batches(self.child):
            return False
        return not any(
            k in ("collect_list", "collect_set") or k in _DCOLLECT
            for k in self._device_kinds())

    def _merge_sorted(self, state, keys, accs, live, elapsed,
                      donate=False):
        """state: None | (main, hot), each None | (keys, accs, num_groups,
        capacity, hashes). Two-level update: every batch merges into the
        small hot table (O(B log B + hot)); the hot table folds into main
        only on overflow, so the O(S) main-table pass is paid once per
        ~_HOT_FACTOR batches instead of per batch. The reference's
        open-addressing AggTable gets the same amortization from its
        in-memory table + sorted bucket spills (agg_table.rs:68-356)."""
        # graft: donation-ok -- _donate_contributions gate (owned
        # child, no collect-kind growth retry, no aliased leaves)
        batch_tbl = self._reduce_batch(keys, accs, live, elapsed,
                                       donate=donate)
        cap_b = live.shape[0]
        main, hot = state if state is not None else (None, None)
        if hot is None:
            hot = batch_tbl
        else:
            hot = self._merge_tables(hot, batch_tbl, elapsed)
        # threshold must clear _shrink_table's initial_capacity floor, or
        # a small batch capacity would fold hot->main on EVERY batch (two
        # O(S) passes per batch — worse than the single-level design)
        if hot[3] >= self._HOT_FACTOR * max(cap_b, self.initial_capacity):
            main = hot if main is None else self._merge_tables(main, hot,
                                                               elapsed)
            hot = None
        return (main, hot)

    def _compact(self, state, elapsed):
        """Collapse (main, hot) into one table for emit / spill / the skip
        decision. Returns a 5-tuple or None. A hash-table-backed state
        exports through its hash-sorted conversion."""
        if state is None:
            return None
        from auron_tpu.hashtable import HashAggState
        if isinstance(state[0], HashAggState):
            hs = state[0]
            with timer(elapsed):
                tbl = hs.to_sorted_table()
            return None if tbl is None else \
                self._shrink_table(tbl, hs.count)
        main, hot = state
        if main is None:
            return hot
        if hot is None:
            return main
        return self._merge_tables(main, hot, elapsed)

    # -- finalize → output batch -------------------------------------------
    def _emit(self, state, in_schema: Schema, host=None) -> DeviceBatch:
        from auron_tpu.columnar.batch import ListColumn, resize
        keys, accs, num_groups, cap, _hashes = state
        valid = jnp.arange(cap, dtype=jnp.int32) < num_groups
        ng = int(num_groups)

        # A global bloom state serializes to ~100 KB+ per row; shrink the
        # (single-group) output capacity before attaching it so the string
        # column isn't materialized at state capacity.
        shrink = host is not None and host.has_bloom()
        out_cap = bucket_rows(max(ng, 1), minimum=16) if shrink else cap

        def list_col(a):
            return _list_column_from_acc(a, valid)

        out_cols = list(keys)   # device columns; host cols spliced after
        host_slots = []         # (position, spec_index)

        if self.mode == "partial":
            i = 0
            for si, spec in enumerate(self.specs):
                for (fname, fdt, kind) in spec.state_fields:
                    if kind in HOST_KINDS:
                        host_slots.append((len(out_cols), si))
                        out_cols.append(None)
                        continue
                    data = accs[i]
                    i += 1
                    if kind in _DCOLLECT:
                        out_cols.append(
                            _map_carrier_from_dacc(data, valid))
                    elif isinstance(data, tuple) and len(data) == 3:
                        out_cols.append(StringColumn(
                            data[0], data[1], data[2] & valid))
                    elif isinstance(data, tuple) and data[0].ndim == 1:
                        from auron_tpu.columnar.decimal128 import \
                            Decimal128Column
                        out_cols.append(Decimal128Column(
                            data[0], data[1], valid))
                    elif isinstance(data, tuple):
                        out_cols.append(list_col(data))
                    else:
                        out_cols.append(PrimitiveColumn(data, valid))
        else:
            # final/complete: finalize each agg
            i = 0
            for si, spec in enumerate(self.specs):
                n_state = len(_device_fields(spec))
                state_vals = accs[i: i + n_state]
                i += n_state
                fn = spec.fn
                if fn in ("count", "count_star"):
                    out_cols.append(PrimitiveColumn(state_vals[0], valid))
                elif fn == "sum":
                    s, has = state_vals
                    if isinstance(s, tuple):
                        from auron_tpu.columnar import decimal128 as d128
                        from auron_tpu.columnar.decimal128 import \
                            Decimal128Column
                        h, l = s
                        # Spark non-ANSI: overflow beyond the declared
                        # precision nulls the group
                        fits = d128.fits_precision(h, l, spec.result[1])
                        out_cols.append(Decimal128Column(
                            h, l, valid & has & fits))
                    else:
                        out_cols.append(PrimitiveColumn(s, valid & has))
                elif fn == "avg":
                    s, cnt = state_vals
                    res_dt = spec.result[0]
                    safe = jnp.maximum(cnt, 1)
                    if isinstance(s, tuple):
                        # two-limb sum at the input scale: shift to the
                        # result scale inside the HALF_UP division; Spark
                        # nulls averages that overflow decimal(38)
                        from auron_tpu.columnar import decimal128 as d128
                        from auron_tpu.columnar.decimal128 import \
                            Decimal128Column
                        k = spec.result[2] - spec.state_ps[0][1]
                        qh, ql, fits = d128.avg_pow10_div_half_up(
                            s[0], s[1], safe, k)
                        out_cols.append(Decimal128Column(
                            qh, ql, valid & (cnt > 0) & fits))
                    elif res_dt == DataType.DECIMAL:
                        # scaled-int64 sum at the input scale; same
                        # q*10^k + round(r*10^k/count) composition in
                        # int64, overflow past the 18-digit result → null
                        k = spec.result[2] - spec.state_ps[0][1]
                        shift = 10 ** k
                        a = jnp.abs(s)
                        q0 = a // safe
                        rem = a - q0 * safe
                        fits = q0 < 10 ** (18 - k)
                        frac = (2 * rem * shift + safe) // (2 * safe)
                        q = q0 * shift + frac
                        avg = jnp.where(s < 0, -q, q)
                        out_cols.append(PrimitiveColumn(
                            avg, valid & (cnt > 0) & fits))
                    else:
                        avg = s.astype(jnp.float64) / safe
                        out_cols.append(PrimitiveColumn(
                            avg, valid & (cnt > 0)))
                elif fn in ("min", "max", "first", "first_ignores_null"):
                    if len(state_vals) == 1:   # string acc: validity inside
                        chars, lens, sv = state_vals[0]
                        out_cols.append(StringColumn(chars, lens,
                                                     sv & valid))
                    elif isinstance(state_vals[0], tuple):
                        from auron_tpu.columnar.decimal128 import \
                            Decimal128Column
                        (h, l), has = state_vals
                        out_cols.append(Decimal128Column(h, l, valid & has))
                    else:
                        v, has = state_vals
                        out_cols.append(PrimitiveColumn(v, valid & has))
                elif fn in ("collect_list", "collect_set"):
                    # empty list (not null) for groups with only nulls —
                    # Spark's collect_* semantics
                    if spec.state_fields[0][2] in _DCOLLECT:
                        out_cols.append(_map_carrier_from_dacc(
                            state_vals[0], valid))
                    else:
                        out_cols.append(list_col(state_vals[0]))
                elif fn in ("count_distinct", "sum_distinct",
                            "avg_distinct"):
                    vals, lens = state_vals[0]  # deduped set per group
                    if fn == "count_distinct":
                        out_cols.append(PrimitiveColumn(
                            lens.astype(jnp.int64), valid))
                    else:
                        e = vals.shape[1]
                        mask = (jnp.arange(e, dtype=jnp.int32)[None, :]
                                < lens[:, None])
                        jdt = _JNPT[spec.result[0]]
                        s = jnp.sum(jnp.where(mask, vals, 0),
                                    axis=1).astype(jdt)
                        if fn == "avg_distinct":
                            s = (s.astype(jnp.float64)
                                 / jnp.maximum(lens, 1))
                        # all-null group: no distinct values → NULL
                        out_cols.append(PrimitiveColumn(
                            s, valid & (lens > 0)))
                elif spec.state_fields and spec.state_fields[0][2] in HOST_KINDS:
                    host_slots.append((len(out_cols), si))
                    out_cols.append(None)
                else:
                    raise NotImplementedError(fn)

        if not host_slots:
            batch = DeviceBatch(tuple(out_cols), num_groups)
            return resize(batch, out_cap) if out_cap != cap else batch

        # splice host-aggregated columns (bloom / udaf) at output capacity
        device_batch = DeviceBatch(
            tuple(c for c in out_cols if c is not None), num_groups)
        if out_cap != cap:
            device_batch = resize(device_batch, out_cap)
        key_tuples = _key_tuples_host(device_batch.columns[:len(keys)], ng)
        final_cols = []
        di = 0
        slot_map = dict(host_slots)
        for pos in range(len(out_cols)):
            if pos in slot_map:
                final_cols.append(host.result_column(
                    slot_map[pos], key_tuples, ng, out_cap,
                    partial=self.mode == "partial"))
            else:
                final_cols.append(device_batch.columns[di])
                di += 1
        return DeviceBatch(tuple(final_cols), num_groups)

    # -- spill support ------------------------------------------------------
    # The reference spills the in-mem hash table as sorted buckets and
    # merges with a radix queue on output (agg/agg_table.rs:68-356). Here
    # the spilled unit is the whole accumulator table as a partial-layout
    # batch; on emit, spilled tables re-enter the same device merge kernel —
    # associativity of the accumulators makes re-merging exact.

    def _device_kinds(self) -> list[str]:
        return [kind for spec in self.specs
                for (_f, _d, kind) in _device_fields(spec)]

    def _state_batch(self, state) -> DeviceBatch:
        from auron_tpu.hashtable import HashAggState
        if isinstance(state, HashAggState):
            # spill / fold handoff: export restores the hash-sorted run
            # invariant the bucket spills rely on
            state = self._shrink_table(state.to_sorted_table(),
                                       state.count)
        keys, accs, num_groups, cap, _hashes = state
        valid = jnp.arange(cap, dtype=jnp.int32) < num_groups
        cols = list(keys)
        for kind, a in zip(self._device_kinds(), accs):
            if kind in _DCOLLECT:
                cols.append(_map_carrier_from_dacc(a, valid))
            elif isinstance(a, tuple) and len(a) == 3:
                cols.append(StringColumn(a[0], a[1], a[2] & valid))
            elif isinstance(a, tuple) and a[0].ndim == 1:
                from auron_tpu.columnar.decimal128 import Decimal128Column
                cols.append(Decimal128Column(a[0], a[1], valid))
            elif isinstance(a, tuple):
                cols.append(_list_column_from_acc(a, valid))
            else:
                cols.append(PrimitiveColumn(a, valid))
        return DeviceBatch(tuple(cols), num_groups)

    def _state_contributions(self, batch: DeviceBatch):
        n_keys = len(self.group_exprs)
        keys = tuple(batch.columns[:n_keys])
        live = batch.row_mask()
        accs = []
        idx = n_keys
        for spec in self.specs:
            for (fname, _fdt, kind) in _device_fields(spec):
                col = batch.columns[idx]
                if kind in ("collect_list", "collect_set"):
                    accs.append((col.values,
                                 jnp.where(col.validity, col.lens, 0)))
                    idx += 1
                    continue
                if kind in _DCOLLECT:
                    accs.append((col.keys, col.values,
                                 jnp.where(col.validity, col.lens, 0)))
                    idx += 1
                    continue
                if kind in _STR_KINDS:
                    accs.append((col.chars, col.lens, col.validity))
                    idx += 1
                    continue
                if kind in _DEC_KINDS:
                    accs.append((col.hi, col.lo))
                    idx += 1
                    continue
                data = col.data
                if fname == "has":
                    data = data.astype(jnp.bool_) & col.validity
                accs.append(data)
                idx += 1
        return keys, accs, live

    def _passthrough_batch(self, keys, accs, live, num_rows) -> DeviceBatch:
        """One input batch re-expressed in partial-state layout without
        merging — each row is its own group (adaptive partial-agg
        skipping, reference: agg/agg_ctx.rs:63-196)."""
        return _passthrough_state_batch(keys, accs, live, num_rows)

    # -- map-side combine fold (parallel/exchange + mesh_exchange) ----------
    #
    # A hash exchange whose child is an eligible partial agg elides the
    # partial-agg OPERATOR and folds a per-batch (stateless) combine into
    # the shuffle-split program: contributions → one hash-sort →
    # _reduce_sorted → partial-layout batch, all inside the already-fused
    # split kernel. Groups combine per map batch (host route) or per
    # shard round (all_to_all route) BEFORE rows cross the exchange.
    # Bit-identity: per-batch reduce is exactly today's _batch_reduce
    # step, and the cross-batch merge that the elided partial ladder used
    # to do is the SAME associative merge the final agg performs — so for
    # reassociation-exact kinds the result is unchanged. Float sums are
    # NOT reassociation-exact (the elided hot/main ladder and the final
    # agg's ladder add in different orders) and stay unfolded — the same
    # exactness rule the hashtable dispatch applies
    # (kernels/dispatch.select_hash_agg's float_sum_inexact fallback).

    def combine_fold_reason(self) -> Optional[str]:
        """None when this agg can fold into a shuffle-split program as a
        map-side combine, else why not (explain/telemetry vocabulary)."""
        if self.mode != "partial":
            return "not_partial"
        if not self.group_exprs:
            return "no_group_keys"
        if self.key_domain is not None:
            return "dense_domain"   # keep the dense-kernel dispatch
        kinds = [kind for spec in self.specs
                 for (_f, _d, kind) in spec.state_fields]
        if any(k in HOST_KINDS for k in kinds):
            return "host_state"
        if any(k in ("collect_list", "collect_set") or k in _DCOLLECT
               for k in kinds):
            # element buffers grow by host-side retry; a fixed split
            # program cannot re-enter the growth loop
            return "collect_state"
        exact = {"sum", "min", "max", "or", "first"}
        exact.update(_STR_KINDS)
        exact.update(_DEC_KINDS)
        if any(k not in exact for k in kinds):
            return "unsupported_kind"
        if any(kind == "sum" and fdt in (DataType.FLOAT32, DataType.FLOAT64)
               for spec in self.specs
               for (_f, fdt, kind) in _device_fields(spec)):
            return "float_sum_inexact"
        return None

    def combine_signature(self, mode: str) -> tuple:
        """Hashable trace signature of the folded combine stage — rides
        the split-program cache key (schema/capacity ride separately)."""
        return ("combine_v1", mode, self.group_exprs, self.aggs)

    def build_combine_stage(self, mode: str):
        """Traced (DeviceBatch → (partial-layout DeviceBatch, rows_in))
        stage folded into a shuffle-split program. mode 'combine' merges
        the batch's groups (one stable hash-sort + segment reduce, the
        _batch_reduce_kernel body inlined — no carries, no growth retry:
        eligibility excluded collect kinds); mode 'passthrough' emits
        state-layout rows uncombined (the partial-skip shape — what the
        cost model picks on high-cardinality sites, and the combine=off
        A/B arm). rows_in is the pre-combine live-row count, read by the
        caller in its existing readback fence (combine telemetry)."""
        in_schema = self.child.schema()
        kinds = self._device_kinds()
        # plan DATA only below — this closure is stored in the process-wide
        # split-program cache, so capturing self would pin the whole op
        # subtree (broadcast build buffers included) past query teardown
        group_exprs, aggs, specs = self.group_exprs, self.aggs, self.specs

        def apply(batch: DeviceBatch):
            ectx = EvalContext()
            keys, accs, live = _contribution_columns(
                group_exprs, "partial", aggs, specs, batch, in_schema, ectx)
            rows_in = jnp.sum(live.astype(jnp.int32))
            if mode != "combine":
                return (_passthrough_state_batch(keys, accs, live,
                                                 batch.num_rows), rows_in)
            cap = int(live.shape[0])   # graft: disable=GL001 -- .shape[0] is a static python int, never device data
            h = hashing.xxhash64_columns(list(keys), cap).view(jnp.uint64)
            h = jnp.where(live, h, _HASH_SENTINEL)
            perm = jnp.argsort(h, stable=True)
            keys_s = tuple(gather_column(c, perm, jnp.ones(cap, bool))
                           for c in keys)
            accs_s = tuple(_gather_acc(a, perm) for a in accs)
            meta = tuple((k, 0) for k in kinds)
            new_keys, new_accs, _h, num_groups, _needed = _reduce_sorted(
                keys_s, accs_s, live[perm], h[perm], meta, cap)
            valid = jnp.arange(cap, dtype=jnp.int32) < num_groups
            cols = list(new_keys)
            for kind, a in zip(kinds, new_accs):
                if kind in _STR_KINDS:
                    cols.append(StringColumn(a[0], a[1], a[2] & valid))
                elif kind in _DEC_KINDS:
                    from auron_tpu.columnar.decimal128 import Decimal128Column
                    cols.append(Decimal128Column(a[0], a[1], valid))
                else:
                    cols.append(PrimitiveColumn(a, valid))
            return DeviceBatch(tuple(cols), num_groups), rows_in

        return apply

    # -- dense-domain fast path (auron_tpu/kernels) -------------------------
    #
    # With a planner-proved key-domain bound, grouped aggregation becomes
    # a dense accumulation over [0, key_domain): float sum/count grids run
    # on the dispatched MXU kernel (Pallas VMEM-accumulate on a real TPU,
    # one-hot matmul elsewhere — ~12 B/row HBM traffic instead of the
    # one-hot operands the generic XLA lowering materializes), while
    # integer sums and min/max run as exact dense scatters. The [domain]
    # state is bounded, so none of the spill / partial-skip machinery
    # applies; emit funnels through the general _emit for finalization.

    def _dense_dispatch(self, ctx: ExecContext):
        """Consult the kernel-selection policy (kernels/dispatch.py).
        Returns a dense KernelDecision, or None for the sort path."""
        if self.key_domain is None or self.mode not in ("partial",
                                                        "complete"):
            return None
        from auron_tpu.kernels import dispatch as kdispatch
        in_schema = self.child.schema()
        key_dts = tuple(infer_dtype(e, in_schema)[0]
                        for e in self.group_exprs)
        value_dts = tuple(infer_dtype(a.arg, in_schema)[0]
                          for a in self.aggs if a.arg is not None)
        decision = kdispatch.select_grouped_agg(
            key_domain=self.key_domain, key_dtypes=key_dts,
            agg_fns=tuple(s.fn for s in self.specs),
            value_dtypes=value_dts, conf=ctx.conf,
            metrics=ctx.metrics_for("kernels"))
        return decision if decision.is_dense else None

    def _dense_batch_acc(self, agg, spec, batch, k, live, ectx,
                         in_schema, decision, domain, memo):
        """One batch's dense [domain] accumulator tuple for one spec.

        ``memo`` is the per-batch cache: aggregates over the same
        argument expression share one evaluation and one count scatter
        (sum+count+avg+min+max over a column is the common shape — five
        identical count kernels otherwise, and the eager host loop has
        no jit around it to CSE them)."""
        from auron_tpu.kernels import grouped_agg as gagg

        def counts_for(valid, ckey):
            cnt = memo.get(ckey)
            if cnt is None:
                cnt = gagg.scatter_reduce("count", k, None, valid,
                                          domain, jnp.int64)
                memo[ckey] = cnt
            return cnt

        fn = spec.fn
        if agg.arg is None:   # count_star: live rows per key (== "rows")
            return (counts_for(live, "rows"),)
        akey = repr(agg.arg)
        ev = memo.get(("eval", akey))
        if ev is None:
            v = evaluate(agg.arg, batch, in_schema, ectx)
            ev = (v.col.data, v.validity & live)
            memo[("eval", akey)] = ev
        data, valid = ev
        if fn in ("count", "count_star"):
            return (counts_for(valid, ("cnt", akey)),)
        if fn in ("sum", "avg"):
            sdt = _JNPT[spec.state_fields[0][1]]
            if jnp.issubdtype(jnp.dtype(sdt), jnp.floating):
                # float sums ride the dispatched MXU grids: one launch
                # yields the (sum, count) pair (per-batch counts are
                # 0/1-exact in f32; cross-batch accumulation is
                # f64/int64). The masked 3-term split inside the kernel
                # keeps ~1e-7 rel accuracy at DEFAULT precision.
                v32 = jnp.where(valid, data, 0).astype(jnp.float32)
                c32 = valid.astype(jnp.float32)
                s, c = gagg.sum_count(k, v32, c32, domain,
                                      backend=decision.kernel,
                                      interpret=decision.interpret)
                return (s.astype(jnp.float64), c.astype(jnp.int64))
            # integer sums are contractually exact: dense scatter-add
            s = gagg.scatter_reduce("sum", k, data, valid, domain, sdt)
            return (s, counts_for(valid, ("cnt", akey)))
        if fn in ("min", "max"):
            vdt = _JNPT[spec.state_fields[0][1]]
            val = gagg.scatter_reduce(fn, k, data, valid, domain, vdt)
            return (val, counts_for(valid, ("cnt", akey)))
        raise NotImplementedError(fn)   # unreachable: dispatch gated

    @staticmethod
    def _dense_merge(spec, a, b):
        if spec.fn in ("min", "max"):
            op = jnp.minimum if spec.fn == "min" else jnp.maximum
            return (op(a[0], b[0]), a[1] + b[1])
        return tuple(x + y for x, y in zip(a, b))

    def _dense_domain_stream(self, partition: int, ctx: ExecContext,
                             decision, metrics):
        from auron_tpu.kernels import dispatch as kdispatch
        domain = self.key_domain
        in_schema = self.child.schema()
        ectx = EvalContext(partition_id=partition)
        elapsed = metrics.counter("elapsed_compute")
        kmetrics = ctx.metrics_for("kernels")
        key_jdt = _JNPT[infer_dtype(self.group_exprs[0], in_schema)[0]]

        state = None    # per-spec dense accumulator tuples
        rows = None     # int64[domain] live rows per key (group existence)
        max_k = min_k = saw_null = None   # bound-check scalars (device)
        total_rows = None   # device scalar: readback deferred to emit

        for batch in self.child.execute(partition, ctx):
            ctx.check_cancelled()
            with timer(elapsed, ctx.device_sync) as t:
                live = batch.row_mask()
                kv = evaluate(self.group_exprs[0], batch, in_schema, ectx)
                kdata = kv.col.data.astype(jnp.int64)
                key_live = live & kv.validity
                b_null = jnp.any(live & ~kv.validity)
                b_max = jnp.max(jnp.where(key_live, kdata, jnp.int64(-1)))
                b_min = jnp.min(jnp.where(key_live, kdata, jnp.int64(0)))
                k = jnp.clip(kdata, 0, domain - 1).astype(jnp.int32)
                from auron_tpu.kernels import grouped_agg as gagg
                memo = {"rows": gagg.scatter_reduce(
                    "count", k, None, live, domain, jnp.int64)}
                batch_accs = [
                    self._dense_batch_acc(agg, spec, batch, k, live,
                                          ectx, in_schema, decision,
                                          domain, memo)
                    for agg, spec in zip(self.aggs, self.specs)]
                if state is None:
                    state, rows = batch_accs, memo["rows"]
                    max_k, min_k, saw_null = b_max, b_min, b_null
                    total_rows = jnp.asarray(batch.num_rows, jnp.int64)
                else:
                    state = [self._dense_merge(spec, s, b)
                             for spec, s, b in zip(self.specs, state,
                                                   batch_accs)]
                    rows = rows + memo["rows"]
                    max_k = jnp.maximum(max_k, b_max)
                    min_k = jnp.minimum(min_k, b_min)
                    saw_null = saw_null | b_null
                    total_rows = total_rows + jnp.asarray(batch.num_rows,
                                                          jnp.int64)
                t.track(rows)
        if state is None:
            return

        touched = rows > 0
        ng_dev = jnp.sum(touched.astype(jnp.int32))
        order = jnp.argsort(~touched, stable=True)   # touched keys first
        from auron_tpu.obs import profile as _profile
        # ONE batched readback for every control scalar (each separate
        # int() costs a full RTT on tunneled accelerators); routed
        # through the profiler so the wait books as device time at this
        # moved sync point, like the grow/overflow readbacks above
        ng, mx, mn, nulls, nrows = _profile.timed_get(
            [ng_dev, max_k, min_k, saw_null, total_rows])
        ng = int(ng)
        kdispatch.record_rows(decision, int(nrows), kmetrics)
        # the key_domain hint is a plan-time promise — violations are
        # deterministic defects and must fail the task, not mis-aggregate
        # (run_task_with_retries treats ValueError as no-retry)
        if bool(nulls):
            raise ValueError(
                "dense grouped-agg: NULL group keys under key_domain="
                f"{domain}; the planner's bound is invalid for this data")
        if int(mx) >= domain or int(mn) < 0:
            raise ValueError(
                f"dense grouped-agg: observed key range [{int(mn)}, "
                f"{int(mx)}] violates the planner's key_domain={domain}")
        cap = max(bucket_rows(max(ng, 1)), 16)
        take = order
        if cap > domain:
            take = jnp.concatenate(
                [order, jnp.zeros(cap - domain, order.dtype)])
        take = take[:cap]
        out_valid = jnp.arange(cap, dtype=jnp.int32) < ng_dev
        keys = (PrimitiveColumn(
            jnp.arange(domain, dtype=key_jdt)[take], out_valid),)
        accs = []
        for spec, acc in zip(self.specs, state):
            fn = spec.fn
            if fn in ("count", "count_star"):
                accs.append(acc[0][take])
            elif fn == "avg":
                accs.append(acc[0][take].astype(
                    _JNPT[spec.state_fields[0][1]]))
                accs.append(acc[1][take])
            else:   # sum / min / max: second state field is 'has'
                accs.append(acc[0][take].astype(
                    _JNPT[spec.state_fields[0][1]]))
                accs.append(acc[1][take] > 0)
        tbl = (keys, tuple(accs), ng_dev, cap, jnp.zeros(cap, jnp.uint64))
        yield self._emit(tbl, in_schema)

    def execute(self, partition: int, ctx: ExecContext) -> Iterator[DeviceBatch]:
        from auron_tpu import config as cfg
        from auron_tpu.kernels import dispatch as kdispatch
        metrics = ctx.metrics_for(self)
        decision = self._dense_dispatch(ctx)
        if decision is not None:
            # the chosen backend lands in THIS operator's finalize
            # metrics, so gate logs show which path each agg actually ran
            kdispatch.record_operator_choice(metrics, decision.kernel)
            return count_output(
                self._dense_domain_stream(partition, ctx, decision,
                                          metrics), metrics)
        ht_decision = self._hash_dispatch(ctx)
        ht_ctl = _HashPathCtl(ht_decision, metrics) \
            if ht_decision.is_hash else None
        kdispatch.record_operator_choice(
            metrics, "hashtable" if ht_ctl is not None else "sort")
        elapsed = metrics.counter("elapsed_compute")
        in_schema = self.child.schema()
        ectx = EvalContext(partition_id=partition)
        mem = ctx.mem_manager
        spillable = mem is not None and getattr(mem, "spill_manager", None) is not None
        conf = ctx.conf
        # adaptive partial-agg skipping: only meaningful for keyed partial
        # stages with pure device accumulators (host-side bloom/udaf state
        # cannot pass through row-wise)
        skip_enabled = (self.mode == "partial" and bool(self.group_exprs)
                        and conf.get(cfg.AGG_PARTIAL_SKIP_ENABLED))
        skip_ratio = conf.get(cfg.AGG_PARTIAL_SKIP_RATIO)
        skip_min_rows = conf.get(cfg.AGG_PARTIAL_SKIP_MIN_ROWS)
        donate_contribs = self._donate_contributions(ctx)

        def stream():
            consumer = _AggSpillConsumer(self, mem, metrics, conf) \
                if spillable else None
            host = _HostAggState(self, in_schema, mem=mem, metrics=metrics)
            state = None
            skipping = False
            rows_seen = 0
            # host-side bloom/udaf state cannot pass through row-wise
            skip_pending = skip_enabled and host.empty()
            skipped_rows = metrics.counter("partial_agg_skipped_rows")
            try:
                for batch in self.child.execute(partition, ctx):
                    ctx.check_cancelled()
                    if skipping:
                        keys, accs, live = self._contributions(
                            batch, in_schema, ectx)
                        skipped_rows.add(int(batch.num_rows))
                        yield self._passthrough_batch(keys, accs, live,
                                                      batch.num_rows)
                        continue
                    if self.mode == "final":
                        host.merge_partial(batch)
                    else:
                        host.update(batch, ectx)
                    keys, accs, live = self._contributions(batch, in_schema, ectx)
                    if consumer is not None:
                        # state lives in the consumer between merges so an
                        # external victim spill can take it atomically
                        state = consumer.take_state()
                    # graft: donation-ok -- donate_contribs is the
                    # _donate_contributions gate resolved above
                    state = self._merge(state, keys, accs, live, elapsed,
                                        ht_ctl, donate=donate_contribs)
                    if consumer is not None:
                        state = consumer.observe(state)
                    if not skip_pending:
                        continue
                    # decide ONCE when min_rows is crossed, then latch
                    # either way (the reference also decides at a fixed
                    # observation point, agg_ctx.rs:63-196) — so the steady
                    # state pays no per-batch device sync for bookkeeping
                    rows_seen += int(batch.num_rows)
                    if rows_seen < skip_min_rows:
                        continue
                    skip_pending = False  # decision point reached: latch
                    if consumer is not None:
                        state = consumer.take_state()
                    # exact distinct count needs the levels folded: a key
                    # present in both hot and main would count twice
                    tbl = self._compact(state, elapsed)
                    state = None if tbl is None else (tbl, None)
                    ng = 0 if tbl is None else int(tbl[2])
                    # groups living only in spill runs are invisible in the
                    # in-memory table; without them a pre-decision spill
                    # would suppress skipping in exactly the
                    # memory-pressured high-cardinality case it targets
                    if consumer is not None:
                        ng += consumer.spilled_groups
                    if tbl is not None and ng >= skip_ratio * rows_seen:
                        # fold any spilled runs in, flush the merged
                        # state, then pass the rest of the input through
                        if consumer is not None:
                            for spilled in consumer.drain_spilled_states():
                                k2, a2, l2 = self._state_contributions(
                                    spilled)
                                state = self._merge(state, k2, a2, l2,
                                                    elapsed, ht_ctl)
                        yield self._emit(self._compact(state, elapsed),
                                         in_schema, host)
                        state = None
                        skipping = True
                        if consumer is not None:
                            consumer.observe(None)
                        continue
                    if consumer is not None:
                        state = consumer.observe(state)
                if skipping:
                    return
                if consumer is not None:
                    # re-take: locks out external spills for the final merge
                    # (consumer.state is the source of truth, the local var
                    # may have been spilled away since the last observe)
                    state = consumer.take_state()
                    for spilled in consumer.read_spilled_states():
                        keys, accs, live = self._state_contributions(spilled)
                        state = self._merge(state, keys, accs, live,
                                            elapsed, ht_ctl)
                final_tbl = self._compact(state, elapsed)
                if final_tbl is None:
                    if not self.group_exprs and self.mode in ("final", "complete"):
                        # global agg over empty input: one row of neutral results
                        yield self._empty_global(host)
                    return
                yield self._emit(final_tbl, in_schema, host)
            finally:
                host.close()
                if consumer is not None:
                    consumer.close()

        return count_output(stream(), metrics)

    def _empty_global(self, host=None) -> DeviceBatch:
        from auron_tpu.columnar.batch import ListColumn
        cols = []
        for si, spec in enumerate(self.specs):
            dt = spec.result[0]
            if spec.fn in ("count", "count_star", "count_distinct"):
                cols.append(PrimitiveColumn(jnp.zeros(1, jnp.int64),
                                            jnp.ones(1, bool)))
            elif spec.fn in ("collect_list", "collect_set"):
                if spec.state_fields[0][2] in _DCOLLECT:
                    from auron_tpu.columnar.batch import MapColumn
                    cols.append(MapColumn(
                        jnp.zeros((1, 1), jnp.int64),
                        jnp.zeros((1, 1), jnp.int64),
                        jnp.zeros((1, 1), bool), jnp.zeros(1, jnp.int32),
                        jnp.ones(1, bool)))
                else:
                    cols.append(ListColumn(
                        jnp.zeros((1, 1), _JNPT[spec.elem]),
                        jnp.zeros((1, 1), bool), jnp.zeros(1, jnp.int32),
                        jnp.ones(1, bool)))
            elif host is not None and si in host.entries:
                # empty-input bloom/udaf: serialized empty filter /
                # eval(zero()) — both via the normal result path
                cols.append(host.result_column(si, [()], 1, 1, partial=False))
            elif dt == DataType.STRING:
                cols.append(StringColumn(jnp.zeros((1, 1), jnp.uint8),
                                         jnp.zeros(1, jnp.int32),
                                         jnp.zeros(1, bool)))
            else:
                jdt = _JNPT[dt]
                cols.append(PrimitiveColumn(jnp.zeros(1, jdt),
                                            jnp.zeros(1, bool)))
        return DeviceBatch(tuple(cols), jnp.asarray(1, jnp.int32))

    def __repr__(self):
        fns = ",".join(a.fn for a in self.aggs)
        return f"AggOp[{self.mode}: {len(self.group_exprs)} keys; {fns}]"


def make_acc_spec_from_partial(agg: ir.AggFunction, in_schema: Schema,
                               start_idx: int) -> AccSpec:
    """Spec for the final side: state dtypes read from the partial schema."""
    fn = agg.fn
    if agg.distinct and fn in ("count", "sum", "avg"):
        elem = in_schema[start_idx].elem
        res = {"count": (DataType.INT64, 0, 0),
               "sum": (_SUM_DTYPE[elem], 0, 0),
               "avg": (DataType.FLOAT64, 0, 0)}[fn]
        return AccSpec(f"{fn}_distinct",
                       (("set", elem, "collect_set"),), res, elem=elem)
    if fn in ("count", "count_star"):
        return AccSpec(fn, (("count", DataType.INT64, "sum"),),
                       (DataType.INT64, 0, 0))
    f0 = in_schema[start_idx]
    wide = f0.dtype == DataType.DECIMAL and f0.precision > 18
    if fn == "sum":
        return AccSpec(fn, (("sum", f0.dtype, "dsum" if wide else "sum"),
                            ("has", DataType.BOOL, "or")),
                       (f0.dtype, f0.precision, f0.scale))
    if fn == "avg":
        if f0.dtype == DataType.DECIMAL:
            # the partial side accumulated the sum at the input scale and
            # stashed the result (p, s) in the count field's metadata
            # slots (see make_acc_spec); fall back to an estimate for
            # partial layouts that predate the channel
            f1 = in_schema[start_idx + 1]
            cap = 38 if wide else 18
            rp = f1.precision or (cap if f0.precision >= cap
                                  else max(f0.precision - 10, 1))
            rs = f1.scale or min(f0.scale + 4, rp)
            return AccSpec(fn, (("sum", f0.dtype, "dsum" if wide else "sum"),
                                ("count", DataType.INT64, "sum")),
                           (DataType.DECIMAL, rp, rs),
                           state_ps=((f0.precision, f0.scale), (rp, rs)))
        return AccSpec(fn, (("sum", f0.dtype, "sum"), ("count", DataType.INT64, "sum")),
                       (DataType.FLOAT64, 0, 0))
    if fn in ("min", "max"):
        if f0.dtype == DataType.STRING:
            return AccSpec(fn, (("val", DataType.STRING, f"s{fn}"),),
                           (f0.dtype, f0.precision, f0.scale))
        return AccSpec(fn, (("val", f0.dtype, f"d{fn}" if wide else fn),
                            ("has", DataType.BOOL, "or")),
                       (f0.dtype, f0.precision, f0.scale))
    if fn in ("first", "first_ignores_null"):
        if f0.dtype == DataType.STRING:
            kind = "sfirst_ign" if fn == "first_ignores_null" else "sfirst"
            return AccSpec(fn, (("val", DataType.STRING, kind),),
                           (f0.dtype, f0.precision, f0.scale))
        return AccSpec(fn, (("val", f0.dtype, "dfirst" if wide else "first"),
                            ("has", DataType.BOOL, "or")),
                       (f0.dtype, f0.precision, f0.scale))
    if fn in ("collect_list", "collect_set"):
        if f0.elem == DataType.DECIMAL and f0.precision > 18:
            # the dcollect state field: element (p, s) rides the LIST
            # field's precision/scale slots (see make_acc_spec)
            return AccSpec(fn, (("list", f0.elem, f"d{fn}"),),
                           (DataType.LIST, f0.precision, f0.scale),
                           elem=f0.elem)
        # narrow elements keep their (p, s) the same way — dropping them
        # here made distributed collect over decimal(p<=18) emit raw
        # scaled ints (review finding)
        return AccSpec(fn, (("list", f0.elem, fn),),
                       (DataType.LIST, f0.precision, f0.scale),
                       elem=f0.elem)
    if fn == "bloom_filter":
        return AccSpec(fn, (("bloom", DataType.STRING, "bloom"),),
                       (DataType.STRING, 0, 0))
    if fn.startswith("udaf:"):
        from auron_tpu.exprs.udf import lookup_udaf
        udaf = lookup_udaf(fn[5:])
        rdt = getattr(udaf, "dtype", DataType.FLOAT64)
        return AccSpec(fn, (("udaf", DataType.STRING, "udaf"),),
                       (rdt, getattr(udaf, "precision", 0),
                        getattr(udaf, "scale", 0)))
    raise NotImplementedError(fn)
