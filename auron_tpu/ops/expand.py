"""Expand operator: N projections per input row (GROUPING SETS / ROLLUP /
CUBE lowering — reference: datafusion-ext-plans/src/expand_exec.rs).

TPU design: each projection is the existing project kernel; the outputs are
emitted as one batch per projection rather than row-interleaved — downstream
is always an aggregate, which is order-insensitive, and per-projection
batches keep every kernel dense."""

from __future__ import annotations

from typing import Iterator, Optional

from auron_tpu.columnar.schema import Field, Schema
from auron_tpu.exprs import ir
from auron_tpu.exprs.eval import infer_dtype
from auron_tpu.ops.base import ExecContext, PhysicalOp, count_output, timer
from auron_tpu.ops.project import _project_kernel


class ExpandOp(PhysicalOp):
    name = "expand"
    fusable = True
    fragment_computes = True

    def __init__(self, child: PhysicalOp, projections: list[list[ir.Expr]],
                 names: Optional[list[str]] = None):
        assert projections and all(
            len(p) == len(projections[0]) for p in projections), \
            "expand projections must agree on arity"
        self.child = child
        self.projections = tuple(tuple(p) for p in projections)
        self.fusion_fanout = len(self.projections)
        in_schema = child.schema()
        n_out = len(self.projections[0])
        self.names = list(names or [f"c{i}" for i in range(n_out)])
        fields = []
        for i in range(n_out):
            # result type: first projection wins (all must be compatible —
            # the host converter guarantees it, like the reference's schema)
            dt, p, s = infer_dtype(self.projections[0][i], in_schema)
            fields.append(Field(self.names[i], dt, True, p, s))
        self._schema = Schema(tuple(fields))

    @property
    def children(self):
        return [self.child]

    def schema(self) -> Schema:
        return self._schema

    def build_kernel_fragment(self):
        import jax.numpy as jnp

        from auron_tpu.columnar.batch import DeviceBatch
        from auron_tpu.exprs.eval import EvalContext, evaluate
        from auron_tpu.ops.fused import KernelFragment
        projections, in_schema = self.projections, self.child.schema()

        def apply(batch, partition_id, carry):
            outs = []
            for proj in projections:
                # every projection of one input batch sees the same row
                # offset, exactly like the unfused per-projection kernels
                ctx = EvalContext(partition_id=partition_id,
                                  row_num_offset=carry, memo={})
                cols = tuple(evaluate(e, batch, in_schema, ctx).col
                             for e in proj)
                outs.append(DeviceBatch(cols, batch.num_rows))
            return tuple(outs), \
                carry + jnp.asarray(batch.num_rows, jnp.int64)

        return KernelFragment(key=("expand", projections, in_schema),
                              apply=apply, fanout=len(projections))

    def execute(self, partition: int, ctx: ExecContext) -> Iterator:
        metrics = ctx.metrics_for(self)
        elapsed = metrics.counter("elapsed_compute")
        in_schema = self.child.schema()

        def stream():
            import jax.numpy as jnp
            row_off = 0
            for batch in self.child.execute(partition, ctx):
                for proj in self.projections:
                    kern = _project_kernel(proj, in_schema, batch.capacity)
                    with timer(elapsed, sync=ctx.device_sync) as t:
                        out = t.track(kern(batch, jnp.int32(partition),
                                           jnp.int64(row_off)))
                    yield out
                row_off += int(batch.num_rows)

        return count_output(stream(), metrics)

    def __repr__(self):
        return f"ExpandOp[{len(self.projections)} projections]"
