"""Sort-merge join: order-preserving streaming merge over sorted children.

The reference SMJ advances row cursors over two sorted streams (reference:
datafusion-ext-plans/src/sort_merge_join_exec.rs, joins/smj/stream_cursor.rs)
— a sequential pattern that doesn't vectorize. The TPU design keeps the
*streaming window* idea but replaces cursor advancement with vectorized
binary search:

  - every join key is normalized into order-preserving uint64 words (the
    same encoding the sort operator uses, ops/sort.py:order_words), so a
    multi-column key compares as a fixed-width word vector;
  - the right ("build") side is buffered in a sliding window that covers
    exactly the key range of the current left batch — batches ahead of the
    range stay unpulled, batches behind it are evicted as the left stream
    advances (the streaming bound the reference gets from its cursors);
  - each left batch binary-searches the window's word matrix for its
    match range (lo/hi per row, all lanes parallel), then expands ranges to
    (left_row, window_row) pairs in slot order — ascending left row, then
    ascending window row — so output order is exactly the children's sort
    order. Left-outer rows that match nothing emit one synthesized
    null-extended slot inline, preserving interleaved order.

Join types: inner / left / right / full / semi / anti / existence, with
"left" = the streaming probe side (reference: auron.proto JoinType).
Right/full track a per-window-row matched mask; unmatched window rows are
emitted (null-extended) when their batch slides out of the window, i.e. in
key order.

Memory: the window registers with the memory manager; under pressure it
offloads its device arrays to host DRAM (re-uploaded lazily at next probe)
— the analogue of the reference's build-side spill consumer
(join_hash_map.rs:365-387 + MemConsumer).
"""

from __future__ import annotations

from typing import Iterator, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from auron_tpu.columnar.batch import (DeviceBatch, PrimitiveColumn,
                                      StringColumn, batch_nbytes, compact,
                                      gather_batch, gather_column)
from auron_tpu.columnar.schema import DataType, Field, Schema
from auron_tpu.exprs import ir
from auron_tpu.exprs.eval import EvalContext, evaluate
from auron_tpu.ops.base import ExecContext, PhysicalOp, count_output, timer
from auron_tpu.ops.sort import _concat_all, sort_key_words
from auron_tpu.utils.shapes import bucket_rows
from auron_tpu.runtime.programs import program_cache

__all__ = ["SortMergeJoinOp"]


# ---------------------------------------------------------------------------
# key words
# ---------------------------------------------------------------------------

@program_cache("ops.smj.key_words", maxsize=256)
def _key_words_kernel(key_exprs: tuple, in_schema: Schema, capacity: int):
    """Per-key order-word matrices [capacity, nw_k] (null word included, so
    word order == the child's (asc, nulls_first) sort order) + a per-row
    "never matches" mask (null key or dead row)."""

    @jax.jit
    def kernel(batch: DeviceBatch):
        ctx = EvalContext()
        cols = [evaluate(e, batch, in_schema, ctx).col for e in key_exprs]
        dead = ~batch.row_mask()
        per_key = []
        for c in cols:
            words = sort_key_words([c], [(True, True)])
            per_key.append(jnp.stack(words, axis=1))
            dead = dead | ~c.validity
        return tuple(per_key), dead

    return kernel


def _pad_and_join(per_key, widths: tuple[int, ...]) -> jax.Array:
    """Zero-pad each key's word matrix to the target width and hstack.
    Zero is exactly the word the encoder emits for missing trailing string
    bytes at a wider bucket (ascending keys), so padding is order-exact."""
    parts = []
    for w, t in zip(per_key, widths):
        if w.shape[1] < t:
            w = jnp.pad(w, ((0, 0), (0, t - w.shape[1])))
        parts.append(w)
    return jnp.concatenate(parts, axis=1) if len(parts) > 1 else parts[0]


def _host_row(per_key, row: int) -> tuple[np.ndarray, ...]:
    """One row's key words per key, on host (for window advance/evict
    decisions)."""
    return tuple(np.asarray(w[row]) for w in per_key)


def _host_lex_le(a: tuple[np.ndarray, ...], b: tuple[np.ndarray, ...]) -> bool:
    """a <= b under the padded word order."""
    for aw, bw in zip(a, b):
        t = max(aw.shape[0], bw.shape[0])
        ap = np.zeros(t, np.uint64); ap[:aw.shape[0]] = aw
        bp = np.zeros(t, np.uint64); bp[:bw.shape[0]] = bw
        for x, y in zip(ap.tolist(), bp.tolist()):
            if x < y:
                return True
            if x > y:
                return False
    return True  # equal


# ---------------------------------------------------------------------------
# device kernels
# ---------------------------------------------------------------------------

@program_cache("ops.smj.probe", maxsize=256)
def _probe_kernel(n_words: int, win_cap: int, cap: int, left_outer: bool):
    """Vectorized lexicographic binary search of every left row's key into
    the window's sorted word matrix. Returns per-left-row lower bound,
    match count, emit count (left-outer adds a synthesized slot for
    matchless live rows) and total emit."""
    steps = max(win_cap, 1).bit_length() + 1

    @jax.jit
    def kernel(win_words, win_n, q_words, q_dead, live_n):
        def lex(mid):
            lt = jnp.zeros(cap, bool)
            eq = jnp.ones(cap, bool)
            for w in range(n_words):
                aw = win_words[mid, w]
                qw = q_words[:, w]
                lt = lt | (eq & (aw < qw))
                eq = eq & (aw == qw)
            return lt, lt | eq

        def search(le_mode):
            lo = jnp.zeros(cap, jnp.int32)
            hi = jnp.full(cap, win_n, jnp.int32)

            def body(_, carry):
                lo, hi = carry
                mid = (lo + hi) // 2
                lt, le = lex(mid)
                go = le if le_mode else lt
                active = lo < hi
                lo2 = jnp.where(active & go, mid + 1, lo)
                hi2 = jnp.where(active & ~go, mid, hi)
                return lo2, hi2

            lo, hi = lax.fori_loop(0, steps, body, (lo, hi))
            return lo

        lo = search(False)
        hi = search(True)
        counts = jnp.where(q_dead, 0, hi - lo)
        live = jnp.arange(cap, dtype=jnp.int32) < live_n
        if left_outer:
            emit = jnp.where(live, jnp.maximum(counts, 1), 0)
        else:
            emit = counts
        return lo, counts, emit, jnp.sum(emit)

    return kernel


@program_cache("ops.smj.expand", maxsize=256)
def _expand_kernel(out_cap: int, cap: int):
    """Expand per-left-row emit ranges into slot-ordered
    (left_idx, window_idx, is_real_match) triples. Slot order = ascending
    left row, then ascending window row: the order-preservation invariant."""

    @jax.jit
    def kernel(lo, counts, emit):
        starts = jnp.cumsum(emit) - emit
        total = jnp.sum(emit)
        slots = jnp.arange(out_cap, dtype=jnp.int32)
        left_idx = jnp.clip(
            jnp.searchsorted(starts, slots, side="right").astype(jnp.int32) - 1,
            0, cap - 1)
        offset = slots - starts[left_idx]
        in_range = slots < total
        real = in_range & (offset < counts[left_idx])
        win_idx = jnp.where(real, lo[left_idx] + offset, 0)
        return left_idx, win_idx, real, total

    return kernel


def _gather_pairs(left: DeviceBatch, win: Optional[DeviceBatch], left_idx,
                  win_idx, real, total) -> DeviceBatch:
    ones = jnp.ones_like(real)
    lcols = tuple(gather_column(c, left_idx, ones) for c in left.columns)
    if win is None:
        return DeviceBatch(lcols, total)
    rcols = tuple(gather_column(c, win_idx, real) for c in win.columns)
    return DeviceBatch(lcols + rcols, total)


# ---------------------------------------------------------------------------
# sliding window over the right stream
# ---------------------------------------------------------------------------

class _MergeWindow:
    """Buffered suffix of the right stream covering the live key range.

    Device state (concatenated batch + word matrix) is rebuilt lazily when
    batches are appended/evicted and can be offloaded to host DRAM by the
    memory manager (the MemConsumer role)."""

    consumer_name = "smj-window"

    def __init__(self, key_exprs, schema: Schema, mem, metrics):
        self.key_exprs = key_exprs
        self.schema = schema
        self.mem = mem
        self.metrics = metrics
        #: (batch, per-key word matrices) pairs not yet merged in
        self.pending: list[tuple[DeviceBatch, tuple]] = []
        self.batch: Optional[DeviceBatch] = None     # live-prefix concat
        self.per_key: Optional[tuple] = None          # per-key word matrices
        self.n = 0                                    # live rows
        self.matched: Optional[np.ndarray] = None     # host bool [cap]
        self._host_batch = None                       # offloaded form
        self._bytes = 0
        self._pinned = False
        if mem is not None:
            mem.register_consumer(self)
            self.consumer_name = f"smj-window-{id(self):x}"

    # -- MemConsumer --------------------------------------------------------
    def mem_used(self) -> int:
        return self._bytes

    def pin(self) -> None:
        """Block offload while a probe is reading the device state (the
        refuse-while-merging protocol, same as ops/agg.py's merge guard)."""
        self._pinned = True

    def unpin(self) -> None:
        self._pinned = False

    def spill(self) -> int:
        """Offload device state to host DRAM; next probe re-uploads."""
        if self._pinned or self.batch is None or self._host_batch is not None:
            return 0
        from auron_tpu.columnar.serde import batch_to_host
        freed = self._bytes
        self._host_batch = batch_to_host(self.batch, self.n)
        self.batch = None
        self.per_key = None
        self._bytes = 0
        self.metrics.counter("mem_spill_count").add(1)
        self.metrics.counter("mem_spill_size").add(freed)
        return freed

    # -- window ops ---------------------------------------------------------
    def append(self, batch: DeviceBatch, per_key: tuple) -> None:
        """Queue a right batch with its already-computed key words (the pull
        loop encodes them anyway to read the batch's max key — reusing them
        keeps window maintenance O(total rows), not O(rows × appends)."""
        self.pending.append((batch, per_key))

    def _account(self):
        self._bytes = batch_nbytes(self.batch) if self.batch is not None else 0
        if self.per_key is not None:
            self._bytes += sum(int(w.size) * 8 for w in self.per_key)
        if self.mem is not None:
            self.mem.update_mem_used(self, self._bytes)

    def ensure_built(self) -> None:
        """Materialize device state from pending appends / host offload."""
        parts: list[tuple[DeviceBatch, Optional[tuple], int]] = []
        old_n = self.n
        if self._host_batch is not None:
            from auron_tpu.columnar.serde import host_to_batch
            b = host_to_batch(self._host_batch,
                              bucket_rows(max(self._host_batch.num_rows, 1)))
            parts.append((b, None, int(b.num_rows)))
            self._host_batch = None
        elif self.batch is not None:
            parts.append((self.batch, self.per_key, self.n))
        for b, pk in self.pending:
            parts.append((b, pk, int(b.num_rows)))
        self.pending = []
        if not parts:
            return
        if len(parts) == 1 and parts[0][0] is self.batch \
                and self.per_key is not None:
            return  # unchanged
        batches = [p[0] for p in parts]
        merged = _concat_all(batches) if len(batches) > 1 else batches[0]
        self.batch = merged
        self.n = int(merged.num_rows)
        cap = merged.capacity
        if any(pk is None for _b, pk, _n in parts):
            # reload after host offload: words must be re-encoded
            kern = _key_words_kernel(self.key_exprs, self.schema, cap)
            self.per_key, _ = kern(merged)
        else:
            # splice the per-batch word matrices (live prefixes, widths
            # zero-padded to the window-wide max — order-exact)
            spliced = []
            for ki in range(len(parts[0][1])):
                ws = [pk[ki][:n] for _b, pk, n in parts]
                tw = max(w.shape[1] for w in ws)
                ws = [jnp.pad(w, ((0, 0), (0, tw - w.shape[1])))
                      if w.shape[1] < tw else w for w in ws]
                w = jnp.concatenate(ws, axis=0) if len(ws) > 1 else ws[0]
                if w.shape[0] < cap:
                    w = jnp.pad(w, ((0, cap - w.shape[0]), (0, 0)))
                spliced.append(w)
            self.per_key = tuple(spliced)
        m = np.zeros(cap, bool)
        if self.matched is not None and old_n:
            m[:old_n] = self.matched[:old_n]
        self.matched = m
        self._account()

    def word_widths(self) -> tuple[int, ...]:
        return tuple(w.shape[1] for w in self.per_key)

    def words(self, widths: tuple[int, ...]) -> jax.Array:
        return _pad_and_join(self.per_key, widths)

    def evict_below(self, k: int,
                    want_unmatched: bool = True) -> Optional[DeviceBatch]:
        """Drop the first ``k`` window rows; when ``want_unmatched`` (the
        right/full tracking path) also returns the compacted unmatched
        prefix for null-extension — skipped for join types that discard
        it (one device compact saved per left batch)."""
        if k <= 0 or self.batch is None:
            return None
        k = min(k, self.n)
        cap = self.batch.capacity
        idxs = jnp.arange(cap, dtype=jnp.int32)
        unmatched = None
        if want_unmatched:
            keep_mask = (idxs < k) & (idxs < self.n) & \
                ~jnp.asarray(self.matched[:cap])
            unmatched = compact(self.batch, keep_mask)
        shift = jnp.clip(idxs + k, 0, cap - 1)
        self.batch = gather_batch(self.batch, shift,
                                  jnp.asarray(self.n - k, jnp.int32))
        self.per_key = tuple(w[shift] for w in self.per_key)
        self.matched = np.concatenate(
            [self.matched[k:], np.zeros(k, bool)])
        self.n -= k
        self._account()
        if unmatched is not None and int(unmatched.num_rows) == 0:
            unmatched = None
        return unmatched

    def unmatched_rest(self) -> Optional[DeviceBatch]:
        if self.batch is None or self.n == 0:
            return None
        cap = self.batch.capacity
        keep = self.batch.row_mask() & ~jnp.asarray(self.matched[:cap])
        out = compact(self.batch, keep)
        return out if int(out.num_rows) > 0 else None

    def mark_matched(self, matched_dev) -> None:
        self.matched |= np.asarray(matched_dev)

    def close(self) -> None:
        if self.mem is not None:
            self.mem.unregister_consumer(self)


@program_cache("ops.smj.mark", maxsize=256)
def _mark_kernel(win_cap: int):
    """Matched window rows = union of the per-left-row match intervals
    [lo, lo+count): one +1/-1 scatter and a prefix sum — O(win_cap), no
    pair expansion."""

    @jax.jit
    def kernel(lo, counts):
        has = counts > 0
        starts = jnp.where(has, lo, win_cap)
        ends = jnp.where(has, lo + counts, win_cap)
        delta = jnp.zeros(win_cap + 1, jnp.int32)
        delta = delta.at[starts].add(1, mode="drop")
        delta = delta.at[ends].add(-1, mode="drop")
        return jnp.cumsum(delta[:win_cap]) > 0

    return kernel


# ---------------------------------------------------------------------------
# the operator
# ---------------------------------------------------------------------------

class SortMergeJoinOp(PhysicalOp):
    """Order-preserving merge join; children must be sorted ascending
    (nulls first) on the join keys — the contract the planner establishes,
    as Spark's EnsureRequirements does for the reference
    (sort_merge_join_exec.rs)."""

    name = "sort_merge_join"

    def __init__(self, probe: PhysicalOp, build: PhysicalOp,
                 probe_keys: list[ir.Expr], build_keys: list[ir.Expr],
                 join_type: str = "inner"):
        assert join_type in ("inner", "left", "right", "full", "semi",
                             "anti", "existence")
        self.probe = probe
        self.build = build
        self.probe_keys = tuple(probe_keys)
        self.build_keys = tuple(build_keys)
        self.join_type = join_type
        ps, bs = probe.schema(), build.schema()
        if join_type in ("semi", "anti"):
            self._schema = ps
        elif join_type == "existence":
            self._schema = Schema(tuple(ps.fields) +
                                  (Field("exists", DataType.BOOL, False),))
        else:
            self._schema = Schema(tuple(ps.fields) + tuple(bs.fields))

    @property
    def children(self):
        return [self.probe, self.build]

    def schema(self) -> Schema:
        return self._schema

    # -- execution ----------------------------------------------------------
    def execute(self, partition: int, ctx: ExecContext) -> Iterator[DeviceBatch]:
        metrics = ctx.metrics_for(self)
        elapsed = metrics.counter("elapsed_compute")
        left_schema = self.probe.schema()
        right_schema = self.build.schema()
        jt = self.join_type
        track = jt in ("right", "full")
        left_outer = jt in ("left", "full")

        def null_extended_right(rows: DeviceBatch) -> DeviceBatch:
            cap = rows.capacity
            null_left = tuple(_null_column(f, cap) for f in left_schema)
            return DeviceBatch(null_left + rows.columns, rows.num_rows)

        _sync = ctx.device_sync

        def stream():
            right_iter = self.build.execute(partition, ctx)
            win = _MergeWindow(self.build_keys, right_schema,
                               ctx.mem_manager, metrics)
            right_done = False
            last_right_max = None
            try:
                for left in self.probe.execute(partition, ctx):
                    nL = int(left.num_rows)
                    if nL == 0:
                        continue
                    kern = _key_words_kernel(self.probe_keys, left_schema,
                                             left.capacity)
                    with timer(elapsed, sync=_sync) as t:
                        q_per_key, q_dead = t.track(kern(left))
                    lmax = _host_row(q_per_key, nL - 1)
                    # pull right batches until the window covers lmax
                    while not right_done and (
                            last_right_max is None
                            or _host_lex_le(last_right_max, lmax)):
                        rb = next(right_iter, None)
                        if rb is None:
                            right_done = True
                            break
                        nR = int(rb.num_rows)
                        if nR == 0:
                            continue
                        rkern = _key_words_kernel(self.build_keys,
                                                  right_schema, rb.capacity)
                        with timer(elapsed, sync=_sync) as t:
                            r_per_key, _ = t.track(rkern(rb))
                        last_right_max = _host_row(r_per_key, nR - 1)
                        win.append(rb, r_per_key)
                    win.pin()
                    try:
                        win.ensure_built()
                        for out in self._probe_one(left, nL, q_per_key,
                                                   q_dead, win, elapsed,
                                                   track, left_outer,
                                                   null_extended_right,
                                                   _sync):
                            yield out
                    finally:
                        win.unpin()
                # tail: flush unmatched window + remaining right stream
                if track:
                    win.pin()
                    try:
                        win.ensure_built()
                        rest = win.unmatched_rest()
                    finally:
                        win.unpin()
                    if rest is not None:
                        yield null_extended_right(rest)
                    for rb in right_iter:
                        if int(rb.num_rows) > 0:
                            yield null_extended_right(rb)
            finally:
                win.close()

        return count_output(stream(), metrics)

    def _probe_one(self, left: DeviceBatch, nL: int, q_per_key, q_dead,
                   win: _MergeWindow, elapsed, track: bool, left_outer: bool,
                   null_extended_right, _sync: bool = True):
        jt = self.join_type
        cap = left.capacity

        if win.batch is None or win.n == 0:
            # empty window: no matches possible for this batch
            yield from self._emit_no_window(left, cap)
            return

        widths = tuple(
            max(a, b) for a, b in zip(
                tuple(w.shape[1] for w in q_per_key), win.word_widths()))
        # per-key word-count mismatch across sides can only differ on
        # string keys; unify by zero-padding (order-exact)
        win_words = win.words(widths)
        q_words = _pad_and_join(q_per_key, widths)
        win_cap = win.batch.capacity

        pkern = _probe_kernel(int(win_words.shape[1]), win_cap, cap,
                              left_outer)
        with timer(elapsed, sync=_sync) as t:
            lo, counts, emit, total = t.track(pkern(win_words, win.n, q_words,
                                                    q_dead, left.num_rows))
        total_i = int(total)

        if jt in ("semi", "anti", "existence"):
            has = counts > 0
            with timer(elapsed, sync=_sync) as t:
                if jt == "semi":
                    out = compact(left, has)
                elif jt == "anti":
                    out = compact(left, left.row_mask() & ~has)
                else:
                    col = PrimitiveColumn(has, jnp.ones(cap, bool))
                    out = DeviceBatch(left.columns + (col,), left.num_rows)
                t.track(out)
            if int(out.num_rows) > 0 or jt == "existence":
                yield out
        elif total_i > 0:
            out_cap = bucket_rows(total_i)
            expand = _expand_kernel(out_cap, cap)
            with timer(elapsed, sync=_sync) as t:
                left_idx, win_idx, real, tot = expand(lo, counts, emit)
                out = t.track(_gather_pairs(left, win.batch, left_idx,
                                            win_idx, real, tot))
            if track:
                mark = _mark_kernel(win_cap)
                with timer(elapsed):
                    win.mark_matched(mark(lo, counts))
            yield out

        # advance: window rows strictly below this batch's max key can
        # never match future (ascending) left rows
        k = int(lo[nL - 1])
        evicted = win.evict_below(k, want_unmatched=track)
        if track and evicted is not None:
            yield null_extended_right(evicted)

    def _emit_no_window(self, left: DeviceBatch, cap: int):
        jt = self.join_type
        if jt == "anti":
            yield left
        elif jt == "semi":
            yield DeviceBatch(left.columns, jnp.asarray(0, jnp.int32))
        elif jt == "existence":
            col = PrimitiveColumn(jnp.zeros(cap, bool), jnp.ones(cap, bool))
            yield DeviceBatch(left.columns + (col,), left.num_rows)
        elif jt in ("left", "full"):
            null_right = tuple(_null_column(f, cap)
                               for f in self.build.schema())
            yield DeviceBatch(left.columns + null_right, left.num_rows)
        # inner/right: nothing

    def __repr__(self):
        return (f"SortMergeJoinOp[{self.join_type}, "
                f"{len(self.probe_keys)} keys]")


def _null_column(field: Field, cap: int):
    from auron_tpu.exprs.eval import null_column_for_field
    return null_column_for_field(field, cap)
