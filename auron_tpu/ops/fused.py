"""Whole-stage fusion: one jit-compiled program per operator chain.

The paper's core bet is lowering the host engine's plan into native
vectorized execution; the per-operator analogue this engine shipped with
jits one program per operator per shape bucket, so every operator
boundary round-trips a materialized DeviceBatch through HBM and the
TPC-DS gate is compile-bound (PERF.md). Whole-stage codegen — Neumann's
"compiling query plans", the HyPer lineage in PAPERS.md — maps directly
onto jit composition: a maximal chain of per-batch, row-local operators
(filter, project, expand, limit-within-batch, rename) becomes ONE
``FusedStageOp`` whose body is one XLA program built from the member
ops' ``KernelFragment``s. XLA then eliminates the intermediates
entirely: a fused filter→project chain keeps the filtered batch in
registers/VMEM instead of writing it back to HBM, and the stage costs
one program build instead of one per member.

Fragment contract (``PhysicalOp.build_kernel_fragment``): a pure
traceable function

    apply(batch, partition_id, carry) -> (out_batches, carry')

where ``carry`` is one int64 scalar of per-member streaming state —
the member's ``row_num_offset`` for expression evaluation (advanced by
input rows per batch, exactly like the unfused operators' host-side
``row_off``), or the remaining-row budget for a fused limit. Carries
live on device between batches (an int64[n_members] vector threaded
through the program), so fusion adds no host synchronization; only a
fused limit reads its slot back per batch — the same per-batch sync the
unfused LimitOp paid via ``int(batch.num_rows)``.

Stage breakers — agg cores, joins, sorts, exchanges, window, generate —
never implement fragments, so the planner's fusion pass
(ir/planner.fuse_stages) cannot cross them by construction.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Iterator

import jax
import jax.numpy as jnp

from auron_tpu.columnar.batch import DeviceBatch
from auron_tpu.columnar.schema import Schema
from auron_tpu.ops.base import (ExecContext, PhysicalOp, count_output,
                                timer)
from auron_tpu.runtime import programs


@dataclass(frozen=True)
class KernelFragment:
    """One operator's contribution to a fused stage program.

    ``key`` is a hashable signature that — together with the stage's
    input schema and batch capacity — fully determines ``apply``'s
    traced behavior; it is the program-cache key component for this
    member. ``fanout`` is the number of output batches per input batch
    (ExpandOp > 1). ``init_carry`` seeds the member's carry slot at
    stream start; ``is_limit`` marks a carry that counts a remaining-row
    budget the host must poll for early exit.
    """

    key: tuple
    apply: Callable
    fanout: int = 1
    init_carry: int = 0
    is_limit: bool = False


#: the one compile site for fused stage programs, keyed on
#: (member fragment keys, stage input schema, capacity)
_STAGE_PROGRAMS = programs.register(
    programs.ProgramCache("ops.fused.stage", maxsize=512))


def thread_fragments(fragments, batch: DeviceBatch, partition_id, carries):
    """Traced core shared by every fused program (the stage kernel, the
    exchange's split prologue, the join's probe prologue): thread each
    intermediate batch through the member chain — expand fan-out is
    unrolled statically, and each member's carry advances across the
    intermediate batches in exactly the order the unfused generator
    chain would stream them. Returns (out_batches, carry_list)."""
    outs = (batch,)
    new_carries = []
    for i, frag in enumerate(fragments):
        carry = carries[i]
        nxt = []
        for b in outs:
            res, carry = frag.apply(b, partition_id, carry)
            nxt.extend(res)
        outs = tuple(nxt)
        new_carries.append(jnp.asarray(carry, jnp.int64))
    return outs, new_carries


def sharded_fragment_chain(fragments: list[KernelFragment]):
    """The SPMD form of a fused stage body (parallel/mesh_exchange):
    a traced function running the member chain on ONE mesh shard's
    local batch, with the member carries threaded as an
    ``int64[n_members]`` vector (each shard owns its map partition's
    carries — exactly the per-partition streaming state the unfused
    host loop keeps per ``execute(partition)`` call).

    ``apply(batch, partition_id, carry_vec) -> (out_batch, carry_vec')``

    Only straight chains qualify (fan-out members and fused limits are
    rejected by the exchange's eligibility check before tracing):
    a sharded stage yields exactly one output batch per shard."""

    def apply(batch: DeviceBatch, partition_id, carry_vec):
        outs, new_carries = thread_fragments(
            fragments, batch, partition_id,
            [carry_vec[i] for i in range(len(fragments))])
        (b,) = outs   # eligibility rejected fan-out chains
        return b, (jnp.stack(new_carries) if new_carries
                   else jnp.zeros((0,), jnp.int64))

    return apply


def build_stage_kernel(fragments: list[KernelFragment],
                       donate: bool = False):
    """Compose member fragments into one jitted program. ``donate``
    hands the input batch's buffers to XLA — the chain gathers/projects
    into fresh arrays, so an OWNED input batch is dead the moment the
    program runs (programs.jit keeps donation off the advisory CPU
    backend)."""

    def kernel(batch: DeviceBatch, partition_id, carries):
        outs, new_carries = thread_fragments(fragments, batch,
                                             partition_id, carries)
        return outs, jnp.stack(new_carries)

    # graft: donation-ok -- donate gated on yields_owned_batches by
    # the caller; fused stages never retry on the same inputs
    return programs.jit(kernel, donate_argnums=(0,) if donate else ())


def stage_program(frag_keys: tuple, in_schema: Schema, capacity: int,
                  fragments: list[KernelFragment], donate: bool = False):
    """Central-registry lookup of the stage program. Returns
    (kernel, built) — ``built`` feeds the per-stage counters in the
    ``kernels`` metrics snapshot."""
    return _STAGE_PROGRAMS.get_or_build(
        (frag_keys, in_schema, capacity, donate),
        lambda: build_stage_kernel(fragments, donate))


class FusedStageOp(PhysicalOp):
    """A maximal chain of fusable operators executing as one program.

    ``members`` are ordered upstream→downstream; the stage's input is
    the first member's child. Schema, output batches and row offsets are
    bit-identical to executing the members separately — the fusion pass
    only changes how many XLA programs exist and where the
    intermediates live.
    """

    name = "fused_stage"

    def __init__(self, members: list[PhysicalOp]):
        assert members, "fused stage needs at least one member"
        for m in members:
            assert m.fusable, f"{m!r} is not fusable"
        self.members = list(members)
        self.input = members[0].children[0]
        self._schema = members[-1].schema()

    @property
    def children(self):
        return [self.input]

    @property
    def owns_output(self):
        # a chain with any computing member gathers/projects into fresh
        # arrays; a pure pass-through chain (rename/limit) aliases its
        # input's columns
        if any(m.fragment_computes for m in self.members):
            return True
        return "inherit"

    def schema(self) -> Schema:
        return self._schema

    def fragment_pipeline(self):
        """(fragments, frag_keys) for this stage — also consumed by
        ShuffleExchangeOp when it folds the chain into its split program
        (the exchange-prologue fusion)."""
        fragments = [m.build_kernel_fragment() for m in self.members]
        assert all(f is not None for f in fragments)
        return fragments, tuple(f.key for f in fragments)

    def has_limit(self) -> bool:
        from auron_tpu.ops.limit import LimitOp
        return any(isinstance(m, LimitOp) for m in self.members)

    def _consumer_fold(self, ctx: ExecContext):
        """(fragments, frag_keys) when this stage's input is an inner
        hash join whose matched output can run through the join's
        gather+chain program (ops/joins._gather_consumer_program) — the
        probe-into-consumer fold. The planner's cost pass gates it per
        site via ``probe_fold_consumer`` (ir/cost.choose_probe_fold);
        fan-out members and fused limits keep the stage on its own
        program (the gather program yields exactly one batch and never
        polls a budget)."""
        from auron_tpu.ops.joins import HashJoinOp
        j = self.input
        if not isinstance(j, HashJoinOp) or j.join_type != "inner":
            return None
        if not getattr(j, "probe_fold_consumer", True):
            return None
        if self.has_limit():
            return None
        fragments, frag_keys = self.fragment_pipeline()
        if not fragments or any(f.fanout != 1 for f in fragments):
            return None
        return fragments, frag_keys

    def run_chain(self, source, partition: int,
                  ctx: ExecContext) -> Iterator[DeviceBatch]:
        """Run the member chain over an externally produced batch stream
        — the consumer fold's degraded path (the join fell back to SMJ
        or saw an empty build side): those batches flow through the
        ordinary stage program here, so every batch the join yields is
        chained exactly once on every route."""
        kmetrics = ctx.metrics_for("kernels")
        built_c = kmetrics.counter("fused_stage_programs_built")
        hit_c = kmetrics.counter("fused_stage_program_hits")
        fragments, frag_keys = self.fragment_pipeline()
        in_schema = self.input.schema()
        carries = jnp.asarray([f.init_carry for f in fragments],
                              dtype=jnp.int64)
        for batch in source:
            ctx.check_cancelled()
            kern, built = stage_program(frag_keys, in_schema,
                                        batch.capacity, fragments)
            (built_c if built else hit_c).add(1)
            outs, carries = kern(batch, jnp.int32(partition), carries)
            yield from outs

    def execute(self, partition: int, ctx: ExecContext) -> Iterator[DeviceBatch]:
        metrics = ctx.metrics_for(self)
        from auron_tpu import config as cfg
        if ctx.conf.get(cfg.FUSION_ENABLED):
            fold = self._consumer_fold(ctx)
            if fold is not None:
                # probe-into-consumer: the join runs this stage's
                # fragments inside its gather program and yields
                # already-chained batches — count them as this stage's
                # output (whole-stage attribution, as with the probe
                # prologue fold)
                fragments, frag_keys = fold
                return count_output(
                    self.input.execute(partition, ctx,
                                       _consumer=(self, fragments,
                                                  frag_keys)),
                    metrics)
        elapsed = metrics.counter("elapsed_compute")
        kmetrics = ctx.metrics_for("kernels")
        built_c = kmetrics.counter("fused_stage_programs_built")
        hit_c = kmetrics.counter("fused_stage_program_hits")
        in_schema = self.input.schema()
        fragments, frag_keys = self.fragment_pipeline()
        limit_slots = [i for i, f in enumerate(fragments) if f.is_limit]
        init = [f.init_carry for f in fragments]
        _sync = ctx.device_sync
        # donation sweep: an owned input batch is dead once the chain
        # gathered/projected it into fresh arrays — donate it to XLA
        # (no-op on CPU; pass-through chains alias their input in the
        # output, which donation supports — the input buffer BECOMES
        # the output buffer)
        from auron_tpu.ops.base import yields_owned_batches
        donate = (any(m.fragment_computes for m in self.members)
                  and yields_owned_batches(self.input))

        def stream():
            from auron_tpu.obs import profile as _profile
            carries = jnp.asarray(init, dtype=jnp.int64)
            for batch in self.input.execute(partition, ctx):
                ctx.check_cancelled()
                kern, built = stage_program(frag_keys, in_schema,
                                            batch.capacity, fragments,
                                            donate)
                (built_c if built else hit_c).add(1)
                with timer(elapsed, sync=_sync) as t:
                    outs, carries = t.track(
                        kern(batch, jnp.int32(partition), carries))
                    if limit_slots:
                        # a fused limit's budget readback is a real
                        # per-batch sync point: time it as device wait
                        budgets = _profile.timed_get(
                            [carries[i] for i in limit_slots])
                yield from outs
                # a fused limit exhausts: stop pulling the child (the
                # slot readback is the same per-batch sync the unfused
                # LimitOp paid on int(batch.num_rows))
                if limit_slots and any(int(b) <= 0 for b in budgets):
                    break

        return count_output(stream(), metrics)

    def __repr__(self):
        inner = " -> ".join(repr(m) for m in self.members)
        return f"FusedStageOp[{inner}]"
