"""Spark-compatible murmur3_x86_32 and xxhash64 as vectorized JAX kernels.

Bit-exact with Spark (and with the reference's Rust implementations,
reference: native-engine/datafusion-ext-commons/src/hash/mur.rs,
hash/xxhash.rs, spark_hash.rs): every value contributes the murmur/xxhash of
its little-endian byte representation; multi-column hashes chain the running
hash through the seed; NULL leaves the running hash unchanged. murmur3 with
seed 42 drives hash-shuffle partitioning (reference:
datafusion-ext-plans/src/shuffle/mod.rs:163-188), so exact parity here means
a Spark driver and this engine agree on row placement.

All kernels are row-vectorized: scalar bit-twiddling from the reference
becomes lane-parallel int32/uint64 VPU ops; the per-string block loop is a
``lax.fori_loop`` over the (static, bucketed) width with per-row predication.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from auron_tpu.columnar.batch import Column, DeviceBatch, PrimitiveColumn, StringColumn

SPARK_SHUFFLE_SEED = 42

# numpy scalars, not jnp: a module-level jnp constant forces jax backend
# init at import time, which hangs any process whose ambient accelerator
# client is wedged (round-2 driver gate, MULTICHIP_r02.json rc=124) before
# the dryrun can re-exec itself with a safe platform.
_M3_C1 = np.uint32(0xCC9E2D51)
_M3_C2 = np.uint32(0x1B873593)
_M3_MIX = np.uint32(0xE6546B64)


def _rotl32(x, r):
    return (x << r) | (x >> (32 - r))


def _mix_k1(k1):
    k1 = k1 * _M3_C1
    k1 = _rotl32(k1, 15)
    return k1 * _M3_C2


def _mix_h1(h1, k1):
    h1 = h1 ^ k1
    h1 = _rotl32(h1, 13)
    return h1 * jnp.uint32(5) + _M3_MIX


def _fmix(h1, length):
    h1 = h1 ^ length
    h1 = h1 ^ (h1 >> 16)
    h1 = h1 * jnp.uint32(0x85EBCA6B)
    h1 = h1 ^ (h1 >> 13)
    h1 = h1 * jnp.uint32(0xC2B2AE35)
    return h1 ^ (h1 >> 16)


def murmur3_int32(values: jax.Array, seed: jax.Array) -> jax.Array:
    """murmur3 of a 4-byte LE value (int8/16/32 are widened to i32 first,
    matching Spark). values: int32[n]; seed: int32[n] or scalar → int32[n]."""
    h1 = _mix_h1(jnp.uint32(seed) if jnp.ndim(seed) == 0 else seed.astype(jnp.uint32),
                 _mix_k1(values.astype(jnp.int32).view(jnp.uint32)
                         if values.dtype != jnp.int32 else values.view(jnp.uint32)))
    return _fmix(h1, jnp.uint32(4)).view(jnp.int32)


def murmur3_u32_pair(low: jax.Array, high: jax.Array, seed) -> jax.Array:
    """murmur3 of an 8-byte LE value given as (low, high) uint32 words."""
    h1 = jnp.uint32(seed) if jnp.ndim(seed) == 0 else seed.astype(jnp.uint32)
    h1 = _mix_h1(h1, _mix_k1(low))
    h1 = _mix_h1(h1, _mix_k1(high))
    return _fmix(h1, jnp.uint32(8)).view(jnp.int32)


def murmur3_int64(values: jax.Array, seed: jax.Array) -> jax.Array:
    """murmur3 of an 8-byte LE value: low word then high word."""
    v = values.astype(jnp.int64)
    low = (v & 0xFFFFFFFF).astype(jnp.uint32)
    high = ((v >> 32) & 0xFFFFFFFF).astype(jnp.uint32)
    return murmur3_u32_pair(low, high, seed)


def canonicalize_float(d: jax.Array) -> jax.Array:
    """Spark NormalizeNaNAndZero / Java doubleToLongBits canonicalization:
    -0.0 → 0.0 and every NaN payload → the canonical quiet NaN. Applied to
    float KEY values before hashing, order-word encoding, or equality so
    equal-under-Spark keys agree bit-for-bit; non-float arrays pass
    through."""
    if not jnp.issubdtype(d.dtype, jnp.floating):
        return d
    v = jnp.where(d == 0.0, jnp.zeros((), d.dtype), d)
    return jnp.where(d != d, jnp.full((), jnp.nan, d.dtype), v)


def nan_aware_eq(a: jax.Array, b: jax.Array) -> jax.Array:
    """Elementwise key equality with Spark semantics: NaN == NaN (floats
    only; plain == elsewhere). -0.0 == 0.0 already holds under IEEE ==."""
    same = a == b
    if jnp.issubdtype(a.dtype, jnp.floating):
        same = same | ((a != a) & (b != b))
    return same


def adjacent_eq(col) -> jax.Array:
    """bool[cap-1]: row i structurally equals row i-1 under Spark key
    semantics — null == null, NaN == NaN, struct fieldwise. Shared by
    group-boundary and window-partition detection."""
    from auron_tpu.columnar.batch import (ListColumn, MapColumn,
                                          StringColumn, StringListColumn,
                                          StringMapColumn, StructColumn)
    from auron_tpu.columnar.decimal128 import Decimal128Column
    if isinstance(col, (MapColumn, ListColumn, StringListColumn,
                        StringMapColumn)):
        raise NotImplementedError(
            f"grouping / partitioning on {type(col).__name__} keys is not "
            "supported — Spark itself disallows map-typed keys; key on "
            "the individual elements instead")
    both_valid = col.validity[1:] & col.validity[:-1]
    both_null = ~col.validity[1:] & ~col.validity[:-1]
    if isinstance(col, StructColumn):
        same = jnp.ones_like(both_valid)
        for ch in col.children:
            same = same & adjacent_eq(
                ch.with_validity(ch.validity & col.validity))
    elif isinstance(col, StringColumn):
        same = jnp.all(col.chars[1:] == col.chars[:-1], axis=1) \
            & (col.lens[1:] == col.lens[:-1])
    elif isinstance(col, Decimal128Column):
        same = (col.hi[1:] == col.hi[:-1]) & (col.lo[1:] == col.lo[:-1])
    else:
        same = nan_aware_eq(col.data[1:], col.data[:-1])
    return (both_valid & same) | both_null


def pairwise_eq(pc, probe_idx, bc, build_idx) -> jax.Array:
    """Structural value equality of pc[probe_idx] vs bc[build_idx] under
    Spark key semantics (NaN == NaN; struct fieldwise with null-field ==
    null-field). Does NOT include the top-level validity conjunction —
    equi-join null keys never match, so the caller applies its own
    null rule."""
    from auron_tpu.columnar.batch import (ListColumn, MapColumn,
                                          StringColumn, StringListColumn,
                                          StringMapColumn, StructColumn)
    from auron_tpu.columnar.decimal128 import Decimal128Column
    if isinstance(pc, (MapColumn, ListColumn, StringListColumn,
                       StringMapColumn)):
        raise NotImplementedError(
            f"join keys of {type(pc).__name__} type are not supported")
    if isinstance(pc, StructColumn):
        same = jnp.ones(probe_idx.shape[0], bool)
        for cp, cb in zip(pc.children, bc.children):
            pv = cp.validity[probe_idx] & pc.validity[probe_idx]
            bv = cb.validity[build_idx] & bc.validity[build_idx]
            child_same = pairwise_eq(cp, probe_idx, cb, build_idx)
            same = same & ((pv & bv & child_same) | (~pv & ~bv))
        return same
    if isinstance(pc, StringColumn):
        return jnp.all(pc.chars[probe_idx] == bc.chars[build_idx], axis=1) \
            & (pc.lens[probe_idx] == bc.lens[build_idx])
    if isinstance(pc, Decimal128Column):
        return (pc.hi[probe_idx] == bc.hi[build_idx]) \
            & (pc.lo[probe_idx] == bc.lo[build_idx])
    return nan_aware_eq(pc.data[probe_idx], bc.data[build_idx])


def _f64_bits(d: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Canonicalized bits of f64 as (low, high) uint32 words. Avoids
    f64<->s64 bitcast, which TPU's 64-bit-rewriting pass does not
    implement; f64→2×u32 bitcast is supported."""
    v = canonicalize_float(d)
    pair = lax.bitcast_convert_type(v, jnp.uint32)  # [..., 2]
    # trailing dim order: index 0 = least-significant word on LE targets
    return pair[..., 0], pair[..., 1]


def murmur3_string(chars: jax.Array, lens: jax.Array, seed) -> jax.Array:
    """murmur3 over variable-length bytes held in a fixed-width matrix.

    chars: uint8[n, width] zero-padded; lens: int32[n]. Full 4-byte LE blocks
    mix in order; trailing bytes mix one-at-a-time sign-extended — exactly the
    reference's split_at(len - len%4) scheme (mur.rs:19-29).
    """
    n, width = chars.shape
    nwords = (width + 3) // 4
    padded = chars if width % 4 == 0 else jnp.pad(chars, ((0, 0), (0, 4 - width % 4)))
    u32 = padded.astype(jnp.uint32).reshape(n, nwords, 4)
    words = (u32[:, :, 0] | (u32[:, :, 1] << 8) | (u32[:, :, 2] << 16)
             | (u32[:, :, 3] << 24))  # LE words [n, nwords]
    nfull = (lens // 4).astype(jnp.int32)  # number of full words per row

    seed_arr = jnp.broadcast_to(jnp.uint32(seed) if jnp.ndim(seed) == 0
                                else seed.astype(jnp.uint32), (n,))

    def word_step(i, h1):
        active = i < nfull
        mixed = _mix_h1(h1, _mix_k1(words[:, i]))
        return jnp.where(active, mixed, h1)

    h1 = lax.fori_loop(0, nwords, word_step, seed_arr)

    # Trailing bytes: positions nfull*4 .. lens-1, each sign-extended.
    def tail_step(j, h1):
        pos = nfull * 4 + j
        active = pos < lens
        byte = jnp.take_along_axis(
            chars, jnp.clip(pos, 0, width - 1)[:, None], axis=1)[:, 0]
        half_word = byte.astype(jnp.int8).astype(jnp.int32).view(jnp.uint32)
        mixed = _mix_h1(h1, _mix_k1(half_word))
        return jnp.where(active, mixed, h1)

    h1 = lax.fori_loop(0, 3, tail_step, h1)
    return _fmix(h1, lens.view(jnp.uint32) if lens.dtype == jnp.int32
                 else lens.astype(jnp.uint32)).view(jnp.int32)


# ---------------------------------------------------------------------------
# xxhash64 (Spark XxHash64, seed-chained like murmur)
# ---------------------------------------------------------------------------

# numpy scalars for the same import-time-laziness reason as the murmur
# constants above
_P1 = np.uint64(0x9E3779B185EBCA87)
_P2 = np.uint64(0xC2B2AE3D27D4EB4F)
_P3 = np.uint64(0x165667B19E3779F9)
_P4 = np.uint64(0x85EBCA77C2B2AE63)
_P5 = np.uint64(0x27D4EB2F165667C5)


def _rotl64(x, r):
    return (x << r) | (x >> (64 - r))


def _xx_avalanche(h):
    h = h ^ (h >> 33)
    h = h * _P2
    h = h ^ (h >> 29)
    h = h * _P3
    return h ^ (h >> 32)


def _xx_round(acc, inp):
    acc = acc + inp * _P2
    acc = _rotl64(acc, 31)
    return acc * _P1


def xxhash64_int64(values: jax.Array, seed) -> jax.Array:
    """xxhash64 of one 8-byte LE value (<32 bytes path of xxhash.rs:60-88)."""
    v = values.astype(jnp.int64).view(jnp.uint64)
    h = (jnp.uint64(seed) if jnp.ndim(seed) == 0 else seed.astype(jnp.uint64)) + _P5
    h = h + jnp.uint64(8)
    h = h ^ _xx_round(jnp.uint64(0), v)
    h = _rotl64(h, 27) * _P1 + _P4
    return _xx_avalanche(h).view(jnp.int64)


def xxhash64_int32(values: jax.Array, seed) -> jax.Array:
    """xxhash64 of one 4-byte LE value."""
    v = values.astype(jnp.int32).view(jnp.uint32).astype(jnp.uint64)
    h = (jnp.uint64(seed) if jnp.ndim(seed) == 0 else seed.astype(jnp.uint64)) + _P5
    h = h + jnp.uint64(4)
    h = h ^ (v * _P1)
    h = _rotl64(h, 23) * _P2 + _P3
    return _xx_avalanche(h).view(jnp.int64)


def xxhash64_string(chars: jax.Array, lens: jax.Array, seed) -> jax.Array:
    """xxhash64 over variable-length bytes in a fixed-width matrix.

    Handles all three phases of xxhash.rs:31-88 (32-byte stripes, 8-byte
    blocks, 4-byte block, tail bytes) with per-row predication.
    """
    n, width = chars.shape
    n64 = (width + 7) // 8
    padded = chars if width % 8 == 0 else jnp.pad(chars, ((0, 0), (0, 8 - width % 8)))
    b = padded.astype(jnp.uint64).reshape(n, n64, 8)
    shifts = (jnp.arange(8, dtype=jnp.uint64) * 8)[None, None, :]
    words64 = jnp.sum(b << shifts, axis=2)  # LE u64 words [n, n64]

    u32_padded = chars if width % 4 == 0 else jnp.pad(chars, ((0, 0), (0, 4 - width % 4)))
    w32 = u32_padded.astype(jnp.uint32).reshape(n, (width + 3) // 4, 4)
    words32 = (w32[:, :, 0] | (w32[:, :, 1] << 8) | (w32[:, :, 2] << 16)
               | (w32[:, :, 3] << 24)).astype(jnp.uint64)

    lens_u = lens.astype(jnp.uint64)
    seed_arr = jnp.broadcast_to(jnp.uint64(seed) if jnp.ndim(seed) == 0
                                else seed.astype(jnp.uint64), (n,))

    nstripes = (lens // 32).astype(jnp.int32)  # 32-byte stripes
    has_stripes = lens >= 32

    acc1 = seed_arr + _P1 + _P2
    acc2 = seed_arr + _P2
    acc3 = seed_arr
    acc4 = seed_arr - _P1
    max_stripes = width // 32 + (1 if width % 32 else 0)

    def stripe_step(s, accs):
        a1, a2, a3, a4 = accs
        active = s < nstripes
        base = s * 4

        def w(k):
            idx = jnp.clip(base + k, 0, n64 - 1)
            return words64[jnp.arange(n), idx]

        na1 = _xx_round(a1, w(0))
        na2 = _xx_round(a2, w(1))
        na3 = _xx_round(a3, w(2))
        na4 = _xx_round(a4, w(3))
        return (jnp.where(active, na1, a1), jnp.where(active, na2, a2),
                jnp.where(active, na3, a3), jnp.where(active, na4, a4))

    if max_stripes > 0:
        acc1, acc2, acc3, acc4 = lax.fori_loop(
            0, max_stripes, stripe_step, (acc1, acc2, acc3, acc4))

    merged = (_rotl64(acc1, 1) + _rotl64(acc2, 7) + _rotl64(acc3, 12)
              + _rotl64(acc4, 18))
    for acc in (acc1, acc2, acc3, acc4):
        merged = (merged ^ _xx_round(jnp.uint64(0), acc)) * _P1 + _P4
    h = jnp.where(has_stripes, merged, seed_arr + _P5)
    h = h + lens_u

    # 8-byte blocks after the stripes.
    consumed8 = nstripes * 4  # in u64 words
    n8 = ((lens % 32) // 8).astype(jnp.int32)

    def blk8_step(j, h):
        active = j < n8
        idx = jnp.clip(consumed8 + j, 0, n64 - 1)
        w = words64[jnp.arange(n), idx]
        nh = (_rotl64(h ^ _xx_round(jnp.uint64(0), w), 27)) * _P1 + _P4
        return jnp.where(active, nh, h)

    h = lax.fori_loop(0, 4, blk8_step, h)

    # One 4-byte block.
    consumed4 = (lens // 8 * 2).astype(jnp.int32)  # in u32 words
    has4 = (lens % 8) >= 4
    idx4 = jnp.clip(consumed4, 0, words32.shape[1] - 1)
    w4 = words32[jnp.arange(n), idx4]
    h4 = (_rotl64(h ^ (w4 * _P1), 23)) * _P2 + _P3
    h = jnp.where(has4, h4, h)

    # Tail bytes.
    tail_start = (lens // 4 * 4).astype(jnp.int32)

    def tail_step(j, h):
        pos = tail_start + j
        active = pos < lens
        byte = jnp.take_along_axis(
            chars, jnp.clip(pos, 0, width - 1)[:, None], axis=1)[:, 0].astype(jnp.uint64)
        nh = (_rotl64(h ^ (byte * _P5), 11)) * _P1
        return jnp.where(active, nh, h)

    h = lax.fori_loop(0, 3, tail_step, h)
    return _xx_avalanche(h).view(jnp.int64)


# ---------------------------------------------------------------------------
# Column / batch level hashing (seed chaining + null skipping)
# ---------------------------------------------------------------------------

def _reject_nested(col) -> None:
    from auron_tpu.columnar.batch import (ListColumn, MapColumn,
                                          StringListColumn, StringMapColumn)
    if isinstance(col, (MapColumn, ListColumn, StringListColumn,
                        StringMapColumn)):
        raise NotImplementedError(
            f"hash partitioning / hash join / hash agg on "
            f"{type(col).__name__} keys is not supported — Spark itself "
            "disallows map-typed keys; for array keys, hash the "
            "individual elements instead")


def _hash_column_murmur(col: Column, hashes: jax.Array) -> jax.Array:
    """One column's contribution to the running murmur3 hash (int32[n])."""
    _reject_nested(col)
    from auron_tpu.columnar.batch import StructColumn
    from auron_tpu.columnar.decimal128 import Decimal128Column
    if isinstance(col, StructColumn):
        # Spark create_hashes recurses into struct fields, chaining the
        # running hash through each (spark_hash.rs); a NULL struct row
        # leaves the running hash untouched, like any null column
        new = hashes
        for ch in col.children:
            new = _hash_column_murmur(
                ch.with_validity(ch.validity & col.validity), new)
        return jnp.where(col.validity, new, hashes)
    if isinstance(col, Decimal128Column):
        # limb-pair hashing: chain the low then high limb as two int64
        # words. DELIBERATE DEVIATION from Spark, which hashes wide
        # decimals as minimal big-endian two's-complement byte arrays
        # (variable length — hostile to static shapes); engine-internal
        # consistency is what hash partitioning / hash agg need, and both
        # sides of any exchange run this same kernel.
        new = murmur3_int64(col.lo, hashes.view(jnp.uint32))
        new = murmur3_int64(col.hi, new.view(jnp.uint32))
        return jnp.where(col.validity, new, hashes)
    if isinstance(col, StringColumn):
        new = murmur3_string(col.chars, col.lens, hashes.view(jnp.uint32))
    else:
        d = col.data
        if d.dtype == jnp.bool_:
            new = murmur3_int32(d.astype(jnp.int32), hashes.view(jnp.uint32))
        elif d.dtype in (jnp.dtype(jnp.int8), jnp.dtype(jnp.int16), jnp.dtype(jnp.int32)):
            new = murmur3_int32(d.astype(jnp.int32), hashes.view(jnp.uint32))
        elif d.dtype == jnp.dtype(jnp.int64):
            new = murmur3_int64(d, hashes.view(jnp.uint32))
        elif d.dtype == jnp.dtype(jnp.float32):
            # Java floatToIntBits: -0.0 → 0.0, NaN payloads canonicalized.
            new = murmur3_int32(canonicalize_float(d).view(jnp.int32),
                                hashes.view(jnp.uint32))
        elif d.dtype == jnp.dtype(jnp.float64):
            lo, hi = _f64_bits(d)
            new = murmur3_u32_pair(lo, hi, hashes.view(jnp.uint32))
        else:
            raise NotImplementedError(f"murmur3 for {d.dtype}")
    return jnp.where(col.validity, new, hashes)


def _hash_column_xxhash(col: Column, hashes: jax.Array) -> jax.Array:
    _reject_nested(col)
    from auron_tpu.columnar.batch import StructColumn
    from auron_tpu.columnar.decimal128 import Decimal128Column
    if isinstance(col, StructColumn):
        new = hashes
        for ch in col.children:
            new = _hash_column_xxhash(
                ch.with_validity(ch.validity & col.validity), new)
        return jnp.where(col.validity, new, hashes)
    if isinstance(col, Decimal128Column):
        # limb-pair hashing; see _hash_column_murmur for the Spark deviation
        new = xxhash64_int64(col.lo, hashes.view(jnp.uint64))
        new = xxhash64_int64(col.hi, new.view(jnp.uint64))
        return jnp.where(col.validity, new, hashes)
    if isinstance(col, StringColumn):
        new = xxhash64_string(col.chars, col.lens, hashes.view(jnp.uint64))
    else:
        d = col.data
        if d.dtype == jnp.bool_:
            new = xxhash64_int32(d.astype(jnp.int32), hashes.view(jnp.uint64))
        elif d.dtype in (jnp.dtype(jnp.int8), jnp.dtype(jnp.int16), jnp.dtype(jnp.int32)):
            new = xxhash64_int32(d.astype(jnp.int32), hashes.view(jnp.uint64))
        elif d.dtype == jnp.dtype(jnp.int64):
            new = xxhash64_int64(d, hashes.view(jnp.uint64))
        elif d.dtype == jnp.dtype(jnp.float32):
            new = xxhash64_int32(canonicalize_float(d).view(jnp.int32),
                                 hashes.view(jnp.uint64))
        elif d.dtype == jnp.dtype(jnp.float64):
            lo, hi = _f64_bits(d)
            u64 = lo.astype(jnp.uint64) | (hi.astype(jnp.uint64) << 32)
            new = xxhash64_int64(u64.view(jnp.int64), hashes.view(jnp.uint64))
        else:
            raise NotImplementedError(f"xxhash64 for {d.dtype}")
    return jnp.where(col.validity, new, hashes)


def murmur3_columns(cols: list[Column], capacity: int,
                    seed: int = SPARK_SHUFFLE_SEED) -> jax.Array:
    """Spark create_hashes: running int32 hash chained across columns."""
    hashes = jnp.full((capacity,), seed, jnp.int32)
    for col in cols:
        hashes = _hash_column_murmur(col, hashes)
    return hashes


def xxhash64_columns(cols: list[Column], capacity: int, seed: int = 42) -> jax.Array:
    hashes = jnp.full((capacity,), seed, jnp.int64)
    for col in cols:
        hashes = _hash_column_xxhash(col, hashes)
    return hashes


def murmur3_batch(batch: DeviceBatch, key_indices: list[int],
                  seed: int = SPARK_SHUFFLE_SEED) -> jax.Array:
    return murmur3_columns([batch.columns[i] for i in key_indices],
                           batch.capacity, seed)
