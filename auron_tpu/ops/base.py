"""Operator framework.

The analogue of the reference's ExecutionPlan/ExecutionContext pair
(reference: datafusion-ext-plans/src/common/execution_context.rs:70-767),
re-shaped for a host-driven TPU engine: operators are a tree of
``PhysicalOp``s; ``execute(partition, ctx)`` returns a pull-based iterator of
DeviceBatches. The host loop stays in Python (it only orchestrates); every
per-batch computation inside an operator is a jit-compiled kernel cached per
(operator config, shape bucket), so steady-state execution is a chain of XLA
executions with no per-row host work — the tokio stream chain of the
reference collapses into Python generators driving device kernels.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Iterator, Optional

from auron_tpu.columnar.batch import DeviceBatch
from auron_tpu.columnar.schema import Schema


class Metric:
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0

    def add(self, v):
        self.value += v


class MetricsSet:
    """Per-operator metrics, mirrored into the host tree on finalize —
    canonical names follow the reference (NativeHelper.scala:170-238):
    output_rows, output_batches, elapsed_compute, mem_spill_count, ..."""

    def __init__(self):
        self._metrics: dict[str, Metric] = {}

    def counter(self, name: str) -> Metric:
        if name not in self._metrics:
            self._metrics[name] = Metric()
        return self._metrics[name]

    def snapshot(self) -> dict[str, int]:
        return {k: m.value for k, m in self._metrics.items()}


def _device_sync(value) -> None:
    """Block until a kernel result is materialized on device. ONE leaf is
    enough: all outputs of an executable complete together, and each
    block/readback costs a full round trip (~70 ms on tunneled
    accelerators) — syncing every leaf multiplied that cost by the output
    arity. block_until_ready is unreliable on some PJRT plugins (bench.py
    syncs via readback for the same reason), so fall back to a 1-element
    readback when it raises."""
    import jax
    leaves = [l for l in jax.tree_util.tree_leaves(value)
              if hasattr(l, "block_until_ready")]
    if not leaves:
        return
    # representative sync: the LAST two leaves, fetched in one round trip.
    # A tracked value may mix pass-through inputs with fresh outputs
    # (e.g. a batch whose first columns are inputs and last column is the
    # computed one); the tail leaves are the freshly computed ones in
    # every tracked shape this engine produces.
    try:
        import numpy as _np
        picks = leaves[-2:]
        _np.asarray(jax.device_get([p.ravel()[:1] for p in picks]))
    except Exception:
        for leaf in leaves[-2:]:
            try:
                leaf.block_until_ready()
            except Exception:
                pass


class timer:
    """Context manager adding wall nanoseconds to a metric
    (reference: common/timer_helper.rs). ``track(x)`` registers kernel
    outputs to block on before the clock stops, so elapsed_compute means
    device compute rather than async dispatch (round-3 honest metrics;
    gate: auron.metrics.device_sync, resolved once per ExecContext and
    passed as ``sync``)."""

    __slots__ = ("metric", "t0", "_tracked", "sync")

    def __init__(self, metric: Metric, sync: bool = True):
        self.metric = metric
        self.sync = sync
        self._tracked = None

    def track(self, value):
        """Register a kernel result to sync on at exit; returns it."""
        self._tracked = value
        return value

    def __enter__(self):
        self.t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        if self._tracked is not None and exc[0] is None and self.sync:
            _device_sync(self._tracked)
            self._tracked = None
        self.metric.add(time.perf_counter_ns() - self.t0)
        return False


@dataclass
class ExecContext:
    """Per-task execution context (reference: TaskContext propagated through
    rt.rs:113-139): identity, metrics registry, memory manager hook."""

    stage_id: int = 0
    partition_id: int = 0
    task_id: int = 0
    num_partitions: int = 1
    metrics: dict[str, MetricsSet] = field(default_factory=dict)
    mem_manager: Optional[object] = None
    #: shared cancellation flag (reference: cancel_all_tasks registry,
    #: execution_context.rs:452 + is_task_running checks, rt.rs:208-238).
    #: A threading.Event created EAGERLY so derived contexts (ctx.child)
    #: always share the same registry object — a lazily-created event
    #: would not reach children built before the first cancel; the host
    #: (serving handler, task-kill) flips it from another thread and
    #: operators poll between batches.
    cancel_event: object = field(default_factory=lambda: _new_event())
    # typed config (auron_tpu.config); None = process-wide defaults
    config: Optional[object] = None

    def child(self, **overrides) -> "ExecContext":
        """Derived context for a sub-execution (the map side of an
        exchange, a subquery, a broadcast build): inherits the memory
        manager, config AND the cancellation registry — a cancel on the
        parent must reach every nested execution — while identity fields
        (stage/partition/task) and metrics may be overridden."""
        base = dict(
            stage_id=self.stage_id, partition_id=self.partition_id,
            task_id=self.task_id, num_partitions=self.num_partitions,
            metrics=self.metrics, mem_manager=self.mem_manager,
            cancel_event=self.cancel_event, config=self.config)
        base.update(overrides)
        return ExecContext(**base)

    def cancel(self) -> None:
        """Flip the task's cancellation flag (thread-safe)."""
        self.cancel_event.set()

    @property
    def cancelled(self) -> bool:
        ev = self.cancel_event
        return ev is not None and ev.is_set()

    def check_cancelled(self) -> None:
        """Raise TaskCancelled if the host tore this task down — called
        by operators between child batches so a cancel lands within one
        batch of compute."""
        if self.cancelled:
            raise TaskCancelled(
                f"task {self.task_id} (stage {self.stage_id}, partition "
                f"{self.partition_id}) was cancelled")

    @property
    def conf(self):
        if self.config is None:
            from auron_tpu.config import get_config
            self.config = get_config()
        return self.config

    @property
    def device_sync(self) -> bool:
        """auron.metrics.device_sync resolved once per context (timers are
        on the hot path; see timer.track)."""
        cached = getattr(self, "_device_sync", None)
        if cached is None:
            from auron_tpu import config as cfg
            cached = self.conf.get(cfg.METRICS_DEVICE_SYNC)
            self._device_sync = cached
        return cached

    def metrics_for(self, op_name: str) -> MetricsSet:
        if op_name not in self.metrics:
            self.metrics[op_name] = MetricsSet()
        return self.metrics[op_name]

    def metrics_snapshot(self) -> dict[str, dict[str, int]]:
        return {k: v.snapshot() for k, v in self.metrics.items()}


def _new_event():
    import threading
    return threading.Event()


class TaskCancelled(Exception):
    """The host cancelled this task mid-stream (reference: task-kill
    detection via is_task_running, rt.rs:208-238); operators unwind and
    the runtime tears down without reporting a failure."""


class PhysicalOp:
    """Base physical operator."""

    #: operator display name (metric key prefix)
    name: str = "op"

    #: whole-stage fusion protocol (ops/fused.py): True on operators whose
    #: per-batch work is a pure row-local device computation expressible as
    #: a KernelFragment — the planner's stage-fusion pass
    #: (ir/planner.fuse_stages) chains them into one jit-compiled program.
    #: Stage breakers (agg cores, joins, sorts, exchanges, scans) stay
    #: False and terminate fusion chains.
    fusable: bool = False

    #: kernel fan-out of this op's fragment (ExpandOp emits one batch per
    #: projection); the fusion pass bounds the product along a chain.
    fusion_fanout: int = 1

    #: does this op's fragment do real device compute? Pass-through
    #: fragments (limit's num_rows rewrite, rename's identity) are False:
    #: a stage made ONLY of those would compile a program for work the
    #: unfused operators do host-side for free, so the fusion pass only
    #: creates stages containing at least one computing member.
    fragment_computes: bool = False

    #: may a consumer destroy (donate to XLA) the batches execute() yields?
    #: True for ops that construct fresh device arrays per output batch;
    #: "inherit" for pass-through ops (limit/union/rename/coalesce) whose
    #: outputs alias their children's; False for sources that replay
    #: shared, long-lived batches (device scans, broadcast buffers).
    #: Resolve through ``yields_owned_batches``, never read directly.
    owns_output = True

    def build_kernel_fragment(self) -> Optional["object"]:
        """Return this op's KernelFragment (ops/fused.py) — the traceable
        per-batch function the stage-fusion pass composes into one XLA
        program — or None when the op cannot fuse. Implemented iff
        ``fusable`` is True."""
        return None

    @property
    def children(self) -> list["PhysicalOp"]:
        return []

    def schema(self) -> Schema:
        raise NotImplementedError

    def execute(self, partition: int, ctx: ExecContext) -> Iterator[DeviceBatch]:
        raise NotImplementedError

    def tree_string(self, indent: int = 0) -> str:
        s = "  " * indent + repr(self) + "\n"
        for c in self.children:
            s += c.tree_string(indent + 1)
        return s

    def __repr__(self):
        return type(self).__name__


def yields_owned_batches(op: PhysicalOp) -> bool:
    """True when every batch ``op.execute`` yields is freshly constructed
    and dead to the producer once consumed — the precondition for a
    consumer kernel to donate it to XLA (buffer donation halves peak HBM
    on single-consumer steps; donating a shared batch would corrupt later
    readers). Pass-through ops inherit from their children."""
    owned = getattr(op, "owns_output", True)
    if owned == "inherit":
        return all(yields_owned_batches(c) for c in op.children)
    return bool(owned)


def count_output(stream, metrics: MetricsSet):
    """Wrap a batch stream with output_rows/output_batches counting."""
    rows = metrics.counter("output_rows")
    batches = metrics.counter("output_batches")
    for b in stream:
        rows.add(int(b.num_rows))
        batches.add(1)
        yield b
