"""Operator framework.

The analogue of the reference's ExecutionPlan/ExecutionContext pair
(reference: datafusion-ext-plans/src/common/execution_context.rs:70-767),
re-shaped for a host-driven TPU engine: operators are a tree of
``PhysicalOp``s; ``execute(partition, ctx)`` returns a pull-based iterator of
DeviceBatches. The host loop stays in Python (it only orchestrates); every
per-batch computation inside an operator is a jit-compiled kernel cached per
(operator config, shape bucket), so steady-state execution is a chain of XLA
executions with no per-row host work — the tokio stream chain of the
reference collapses into Python generators driving device kernels.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Iterator, Optional

from auron_tpu.columnar.batch import DeviceBatch
from auron_tpu.columnar.schema import Schema


class Metric:
    __slots__ = ("value", "_mirror", "_owner")

    def __init__(self, mirror: "Optional[Metric]" = None,
                 owner: "Optional[MetricsSet]" = None):
        self.value = 0
        self._mirror = mirror
        #: the MetricsSet this counter was created by — lets a timer
        #: wrapping one counter flush its host/device attribution
        #: (obs/profile) into sibling counters of the same operator
        #: without threading the set through every helper signature
        self._owner = owner

    def add(self, v):
        self.value += v
        m = self._mirror
        if m is not None:
            m.value += v


class MetricsSet:
    """Per-operator metrics, mirrored into the host tree on finalize —
    canonical names follow the reference (NativeHelper.scala:170-238):
    output_rows, output_batches, elapsed_compute, mem_spill_count, ...

    A set may carry a ``mirror``: every counter then chains its adds
    into the same-named counter of the mirror set. That is how per-op
    POSITIONAL sets (ExecContext.metrics_for(op) — the metric-tree /
    EXPLAIN ANALYZE source, obs/metric_tree.py) stay consistent with
    the legacy name-keyed aggregate (``ctx.metrics[op.name]``) without
    double bookkeeping at call sites."""

    def __init__(self, mirror: "Optional[MetricsSet]" = None):
        self._metrics: dict[str, Metric] = {}
        self._mirror = mirror

    def counter(self, name: str) -> Metric:
        m = self._metrics.get(name)
        if m is None:
            chained = (self._mirror.counter(name)
                       if self._mirror is not None else None)
            m = self._metrics[name] = Metric(chained, owner=self)
        return m

    def snapshot(self) -> dict[str, int]:
        return {k: m.value for k, m in self._metrics.items()}


def _device_sync(value) -> None:
    """Block until a kernel result is materialized on device. ONE leaf is
    enough: all outputs of an executable complete together, and each
    block/readback costs a full round trip (~70 ms on tunneled
    accelerators) — syncing every leaf multiplied that cost by the output
    arity. block_until_ready is unreliable on some PJRT plugins (bench.py
    syncs via readback for the same reason), so fall back to a 1-element
    readback when it raises."""
    import jax
    leaves = [l for l in jax.tree_util.tree_leaves(value)
              if hasattr(l, "block_until_ready")]
    if not leaves:
        return
    # representative sync: the LAST two leaves, fetched in one round trip.
    # A tracked value may mix pass-through inputs with fresh outputs
    # (e.g. a batch whose first columns are inputs and last column is the
    # computed one); the tail leaves are the freshly computed ones in
    # every tracked shape this engine produces.
    try:
        import numpy as _np
        picks = leaves[-2:]
        # graft: disable=GL001 -- this IS the serial-mode sanctioned sync helper (timer.track attributes it)
        _np.asarray(jax.device_get([p.ravel()[:1] for p in picks]))
    except Exception:
        for leaf in leaves[-2:]:
            try:
                leaf.block_until_ready()   # graft: disable=GL001 -- plugin fallback of the sanctioned sync helper
            except Exception:   # graft: disable=GL004 -- plugin-dependent sync fallback; the wait is best-effort by contract
                pass


class timer:
    """Context manager adding wall nanoseconds to a metric
    (reference: common/timer_helper.rs). ``track(x)`` registers kernel
    outputs to block on before the clock stops, so elapsed_compute means
    device compute rather than async dispatch (round-3 honest metrics;
    gate: auron.metrics.device_sync, resolved once per ExecContext and
    passed as ``sync``).

    When the profiler is on (``auron.profile.enabled``, obs/profile.py)
    and the metric belongs to a MetricsSet, the scope additionally opens
    an attribution frame: wrapped program calls record their
    dispatch/device split into it, ``track`` marks the dispatch→device
    boundary for kernels that bypass the program registry, and
    ``bucket`` classifies kernel-free host sections (scan decode waits
    → "convert", shuffle serde → "serde"). The flush lands
    ``elapsed_device`` / ``elapsed_host_*`` counters next to this
    metric in the same set — EXPLAIN ANALYZE's host/device columns."""

    __slots__ = ("metric", "t0", "_tracked", "sync", "_frame",
                 "_bucket", "_t_track")

    def __init__(self, metric: Metric, sync: bool = True,
                 bucket: "Optional[str]" = None):
        self.metric = metric
        self.sync = sync
        self._tracked = None
        self._bucket = bucket
        self._frame = None
        self._t_track = 0

    def track(self, value):
        """Register a kernel result to sync on at exit; returns it."""
        self._tracked = value
        if self._frame is not None:
            self._t_track = time.perf_counter_ns()
        return value

    def __enter__(self):
        if self.metric._owner is not None:
            from auron_tpu.obs import profile as _profile
            self._frame = _profile.push_frame()
        self.t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        if self._tracked is not None and exc[0] is None and self.sync:
            _device_sync(self._tracked)
            self._tracked = None
        wall = time.perf_counter_ns() - self.t0
        self.metric.add(wall)
        if self._frame is not None:
            from auron_tpu.obs import profile as _profile
            _profile.pop_frame(
                self._frame, self.metric._owner, wall,
                (self._t_track - self.t0) if self._t_track else None,
                self._bucket)
            self._frame = None
            self._t_track = 0
        return False


@dataclass
class ExecContext:
    """Per-task execution context (reference: TaskContext propagated through
    rt.rs:113-139): identity, metrics registry, memory manager hook."""

    stage_id: int = 0
    partition_id: int = 0
    task_id: int = 0
    num_partitions: int = 1
    metrics: dict[str, MetricsSet] = field(default_factory=dict)
    mem_manager: Optional[object] = None
    #: shared cancellation flag (reference: cancel_all_tasks registry,
    #: execution_context.rs:452 + is_task_running checks, rt.rs:208-238).
    #: A threading.Event created EAGERLY so derived contexts (ctx.child)
    #: always share the same registry object — a lazily-created event
    #: would not reach children built before the first cancel; the host
    #: (serving handler, task-kill) flips it from another thread and
    #: operators poll between batches.
    cancel_event: object = field(default_factory=lambda: _new_event())
    #: the task's stall-watchdog heartbeat (runtime/watchdog.TaskHeartbeat)
    #: when auron.watchdog.stall_timeout_s arms the monitor; operators
    #: beat it through ``checkpoint`` so the monitor can tell a slow
    #: batch from a wedged one
    heartbeat: Optional[object] = None
    # typed config (auron_tpu.config); None = process-wide defaults
    config: Optional[object] = None
    #: per-op-INSTANCE metric sets keyed (id(op), suffix) — the
    #: positional source the metric tree mirrors from
    #: (obs/metric_tree.mirror); shared with child contexts like
    #: ``metrics`` so map-side work attributes to the same plan nodes
    op_metrics: dict = field(default_factory=dict)

    def child(self, **overrides) -> "ExecContext":
        """Derived context for a sub-execution (the map side of an
        exchange, a subquery, a broadcast build): inherits the memory
        manager, config AND the cancellation registry — a cancel on the
        parent must reach every nested execution — while identity fields
        (stage/partition/task) and metrics may be overridden."""
        base = dict(
            stage_id=self.stage_id, partition_id=self.partition_id,
            task_id=self.task_id, num_partitions=self.num_partitions,
            metrics=self.metrics, mem_manager=self.mem_manager,
            cancel_event=self.cancel_event, heartbeat=self.heartbeat,
            config=self.config, op_metrics=self.op_metrics)
        base.update(overrides)
        return ExecContext(**base)

    def cancel(self) -> None:
        """Flip the task's cancellation flag (thread-safe)."""
        self.cancel_event.set()

    @property
    def cancelled(self) -> bool:
        ev = self.cancel_event
        return ev is not None and ev.is_set()

    def check_cancelled(self) -> None:
        """Raise the task's teardown error if the host tore it down —
        called by operators between child batches so a cancel lands
        within one batch of compute. Three teardown verdicts, most
        specific first: a stall flag from the watchdog monitor raises
        the classified ``errors.TaskStalled`` (retry driver: transient
        once); a CancelToken registry raises its own classified error
        (QueryCancelled / DeadlineExceeded by reason); a bare Event
        registry keeps the legacy TaskCancelled."""
        hb = self.heartbeat
        if hb is not None and getattr(hb, "stalled", False):
            from auron_tpu import errors
            raise errors.TaskStalled(
                f"task {self.task_id} (stage {self.stage_id}, partition "
                f"{self.partition_id}) flagged stalled by the watchdog "
                f"(last heartbeat at {hb.last_site or '?'})")
        ev = self.cancel_event
        if ev is not None and ev.is_set():
            raise_for = getattr(ev, "raise_for_status", None)
            if raise_for is not None:
                raise_for()
            raise TaskCancelled(
                f"task {self.task_id} (stage {self.stage_id}, partition "
                f"{self.partition_id}) was cancelled")

    def checkpoint(self, site: str = "") -> None:
        """The cooperative-lifecycle poll for long-running loops (batch
        drives, shuffle fetch/materialize, spill consumers): beat the
        stall watchdog with ``site`` (the last-heartbeat attribution a
        StallReport prints), give the lifecycle chaos sites traffic
        (``cancel.race`` races a cancel against this very poll,
        ``task.hang`` wedges mid-stream — both no-ops at one cached
        epoch-compare each when unarmed), AND surface any pending
        cancellation."""
        hb = self.heartbeat
        if hb is not None:
            hb.beat(site)
        from auron_tpu.runtime import faults
        faults.lifecycle_poll(self)
        if hb is not None and not hb.stalled:
            # an injected hang may have slept here: re-beat so the
            # SLEEP is not misread as the task's own silence (a stall
            # flag set meanwhile survives — beats never clear it)
            hb.beat(site)
        self.check_cancelled()

    @property
    def should_stop(self) -> bool:
        """True when this task must unwind (cancelled OR stall-flagged)
        without raising — the poll the fault plane's interruptible hang
        loop uses (runtime/faults.maybe_fail)."""
        hb = self.heartbeat
        if hb is not None and getattr(hb, "stalled", False):
            return True
        return self.cancelled

    @property
    def conf(self):
        if self.config is None:
            from auron_tpu.config import get_config
            self.config = get_config()
        return self.config

    @property
    def device_sync(self) -> bool:
        """Should per-operator timers block on kernel outputs? Resolved
        once per context (timers are on the hot path; see timer.track):
        auron.metrics.device_sync, overridden to False by pipelined
        execution (auron.pipeline.enabled) — under pipelining the
        per-batch sync points move to the semantic materialization
        boundaries (runtime/pipeline.py), and a timer that blocked per
        batch would serialize exactly the overlap the mode exists to
        create."""
        cached = getattr(self, "_device_sync", None)
        if cached is None:
            from auron_tpu import config as cfg
            cached = (self.conf.get(cfg.METRICS_DEVICE_SYNC)
                      and not self.pipelined)
            self._device_sync = cached
        return cached

    @property
    def mesh_plane(self):
        """The process's SPMD mesh plane (parallel/mesh.current_plane),
        resolved once per context like ``pipelined`` — None when
        ``auron.mesh.enabled`` is off or fewer than 2 devices exist.
        PROCESS-GLOBAL by the knob's contract (the device set is
        process state)."""
        cached = getattr(self, "_mesh_plane", None)
        if cached is None:
            from auron_tpu.parallel import mesh
            cached = (mesh.current_plane(),)
            self._mesh_plane = cached
        return cached[0]

    @property
    def pipelined(self) -> bool:
        """auron.pipeline.enabled resolved once per context — from the
        PROCESS-GLOBAL config by the knob's contract (sync points must
        move consistently across planes that cannot see a session
        config; see runtime/pipeline.enabled)."""
        cached = getattr(self, "_pipelined", None)
        if cached is None:
            from auron_tpu.runtime import pipeline
            cached = pipeline.enabled()
            self._pipelined = cached
        return cached

    def metrics_for(self, op, suffix: str = "") -> MetricsSet:
        """The metric set for ``op``.

        Passing a *string* returns the legacy name-keyed set (shared by
        every same-named op — plan-wide categories like "kernels" and
        "recovery" live here). Passing the *PhysicalOp instance* returns
        a per-instance set whose counters chain into the name-keyed one,
        giving the metric tree positional attribution
        (obs/metric_tree.py) while every existing name-keyed consumer
        keeps seeing the aggregate."""
        if isinstance(op, str):
            name = op + suffix
            if name not in self.metrics:
                self.metrics[name] = MetricsSet()
            return self.metrics[name]
        key = (id(op), suffix)
        entry = self.op_metrics.get(key)
        if entry is None:
            # the cache value PINS the op: id() keys are only unique
            # while the object lives, and a gc'd subquery plan's id can
            # be recycled by a later op in the same task
            entry = (op, MetricsSet(
                mirror=self.metrics_for(op.name + suffix)))
            self.op_metrics[key] = entry
        return entry[1]

    def op_metric_sets(self, op) -> list[MetricsSet]:
        """Every per-instance metric set ``op`` recorded under this
        context (all suffixes — an exchange records both its write side
        and its "_read" side)."""
        oid = id(op)
        return [entry[1] for (i, _s), entry in self.op_metrics.items()
                if i == oid]

    def metrics_snapshot(self) -> dict[str, dict[str, int]]:
        return {k: v.snapshot() for k, v in self.metrics.items()}


def _new_event():
    import threading
    return threading.Event()


class TaskCancelled(Exception):
    """The host cancelled this task mid-stream (reference: task-kill
    detection via is_task_running, rt.rs:208-238); operators unwind and
    the runtime tears down without reporting a failure."""


class PhysicalOp:
    """Base physical operator."""

    #: operator display name (metric key prefix)
    name: str = "op"

    #: whole-stage fusion protocol (ops/fused.py): True on operators whose
    #: per-batch work is a pure row-local device computation expressible as
    #: a KernelFragment — the planner's stage-fusion pass
    #: (ir/planner.fuse_stages) chains them into one jit-compiled program.
    #: Stage breakers (agg cores, joins, sorts, exchanges, scans) stay
    #: False and terminate fusion chains.
    fusable: bool = False

    #: kernel fan-out of this op's fragment (ExpandOp emits one batch per
    #: projection); the fusion pass bounds the product along a chain.
    fusion_fanout: int = 1

    #: does this op's fragment do real device compute? Pass-through
    #: fragments (limit's num_rows rewrite, rename's identity) are False:
    #: a stage made ONLY of those would compile a program for work the
    #: unfused operators do host-side for free, so the fusion pass only
    #: creates stages containing at least one computing member.
    fragment_computes: bool = False

    #: SPMD layout declaration (parallel/mesh.buffer_spec): what KIND of
    #: buffer this op's output is, for the replicate-vs-shard decision —
    #: "broadcast"/"hash_build" replicate across the mesh, "scan_batch"/
    #: "shuffle_entry"/"agg_partial" shard on the batch dim. None = no
    #: declared kind (shards by default). The planner's annotate_mesh
    #: pass resolves this into ``mesh_spec`` on each node.
    mesh_buffer_kind: Optional[str] = None

    #: resolved sharding spec ("replicate" | "shard" | "gang"), stamped
    #: by ir/planner.annotate_mesh when the mesh plane is active; "gang"
    #: marks an exchange whose materialization occupies the whole mesh
    mesh_spec: Optional[str] = None

    #: may a consumer destroy (donate to XLA) the batches execute() yields?
    #: True for ops that construct fresh device arrays per output batch;
    #: "inherit" for pass-through ops (limit/union/rename/coalesce) whose
    #: outputs alias their children's; False for sources that replay
    #: shared, long-lived batches (device scans, broadcast buffers).
    #: Resolve through ``yields_owned_batches``, never read directly.
    owns_output = True

    def build_kernel_fragment(self) -> Optional["object"]:
        """Return this op's KernelFragment (ops/fused.py) — the traceable
        per-batch function the stage-fusion pass composes into one XLA
        program — or None when the op cannot fuse. Implemented iff
        ``fusable`` is True."""
        return None

    @property
    def children(self) -> list["PhysicalOp"]:
        return []

    def schema(self) -> Schema:
        raise NotImplementedError

    def execute(self, partition: int, ctx: ExecContext) -> Iterator[DeviceBatch]:
        raise NotImplementedError

    def tree_string(self, indent: int = 0) -> str:
        s = "  " * indent + repr(self) + "\n"
        for c in self.children:
            s += c.tree_string(indent + 1)
        return s

    def __repr__(self):
        return type(self).__name__


def yields_owned_batches(op: PhysicalOp) -> bool:
    """True when every batch ``op.execute`` yields is freshly constructed
    and dead to the producer once consumed — the precondition for a
    consumer kernel to donate it to XLA (buffer donation halves peak HBM
    on single-consumer steps; donating a shared batch would corrupt later
    readers). Pass-through ops inherit from their children."""
    owned = getattr(op, "owns_output", True)
    if owned == "inherit":
        return all(yields_owned_batches(c) for c in op.children)
    return bool(owned)


def count_output(stream, metrics: MetricsSet, timed: bool = False):
    """Wrap a batch stream with output_rows/output_batches counting.

    ``timed=True`` additionally accrues the time spent INSIDE the
    producer's ``next()`` into ``elapsed_compute`` — the inclusive
    host-side elapsed for operators that run no device kernels of their
    own (scans, limits, exchange reads) so EXPLAIN ANALYZE shows a
    nonzero elapsed on every plan node. Operators that time their
    kernels explicitly must NOT pass it (they would double-count)."""
    rows = metrics.counter("output_rows")
    batches = metrics.counter("output_batches")
    if not timed:
        for b in stream:
            rows.add(int(b.num_rows))
            batches.add(1)
            yield b
        return
    elapsed = metrics.counter("elapsed_compute")
    it = iter(stream)
    while True:
        t0 = time.perf_counter_ns()
        try:
            b = next(it)
        except StopIteration:
            elapsed.add(time.perf_counter_ns() - t0)
            return
        elapsed.add(time.perf_counter_ns() - t0)
        rows.add(int(b.num_rows))
        batches.add(1)
        yield b
