"""Uncorrelated scalar subquery resolution (reference:
datafusion-ext-exprs/src/spark_scalar_subquery_wrapper.rs — there the
host engine evaluates the subquery and the wrapper fetches the value
through JNI; here the engine executes the embedded child plan itself).

``ScalarSubqueryBinderOp`` wraps any plan subtree containing
scalar_subquery expressions: at first execute it runs each subquery plan
to a single value (0 rows → NULL, >1 rows → error, matching Spark's
"more than one row returned by a subquery used as an expression"), then
re-plans the subtree with the values substituted as literals so every
downstream kernel sees plain constants. Resolution happens once per
TASK, not per partition — the resolved inner op is cached."""

from __future__ import annotations

import threading
from typing import Iterator

from auron_tpu.columnar.batch import DeviceBatch
from auron_tpu.columnar.schema import Schema
from auron_tpu.exprs import ir
from auron_tpu.ir import pb
from auron_tpu.ops.base import ExecContext, PhysicalOp


class ScalarSubqueryBinderOp(PhysicalOp):
    name = "scalar_subquery_binder"

    def __init__(self, node: pb.PlanNode, planner_ctx):
        self._node = node
        self._planner_ctx = planner_ctx
        self._lock = threading.Lock()
        self._inner: PhysicalOp | None = None
        self._schema_op: PhysicalOp | None = None

    # -- schema before resolution: substitute typed NULLs ------------------

    def _placeholder_plan(self) -> PhysicalOp:
        from auron_tpu.ir.planner import (PhysicalPlanner,
                                          _collect_subqueries,
                                          substitute_subqueries)
        from auron_tpu.ir.serde import expr_to_proto
        from auron_tpu.ir.planner import subquery_key
        subs = _collect_subqueries(self._node)
        values = {}
        for q in subs:
            from auron_tpu.ir.serde import _P_TO_DT
            lit = ir.Literal(None, _P_TO_DT[q.dtype], q.precision, q.scale)
            values[subquery_key(q)] = expr_to_proto(lit)
        node = substitute_subqueries(self._node, values)
        return PhysicalPlanner(self._planner_ctx).create_plan(node)

    def schema(self) -> Schema:
        if self._inner is not None:
            return self._inner.schema()
        if self._schema_op is None:
            self._schema_op = self._placeholder_plan()
        return self._schema_op.schema()

    @property
    def children(self):
        inner = self._inner or self._schema_op
        return [inner] if inner is not None else []

    # -- resolution --------------------------------------------------------

    def _resolve_one(self, q: "pb.ScalarSubqueryE", ctx: ExecContext):
        """Run one subquery plan to completion, single partition."""
        from auron_tpu.ir.planner import PhysicalPlanner
        # plan_task, not create_plan: the subquery's own plan may contain
        # further scalar subqueries (nested binder resolves them)
        op = PhysicalPlanner(self._planner_ctx).plan_task(
            pb.TaskDefinition(plan=q.plan))
        # ctx.child keeps the cancellation registry: cancelling the task
        # also stops an in-flight subquery resolution
        sub_ctx = ctx.child(partition_id=0, num_partitions=1, metrics={})
        rows = 0
        value = None
        from auron_tpu.columnar.arrow_bridge import to_arrow
        from auron_tpu.obs import profile as _profile
        for batch in op.execute(0, sub_ctx):
            sub_ctx.checkpoint("subquery.collect")
            n = int(_profile.timed_get(batch.num_rows))
            if n == 0:
                continue
            rb = to_arrow(batch, op.schema())
            rows += rb.num_rows
            if rows > 1:
                from auron_tpu import errors
                raise errors.ScalarSubqueryError(
                    "more than one row returned by a subquery used as "
                    "an expression")
            value = rb.column(0)[0].as_py()
        return self._normalize(value, q)

    @staticmethod
    def _normalize(value, q: "pb.ScalarSubqueryE"):
        """Arrow python scalar → the engine's Literal value convention
        (decimals are UNSCALED ints; dates are epoch days; timestamps
        epoch micros)."""
        if value is None:
            return None
        import datetime
        import decimal

        from auron_tpu.columnar.schema import DataType
        from auron_tpu.ir.serde import _P_TO_DT
        dt = _P_TO_DT[q.dtype]
        if dt == DataType.DECIMAL and isinstance(value, decimal.Decimal):
            return int(value.scaleb(q.scale).to_integral_value())
        if dt == DataType.DATE32 and isinstance(value, datetime.date):
            return (value - datetime.date(1970, 1, 1)).days
        if dt == DataType.TIMESTAMP_US \
                and isinstance(value, datetime.datetime):
            if value.tzinfo is None:
                value = value.replace(tzinfo=datetime.timezone.utc)
            # integer arithmetic: float .timestamp() has ~0.24 us ulp at
            # the current epoch and can be off by one microsecond
            epoch = datetime.datetime(1970, 1, 1,
                                      tzinfo=datetime.timezone.utc)
            return (value - epoch) // datetime.timedelta(microseconds=1)
        return value

    def _resolved_inner(self, ctx: ExecContext) -> PhysicalOp:
        with self._lock:
            if self._inner is not None:
                return self._inner
            from auron_tpu.ir.planner import (PhysicalPlanner,
                                              _collect_subqueries,
                                              substitute_subqueries,
                                              subquery_key)
            from auron_tpu.ir.serde import _P_TO_DT, expr_to_proto
            values = {}
            for q in _collect_subqueries(self._node):
                key = subquery_key(q)
                if key in values:
                    continue
                v = self._resolve_one(q, ctx)
                lit = ir.Literal(v, _P_TO_DT[q.dtype], q.precision,
                                 q.scale)
                values[key] = expr_to_proto(lit)
            node = substitute_subqueries(self._node, values)
            planner = PhysicalPlanner(self._planner_ctx)
            # finalize_plan: the substituted plan gets the same
            # stage-fusion pass a subquery-free task would
            self._inner = planner.finalize_plan(planner.create_plan(node))
            return self._inner

    def execute(self, partition: int,
                ctx: ExecContext) -> Iterator[DeviceBatch]:
        yield from self._resolved_inner(ctx).execute(partition, ctx)

    def __repr__(self):
        return "ScalarSubqueryBinderOp"
