"""Projection / filter operators (+ fused filter-project).

reference: datafusion-ext-plans/src/project_exec.rs, filter_exec.rs; the
fusion mirrors CachedExprsEvaluator's project+filter fusion (reference:
datafusion-ext-plans/src/common/cached_exprs_evaluator.rs:50+) — here the
fused path is a single jit kernel, so XLA CSEs shared subexpressions and
fuses everything into one HLO computation.
"""

from __future__ import annotations

from typing import Iterator

import jax
import jax.numpy as jnp

from auron_tpu.columnar.batch import DeviceBatch, compact
from auron_tpu.columnar.schema import DataType, Field, Schema
from auron_tpu.exprs import ir
from auron_tpu.exprs.eval import (EvalContext, evaluate, infer_dtype,
                                  infer_field)
from auron_tpu.ops.base import ExecContext, PhysicalOp, count_output, timer
from auron_tpu.runtime.programs import program_cache


def project_schema(exprs: tuple, names: tuple[str, ...], in_schema: Schema) -> Schema:
    # infer_field keeps nested metadata (list elem / map key+value /
    # struct children) that the (dtype, p, s) 3-tuple cannot carry
    return Schema(tuple(infer_field(e, in_schema, name=n)
                        for e, n in zip(exprs, names)))


@program_cache("ops.project.project", maxsize=512)
def _project_kernel(exprs: tuple, in_schema: Schema, capacity: int):
    """One compiled kernel per (expression tuple, schema, capacity)."""

    @jax.jit
    def kernel(batch: DeviceBatch, partition_id, row_num_offset):
        ctx = EvalContext(partition_id=partition_id,
                          row_num_offset=row_num_offset, memo={})
        cols = tuple(evaluate(e, batch, in_schema, ctx).col for e in exprs)
        return DeviceBatch(cols, batch.num_rows)

    return kernel


@program_cache("ops.project.filter", maxsize=512)
def _filter_kernel(predicates: tuple, in_schema: Schema, capacity: int):
    @jax.jit
    def kernel(batch: DeviceBatch, partition_id, row_num_offset):
        ctx = EvalContext(partition_id=partition_id,
                          row_num_offset=row_num_offset, memo={})
        keep = batch.row_mask()
        for p in predicates:
            v = evaluate(p, batch, in_schema, ctx)
            keep = keep & v.data.astype(bool) & v.validity
        return compact(batch, keep)

    return kernel


@program_cache("ops.project.filter_project", maxsize=512)
def _filter_project_kernel(predicates: tuple, exprs: tuple, in_schema: Schema,
                           capacity: int):
    @jax.jit
    def kernel(batch: DeviceBatch, partition_id, row_num_offset):
        ctx = EvalContext(partition_id=partition_id,
                          row_num_offset=row_num_offset, memo={})
        keep = batch.row_mask()
        for p in predicates:
            v = evaluate(p, batch, in_schema, ctx)
            keep = keep & v.data.astype(bool) & v.validity
        filtered = compact(batch, keep)
        cols = tuple(evaluate(e, filtered, in_schema, ctx).col for e in exprs)
        return DeviceBatch(cols, filtered.num_rows)

    return kernel


class ProjectOp(PhysicalOp):
    name = "project"
    fusable = True
    fragment_computes = True

    def __init__(self, child: PhysicalOp, exprs: list[ir.Expr], names: list[str]):
        self.child = child
        self.exprs = tuple(exprs)
        self.names = tuple(names)
        self._schema = project_schema(self.exprs, self.names, child.schema())

    @property
    def children(self):
        return [self.child]

    def schema(self) -> Schema:
        return self._schema

    def build_kernel_fragment(self):
        from auron_tpu.ops.fused import KernelFragment
        exprs, in_schema = self.exprs, self.child.schema()

        def apply(batch, partition_id, carry):
            ctx = EvalContext(partition_id=partition_id,
                              row_num_offset=carry, memo={})
            cols = tuple(evaluate(e, batch, in_schema, ctx).col
                         for e in exprs)
            out = DeviceBatch(cols, batch.num_rows)
            return (out,), carry + jnp.asarray(batch.num_rows, jnp.int64)

        return KernelFragment(key=("project", exprs, in_schema),
                              apply=apply)

    def execute(self, partition: int, ctx: ExecContext) -> Iterator[DeviceBatch]:
        metrics = ctx.metrics_for(self)
        elapsed = metrics.counter("elapsed_compute")
        in_schema = self.child.schema()
        _sync = ctx.device_sync

        def stream():
            row_off = 0
            for batch in self.child.execute(partition, ctx):
                kern = _project_kernel(self.exprs, in_schema, batch.capacity)
                with timer(elapsed, sync=_sync) as t:
                    out = t.track(kern(batch, jnp.int32(partition),
                                       jnp.int64(row_off)))
                row_off += int(batch.num_rows)
                yield out

        return count_output(stream(), metrics)

    def __repr__(self):
        return f"ProjectOp[{', '.join(self.names)}]"


class FilterOp(PhysicalOp):
    name = "filter"
    fusable = True
    fragment_computes = True

    def __init__(self, child: PhysicalOp, predicates: list[ir.Expr]):
        self.child = child
        self.predicates = tuple(predicates)

    @property
    def children(self):
        return [self.child]

    def schema(self) -> Schema:
        return self.child.schema()

    def build_kernel_fragment(self):
        from auron_tpu.ops.fused import KernelFragment
        predicates, in_schema = self.predicates, self.child.schema()

        def apply(batch, partition_id, carry):
            ctx = EvalContext(partition_id=partition_id,
                              row_num_offset=carry, memo={})
            keep = batch.row_mask()
            for p in predicates:
                v = evaluate(p, batch, in_schema, ctx)
                keep = keep & v.data.astype(bool) & v.validity
            out = compact(batch, keep)
            return (out,), carry + jnp.asarray(batch.num_rows, jnp.int64)

        return KernelFragment(key=("filter", predicates, in_schema),
                              apply=apply)

    def execute(self, partition: int, ctx: ExecContext) -> Iterator[DeviceBatch]:
        metrics = ctx.metrics_for(self)
        elapsed = metrics.counter("elapsed_compute")
        in_schema = self.child.schema()
        _sync = ctx.device_sync

        def stream():
            row_off = 0
            for batch in self.child.execute(partition, ctx):
                kern = _filter_kernel(self.predicates, in_schema, batch.capacity)
                with timer(elapsed, sync=_sync) as t:
                    out = t.track(kern(batch, jnp.int32(partition),
                                       jnp.int64(row_off)))
                row_off += int(batch.num_rows)
                yield out

        return count_output(stream(), metrics)

    def __repr__(self):
        return f"FilterOp[{len(self.predicates)} predicates]"


class FilterProjectOp(PhysicalOp):
    """Fused filter+project — one kernel launch, full XLA fusion."""

    name = "filter_project"
    fusable = True
    fragment_computes = True

    def __init__(self, child: PhysicalOp, predicates: list[ir.Expr],
                 exprs: list[ir.Expr], names: list[str]):
        self.child = child
        self.predicates = tuple(predicates)
        self.exprs = tuple(exprs)
        self.names = tuple(names)
        self._schema = project_schema(self.exprs, self.names, child.schema())

    @property
    def children(self):
        return [self.child]

    def schema(self) -> Schema:
        return self._schema

    def build_kernel_fragment(self):
        from auron_tpu.ops.fused import KernelFragment
        predicates, exprs = self.predicates, self.exprs
        in_schema = self.child.schema()

        def apply(batch, partition_id, carry):
            # ONE shared EvalContext, like _filter_project_kernel: the
            # memo keys on (batch, expr) so predicate/projection CSE
            # still only shares within the same intermediate batch
            ctx = EvalContext(partition_id=partition_id,
                              row_num_offset=carry, memo={})
            keep = batch.row_mask()
            for p in predicates:
                v = evaluate(p, batch, in_schema, ctx)
                keep = keep & v.data.astype(bool) & v.validity
            filtered = compact(batch, keep)
            cols = tuple(evaluate(e, filtered, in_schema, ctx).col
                         for e in exprs)
            out = DeviceBatch(cols, filtered.num_rows)
            return (out,), carry + jnp.asarray(batch.num_rows, jnp.int64)

        return KernelFragment(
            key=("filter_project", predicates, exprs, in_schema),
            apply=apply)

    def execute(self, partition: int, ctx: ExecContext) -> Iterator[DeviceBatch]:
        metrics = ctx.metrics_for(self)
        elapsed = metrics.counter("elapsed_compute")
        in_schema = self.child.schema()
        _sync = ctx.device_sync

        def stream():
            row_off = 0
            for batch in self.child.execute(partition, ctx):
                kern = _filter_project_kernel(self.predicates, self.exprs,
                                              in_schema, batch.capacity)
                with timer(elapsed, sync=_sync) as t:
                    out = t.track(kern(batch, jnp.int32(partition),
                                       jnp.int64(row_off)))
                row_off += int(batch.num_rows)
                yield out

        return count_output(stream(), metrics)

    def __repr__(self):
        return f"FilterProjectOp[{len(self.predicates)} predicates -> {', '.join(self.names)}]"
