"""Generate operator: explode / posexplode / json_tuple / host UDTF.

Reference: datafusion-ext-plans/src/generate/ (explode.rs, json_tuple.rs,
spark_udtf_wrapper.rs). TPU design: explode over the padded ListColumn
layout is a single device kernel — flatten [cap, max_elems] → [cap*max_elems],
mask slots past each list's length, and compact; pass-through columns ride
along via a row-index gather. json_tuple and UDTFs are host generators (the
reference round-trips those to the JVM the same way, spark_udtf_wrapper.rs),
operating on Arrow batches at the host boundary.
"""

from __future__ import annotations

import json
from typing import Iterator, Optional

import jax
import jax.numpy as jnp
import pyarrow as pa

from auron_tpu.columnar.arrow_bridge import to_arrow, to_device
from auron_tpu.columnar.batch import (DeviceBatch, ListColumn,
                                      PrimitiveColumn, compact)
from auron_tpu.columnar.schema import DataType, Field, Schema
from auron_tpu.exprs import ir
from auron_tpu.exprs import udf as udf_registry
from auron_tpu.exprs.eval import EvalContext, evaluate, infer_dtype
from auron_tpu.ops.base import ExecContext, PhysicalOp, count_output, timer
from auron_tpu.utils.shapes import bucket_rows
from auron_tpu.runtime.programs import program_cache


@program_cache("ops.generate.explode", maxsize=128)
def _explode_kernel(generator: ir.Expr, pass_through: tuple, with_pos: bool,
                    outer: bool, in_schema: Schema, capacity: int):
    """One launch: rows × list elements → flattened live rows."""

    @jax.jit
    def kernel(batch: DeviceBatch):
        ectx = EvalContext()
        from auron_tpu.columnar.batch import StringColumn, StringListColumn
        v = evaluate(generator, batch, in_schema, ectx)
        col = v.col
        assert isinstance(col, (ListColumn, StringListColumn)), \
            "explode needs a list column"
        cap, m = col.capacity, col.max_elems
        flat_n = cap * m
        live = batch.row_mask()

        elem_idx = jnp.tile(jnp.arange(m, dtype=jnp.int32), cap)
        row_idx = jnp.repeat(jnp.arange(cap, dtype=jnp.int32), m)
        in_list = elem_idx < col.lens[row_idx]
        keep = in_list & live[row_idx]
        if isinstance(col, StringListColumn):
            values = None   # string payloads flatten to (chars, lens)
            flat_chars = col.chars.reshape(flat_n, col.width)
            flat_slens = col.slens.reshape(flat_n)
        else:
            values = col.values.reshape(flat_n)
        elem_valid = col.elem_valid.reshape(flat_n)

        outer_slot = jnp.zeros(flat_n, bool)
        if outer:
            # rows with empty/null lists still emit one row (null element,
            # null pos — Spark posexplode_outer)
            empty = (col.lens == 0) | ~col.validity
            outer_slot = (elem_idx == 0) & empty[row_idx] & live[row_idx]
            keep = keep | outer_slot
            elem_valid = elem_valid & ~outer_slot

        from auron_tpu.columnar.batch import gather_column
        cols = [gather_column(batch.columns[i], row_idx, keep)
                for i in pass_through]
        if with_pos:
            cols.append(PrimitiveColumn(
                elem_idx.astype(jnp.int64), keep & ~outer_slot))
        if values is None:
            cols.append(StringColumn(flat_chars, flat_slens,
                                     elem_valid & keep))
        else:
            cols.append(PrimitiveColumn(values, elem_valid & keep))

        flat = DeviceBatch(tuple(cols), jnp.asarray(flat_n, jnp.int32))
        return compact(flat, keep)

    return kernel


class GenerateOp(PhysicalOp):
    name = "generate"

    def __init__(self, child: PhysicalOp, kind: str,
                 generator: Optional[ir.Expr] = None,
                 json_fields: Optional[list[str]] = None,
                 udtf_name: Optional[str] = None,
                 required_child_output: Optional[list[int]] = None,
                 outer: bool = False,
                 output_names: Optional[list[str]] = None):
        assert kind in ("explode", "posexplode", "json_tuple", "udtf")
        self.child = child
        self.kind = kind
        self.generator = generator
        self.json_fields = list(json_fields or [])
        self.udtf_name = udtf_name
        in_schema = child.schema()
        self.required_child_output = list(
            required_child_output
            if required_child_output is not None
            else range(len(in_schema)))
        self.outer = outer

        pass_fields = [in_schema[i] for i in self.required_child_output]
        gen_fields: list[Field] = []
        if kind in ("explode", "posexplode"):
            if kind == "posexplode":
                gen_fields.append(Field("pos", DataType.INT64, False))
            dt, _, _ = infer_dtype(generator, in_schema)
            assert dt == DataType.LIST, "explode generator must be a list"
            from auron_tpu.exprs.fn_arrays import elem_dtype_of
            elem = elem_dtype_of(generator, in_schema)
            gen_fields.append(Field("col", elem or DataType.INT64, True))
        elif kind == "json_tuple":
            gen_fields = [Field(n, DataType.STRING, True)
                          for n in self.json_fields]
        else:  # udtf
            self._udtf = udf_registry.lookup_udtf(udtf_name)
            gen_fields = [Field(n, dt, True)
                          for n, dt in self._udtf.output_fields]
        names = output_names
        if names:
            gen_fields = [f.with_name(n) for f, n in zip(gen_fields, names)]
        self._schema = Schema(tuple(pass_fields) + tuple(gen_fields))

    @property
    def children(self):
        return [self.child]

    def schema(self) -> Schema:
        return self._schema

    # -- host paths ---------------------------------------------------------

    def _json_tuple_host(self, rb: pa.RecordBatch,
                         in_schema: Schema) -> pa.RecordBatch:
        # row count is preserved (bad JSON yields nulls), so pass-through
        # columns are reused as-is
        texts = rb.column(self.generator.index).to_pylist()
        outs: list[list] = [[] for _ in self.json_fields]
        for t in texts:
            vals = [None] * len(self.json_fields)
            if t is not None:
                try:
                    obj = json.loads(t)
                    for j, f in enumerate(self.json_fields):
                        v = obj.get(f) if isinstance(obj, dict) else None
                        if v is not None and not isinstance(v, str):
                            v = json.dumps(v)
                        vals[j] = v
                except (ValueError, TypeError):
                    pass
            for j, v in enumerate(vals):
                outs[j].append(v)
        arrays = [rb.column(i) for i in self.required_child_output]
        arrays += [pa.array(o, pa.string()) for o in outs]
        from auron_tpu.columnar.arrow_bridge import schema_to_arrow
        return pa.RecordBatch.from_arrays(
            arrays, schema=schema_to_arrow(self._schema))

    def _udtf_host(self, rb: pa.RecordBatch) -> pa.RecordBatch:
        rows = rb.to_pylist()
        out_rows = []
        for row in rows:
            vals = tuple(row.values())
            produced = list(self._udtf(vals))
            if not produced and self.outer:
                produced = [(None,) * (len(self._schema)
                                       - len(self.required_child_output))]
            for gen in produced:
                passed = tuple(vals[i] for i in self.required_child_output)
                out_rows.append(passed + tuple(gen))
        from auron_tpu.columnar.arrow_bridge import schema_to_arrow
        sch = schema_to_arrow(self._schema)
        cols = list(zip(*out_rows)) if out_rows else [[] for _ in sch]
        return pa.RecordBatch.from_arrays(
            [pa.array(list(c), type=f.type) for c, f in zip(cols, sch)],
            schema=sch)

    # -- execute ------------------------------------------------------------

    def execute(self, partition: int, ctx: ExecContext) -> Iterator[DeviceBatch]:
        metrics = ctx.metrics_for(self)
        elapsed = metrics.counter("elapsed_compute")
        in_schema = self.child.schema()

        def stream():
            for batch in self.child.execute(partition, ctx):
                if self.kind in ("explode", "posexplode"):
                    kern = _explode_kernel(
                        self.generator, tuple(self.required_child_output),
                        self.kind == "posexplode", self.outer,
                        in_schema, batch.capacity)
                    with timer(elapsed, sync=ctx.device_sync) as t:
                        out = t.track(kern(batch))
                    yield out
                else:
                    rb = to_arrow(batch, in_schema)
                    out = (self._json_tuple_host(rb, in_schema)
                           if self.kind == "json_tuple"
                           else self._udtf_host(rb))
                    if out.num_rows:
                        dev, _ = to_device(
                            out, capacity=bucket_rows(out.num_rows))
                        yield dev

        return count_output(stream(), metrics)

    def __repr__(self):
        return f"GenerateOp[{self.kind}, outer={self.outer}]"
