"""Equi-joins: broadcast hash join and sort-merge join, TPU-style.

The reference implements BHJ as an open-addressing JoinHashMap serialized
into a RecordBatch column for cross-task reuse (reference:
datafusion-ext-plans/src/joins/join_hash_map.rs:44-73,365) and SMJ as
streaming cursors (reference: joins/smj/stream_cursor.rs). Sequential probe
chains and cursor advances don't vectorize, so this engine uses one
primitive for both: the build side is sorted by xxhash64(join keys) once,
and each probe batch binary-searches the sorted hash array (vectorized
searchsorted = log2(B) gathers per probe row, all lanes in parallel).
Candidate ranges are expanded into (probe_idx, build_idx) pairs with a
static output capacity chosen by the host from the exact match count, then
verified by exact key comparison (hash collisions drop out via compaction).

Join types: inner / left / right / full / semi / anti / existence
(reference: auron.proto JoinType + bhj/full_join.rs probe variants).
"""

from __future__ import annotations

from typing import Iterator, Optional

import numpy as np

import jax
import jax.numpy as jnp

from auron_tpu.columnar.batch import (DeviceBatch, PrimitiveColumn, StringColumn,
                                      compact, gather_column)
from auron_tpu.memmgr.consumer import BufferedSpillConsumer
from auron_tpu.columnar.schema import DataType, Field, Schema
from auron_tpu.exprs import ir
from auron_tpu.exprs.eval import EvalContext, evaluate
from auron_tpu.ops import hashing
from auron_tpu.ops.base import ExecContext, PhysicalOp, count_output, timer
from auron_tpu.ops.sort import _concat_all
from auron_tpu.runtime import programs
from auron_tpu.runtime.programs import program_cache
from auron_tpu.utils.shapes import bucket_rows

# sentinel hashes guaranteeing null keys never match (numpy scalars so the
# import doesn't force jax backend init — see ops/hashing.py)
_NULL_PROBE = np.uint64(0xFFFFFFFFFFFFFFFF)
_NULL_BUILD = np.uint64(0xFFFFFFFFFFFFFFFE)


def _key_hashes(cols, cap, live, null_sentinel) -> jax.Array:
    h = hashing.xxhash64_columns(list(cols), cap).view(jnp.uint64)
    any_null = jnp.zeros(cap, bool)
    for c in cols:
        any_null = any_null | ~c.validity
    h = jnp.where(any_null | ~live, null_sentinel, h)
    return h


def _take_cols(cols, idx, valid):
    return tuple(gather_column(c, idx, valid) for c in cols)


def _candidate_lookup(h, index_kind: str, index_args: tuple, rounds: int):
    """(lo, counts) of each probe hash's candidate run in the sorted
    build table — via two binary searches ('sorted'), or one hash-table
    probe of the run index ('ht', auron_tpu/hashtable). Both return the
    EXACT same (lo, counts) for present hashes and counts == 0 for
    absent ones, so downstream expand + exact-key verification make the
    two candidate searches bit-identical end to end."""
    if index_kind == "ht":
        from auron_tpu.hashtable.core import EMPTY, probe_hash_index
        idx_h, idx_lo, idx_cnt = index_args
        live = h != EMPTY       # null/dead probe rows match nothing
        slot, found = probe_hash_index(idx_h, h, live, rounds)
        lo = jnp.where(found, idx_lo[slot], 0).astype(jnp.int32)
        counts = jnp.where(found, idx_cnt[slot], 0).astype(jnp.int32)
        return lo, counts
    (build_hashes,) = index_args
    lo = jnp.searchsorted(build_hashes, h, side="left").astype(jnp.int32)
    hi = jnp.searchsorted(build_hashes, h, side="right").astype(jnp.int32)
    return lo, hi - lo


def _probe_count_body(probe: DeviceBatch, index_kind: str,
                      index_args: tuple, rounds: int, key_exprs: tuple,
                      in_schema: Schema):
    """Traced probe-side candidate search over the build-side index."""
    ctx = EvalContext()
    keys = tuple(evaluate(e, probe, in_schema, ctx).col for e in key_exprs)
    h = _key_hashes(keys, probe.capacity, probe.row_mask(), _NULL_PROBE)
    lo, counts = _candidate_lookup(h, index_kind, index_args, rounds)
    total = jnp.sum(counts)
    return h, lo, counts, total


@program_cache("ops.joins.probe_count", maxsize=256)
def _probe_count_kernel(key_exprs: tuple, in_schema: Schema, capacity: int,
                        build_cap: int, index_kind: str, rounds: int):
    @jax.jit
    def kernel(probe: DeviceBatch, *index_args):
        return _probe_count_body(probe, index_kind, index_args, rounds,
                                 key_exprs, in_schema)

    return kernel


#: probe-prologue programs: the probe-side fused-stage chain + key hashing
#: + candidate search in ONE XLA program (the join-side analogue of the
#: exchange's fused split) — the probe chain's intermediate batch goes
#: straight into the hash probe without an extra program boundary
_PROBE_PROGRAMS = programs.register(
    programs.ProgramCache("ops.joins.fused_probe", maxsize=256))

#: probe-epilogue programs (Fusion 2.0): candidate expansion + exact-key
#: verification + pair gather + compaction + the CONSUMER stage's fragment
#: chain in ONE XLA program — the inner join's matched output feeds the
#: downstream fused chain without materializing the joined batch between
#: two program launches (the dual of the probe prologue above)
_GATHER_PROGRAMS = programs.register(
    programs.ProgramCache("ops.joins.gather_consumer", maxsize=256))


def _gather_consumer_program(frag_keys: tuple, key_exprs: tuple,
                             probe_schema: Schema, build_schema: Schema,
                             out_cap: int, capacity: int, build_cap: int,
                             fragments):
    """One program per (consumer chain, join keys, schemas, capacities):
    the inner join's match/gather phase — expand, verify, gather both
    sides, compact — runs fused with the consumer FusedStageOp's member
    fragments. The compacted joined batch the chain sees is exactly the
    batch ``_probe_one`` would have yielded standalone (same expand, same
    ``_keys_match``, same stable compaction), and the fragments are the
    same traced bodies the consumer's own stage program would run, so the
    fold is bit-identical — it only removes one program boundary."""

    def build():
        from auron_tpu.ops.fused import thread_fragments
        from auron_tpu.runtime import programs as _programs

        def kernel(probe: DeviceBatch, build_batch: DeviceBatch,
                   build_keys: tuple, lo, counts, partition_id, carries):
            ctx = EvalContext()
            probe_key_cols = tuple(
                evaluate(e, probe, probe_schema, ctx).col for e in key_exprs)
            # candidate expansion (same body as _expand_kernel)
            starts = jnp.cumsum(counts) - counts
            total = jnp.sum(counts)
            slots = jnp.arange(out_cap, dtype=jnp.int32)
            probe_idx = jnp.searchsorted(
                starts, slots, side="right").astype(jnp.int32) - 1
            probe_idx = jnp.clip(probe_idx, 0, capacity - 1)
            offset = slots - starts[probe_idx]
            build_idx = lo[probe_idx] + offset
            in_range = slots < total
            build_idx = jnp.where(in_range, build_idx, 0)
            ok = _keys_match(probe_key_cols, probe_idx, build_keys,
                             build_idx) & in_range
            out_probe = _take_cols(probe.columns, probe_idx,
                                   jnp.ones_like(probe_idx, bool))
            out_build = _take_cols(build_batch.columns, build_idx,
                                   jnp.ones_like(build_idx, bool))
            pair = DeviceBatch(tuple(out_probe) + tuple(out_build),
                               jnp.asarray(out_cap, jnp.int32))
            matched = compact(pair, ok)
            outs, new_carries = thread_fragments(fragments, matched,
                                                 partition_id, carries)
            (b,) = outs   # fan-out chains rejected by eligibility
            return b, jnp.stack(new_carries)

        # donation stays off: the probe batch may still feed a
        # left/full unmatched pass upstream in future variants; the
        # gather allocates fresh output arrays regardless
        return _programs.jit(kernel)

    return _GATHER_PROGRAMS.get_or_build(
        (frag_keys, key_exprs, probe_schema, build_schema, out_cap,
         capacity, build_cap), build)


def _fused_probe_program(frag_keys: tuple, key_exprs: tuple,
                         in_schema: Schema, out_schema: Schema,
                         capacity: int, build_cap: int, fragments,
                         index_kind: str, rounds: int,
                         donate: bool = False):
    """One program per (probe chain, join keys, schema, capacities,
    candidate-search backend): member fragments thread the batch, then
    the probe-count body runs on the chain output. Returns the
    transformed batch too — the join's match/gather phase consumes it,
    and the downstream eager key evaluation (_keys_match) sees exactly
    the batch the standalone chain would have produced, keeping fused
    results bit-identical. ``donate`` hands the raw input batch to XLA
    when the probe child owns it (dead after the chain; no-op on CPU)."""

    def build():
        from auron_tpu.ops.fused import thread_fragments
        from auron_tpu.runtime import programs as _programs

        def kernel(batch: DeviceBatch, partition_id, carries,
                   *index_args):
            outs, new_carries = thread_fragments(fragments, batch,
                                                 partition_id, carries)
            (b,) = outs   # fan-out chains never take this path
            h, lo, counts, total = _probe_count_body(
                b, index_kind, index_args, rounds, key_exprs, out_schema)
            return b, lo, counts, total, jnp.stack(new_carries)

        # graft: donation-ok -- probe chain owns the raw batch
        # (fragment_computes gate); probe programs never re-run
        return _programs.jit(kernel,
                             donate_argnums=(0,) if donate else ())

    return _PROBE_PROGRAMS.get_or_build(
        (frag_keys, key_exprs, in_schema, capacity, build_cap,
         index_kind, rounds, donate), build)


@program_cache("ops.joins.expand", maxsize=256)
def _expand_kernel(out_cap: int, capacity: int):
    """Expand candidate ranges to (probe_idx, build_idx) pairs."""

    @jax.jit
    def kernel(lo, counts):
        starts = jnp.cumsum(counts) - counts  # exclusive prefix
        total = jnp.sum(counts)
        slots = jnp.arange(out_cap, dtype=jnp.int32)
        # probe row owning slot t: last row with starts <= t
        probe_idx = jnp.searchsorted(starts, slots, side="right").astype(jnp.int32) - 1
        probe_idx = jnp.clip(probe_idx, 0, capacity - 1)
        offset = slots - starts[probe_idx]
        build_idx = lo[probe_idx] + offset
        in_range = slots < total
        return probe_idx, jnp.where(in_range, build_idx, 0), in_range

    return kernel


class _BuildSide:
    """Sorted-by-hash build table, plus (when enabled and the build side
    fits) the hash-table candidate index over its hash runs."""

    def __init__(self, batch: DeviceBatch, schema: Schema, key_exprs,
                 metrics, conf=None):
        self.schema = schema
        cap = batch.capacity
        ctx = EvalContext()
        keys = tuple(evaluate(e, batch, schema, ctx).col for e in key_exprs)
        h = _key_hashes(keys, cap, batch.row_mask(), _NULL_BUILD)
        perm = jnp.argsort(h, stable=True)
        from auron_tpu.columnar.batch import gather_batch
        self.batch = gather_batch(batch, perm, batch.num_rows)
        self.hashes = h[perm]
        self.keys = tuple(gather_column(c, perm, jnp.ones(cap, bool))
                          for c in keys)
        self.capacity = cap
        # matched mask for right/full joins, or-accumulated across batches
        self.matched = jnp.zeros(cap, bool)
        # hash-run candidate index (auron_tpu/hashtable): probe hash →
        # (run lo, run length) in O(probe rounds) gathers instead of two
        # O(log B) searchsorted passes; None keeps the searchsorted path
        # (disabled, too large, or sentinel-colliding hashes)
        self.index = None
        self.rounds = 64
        if conf is not None:
            from auron_tpu import config as cfg
            if conf.get(cfg.HASHTABLE_ENABLED) \
                    and conf.get(cfg.HASHTABLE_BACKEND) != "sort":
                from auron_tpu.hashtable import build_join_index
                self.rounds = max(1, conf.get(
                    cfg.HASHTABLE_MAX_PROBE_ROUNDS))
                self.index = build_join_index(self.hashes, self.rounds)
        if metrics is not None:
            metrics.counter(
                "dispatch_ht_index" if self.index is not None
                else "dispatch_searchsorted").add(1)

    @property
    def index_kind(self) -> str:
        return "ht" if self.index is not None else "sorted"

    def index_args(self) -> tuple:
        if self.index is not None:
            return (self.index.th, self.index.lo, self.index.cnt)
        return (self.hashes,)


def _keys_match(probe_keys, probe_idx, build_keys, build_idx) -> jax.Array:
    """Exact equality verification per candidate pair: structural value
    equality (NaN == NaN, struct fieldwise) but top-level NULL keys never
    match (SQL equi-join)."""
    from auron_tpu.ops.hashing import pairwise_eq
    ok = jnp.ones(probe_idx.shape[0], bool)
    for pc, bc in zip(probe_keys, build_keys):
        pv = pc.validity[probe_idx]
        bv = bc.validity[build_idx]
        ok = ok & pv & bv & pairwise_eq(pc, probe_idx, bc, build_idx)
    return ok


class HashJoinOp(PhysicalOp):
    """Generic equi-join; build side fully materialized (broadcast pattern).

    join_type: inner | left | right | full | semi | anti | existence
    (probe side is 'left' in naming below).
    """

    name = "hash_join"

    #: SPMD layout contract (ir/planner.annotate_mesh → parallel/mesh
    #: buffer_spec): the build side REPLICATES across the mesh — every
    #: probe shard reads the full relation, so a sharded probe stage
    #: never exchanges build rows; probe batches shard on the batch dim.
    mesh_build_kind = "hash_build"

    #: Fusion 2.0 plan facts, stamped per-instance by the planner's
    #: _fold_combine pass; class defaults keep hand-built op trees (and
    #: plans produced with the fusion pass disabled) on sane behavior.
    #: cost_site is the (plan_fp, site) key for the ir/cost history;
    #: probe_fold_consumer gates the probe-into-consumer fold the
    #: downstream FusedStageOp asks for (ir/cost.choose_probe_fold).
    cost_site = None
    probe_fold_consumer = True

    def __init__(self, probe: PhysicalOp, build: PhysicalOp,
                 probe_keys: list[ir.Expr], build_keys: list[ir.Expr],
                 join_type: str = "inner"):
        assert join_type in ("inner", "left", "right", "full", "semi",
                             "anti", "existence")
        self.probe = probe
        self.build = build
        self.probe_keys = tuple(probe_keys)
        self.build_keys = tuple(build_keys)
        self.join_type = join_type

        ps, bs = probe.schema(), build.schema()
        if join_type in ("semi", "anti"):
            self._schema = ps
        elif join_type == "existence":
            self._schema = Schema(tuple(ps.fields) + (Field("exists", DataType.BOOL, False),))
        else:
            self._schema = Schema(tuple(ps.fields) + tuple(bs.fields))

    @property
    def children(self):
        return [self.probe, self.build]

    def schema(self) -> Schema:
        return self._schema

    def execute(self, partition: int, ctx: ExecContext,
                _consumer=None) -> Iterator[DeviceBatch]:
        """``_consumer`` is the probe-into-consumer fold handshake
        (ops/fused.FusedStageOp.execute): ``(consumer_op, fragments,
        frag_keys)`` of the downstream fused chain. The inner join's
        matched output then runs through ``_gather_consumer_program`` —
        match phase + consumer chain in one launch — and every batch this
        generator yields is ALREADY chained; degraded paths (SMJ
        fallback, empty build) chain via the consumer's ordinary stage
        program instead so the contract holds on every route."""
        metrics = ctx.metrics_for(self)
        elapsed = metrics.counter("elapsed_compute")
        build_time = metrics.counter("build_hash_map_time")
        probe_schema = self.probe.schema()
        build_schema = self.build.schema()
        mem = ctx.mem_manager
        spillable = mem is not None and \
            getattr(mem, "spill_manager", None) is not None
        fold_state = None
        if _consumer is not None:
            consumer_op, cfrags, cfrag_keys = _consumer
            fold_state = {
                "op": consumer_op, "fragments": cfrags,
                "frag_keys": cfrag_keys, "partition": partition,
                "carries": jnp.asarray([f.init_carry for f in cfrags],
                                       jnp.int64),
            }
            ctx.metrics_for(consumer_op).counter(
                "probe_consumer_folded").add(1)
            km = ctx.metrics_for("kernels")
            fold_state["built_c"] = km.counter(
                "gather_consumer_programs_built")
            fold_state["hit_c"] = km.counter("gather_consumer_program_hits")

        def stream():
            consumer = _JoinBuildConsumer(self, mem, metrics, ctx.conf) \
                if spillable else None
            # per-run probe statistics for the ir/cost history: matched
            # candidate totals are already host-synced (int(total) gates
            # the output capacity), so observing them adds no sync
            probe_rows_out = 0
            probe_batches = 0
            try:
                build_batches = []
                with timer(build_time):
                    for b in self.build.execute(partition, ctx):
                        # the build side materializes fully before any
                        # probe batch streams: without a poll here a
                        # cancel/deadline waits out the whole build
                        ctx.checkpoint("join.build")
                        if consumer is not None:
                            consumer.add(b)
                        else:
                            build_batches.append(b)
                if consumer is not None and consumer.spills:
                    # Build side exceeded its memory share: degrade to an
                    # external sort-merge join over spilled runs (the
                    # reference's smj-fallback knob, conf.rs:53-55, in the
                    # memory-safe direction).
                    metrics.counter("fallback_smj_count").add(1)
                    out = self._smj_fallback(consumer, partition, ctx)
                    if fold_state is not None:
                        out = fold_state["op"].run_chain(out, partition, ctx)
                    yield from out
                    return
                if consumer is not None:
                    build_batches = consumer.take_buffered()
                with timer(build_time):
                    merged = None
                    if build_batches:
                        merged = _concat_all(build_batches) \
                            if len(build_batches) > 1 else build_batches[0]
                if merged is None:
                    out = self._empty_build_stream(partition, ctx,
                                                   probe_schema)
                    if fold_state is not None:
                        out = fold_state["op"].run_chain(out, partition, ctx)
                    yield from out
                    return
                side = _BuildSide(merged, build_schema, self.build_keys,
                                  metrics, conf=ctx.conf)

                stats = [0, 0]
                fold = self._probe_fold(ctx)
                if fold is not None:
                    yield from self._probe_fused(fold, side, partition, ctx,
                                                 probe_schema, build_schema,
                                                 elapsed, fold_state, stats)
                else:
                    for probe in self.probe.execute(partition, ctx):
                        yield from self._probe_one(probe, side, probe_schema,
                                                   build_schema, elapsed,
                                                   ctx.device_sync,
                                                   fold_state=fold_state,
                                                   stats=stats)
                probe_rows_out, probe_batches = stats

                if self.join_type in ("right", "full"):
                    yield self._unmatched_build(side, probe_schema,
                                                build_schema)
            finally:
                if consumer is not None:
                    consumer.close()
                if probe_batches:
                    from auron_tpu.ir import cost as cost_mod
                    cost_mod.observe(self.cost_site, probe_rows_out,
                                     probe_rows_out, probe_batches)

        return count_output(stream(), metrics)

    def _smj_fallback(self, consumer: "_JoinBuildConsumer", partition: int,
                      ctx: ExecContext) -> Iterator[DeviceBatch]:
        """Oversized build side: sort both sides externally (SortOp handles
        the spill-backed sorting) and stream an order-preserving merge join
        with a bounded window."""
        from auron_tpu.ops.smj import SortMergeJoinOp
        from auron_tpu.ops.sort import SortOp
        replay = _SpillReplayOp(self.build.schema(), consumer.spills,
                                consumer.take_buffered())
        probe_sorted = SortOp(self.probe,
                              [ir.SortOrder(e) for e in self.probe_keys])
        build_sorted = SortOp(replay,
                              [ir.SortOrder(e) for e in self.build_keys])
        smj = SortMergeJoinOp(probe_sorted, build_sorted,
                              list(self.probe_keys), list(self.build_keys),
                              self.join_type)
        yield from smj.execute(partition, ctx)

    # -- helpers ------------------------------------------------------------
    def _probe_fold(self, ctx: ExecContext):
        """(fragments, frag_keys, input_op) when the probe side is a
        fused chain whose fragments can fold into the probe-count
        program, else None."""
        from auron_tpu import config as cfg
        from auron_tpu.ops.fused import FusedStageOp
        if not ctx.conf.get(cfg.FUSION_ENABLED):
            return None
        if not isinstance(self.probe, FusedStageOp) \
                or self.probe.has_limit():
            return None
        fragments, frag_keys = self.probe.fragment_pipeline()
        if not fragments or any(f.fanout != 1 for f in fragments):
            return None
        return fragments, frag_keys, self.probe.input

    def _probe_fused(self, fold, side: _BuildSide, partition: int,
                     ctx: ExecContext, probe_schema, build_schema, elapsed,
                     fold_state=None, stats=None):
        """Probe loop with the chain folded into the probe program: one
        XLA launch runs the member fragments AND the candidate search;
        the transformed batch comes back for the match/gather phase."""
        fragments, frag_keys, input_op = fold
        kmetrics = ctx.metrics_for("kernels")
        built_c = kmetrics.counter("fused_probe_programs_built")
        hit_c = kmetrics.counter("fused_probe_program_hits")
        # the folded chain still OWNS its plan node: the probe program
        # runs the member fragments and returns the transformed batch,
        # so the FusedStageOp node gets its real output rows and the
        # program's time (the whole-stage attribution — without this,
        # EXPLAIN ANALYZE would show the elided node as dead)
        fmetrics = ctx.metrics_for(self.probe)
        f_elapsed = fmetrics.counter("elapsed_compute")
        f_rows = fmetrics.counter("output_rows")
        f_batches = fmetrics.counter("output_batches")
        fmetrics.counter("probe_search_folded").add(1)
        in_schema = input_op.schema()
        _sync = ctx.device_sync
        # donation sweep: the raw probe batch is dead once the chain
        # produced the transformed batch — donate it when owned
        from auron_tpu.ops.base import yields_owned_batches
        donate = (any(getattr(m, "fragment_computes", False)
                      for m in self.probe.members)
                  and yields_owned_batches(input_op))
        carries = jnp.asarray([f.init_carry for f in fragments], jnp.int64)
        for raw in input_op.execute(partition, ctx):
            ctx.check_cancelled()
            kern, built = _fused_probe_program(
                frag_keys, self.probe_keys, in_schema, probe_schema,
                raw.capacity, side.capacity, fragments,
                side.index_kind, side.rounds, donate)
            (built_c if built else hit_c).add(1)
            with timer(f_elapsed, sync=_sync) as t:
                probe, lo, counts, total, carries = t.track(
                    kern(raw, jnp.int32(partition), carries,
                         *side.index_args()))
            f_rows.add(int(probe.num_rows))
            f_batches.add(1)
            yield from self._probe_one(probe, side, probe_schema,
                                       build_schema, elapsed, _sync,
                                       pre=(lo, counts, total),
                                       fold_state=fold_state, stats=stats)

    def _probe_one(self, probe: DeviceBatch, side: _BuildSide, probe_schema,
                   build_schema, elapsed, _sync: bool = True, pre=None,
                   fold_state=None, stats=None):
        cap = probe.capacity
        if pre is None:
            kern = _probe_count_kernel(self.probe_keys, probe_schema, cap,
                                       side.capacity, side.index_kind,
                                       side.rounds)
            with timer(elapsed, sync=_sync) as t:
                _h, lo, counts, total = t.track(
                    kern(probe, *side.index_args()))
        else:   # the fused probe program already ran the candidate search
            lo, counts, total = pre
        total_i = int(total)
        if stats is not None:
            stats[0] += total_i
            stats[1] += 1

        if fold_state is not None:
            # probe-into-consumer fold (inner joins only — eligibility is
            # the consumer's _consumer_fold): expand + verify + gather +
            # compact + consumer chain, one launch; the consumer carries
            # advance across matched batches exactly as its own stage
            # program would have advanced them
            if total_i == 0:
                # no candidates → the unfused join yields no batch here,
                # so the consumer chain (and its carries) never see one
                return
            out_cap = bucket_rows(total_i)
            kern, built = _gather_consumer_program(
                fold_state["frag_keys"], self.probe_keys, probe_schema,
                build_schema, out_cap, cap, side.capacity,
                fold_state["fragments"])
            (fold_state["built_c"] if built else fold_state["hit_c"]).add(1)
            with timer(elapsed, sync=_sync) as t:
                out, fold_state["carries"] = t.track(kern(
                    probe, side.batch, side.keys, lo, counts,
                    jnp.int32(fold_state["partition"]),
                    fold_state["carries"]))
            yield out
            return

        ctx = EvalContext()
        probe_key_cols = tuple(evaluate(e, probe, probe_schema, ctx).col
                               for e in self.probe_keys)

        if self.join_type in ("semi", "anti", "existence", "left", "full") \
                or total_i > 0:
            out_cap = bucket_rows(max(total_i, 1))
            expand = _expand_kernel(out_cap, cap)
            with timer(elapsed, sync=_sync) as t:
                probe_idx, build_idx, in_range = expand(lo, counts)
                ok = t.track(_keys_match(probe_key_cols, probe_idx, side.keys,
                                         build_idx) & in_range)
        else:
            probe_idx = build_idx = ok = None

        if self.join_type in ("right", "full") and ok is not None:
            side.matched = side.matched.at[jnp.where(ok, build_idx, side.capacity)] \
                .set(True, mode="drop") | side.matched

        if self.join_type in ("semi", "anti", "existence"):
            matched_probe = jnp.zeros(cap, bool)
            if ok is not None:
                matched_probe = matched_probe.at[
                    jnp.where(ok, probe_idx, cap)].set(True, mode="drop")
            if self.join_type == "semi":
                out = compact(probe, matched_probe)
                yield out
            elif self.join_type == "anti":
                out = compact(probe, ~matched_probe & probe.row_mask())
                yield out
            else:  # existence
                cols = probe.columns + (PrimitiveColumn(
                    matched_probe, jnp.ones(cap, bool)),)
                yield DeviceBatch(cols, probe.num_rows)
            return

        outputs = []
        if total_i > 0:
            n_valid = jnp.sum(ok.astype(jnp.int32))
            valid_slots = ok
            out_probe = _take_cols(probe.columns, probe_idx,
                                   jnp.ones_like(probe_idx, bool))
            out_build = _take_cols(side.batch.columns, build_idx,
                                   jnp.ones_like(build_idx, bool))
            pair_batch = DeviceBatch(tuple(out_probe) + tuple(out_build),
                                     jnp.asarray(ok.shape[0], jnp.int32))
            matched_out = compact(pair_batch, valid_slots)
            outputs.append(matched_out)

        if self.join_type in ("left", "full"):
            # unmatched probe rows with nulls on build side
            matched_probe = jnp.zeros(cap, bool)
            if ok is not None:
                matched_probe = matched_probe.at[
                    jnp.where(ok, probe_idx, cap)].set(True, mode="drop")
            unmatched = ~matched_probe & probe.row_mask()
            left_out = compact(probe, unmatched)
            null_build = tuple(_null_column_like(c, cap)
                               for c in side.batch.columns)
            outputs.append(DeviceBatch(left_out.columns + null_build,
                                       left_out.num_rows))
        yield from outputs

    def _unmatched_build(self, side: _BuildSide, probe_schema, build_schema):
        unmatched = ~side.matched & side.batch.row_mask()
        build_out = compact(side.batch, unmatched)
        cap = side.capacity
        null_probe = tuple(_null_column_like_schema(f, cap)
                           for f in probe_schema)
        return DeviceBatch(null_probe + build_out.columns, build_out.num_rows)

    def _empty_build_stream(self, partition, ctx, probe_schema):
        for probe in self.probe.execute(partition, ctx):
            cap = probe.capacity
            if self.join_type in ("anti",):
                yield probe
            elif self.join_type in ("semi",):
                yield DeviceBatch(probe.columns, jnp.asarray(0, jnp.int32))
            elif self.join_type == "existence":
                cols = probe.columns + (PrimitiveColumn(
                    jnp.zeros(cap, bool), jnp.ones(cap, bool)),)
                yield DeviceBatch(cols, probe.num_rows)
            elif self.join_type in ("left", "full"):
                null_build = tuple(_null_column_like_schema(f, cap)
                                   for f in self.build.schema())
                yield DeviceBatch(probe.columns + null_build, probe.num_rows)
            # inner/right with empty build: no output

    def __repr__(self):
        return f"HashJoinOp[{self.join_type}, {len(self.probe_keys)} keys]"


def _null_column_like(col, cap):
    from auron_tpu.columnar.decimal128 import Decimal128Column
    if isinstance(col, StringColumn):
        return StringColumn(jnp.zeros((cap, col.width), jnp.uint8),
                            jnp.zeros(cap, jnp.int32), jnp.zeros(cap, bool))
    if isinstance(col, Decimal128Column):
        return Decimal128Column(jnp.zeros(cap, jnp.int64),
                                jnp.zeros(cap, jnp.int64),
                                jnp.zeros(cap, bool))
    return PrimitiveColumn(jnp.zeros(cap, col.data.dtype), jnp.zeros(cap, bool))


def _null_column_like_schema(field: Field, cap):
    from auron_tpu.exprs.eval import null_column_for_field
    return null_column_for_field(field, cap)


class _JoinBuildConsumer(BufferedSpillConsumer):
    """Build-side buffering registered with the memory manager (the
    MemConsumer role the reference's broadcast-join build plays,
    join_hash_map.rs:365-387). Under pressure, buffered batches spill as
    unsorted runs to tiered storage; their presence switches the join to
    the external sort-merge fallback."""

    def __init__(self, op: "HashJoinOp", mem, metrics, conf):
        super().__init__(f"join-build-{id(op):x}", mem, metrics, conf)


class _SpillReplayOp(PhysicalOp):
    """Replays spilled build-side runs (plus any still-resident batches) as
    a child stream for the sort-merge fallback."""

    name = "spill_replay"

    def __init__(self, schema: Schema, spills, batches: list[DeviceBatch]):
        self._schema = schema
        self.spills = spills
        self.batches = batches

    def schema(self) -> Schema:
        return self._schema

    def execute(self, partition: int, ctx: ExecContext) -> Iterator[DeviceBatch]:
        from auron_tpu.columnar.serde import (deserialize_host_batch,
                                              host_to_batch)
        def stream():
            for s in self.spills:
                for frame in s.frames():
                    host, _ = deserialize_host_batch(frame)
                    if host.num_rows:
                        yield host_to_batch(host, bucket_rows(host.num_rows))
            for b in self.batches:
                yield b
        return stream()

    def __repr__(self):
        return f"_SpillReplayOp[{len(self.spills)} spills]"


# canonical SMJ implementation (order-preserving streaming merge) lives in
# ops/smj.py; re-exported here so plan builders import one joins module
from auron_tpu.ops.smj import SortMergeJoinOp  # noqa: E402
