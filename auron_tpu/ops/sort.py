"""Sort operator.

The reference's external sort is row-format blocks + loser-tree k-way merge
with key prefixes (reference: datafusion-ext-plans/src/sort_exec.rs). On TPU
the economics invert: one big device sort beats incremental merging, so the
design is: buffer the (bounded) partition, normalize every sort key into
order-preserving uint64 words, and run a chain of stable argsorts
(least-significant key first) that XLA lowers to its parallel sort. Nulls
first/last and asc/desc are encoded into the key words themselves:

  int64     → x XOR sign-bit        (order-preserving unsigned map)
  float     → IEEE trick: flip all bits if negative else flip sign bit
  string    → big-endian byte words (zero padding already sorts prefixes first)
  desc      → bitwise NOT of the word
  null rank → one leading word per key: 0/1 by nulls_first

Spill for over-HBM partitions hooks in at the buffer stage via the memory
manager (sorted-run spill + host merge), added with the memmgr subsystem.
"""

from __future__ import annotations

from typing import Iterator, Optional

import jax
import jax.numpy as jnp

from auron_tpu.columnar.batch import (DeviceBatch, ListColumn,
                                      PrimitiveColumn, StringColumn,
                                      unify_column_widths,
                                      concat_columns, gather_batch)
from auron_tpu.columnar.schema import DataType, Schema
from auron_tpu.exprs import ir
from auron_tpu.exprs.eval import EvalContext, evaluate
from auron_tpu.memmgr.consumer import BufferedSpillConsumer
from auron_tpu.ops.base import (ExecContext, PhysicalOp, count_output,
                                timer, yields_owned_batches)
from auron_tpu.runtime import programs
from auron_tpu.runtime.programs import program_cache
from auron_tpu.utils.shapes import bucket_rows


def _sort_donate(batches: list[DeviceBatch], child: PhysicalOp) -> bool:
    """Donate the sort input when it is dead after the kernel: a multi-
    batch merge is always a fresh local concat; a single batch is safe
    only when the child constructs fresh outputs (donating a replayed
    broadcast/device-scan batch would poison later readers). CPU treats
    donation as advisory, so skip it there (programs.jit also guards)."""
    if jax.default_backend() == "cpu":
        return False
    return len(batches) > 1 or yields_owned_batches(child)


def string_be_words(chars: "jax.Array") -> "jax.Array":
    """[n, w] uint8 → [n, ceil(w/8)] big-endian uint64 words whose
    unsigned order equals byte-lexicographic order (zero padding sorts
    prefixes first; SQL strings never contain NUL). THE one definition of
    the order-preserving string encoding — order_words and the
    string-list sort share it."""
    n, w = chars.shape
    pad = (-w) % 8
    if pad:
        chars = jnp.pad(chars, ((0, 0), (0, pad)))
    u = chars.astype(jnp.uint64).reshape(n, -1, 8)
    shifts = jnp.asarray([56, 48, 40, 32, 24, 16, 8, 0], jnp.uint64)
    return jnp.sum(u << shifts[None, None, :], axis=2)


def order_words(col, ascending: bool, nulls_first: bool) -> list[jax.Array]:
    """Normalize one sort key column into order-preserving uint64 words,
    most significant first (excluding the null-rank word, which the caller
    gets separately)."""
    from auron_tpu.columnar.batch import StructColumn
    from auron_tpu.columnar.decimal128 import Decimal128Column
    words: list[jax.Array] = []
    if isinstance(col, StructColumn):
        # struct ordering is fieldwise; each field contributes its own
        # null-rank word (null fields sort first ascending, like Spark's
        # InterpretedOrdering) then its value words, nulls neutralized
        for ch in col.children:
            cv = ch.validity & col.validity
            words.append(jnp.where(cv, jnp.uint64(1), jnp.uint64(0)))
            words.extend(jnp.where(cv, w, jnp.uint64(0))
                         for w in order_words(ch, True, True))
        if not ascending:
            words = [~w for w in words]
        return words
    if isinstance(col, Decimal128Column):
        # signed 128-bit order: sign-flipped hi limb, then unsigned lo
        hi_w = col.hi.astype(jnp.uint64) ^ jnp.uint64(1 << 63)
        lo_w = col.lo.astype(jnp.uint64)
        words = [hi_w, lo_w]
        if not ascending:
            words = [~w for w in words]
        return words
    if isinstance(col, StringColumn):
        be = string_be_words(col.chars)
        words.extend(be[:, i] for i in range(be.shape[1]))
    else:
        d = col.data
        if d.dtype == jnp.bool_:
            u = d.astype(jnp.uint64)
        elif jnp.issubdtype(d.dtype, jnp.signedinteger):
            u = d.astype(jnp.int64).astype(jnp.uint64) ^ jnp.uint64(1 << 63)
        elif d.dtype == jnp.dtype(jnp.float32):
            # Spark ordering: -0.0 == 0.0 and every NaN is the same
            # (greatest) value — canonicalize before bit-twiddling so
            # equal-under-Spark keys produce identical order words (SMJ
            # and window group detection compare words for equality)
            from auron_tpu.ops.hashing import canonicalize_float
            d = canonicalize_float(d)
            b = d.view(jnp.int32).astype(jnp.int64).astype(jnp.uint64) \
                & jnp.uint64(0xFFFFFFFF)
            sign = (b >> 31) & 1
            u = jnp.where(sign == 1, (~b) & jnp.uint64(0xFFFFFFFF),
                          b | jnp.uint64(0x80000000))
        elif d.dtype == jnp.dtype(jnp.float64):
            from jax import lax
            from auron_tpu.ops.hashing import canonicalize_float
            d = canonicalize_float(d)
            pair = lax.bitcast_convert_type(d, jnp.uint32)
            b = pair[..., 0].astype(jnp.uint64) | (pair[..., 1].astype(jnp.uint64) << 32)
            sign = (b >> 63) & 1
            u = jnp.where(sign == 1, ~b, b | jnp.uint64(1 << 63))
        else:
            u = d.astype(jnp.uint64)
        words.append(u)
    if not ascending:
        words = [~w for w in words]
    return words


def sort_key_words(key_cols, orders) -> list[jax.Array]:
    """All order words for a composite key, most-significant first: per key,
    one null-rank word then the value words (nulls neutralized to 0)."""
    all_words: list[jax.Array] = []
    for col, (asc, nf) in zip(key_cols, orders):
        null_word = jnp.where(col.validity,
                              jnp.uint64(1 if nf else 0),
                              jnp.uint64(0 if nf else 1))
        words = order_words(col, asc, nf)
        # null rows: neutralize value words so they compare equal
        words = [jnp.where(col.validity, w, 0) for w in words]
        all_words.append(null_word)
        all_words.extend(words)
    return all_words


def sort_permutation(batch: DeviceBatch, key_cols, orders) -> jax.Array:
    """Stable multi-key sort permutation. orders: list[(ascending,
    nulls_first)] aligned with key_cols. Padding rows sort to the end."""
    cap = batch.capacity
    live = batch.row_mask()
    all_words = sort_key_words(key_cols, orders)
    # dead rows to the very end: leading liveness word
    lead = jnp.where(live, jnp.uint64(0), jnp.uint64(1))
    perm = jnp.arange(cap, dtype=jnp.int32)
    for w in reversed(all_words):
        perm = perm[jnp.argsort(w[perm], stable=True)]
    perm = perm[jnp.argsort(lead[perm], stable=True)]
    return perm


@program_cache("ops.sort.sort", maxsize=256)
def _sort_kernel(sort_exprs: tuple, in_schema: Schema, capacity: int,
                 donate: bool):
    def kernel(batch: DeviceBatch):
        ctx = EvalContext()
        key_cols = [evaluate(s.expr, batch, in_schema, ctx).col
                    for s in sort_exprs]
        orders = [(s.ascending, s.nulls_first) for s in sort_exprs]
        perm = sort_permutation(batch, key_cols, orders)
        return gather_batch(batch, perm, batch.num_rows)

    # the un-sorted input is dead after the gather — donating it halves
    # peak HBM for the sort step (callers gate on ownership + platform)
    # graft: donation-ok -- _sort_donate gate (owned batches only)
    return programs.jit(kernel, donate_argnums=(0,) if donate else ())


def key_word_layout(sort_exprs: tuple, in_schema: Schema,
                    batch: DeviceBatch) -> list[tuple[int, int]]:
    """Per sort key: (word count incl. null word, pad word). Word counts
    depend on evaluated string widths, which are static per batch structure
    — jax.eval_shape gets them without compute. The pad word is what the
    kernel itself would emit for the missing trailing chars of a narrower
    width bucket (0 for ascending, ~0 for descending), letting the spill
    merge align runs whose strings landed in different buckets."""
    ectx = EvalContext()
    shapes = jax.eval_shape(
        lambda b: tuple(evaluate(s.expr, b, in_schema, ectx).col
                        for s in sort_exprs), batch)
    layout = []
    for s, col in zip(sort_exprs, shapes):
        if isinstance(col, StringColumn):
            n_value_words = (col.chars.shape[1] + 7) // 8
        else:
            n_value_words = 1
        pad = 0 if s.ascending else (1 << 64) - 1
        layout.append((1 + n_value_words, pad))
    return layout


@program_cache("ops.sort.sort_with_words", maxsize=256)
def _sort_with_words_kernel(sort_exprs: tuple, in_schema: Schema,
                            capacity: int, donate: bool):
    """Sorted batch + its order-word matrix [capacity, W] — the words ride
    into the spill so the host k-way merge (memmgr.merge) compares exactly
    what the device sorted."""

    def kernel(batch: DeviceBatch):
        ctx = EvalContext()
        key_cols = [evaluate(s.expr, batch, in_schema, ctx).col
                    for s in sort_exprs]
        orders = [(s.ascending, s.nulls_first) for s in sort_exprs]
        perm = sort_permutation(batch, key_cols, orders)
        words = jnp.stack(sort_key_words(key_cols, orders), axis=1)
        return gather_batch(batch, perm, batch.num_rows), words[perm]

    # graft: donation-ok -- _sort_donate gate (owned batches only);
    # the k-way merge consumes each gathered run exactly once
    return programs.jit(kernel, donate_argnums=(0,) if donate else ())


def _concat_all(batches: list[DeviceBatch]) -> DeviceBatch:
    """Concatenate buffered batches into one capacity-bucketed batch."""
    total_cap = bucket_rows(sum(b.capacity for b in batches))
    cols = []
    ncols = batches[0].num_columns
    for i in range(ncols):
        parts = unify_column_widths([b.columns[i] for b in batches])
        merged = parts[0]
        for p in parts[1:]:
            merged = concat_columns(merged, p)
        cols.append(merged)
    stacked_cap = sum(b.capacity for b in batches)
    from auron_tpu.columnar.batch import compact, resize
    live = jnp.concatenate([b.row_mask() for b in batches])
    num = sum(int(b.num_rows) for b in batches)
    stacked = DeviceBatch(tuple(cols), jnp.asarray(stacked_cap, jnp.int32))
    compacted = compact(stacked, live)
    out = resize(compacted, total_cap) if total_cap >= stacked_cap else compacted
    return DeviceBatch(out.columns, jnp.asarray(num, jnp.int32))


class _SortSpillConsumer(BufferedSpillConsumer):
    """Per-execution buffering state registered with the memory manager
    (the MemConsumer role SortExec plays in the reference,
    sort_exec.rs:375). A spill sorts the buffer into one run and writes it
    with its order words so the host k-way merge compares exactly what the
    device sorted."""

    def __init__(self, op: "SortOp", in_schema: Schema, mem_manager,
                 metrics, frame_rows: Optional[int] = None, conf=None):
        from auron_tpu import config as cfg
        conf = conf or cfg.get_config()
        self.op = op
        self.in_schema = in_schema
        super().__init__(f"sort-{id(op):x}", mem_manager, metrics, conf,
                         frame_rows=frame_rows)

    def _write_run(self, spill, batches: list[DeviceBatch]) -> None:
        import numpy as np
        from auron_tpu.columnar.serde import (batch_to_host,
                                              serialize_host_batch,
                                              slice_host_batch)
        from auron_tpu.memmgr.merge import (ORDER_WORDS_EXTRA,
                                            WORD_LAYOUT_EXTRA)
        merged = _concat_all(batches) if len(batches) > 1 else batches[0]
        layout = np.asarray(
            key_word_layout(self.op.sort_exprs, self.in_schema, merged),
            dtype=np.uint64)
        kern = _sort_with_words_kernel(self.op.sort_exprs, self.in_schema,
                                       merged.capacity,
                                       _sort_donate(batches, self.op.child))
        run, words = kern(merged)
        # the sort-collect spill's semantic sync point: under pipelined
        # execution this readback carries the device wait (booked as
        # device when a timer frame is open, obs/profile.timed_get)
        from auron_tpu.obs import profile as _profile
        n = int(_profile.timed_get(run.num_rows))
        host = batch_to_host(run, n)
        host_words = np.asarray(words[:n])
        for lo in range(0, max(n, 1), self.frame_rows):
            hi = min(lo + self.frame_rows, n)
            spill.write_frame(serialize_host_batch(
                slice_host_batch(host, lo, hi),
                extras={ORDER_WORDS_EXTRA: host_words[lo:hi],
                        WORD_LAYOUT_EXTRA: layout},
                codec_level=self.codec_level))


class SortOp(PhysicalOp):
    name = "sort"

    def __init__(self, child: PhysicalOp, sort_exprs: list[ir.SortOrder],
                 fetch: Optional[int] = None):
        self.child = child
        self.sort_exprs = tuple(sort_exprs)
        self.fetch = fetch

    @property
    def children(self):
        return [self.child]

    def schema(self) -> Schema:
        return self.child.schema()

    def _limit(self, stream):
        remaining = self.fetch
        for out in stream:
            if remaining is None:
                yield out
                continue
            if remaining <= 0:
                return
            n = int(out.num_rows)
            if n > remaining:
                out = DeviceBatch(out.columns, jnp.asarray(remaining, jnp.int32))
            remaining -= n
            yield out
            if remaining <= 0:
                return

    def execute(self, partition: int, ctx: ExecContext) -> Iterator[DeviceBatch]:
        metrics = ctx.metrics_for(self)
        elapsed = metrics.counter("elapsed_compute")
        in_schema = self.child.schema()
        _sync = ctx.device_sync
        mem = ctx.mem_manager
        spillable = mem is not None and getattr(mem, "spill_manager", None) is not None

        def in_mem_stream(batches):
            if not batches:
                return
            donate = _sort_donate(batches, self.child)
            with timer(elapsed, sync=_sync) as t:
                merged = _concat_all(batches) if len(batches) > 1 else batches[0]
                kern = _sort_kernel(self.sort_exprs, in_schema,
                                    merged.capacity, donate)
                out = t.track(kern(merged))
            yield out

        def external_stream(consumer):
            """Runs on tiered storage + final host k-way merge."""
            from auron_tpu.columnar.serde import host_to_batch
            from auron_tpu.memmgr.merge import merge_sorted_runs
            if consumer.buffered:
                consumer.spill()   # final in-mem run joins the merge
            for host in merge_sorted_runs(
                    [s.frames() for s in consumer.spills]):
                # lifecycle poll per merged run batch: cancels land
                # mid-merge and the stall watchdog sees spill progress
                ctx.checkpoint("spill.merge")
                yield host_to_batch(host, bucket_rows(host.num_rows))

        def stream():
            if not spillable:
                collected = []
                for b in self.child.execute(partition, ctx):
                    ctx.checkpoint("sort.collect")   # cancel lands mid-collect too
                    collected.append(b)
                yield from self._limit(in_mem_stream(collected))
                return
            consumer = _SortSpillConsumer(self, in_schema, mem, metrics,
                                          conf=ctx.conf)
            try:
                for batch in self.child.execute(partition, ctx):
                    ctx.checkpoint("sort.collect")
                    consumer.add(batch)
                # claim the buffer FIRST (take_buffered) so a concurrent
                # victim spill can't serialize batches the in-mem sort
                # may have donated to XLA; wait out any in-flight spill
                # so the (buffer, spills) view below is consistent — an
                # unpublished run would otherwise vanish silently
                taken = consumer.take_buffered()
                consumer.wait_spills_published()
                if not consumer.spills:
                    yield from self._limit(in_mem_stream(taken))
                else:
                    # a victim spill raced in: hand the claimed batches
                    # back so external_stream's final spill includes them
                    for b in taken:
                        consumer.add(b)
                    yield from self._limit(external_stream(consumer))
            finally:
                consumer.close()

        return count_output(stream(), metrics)

    def __repr__(self):
        return f"SortOp[{len(self.sort_exprs)} keys, fetch={self.fetch}]"
