"""Frame checksums for the durable tiers (RSS map outputs, spill files).

The reference inherits shuffle integrity from Spark's shuffle layer
(frame CRCs on the block store path); here the durable tiers carry their
own: every frame written to shared storage or a spill file is followed
by a 32-bit checksum, and every fetch verifies before deserializing —
a flipped byte surfaces as a classified corruption error (lineage
recompute), never as silently wrong rows.

Algorithm: CRC32C (Castagnoli — hardware-accelerated on every modern
ISA) when a native ``crc32c`` module is present in the image; otherwise
zlib's CRC-32 (also C-speed, always available). The algorithm id is
recorded in each file's header/trailer, so readers verify with the
writer's algorithm and *reject* frames whose algorithm they cannot
compute instead of misreading them. No dependency is installed for
this: the module gates on what the image provides.
"""

from __future__ import annotations

import struct
import zlib

#: per-frame record header shared by both durable tiers (RSS map
#: outputs, spill files): <I frame_len><I frame_crc>
FRAME_HDR = struct.Struct("<II")

#: algorithm ids recorded on disk (one byte)
ALGO_NONE = 0     # checksumming disabled (auron.durability.checksum=false)
ALGO_CRC32C = 1   # Castagnoli, native module
ALGO_CRC32 = 2    # zlib crc32 fallback

#: hardware CRC32C, whichever provider the image bakes in (both compute
#: the same Castagnoli polynomial, so files interoperate): the
#: standalone ``crc32c`` module, or google's ``google_crc32c`` (the C
#: implementation runs the SSE4.2/ARMv8 CRC instructions — measured
#: ~15 GiB/s on cache-warm 256 KiB frames vs ~0.4 GiB/s for this
#: image's un-SIMD'd zlib).
_crc32c_fn = None
try:
    import crc32c as _crc32c_mod
    _crc32c_fn = _crc32c_mod.crc32c
except ImportError:
    try:
        import google_crc32c as _gcrc32c_mod
        _crc32c_fn = _gcrc32c_mod.value
    except ImportError:
        pass


def preferred_algo() -> int:
    """The algorithm new files are written with (checksumming on)."""
    return ALGO_CRC32C if _crc32c_fn is not None else ALGO_CRC32


def write_algo() -> int:
    """Checksum algorithm for new durable-tier files: the preferred
    algorithm, or ALGO_NONE when the ``auron.durability.checksum`` knob
    is off (same on-disk format, no verification). The single
    knob-to-algorithm mapping for BOTH tiers — shuffle and spill must
    not diverge."""
    from auron_tpu import config as cfg
    if cfg.get_config().get(cfg.DURABILITY_CHECKSUM):
        return preferred_algo()
    return ALGO_NONE


def compute(data: bytes, algo: int) -> int:
    """Checksum ``data`` under ``algo``; 0 for ALGO_NONE."""
    if algo == ALGO_NONE:
        return 0
    if algo == ALGO_CRC32C:
        if _crc32c_fn is None:
            raise UnsupportedChecksum(
                "frame was written with CRC32C but no crc32c module is "
                "available in this environment")
        return _crc32c_fn(data) & 0xFFFFFFFF
    if algo == ALGO_CRC32:
        return zlib.crc32(data) & 0xFFFFFFFF
    raise UnsupportedChecksum(f"unknown checksum algorithm id {algo}")


def verify(data: bytes, expected: int, algo: int) -> bool:
    """True when ``data`` matches ``expected`` under ``algo`` (always
    True for ALGO_NONE — verification disabled)."""
    if algo == ALGO_NONE:
        return True
    return compute(data, algo) == expected


class UnsupportedChecksum(Exception):
    """Reader cannot compute the writer's algorithm (or the algo byte is
    unknown) — callers convert this into their tier's corruption error
    so the frame is rejected, not misread."""


def verify_or_raise(data: bytes, expected: int, algo: int, make_err,
                    what: str = "frame") -> None:
    """Verify ``data`` or raise the tier's corruption error.

    ``make_err(msg)`` builds the tier-specific corruption exception
    (ShuffleCorruption / SpillCorruption); an unsupported algorithm is
    converted through it too, so unverifiable frames are rejected with
    the same classified error as mismatching ones."""
    try:
        ok = verify(data, expected, algo)
    except UnsupportedChecksum as e:
        raise make_err(str(e)) from e
    if not ok:
        raise make_err(f"{what} checksum mismatch")
