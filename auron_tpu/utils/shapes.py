"""Static-shape bucketing.

XLA traces/compiles one HLO module per distinct input shape. To bound
recompilation we round every dynamic extent (row counts, string widths, hash
table sizes) up to a small set of buckets; the true extent rides along as a
device scalar and kernels mask the padding.

This mirrors what the reference never had to do — its Rust engine handled
dynamic batch sizes natively (reference: native-engine/datafusion-ext-commons/
src/lib.rs batch_size()) — and is the central trick that makes a columnar SQL
engine compile onto a static-shape compiler.
"""

from __future__ import annotations

DEFAULT_BATCH_CAPACITY = 8192

# Width buckets for fixed-width device string columns (bytes per slot).
STRING_WIDTH_BUCKETS = (8, 16, 32, 64, 128, 256)


def next_pow2(n: int) -> int:
    if n <= 1:
        return 1
    return 1 << (n - 1).bit_length()


def bucket_rows(n: int, minimum: int = 16) -> int:
    """Round a row count up to a power of two (>= minimum)."""
    return max(minimum, next_pow2(n))


def bucket_string_width(max_len: int) -> int:
    """Round a max string byte-length up to a width bucket."""
    for w in STRING_WIDTH_BUCKETS:
        if max_len <= w:
            return w
    # Degenerate long strings: round to next multiple of 256.
    return ((max_len + 255) // 256) * 256
