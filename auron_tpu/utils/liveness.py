"""Process-liveness tags for the durable tiers' orphan sweeps.

A crashed (SIGKILLed, OOM-killed, preempted) engine process leaves
artifacts on shared storage — uncommitted RSS map attempts, disk spill
files, in-flight query journals — that no in-process cleanup path can
ever reclaim: the cleanup code died with the process.  The startup
sweeps (``FileShuffleService``, ``SpillManager``, ``runtime/journal``)
reclaim them instead, and this module is their ownership oracle.

An owner tag is ``host:pid:epoch``.  ``epoch`` is the owning process's
start time in kernel clock ticks (``/proc/<pid>/stat`` field 22), which
makes the verdict robust against pid recycling: a new process that
happens to reuse a dead writer's pid has a different start time, so the
dead writer's artifacts still sweep.  Where ``/proc`` is unavailable
the epoch is 0 and the check degrades to pid-existence (the
conservative direction: a recycled pid reads as live and the artifact
is merely kept one sweep longer).

Sweeps are HOST-SCOPED by the tag's host field: on a shared-storage RSS
root another host's live writer must never read as dead just because
its pid means nothing here.
"""

from __future__ import annotations

import os
import socket
from typing import Optional, Tuple

_HOST = socket.gethostname()


def process_epoch(pid: int) -> int:
    """Start time of ``pid`` in kernel ticks; 0 when unknowable (no
    /proc, or the process is gone)."""
    try:
        with open(f"/proc/{pid}/stat", "rb") as f:
            stat = f.read().decode("ascii", "replace")
        # comm (field 2) may contain spaces/parens: parse after the
        # LAST ')'; starttime is field 22 overall = index 19 of the
        # post-paren fields (state is field 3)
        rest = stat.rsplit(")", 1)[1].split()
        return int(rest[19])
    except (OSError, IndexError, ValueError):
        return 0


#: (pid, tag) memo — the process's own epoch is immutable, and own_tag
#: sits on per-submission screens and per-artifact stamps; keyed by pid
#: so a fork() child re-derives its own
_OWN_TAG: Tuple[Optional[int], str] = (None, "")


def own_tag() -> str:
    """This process's owner tag (``host:pid:epoch``)."""
    global _OWN_TAG
    pid = os.getpid()
    if _OWN_TAG[0] != pid:
        _OWN_TAG = (pid, f"{_HOST}:{pid}:{process_epoch(pid)}")
    return _OWN_TAG[1]


def parse_tag(tag: str) -> Optional[Tuple[str, int, int]]:
    """``(host, pid, epoch)`` of a tag, or None when malformed."""
    try:
        host, pid_s, epoch_s = tag.strip().rsplit(":", 2)
        return host, int(pid_s), int(epoch_s)
    except ValueError:
        return None


def pid_alive(pid: int) -> bool:
    """Does a process with this pid exist on THIS host right now?"""
    if pid <= 0:
        return False
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:   # exists, owned by someone else
        return True
    except OSError:
        return True           # unknowable: conservative = alive
    return True


def note_swept(counter: str, removed: int, directory: str,
               what: str) -> None:
    """Shared emission half of the startup orphan sweeps (spill / RSS /
    journal tiers): one warning line + the tier's
    ``auron_*_orphans_swept_total`` registry counter. One definition so
    the three sweeps' observability cannot drift."""
    if not removed:
        return
    import logging
    logging.getLogger("auron_tpu").warning(
        "%s startup sweep removed %d orphaned artifact(s) of dead "
        "writers under %s", what, removed, directory)
    try:
        from auron_tpu.obs import registry as obs_registry
        if obs_registry.enabled():
            obs_registry.get_registry().counter(counter).add(removed)
    except Exception:   # pragma: no cover - telemetry best-effort
        pass


def owner_dead(pid: int, epoch: int) -> bool:
    """Provably-dead verdict for a SAME-HOST ``(pid, epoch)`` owner —
    the one shared core of the spill/RSS/journal sweeps (host scoping
    is the caller's: tag-host vs hash-digest formats differ per tier).
    False for this very process and for a live pid whose epoch matches
    or cannot be compared; True only when the pid is gone or its start
    time proves the pid was recycled."""
    if pid == os.getpid():
        return False
    if not pid_alive(pid):
        return True
    if epoch:
        live_epoch = process_epoch(pid)
        if live_epoch and live_epoch != epoch:
            return True   # recycled pid: the recorded owner is dead
    return False


def is_live(tag: str) -> bool:
    """Is the tag's owning process still running?

    Returns True (= do NOT sweep) for: this very process, a live pid
    whose epoch matches (or whose epoch cannot be compared), another
    host's tag (their sweep, not ours), and malformed tags.  Returns
    False only for a provably dead same-host owner."""
    parsed = parse_tag(tag)
    if parsed is None:
        return True
    host, pid, epoch = parsed
    if host != _HOST:
        return True
    return not owner_dead(pid, epoch)
