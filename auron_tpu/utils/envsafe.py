"""Safe child-process environments for platform-sensitive re-execs.

The driver environment may carry a sitecustomize on PYTHONPATH that
re-registers an accelerator PJRT plugin at interpreter start and forces
jax's platform selection back to the accelerator — overriding any
``JAX_PLATFORMS`` env var a child was given (observed: round-2 multichip
gate, MULTICHIP_r02.json rc=124, hung in ``make_c_api_client`` against a
wedged TPU client). Subprocesses that must be immune to the ambient
accelerator state build their env here.

Reference analogue: the reference's native tests run "without a JVM" by
branching on ``is_jni_bridge_inited()`` (reference:
native-engine/auron-memmgr/src/spill.rs:78-87); here the equivalent of
"without the JVM" is "without the accelerator plugin".
"""

from __future__ import annotations

import os


def watchdogged_child_code(body: str, parent_timeout_s: int,
                           margin_s: int = 30) -> tuple[str, int]:
    """Wrap python ``-c`` code with a faulthandler watchdog.

    The watchdog thread fires even when the main thread is stuck inside
    native code (e.g. a wedged PJRT client init), printing every stack to
    stderr and hard-exiting — so a hang becomes a fast diagnosable failure
    instead of an opaque parent-side SIGKILL. Returns ``(code,
    watchdog_s)`` where the watchdog fires ``margin_s`` BEFORE the
    parent's ``parent_timeout_s`` so the stack dump always wins the race
    against the parent's kill.
    """
    watchdog_s = max(parent_timeout_s - margin_s, 5)
    code = (
        "import faulthandler\n"
        f"faulthandler.dump_traceback_later({watchdog_s}, exit=True)\n"
        f"{body}\n"
        "faulthandler.cancel_dump_traceback_later()\n"
    )
    return code, watchdog_s


def strip_sitecustomize_entries(pythonpath: str, relative_base: str) -> list[str]:
    """Drop PYTHONPATH entries that carry an interpreter-startup hook.

    Any entry with a ``sitecustomize.py``/``usercustomize.py`` runs
    arbitrary code before env pinning can matter, so such entries are
    dropped wholesale. Relative entries are probed against
    ``relative_base`` (the child's cwd), not the parent's cwd.
    """
    keep = []
    for entry in pythonpath.split(os.pathsep):
        if not entry:
            continue
        probe_base = entry if os.path.isabs(entry) else os.path.join(
            relative_base, entry)
        if any(os.path.exists(os.path.join(probe_base, hook))
               for hook in ("sitecustomize.py", "usercustomize.py")):
            continue
        keep.append(entry)
    return keep


def cpu_child_env(child_cwd: str, n_devices: int | None = None) -> dict:
    """A copy of os.environ pinned to the CPU platform with every route by
    which an accelerator plugin could re-register stripped."""
    env = dict(os.environ)

    keep = strip_sitecustomize_entries(env.get("PYTHONPATH", ""), child_cwd)
    if keep:
        env["PYTHONPATH"] = os.pathsep.join(keep)
    else:
        env.pop("PYTHONPATH", None)

    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    if n_devices is not None:
        flags.append(f"--xla_force_host_platform_device_count={n_devices}")
    if flags:
        env["XLA_FLAGS"] = " ".join(flags)
    else:
        env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    # belt-and-braces: these only matter if a plugin still registers, but
    # they must not steer initialization at an accelerator
    for var in ("JAX_PLATFORM_NAME", "PJRT_DEVICE"):
        env.pop(var, None)
    return env
