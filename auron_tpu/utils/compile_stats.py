"""Process-wide XLA compile accounting (round-5 directive 7).

The reference pays plan-build per task but never kernel-compile per query
(DataFusion's physical operators are interpreted, planner.rs:121-856); on
this engine every jitted kernel is an XLA program, so compile latency is
a first-class perf axis — on a real TPU a single program build costs
seconds over the tunnel. This module hooks ``jax.monitoring``'s
``backend_compile_duration`` event (fired on every real backend compile,
including shape-driven recompiles that python-level kernel caches cannot
see) and exposes cheap snapshots so the executor and the TPC-DS runner
can attribute compiles and compile-seconds per task / per query.

A healthy steady state compiles ~0 new programs: kernels are cached by
(exprs, schema, bucketed capacity), so re-running a query suite in one
process should be all cache hits — ``delta()`` makes that measurable.
"""

from __future__ import annotations

import threading
from typing import NamedTuple

_LOCK = threading.Lock()
_N = {"count": 0}
_S = {"seconds": 0.0}
_INSTALLED = False

_EVENT = "/jax/core/compile/backend_compile_duration"


class CompileSnapshot(NamedTuple):
    count: int
    seconds: float


def install() -> None:
    """Register the monitoring listener once per process (idempotent)."""
    global _INSTALLED
    with _LOCK:
        if _INSTALLED:
            return
        import jax.monitoring as mon

        def _listen(name: str, dur: float, **_kw) -> None:
            if name == _EVENT:
                with _LOCK:
                    _N["count"] += 1
                    _S["seconds"] += dur

        mon.register_event_duration_secs_listener(_listen)
        _INSTALLED = True


def snapshot() -> CompileSnapshot:
    install()
    with _LOCK:
        return CompileSnapshot(_N["count"], _S["seconds"])


def delta(since: CompileSnapshot) -> CompileSnapshot:
    now = snapshot()
    return CompileSnapshot(now.count - since.count,
                           now.seconds - since.seconds)
