"""Process-wide XLA compile accounting (round-5 directive 7).

The reference pays plan-build per task but never kernel-compile per query
(DataFusion's physical operators are interpreted, planner.rs:121-856); on
this engine every jitted kernel is an XLA program, so compile latency is
a first-class perf axis — on a real TPU a single program build costs
seconds over the tunnel. This module hooks ``jax.monitoring``'s
``backend_compile_duration`` event (fired on every real backend compile,
including shape-driven recompiles that python-level kernel caches cannot
see) and exposes cheap snapshots so the executor and the TPC-DS runner
can attribute compiles and compile-seconds per task / per query.

A healthy steady state compiles ~0 new programs: kernels are cached by
(exprs, schema, bucketed capacity), so re-running a query suite in one
process should be all cache hits — ``delta()`` makes that measurable.
"""

from __future__ import annotations

import threading
from typing import NamedTuple

_LOCK = threading.Lock()
_N = {"count": 0}
_S = {"seconds": 0.0}
_INSTALLED = False

_EVENT = "/jax/core/compile/backend_compile_duration"


class CompileSnapshot(NamedTuple):
    count: int
    seconds: float


def install() -> None:
    """Register the monitoring listener once per process (idempotent)."""
    global _INSTALLED
    with _LOCK:
        if _INSTALLED:
            return
        import jax.monitoring as mon

        def _listen(name: str, dur: float, **_kw) -> None:
            if name == _EVENT:
                with _LOCK:
                    _N["count"] += 1
                    _S["seconds"] += dur
                    _SINCE_CLEAR["count"] += 1

        mon.register_event_duration_secs_listener(_listen)
        _INSTALLED = True


def snapshot() -> CompileSnapshot:
    install()
    with _LOCK:
        return CompileSnapshot(_N["count"], _S["seconds"])


def delta(since: CompileSnapshot) -> CompileSnapshot:
    now = snapshot()
    return CompileSnapshot(now.count - since.count,
                           now.seconds - since.seconds)


#: programs compiled since the last cache clear (distinct from the
#: monotonic totals above)
_SINCE_CLEAR = {"count": 0}

#: default ceiling on live compiled programs per process. The XLA CPU
#: backend's JIT has been observed to SEGFAULT inside backend_compile
#: after ~500-700 programs accumulate in one long-lived process (1-CPU
#: container, jax 0.8 era) — long before any visible memory pressure.
#: Clearing jax's compilation caches trades bounded recompiles for
#: survival; kernels rebuild lazily from the engine's own builder caches.
DEFAULT_MAX_LIVE_PROGRAMS = 400


#: process-lifetime count of cache clears (observability for the
#: suite runners' compile-budget note)
_CLEARS = {"count": 0}


def clears() -> int:
    with _LOCK:
        return _CLEARS["count"]


def maybe_clear(limit: int | None = None) -> bool:
    """Clear jax's compilation caches when more than ``limit`` programs
    were built since the last clear, OR when the central program-cache
    registry (runtime/programs.py) holds that many live builder entries —
    raw backend compiles miss programs restored from the persistent XLA
    cache, and the registry's python-side memos would otherwise pin
    kernel closures past the ceiling. Both halves clear together so the
    documented ``auron.max_live_programs`` semantics hold at every
    compile site. Returns True when a clear happened. Call between
    tasks / test modules — never mid-kernel."""
    install()   # counting must be live for the ceiling to mean anything
    if limit is None:
        # single binding through the typed config layer (session override
        # > AURON_CONF_MAX_LIVE_PROGRAMS env > default — the documented
        # precedence); a malformed value raises there, loudly
        from auron_tpu import config as cfg
        limit = cfg.get_config().get(cfg.MAX_LIVE_PROGRAMS)
    if limit <= 0:
        return False
    from auron_tpu.runtime import programs
    with _LOCK:
        due = _SINCE_CLEAR["count"] >= limit
        if due:
            _SINCE_CLEAR["count"] = 0
    if not due and programs.total_live() >= limit:
        due = True
        with _LOCK:
            _SINCE_CLEAR["count"] = 0
    if not due:
        return False
    import jax
    jax.clear_caches()
    programs.clear_all()
    with _LOCK:
        _CLEARS["count"] += 1
    return True
