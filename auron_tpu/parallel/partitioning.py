"""Output partitioners.

Spark-exact row→partition assignment (reference: datafusion-ext-plans/src/
shuffle/mod.rs:111-279): hash (murmur3 seed 42, pmod), round-robin, range
(binary search over sampled bounds), single. Producing the partition-id
column is a device kernel; what happens with it (host split vs ICI
all-to-all) is the exchange's business.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from auron_tpu.columnar.batch import DeviceBatch, StringColumn
from auron_tpu.columnar.schema import Schema
from auron_tpu.exprs import ir
from auron_tpu.exprs.eval import EvalContext, evaluate
from auron_tpu.ops import hashing


@dataclass(frozen=True)
class HashPartitioning:
    exprs: tuple
    num_partitions: int

    def partition_ids(self, batch: DeviceBatch, schema: Schema) -> jax.Array:
        ctx = EvalContext()
        cols = [evaluate(e, batch, schema, ctx).col for e in self.exprs]
        h = hashing.murmur3_columns(cols, batch.capacity,
                                    hashing.SPARK_SHUFFLE_SEED)
        n = jnp.int32(self.num_partitions)
        return ((h % n) + n) % n  # pmod: Spark keeps sign-safe modulo


@dataclass(frozen=True)
class RoundRobinPartitioning:
    num_partitions: int
    start: int = 0

    def partition_ids(self, batch: DeviceBatch, schema: Schema) -> jax.Array:
        idx = jnp.arange(batch.capacity, dtype=jnp.int32) + self.start
        return idx % jnp.int32(self.num_partitions)


@dataclass(frozen=True)
class SinglePartitioning:
    num_partitions: int = 1

    def partition_ids(self, batch: DeviceBatch, schema: Schema) -> jax.Array:
        return jnp.zeros(batch.capacity, jnp.int32)


@dataclass(frozen=True)
class RangePartitioning:
    """Range partitioning over sampled bounds. ``bounds`` is a host-side
    tuple of row tuples (one per boundary) computed by sampling the input —
    the reference samples on the JVM side too (reference:
    NativeShuffleExchangeBase.scala:313+)."""

    sort_orders: tuple     # tuple[ir.SortOrder]
    num_partitions: int
    bounds: tuple          # tuple of key tuples, len == num_partitions - 1

    def partition_ids(self, batch: DeviceBatch, schema: Schema) -> jax.Array:
        from auron_tpu.ops.sort import order_words
        ctx = EvalContext()
        cap = batch.capacity
        if not self.bounds:
            return jnp.zeros(cap, jnp.int32)

        # Normalize both rows and bounds into uint64 word tuples, then
        # lexicographic searchsorted implemented as vectorized compares
        # against each bound (num_partitions is small).
        row_words = []
        for so, key_idx in zip(self.sort_orders, range(len(self.sort_orders))):
            col = evaluate(so.expr, batch, schema, ctx).col
            null_word = jnp.where(col.validity,
                                  jnp.uint64(1 if so.nulls_first else 0),
                                  jnp.uint64(0 if so.nulls_first else 1))
            words = [jnp.where(col.validity, w, 0)
                     for w in order_words(col, so.ascending, so.nulls_first)]
            row_words.append(null_word)
            row_words.extend(words)

        pid = jnp.zeros(cap, jnp.int32)
        for bound in self.bounds:
            # bound is already normalized to matching uint64 words
            ge = jnp.zeros(cap, bool)
            eq = jnp.ones(cap, bool)
            for w, bw in zip(row_words, bound):
                bw = jnp.uint64(bw)
                ge = ge | (eq & (w > bw))
                eq = eq & (w == bw)
            pid = pid + (ge | eq).astype(jnp.int32)
        return jnp.minimum(pid, self.num_partitions - 1)


def compute_range_bounds(sample_batches, sort_orders, schema: Schema,
                         num_partitions: int) -> tuple:
    """Host-side bound computation from sampled batches: normalize sample
    keys to uint64 words, sort lexicographically, take evenly spaced
    boundaries. Returns tuple of word tuples aligned with
    RangePartitioning.partition_ids."""
    from auron_tpu.ops.sort import order_words
    ctx = EvalContext()
    rows = []
    for batch in sample_batches:
        words_cols = []
        for so in sort_orders:
            col = evaluate(so.expr, batch, schema, ctx).col
            null_word = jnp.where(col.validity,
                                  jnp.uint64(1 if so.nulls_first else 0),
                                  jnp.uint64(0 if so.nulls_first else 1))
            words = [jnp.where(col.validity, w, 0)
                     for w in order_words(col, so.ascending, so.nulls_first)]
            words_cols.append(np.asarray(null_word))
            words_cols.extend(np.asarray(w) for w in words)
        n = int(batch.num_rows)
        mat = np.stack(words_cols, axis=1)[:n]  # [n, n_words]
        rows.append(mat)
    if not rows:
        return ()
    allrows = np.concatenate(rows, axis=0)
    if allrows.shape[0] == 0:
        return ()
    # lexicographic sort by word tuple
    order = np.lexsort(tuple(allrows[:, i] for i in range(allrows.shape[1] - 1, -1, -1)))
    allrows = allrows[order]
    n = allrows.shape[0]
    bounds = []
    for k in range(1, num_partitions):
        idx = min(n - 1, (k * n) // num_partitions)
        bounds.append(tuple(int(x) for x in allrows[idx]))
    # dedupe equal bounds (degenerate distributions)
    out = []
    for b in bounds:
        if not out or b != out[-1]:
            out.append(b)
    return tuple(out)
