"""SPMD mesh plane: device layout, sharding specs, gang scheduling.

The north star names "the compacted shuffle running as ICI all-to-all on
a pod slice" (PAPER.md); this module is the layout half of that plane —
the part that knows WHICH devices exist, HOW a buffer lays out across
them, and WHO may occupy the mesh right now:

- ``current_plane()`` resolves the ``auron.mesh.*`` knobs into one
  process-wide :class:`MeshPlane` (the device set is process state, so
  the plane is process-global by contract, like
  ``auron.pipeline.enabled``). The plane survives unrelated config
  flips: it is rebuilt only when its OWN parameters change, because it
  owns live scheduling state (the gang lock below).
- Per-buffer replicate-vs-shard decisions (:func:`buffer_spec`, the
  SNIPPETS.md [2]/[3] pattern): scan batches and shuffle entries shard
  on the batch dim (``PartitionSpec(axis)``), broadcast relations and
  hash-table build sides replicate (``PartitionSpec()``) — operators
  declare their buffer kind via ``PhysicalOp.mesh_buffer_kind`` and the
  planner's ``annotate_mesh`` pass stamps the resolved spec on each
  node (``op.mesh_spec``).
- :func:`stack_global_batch` / :func:`local_shard` move between the
  engine's per-partition DeviceBatches and mesh-global sharded arrays
  (one shard per map partition / one shard per reducer device).
- :meth:`MeshPlane.gang` is the gang-scheduling door: a sharded stage
  occupies the WHOLE mesh, so one stage runs at a time (FIFO tickets,
  cancel-aware waits); the PR 9 scheduler's weighted-round-robin turn
  is taken on entry, so fairness operates BETWEEN sharded stages and
  never interleaves two inside the mesh.

Works identically on a virtual CPU mesh
(``--xla_force_host_platform_device_count``, the tier-1 environment)
and a real TPU slice.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Optional

import numpy as np

logger = logging.getLogger("auron_tpu")

#: buffer-kind → layout decision (the replicate-vs-shard table). Kinds
#: are declared by operators (``mesh_buffer_kind``); anything undeclared
#: shards — replication is the exception (small, reused-by-every-shard
#: relations), sharding the rule (throughput scales with devices).
_BUFFER_SPECS = {
    "broadcast": "replicate",     # BroadcastExchangeOp collected batches
    "hash_build": "replicate",    # hash-join build side (probe shards)
    "scan_batch": "shard",        # file/memory scan output batches
    "shuffle_entry": "shard",     # exchange buffer entries
    "agg_partial": "shard",       # partial-agg state rows entering a shuffle
}


def buffer_spec(kind: Optional[str]) -> str:
    """'replicate' | 'shard' for a declared buffer kind (default shard)."""
    return _BUFFER_SPECS.get(kind or "", "shard")


def _token_raise(token) -> None:
    """Raise the token's classified error when it is set (QueryCancelled
    / DeadlineExceeded by reason; legacy TaskCancelled for bare Events)
    — the gang door's dequeue-without-starting check."""
    if token is None or not token.is_set():
        return
    raise_for = getattr(token, "raise_for_status", None)
    if raise_for is not None:
        raise_for()
    from auron_tpu.ops.base import TaskCancelled
    raise TaskCancelled("cancelled while queued for the mesh gang")


class MeshPlane:
    """One process's SPMD device layout + the sharded-stage gang door."""

    def __init__(self, devices, axis: str = "data"):
        self.devices = list(devices)
        self.axis = axis
        self._meshes: dict = {}
        # gang scheduling: FIFO ticket queue + condition. A sharded
        # stage holds the WHOLE mesh (one slot = the mesh); contenders
        # park here, woken by release, polling their cancel token so a
        # dead query never waits out a long stage.
        self._cond = threading.Condition()
        self._queue: deque = deque()
        self._holder: Optional[str] = None
        self._holder_thread: Optional[threading.Thread] = None
        #: slot-accounting counters (tests/test_scheduler.py pins these)
        self.gang_acquired = 0
        self.gang_contended = 0
        self.gang_wait_ns = 0
        # -- fault domain --------------------------------------------------
        #: quarantined device indices (into self.devices): chips a
        #: MeshUnavailable was attributed to. Submeshes rebuild from the
        #: remaining healthy devices; exchanges wider than the healthy
        #: set route host-side (exchange_route).
        self._quarantined: set = set()
        self._quarantine_epoch = 0
        #: demotion/straggler ledger (stats() + executor finalize "mesh")
        self.demotions: dict = {}
        self.stragglers = 0
        self.device_losses = 0
        #: rolling per-round duration window (straggler defense baseline)
        from auron_tpu.runtime.watchdog import MeshRoundStats
        self.round_stats = MeshRoundStats()

    @property
    def num_devices(self) -> int:
        return len(self.devices)

    # -- fault domain --------------------------------------------------------

    def healthy_devices(self) -> list:
        with self._cond:
            if not self._quarantined:
                return list(self.devices)
            return [d for i, d in enumerate(self.devices)
                    if i not in self._quarantined]

    @property
    def usable_width(self) -> int:
        """Devices still eligible for a submesh (total minus quarantine):
        the width exchange_route checks the square contract against."""
        with self._cond:
            return len(self.devices) - len(self._quarantined)

    def quarantine(self, device_index: Optional[int], reason: str) -> int:
        """Retire one device from every future submesh. ``device_index``
        None (XLA carried no device identity) retires the tail device of
        the current healthy set — deterministic, and shrinking the mesh
        by one either way (a wrongly blamed healthy chip costs capacity,
        never correctness). Returns the retired index."""
        with self._cond:
            if device_index is not None \
                    and device_index in self._quarantined:
                # a stale submesh (built pre-quarantine, e.g. a query
                # parked at the gang door) re-reporting the SAME dead
                # chip: already retired — blaming the tail here would
                # compound one real loss into one lost chip per
                # concurrent query
                return device_index
            healthy = [i for i in range(len(self.devices))
                       if i not in self._quarantined]
            if device_index is None or device_index not in healthy:
                device_index = healthy[-1] if healthy else 0
            self._quarantined.add(device_index)
            self._quarantine_epoch += 1
            self.device_losses += 1
            # submesh cache entries may include the dead device: drop
            # them all; mesh_for rebuilds from the healthy set
            self._meshes.clear()
        from auron_tpu.obs import trace
        trace.event("mesh", "mesh.quarantine", device=device_index,
                    reason=reason, usable=self.usable_width)
        logger.warning(
            "mesh fault domain: quarantined device %d (%s); %d/%d "
            "devices remain usable", device_index, reason,
            self.usable_width, self.num_devices)
        try:
            from auron_tpu.obs import registry as obs_registry
            if obs_registry.enabled():
                obs_registry.get_registry().counter(
                    "auron_mesh_quarantines_total").inc()
        except Exception:   # pragma: no cover - obs best-effort
            pass
        return device_index

    def quarantined(self) -> list:
        with self._cond:
            return sorted(self._quarantined)

    def clear_quarantine(self) -> None:
        """Re-admit every quarantined device (tests / operator reset
        after the hardware was actually serviced)."""
        with self._cond:
            if self._quarantined:
                self._quarantined.clear()
                self._quarantine_epoch += 1
                self._meshes.clear()

    def record_demotion(self, reason: str) -> None:
        with self._cond:
            self.demotions[reason] = self.demotions.get(reason, 0) + 1
        try:
            from auron_tpu.obs import registry as obs_registry
            if obs_registry.enabled():
                obs_registry.get_registry().counter(
                    "auron_mesh_demotions_total", reason=reason).inc()
        except Exception:   # pragma: no cover - obs best-effort
            pass

    def record_straggler(self) -> None:
        with self._cond:
            self.stragglers += 1
        try:
            from auron_tpu.obs import registry as obs_registry
            if obs_registry.enabled():
                obs_registry.get_registry().counter(
                    "auron_mesh_stragglers_total").inc()
        except Exception:   # pragma: no cover - obs best-effort
            pass

    def mesh_for(self, n: int):
        """The leading-n-HEALTHY-device submesh (cached per quarantine
        epoch): an exchange with n output partitions runs on exactly n
        devices — the all-to-all's square contract (one output
        partition per device). Quarantined devices never join a
        submesh."""
        from jax.sharding import Mesh
        with self._cond:
            epoch = self._quarantine_epoch
        key = (n, epoch)
        m = self._meshes.get(key)
        if m is None:
            healthy = self.healthy_devices()
            assert 1 <= n <= len(healthy), \
                f"submesh width {n} exceeds usable mesh ({len(healthy)})"
            m = Mesh(np.array(healthy[:n]), (self.axis,))
            self._meshes[key] = m
        return m

    # -- gang scheduling -----------------------------------------------------

    @contextmanager
    def gang(self, token=None, heartbeat=None):
        """Occupy the whole mesh for one sharded stage.

        Takes the PR 9 scheduler's weighted-round-robin turn first (when
        the token carries a slot), so WRR fairness decides the order in
        which queries' sharded stages reach the mesh — then serializes
        them FIFO: two sharded stages never interleave inside the mesh.
        A cancel/deadline landing while parked dequeues with the token's
        classified error, never holding (or waiting for) a dead stage.
        ``heartbeat`` (the task's stall-watchdog TaskHeartbeat) is
        beaten every poll tick while parked: waiting behind another
        query's long sharded stage is legitimate liveness, not a stall
        — the compile-credit precedent from the lifecycle plane."""
        # RE-ENTRANT per thread: a stage driving the mesh may pull a
        # child exchange that mesh-routes too (exchange above exchange);
        # the nested stage belongs to the same gang occupation, and a
        # second acquisition on this thread would deadlock against
        # itself.
        me = threading.current_thread()
        with self._cond:
            if self._holder_thread is me:
                reentrant = True
            else:
                reentrant = False
        if reentrant:
            yield self
            return
        from auron_tpu.runtime import faults as _faults
        from auron_tpu.runtime import scheduler as _scheduler
        _scheduler.turn(token)
        # the gang-door chaos site (mesh.gang:cancel): a cancel racing
        # the door itself — fired before AND while parked, so both the
        # uncontended fast path and a parked ticket prove the dequeue-
        # without-starting contract
        _faults.maybe_cancel("mesh.gang", token)
        ticket = object()
        qid = (getattr(token, "query_id", "") or "") if token is not None \
            else ""
        t0 = time.perf_counter_ns()
        contended = False
        with self._cond:
            self._queue.append(ticket)
            try:
                # a cancel that landed BEFORE the door (or the injected
                # one above) dequeues here — the round never starts
                _token_raise(token)
                while self._holder is not None \
                        or self._queue[0] is not ticket:
                    contended = True
                    if heartbeat is not None:
                        heartbeat.beat("mesh.gang")
                    self._cond.wait(0.05)
                    _faults.maybe_cancel("mesh.gang", token)
                    _token_raise(token)
            except BaseException:
                self._queue.remove(ticket)
                self._cond.notify_all()
                raise
            self._queue.popleft()
            self._holder = qid or "anonymous"
            self._holder_thread = me
            self.gang_acquired += 1
            if contended:
                self.gang_contended += 1
            wait_ns = time.perf_counter_ns() - t0
            self.gang_wait_ns += wait_ns
        from auron_tpu.obs import trace
        trace.event("mesh", "mesh.gang", query=qid,
                    wait_ms=round(wait_ns / 1e6, 3), contended=contended)
        try:
            yield self
        finally:
            with self._cond:
                self._holder = None
                self._holder_thread = None
                self._cond.notify_all()

    def gang_holder(self) -> Optional[str]:
        with self._cond:
            return self._holder

    def stats(self) -> dict:
        with self._cond:
            return {"devices": self.num_devices, "axis": self.axis,
                    "gang_acquired": self.gang_acquired,
                    "gang_contended": self.gang_contended,
                    "gang_wait_ms": round(self.gang_wait_ns / 1e6, 3),
                    "gang_holder": self._holder,
                    "gang_queued": len(self._queue),
                    "quarantined": sorted(self._quarantined),
                    "usable": (len(self.devices)
                               - len(self._quarantined)),
                    "demotions": dict(self.demotions),
                    "stragglers": self.stragglers,
                    "device_losses": self.device_losses}


#: (params, plane) — the plane persists across UNRELATED config flips
#: (it owns the live gang lock; rebuilding it mid-query would hand a
#: second sharded stage a fresh, free lock) and rebuilds only when its
#: own parameters (enabled/devices/axis) change
_PLANE_LOCK = threading.Lock()
_PLANE: tuple = (None, None)
_EPOCH: int = -1


def current_plane() -> Optional[MeshPlane]:
    """The process's MeshPlane, or None when ``auron.mesh.enabled`` is
    off or fewer than 2 devices are visible. Config-epoch cached: the
    armed hot path costs one int compare."""
    global _PLANE, _EPOCH
    from auron_tpu import config as cfg
    epoch = cfg.config_epoch()
    if epoch == _EPOCH:
        return _PLANE[1]
    conf = cfg.get_config()
    params = (bool(conf.get(cfg.MESH_ENABLED)),
              int(conf.get(cfg.MESH_DEVICES)),
              str(conf.get(cfg.MESH_AXIS)))
    with _PLANE_LOCK:
        if _PLANE[0] == params:
            _EPOCH = epoch
            return _PLANE[1]
        plane = None
        if params[0]:
            try:
                import jax
                devs = list(jax.devices())
            except Exception:   # backend init failure: no mesh
                devs = []
            limit = params[1] if params[1] > 0 else len(devs)
            devs = devs[:limit]
            multihost = False
            try:
                import jax as _jax
                multihost = _jax.process_count() > 1
            except Exception:
                pass
            # single-host only: the reducer read path slices addressable
            # shards; multihost deployments shuffle through the RSS tier
            # by construction (the durable fallback)
            if len(devs) >= 2 and not multihost:
                plane = MeshPlane(devs, axis=params[2])
        _PLANE = (params, plane)
        _EPOCH = epoch
        return plane


def reset_plane() -> None:
    """Drop the cached plane (tests)."""
    global _PLANE, _EPOCH
    with _PLANE_LOCK:
        _PLANE = (None, None)
        _EPOCH = -1


def clear_quarantine() -> None:
    """Re-admit quarantined devices on the cached plane regardless of
    the current ``auron.mesh.enabled`` value (test/chaos hygiene: a
    quarantine injected by one run must not silently reroute the
    next)."""
    plane = _PLANE[1]
    if plane is not None:
        plane.clear_quarantine()


# ---------------------------------------------------------------------------
# routing decision (the exchange's eligibility check, unit-testable pure)
# ---------------------------------------------------------------------------

def exchange_route(partitioning, num_partitions: int,
                   input_partitions: int,
                   plane: Optional[MeshPlane]) -> tuple[str, str]:
    """(route, reason) for one shuffle exchange: ``all_to_all`` when the
    source and sink stages can share the mesh, else ``device_buffer``
    (the host-orchestrated classic path). RSS exchanges are routed by
    construction (the durable/multihost tier) and never call this."""
    from auron_tpu.parallel.partitioning import HashPartitioning
    if plane is None:
        return "device_buffer", "mesh_disabled"
    if not isinstance(partitioning, HashPartitioning):
        return ("device_buffer",
                f"partitioning_{type(partitioning).__name__}")
    if num_partitions < 2:
        return "device_buffer", "single_output"
    # the square contract is checked against the HEALTHY width: after a
    # quarantine the plane rebuilds a smaller submesh while
    # 2 <= num_partitions <= usable still holds, and routes host-side
    # (with the reason telling you WHY) once it does not
    usable = getattr(plane, "usable_width", plane.num_devices)
    if num_partitions > usable:
        # blame the quarantine only when it is what actually broke the
        # square contract — an exchange wider than the FULL mesh never
        # had a mesh route to lose
        if usable < plane.num_devices \
                and num_partitions <= plane.num_devices:
            return ("device_buffer",
                    f"mesh_quarantined_{usable}<{num_partitions}")
        return ("device_buffer",
                f"mesh_too_narrow_{usable}<{num_partitions}")
    if input_partitions > num_partitions:
        return ("device_buffer",
                f"fan_in_exceeds_mesh_{input_partitions}>{num_partitions}")
    return "all_to_all", "mesh"


# ---------------------------------------------------------------------------
# layout helpers: per-partition batches <-> mesh-global sharded arrays
# ---------------------------------------------------------------------------

def replicate(tree, mesh):
    """Replicate every array leaf of ``tree`` across the mesh
    (``NamedSharding(mesh, P())`` — the SNIPPETS [2]/[3] pattern): the
    device_put half of the "replicate" spec for broadcast relations and
    hash-table build sides. NOT yet called on the execution hot path —
    today only the sharded EXCHANGE runs inside the mesh, and its
    programs close over nothing replicated; stage bodies that read a
    build side per shard (the fused-probe lowering, the HBM-tier item)
    are the consumers this helper exists for. Kept honest by a unit
    test asserting the fully-replicated layout."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    sharding = NamedSharding(mesh, P())
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(x, sharding), tree)


def stack_global_batch(batches: list, mesh, axis: str):
    """Stack one round's per-map-partition batches into mesh-global
    sharded arrays: shard i of every leaf is map partition i's rows.

    Returns ``(columns, num_rows, capacity)`` where ``columns`` is the
    DeviceBatch column tuple with every leaf ``[n_dev * capacity, ...]``
    sharded on the batch dim, and ``num_rows`` is ``int32[n_dev]`` (one
    live count per shard). Ragged inputs are normalized first — string
    widths / list element counts unified, capacities padded to the
    round's max — so every shard is shape-identical (the static-shape
    contract every mesh kernel compiles against)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from auron_tpu.columnar.batch import resize, unify_column_widths

    n_dev = len(batches)
    assert n_dev == mesh.shape[axis], \
        f"{n_dev} shards for a {mesh.shape[axis]}-device mesh"
    cap = max(b.capacity for b in batches)
    batches = [resize(b, cap) if b.capacity != cap else b
               for b in batches]
    cols = []
    for i in range(batches[0].num_columns):
        cols.append(unify_column_widths([b.columns[i] for b in batches]))
    sharding = NamedSharding(mesh, P(axis))
    global_cols = tuple(
        jax.tree_util.tree_map(
            lambda *ls: jax.device_put(jnp.concatenate(ls, axis=0),
                                       sharding),
            *unified)
        for unified in cols)
    # per-shard live counts WITHOUT a host readback (num_rows scalars
    # stay device-resident; the stack is one tiny transfer)
    num_rows = jax.device_put(
        jnp.stack([jnp.asarray(b.num_rows, jnp.int32) for b in batches]),
        sharding)
    return global_cols, num_rows, cap


def local_shard(arr, d: int, mesh):
    """Device ``d``'s addressable shard of a mesh-global array — the
    zero-copy per-device view the reducer read path slices (single-host;
    multihost reducers go through the RSS tier by construction)."""
    dev = mesh.devices.flat[d]
    # graft: disable=GL001 -- documented single-host reducer read path; multihost routes RSS by construction (ROADMAP scale-out)
    for s in arr.addressable_shards:
        if s.device == dev:
            return s.data
    raise ValueError(f"no addressable shard on device {dev}")
