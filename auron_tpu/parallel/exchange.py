"""Stage exchange (shuffle + broadcast).

The reference's exchange is file-based: BufferedData staging → ONE
per-partition-sorted compaction → spill file + offset index, fetched
through Spark's block store (reference:
datafusion-ext-plans/src/shuffle/buffered_data.rs:48-225,
sort_repartitioner.rs:44-254; SURVEY.md §3.3). This engine keeps that
exact shape at HBM granularity:

- the split is ONE stable sort-by-partition-id per input batch (not P
  compaction passes): rows land contiguous per target partition with a
  host-side offset index — buffered_data.rs's sorted compaction verbatim;
- sorted batches stay device-resident and are REGISTERED with the memory
  manager; under pressure they spill to host storage via the columnar
  serde, offsets riding along as a frame extra — the
  SortShuffleRepartitioner spill contract;
- a reducer partition reads its row range from each entry (device slice
  or host-restored), never touching other partitions' rows;
- range partitioning samples its bounds from the FIRST batches of the
  same materialization pass (no second execution of the child).

ShuffleExchangeOp is a stage boundary: the upstream subtree runs once per
*input* partition (all materialized on first demand, memoized),
downstream partitions then stream their buckets. In SPMD execution the
same sorted-compaction rides `lax.all_to_all`
(auron_tpu.parallel.mesh_exchange).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Iterator, Optional

import numpy as np

import jax
import jax.numpy as jnp

from auron_tpu.columnar.batch import DeviceBatch, gather_batch
from auron_tpu.columnar.schema import Schema
from auron_tpu.exprs.eval import EvalContext, evaluate
from auron_tpu.ops import hashing
from auron_tpu.ops.base import (ExecContext, PhysicalOp, count_output,
                                timer, yields_owned_batches)
from auron_tpu.parallel.partitioning import (HashPartitioning,
                                             RangePartitioning,
                                             RoundRobinPartitioning,
                                             SinglePartitioning)
from auron_tpu.runtime import programs
from auron_tpu.runtime.programs import program_cache
from auron_tpu.utils.shapes import bucket_rows

#: rows sampled for range bounds (reference samples client-side too,
#: NativeShuffleExchangeBase.scala:313+)
_RANGE_SAMPLE_ROWS = 10_000

logger = logging.getLogger("auron_tpu")


def _split_body(batch: DeviceBatch, pids, num_partitions: int):
    """Traced split body: stable sort rows by target partition id (dead
    rows to the end) + per-partition counts (reference:
    shuffle/buffered_data.rs:88-160)."""
    live = batch.row_mask()
    key = jnp.where(live, pids, num_partitions)
    perm = jnp.argsort(key, stable=True)
    sorted_batch = gather_batch(batch, perm, batch.num_rows)
    counts = jax.ops.segment_sum(
        live.astype(jnp.int32), jnp.clip(key, 0, num_partitions),
        num_segments=num_partitions + 1)[:num_partitions]
    return sorted_batch, counts


@program_cache("parallel.exchange.sort_by_pid", maxsize=256)
def _sort_by_pid_kernel(num_partitions: int, capacity: int, donate: bool):
    """ONE compaction for all partitions. ``donate`` hands the input
    batch's buffers to XLA (the un-sorted input is dead after the call —
    halves peak HBM for the split); callers pass it only for owned
    input streams on non-CPU backends (see yields_owned_batches)."""

    def kernel(batch: DeviceBatch, pids):
        return _split_body(batch, pids, num_partitions)

    # graft: donation-ok -- callers gate on owned input streams;
    # a task retry re-splits from source, never the donated array
    return programs.jit(kernel, donate_argnums=(0,) if donate else ())


#: fused split programs: the upstream fused-stage chain (when present),
#: the partition-id computation and the sort-by-pid compaction in ONE
#: XLA program — the whole-stage-fusion prologue of the exchange
_SPLIT_PROGRAMS = programs.register(
    programs.ProgramCache("parallel.exchange.fused_split", maxsize=256))


def _fused_split_program(frag_keys: tuple, part_sig: tuple,
                         in_schema: Schema, out_schema: Schema,
                         n_out: int, capacity: int, donate: bool,
                         fragments, part_exprs,
                         combine=None, combine_sig=None):
    """One program per (chain signature, partitioning, schema, capacity):
    runs the member fragments, computes partition ids on the chain
    output, and splits — intermediates never touch HBM. The carry vector
    is the members' carries plus one trailing slot counting rows seen at
    the split (the round-robin start offset, kept on device).

    ``combine`` (ops/agg.AggOp.build_combine_stage) is the map-side
    combine fold: the elided partial agg's per-batch combine (or
    state-layout passthrough) runs between the chain and the partition-id
    computation, so ``out_schema``/``part_exprs`` see the partial state
    layout and groups merge BEFORE the split. Stateless — no carries —
    and the program grows one extra output: the pre-combine live-row
    count, read by the caller in its existing counts fence (combine
    telemetry never adds a sync point). ``combine_sig`` keys the trace."""

    def build():
        from auron_tpu.ops.fused import thread_fragments
        n_frags = len(fragments)
        kind = part_sig[0]

        def kernel(batch: DeviceBatch, partition_id, carries):
            outs, new_carries = thread_fragments(fragments, batch,
                                                 partition_id, carries)
            (b,) = outs   # fan-out chains never take this path
            comb_in = None
            if combine is not None:
                b, comb_in = combine(b)
            if kind == "hash":
                ctx = EvalContext()
                cols = [evaluate(e, b, out_schema, ctx).col
                        for e in part_exprs]
                h = hashing.murmur3_columns(cols, b.capacity,
                                            hashing.SPARK_SHUFFLE_SEED)
                nn = jnp.int32(n_out)
                pids = ((h % nn) + nn) % nn
            elif kind == "round_robin":
                start = carries[n_frags].astype(jnp.int32)
                pids = (jnp.arange(b.capacity, dtype=jnp.int32) + start) \
                    % jnp.int32(n_out)
            else:   # single
                pids = jnp.zeros(b.capacity, jnp.int32)
            sorted_batch, counts = _split_body(b, pids, n_out)
            new_carries.append(carries[n_frags]
                               + jnp.asarray(b.num_rows, jnp.int64))
            if combine is not None:
                return sorted_batch, counts, jnp.stack(new_carries), comb_in
            return sorted_batch, counts, jnp.stack(new_carries)

        # graft: donation-ok -- host split path (the mesh exchange
        # keeps donation OFF by contract for its escalation re-run)
        return programs.jit(kernel,
                            donate_argnums=(0,) if donate else ())

    return _SPLIT_PROGRAMS.get_or_build(
        (frag_keys, part_sig, in_schema, n_out, capacity, donate,
         combine_sig), build)


def _split_signature(partitioning) -> Optional[tuple]:
    """Hashable partitioning signature for the fused split program, or
    None when the partitioning cannot fuse (range bounds are sampled
    host-side mid-stream)."""
    if isinstance(partitioning, HashPartitioning):
        return ("hash", partitioning.exprs)
    if isinstance(partitioning, RoundRobinPartitioning):
        return ("round_robin",)
    if isinstance(partitioning, SinglePartitioning):
        return ("single",)
    return None


def _record_route(op, metrics, route: str, reason: str, **attrs) -> None:
    """Record one exchange's routing decision (all_to_all vs
    device_buffer vs rss) on its metric set AND the 'mesh' trace
    category — the per-exchange table tools/mesh_report.py prints, and
    what the mesh battery asserts against (recorded, never inferred)."""
    metrics.counter("exchange_route_" + route).add(1)
    from auron_tpu.obs import trace
    trace.event("mesh", "exchange.route", op=repr(op), route=route,
                reason=reason, partitions=op.num_partitions,
                maps=getattr(op, "input_partitions", 1), **attrs)


class _ExchangeBuffer:
    """MemConsumer owning the sorted shuffle entries of one exchange.

    Each entry is one input batch sorted by partition id plus its host
    offset index. Device entries spill (oldest first) to tiered host
    storage via the columnar serde when the memory manager picks this
    consumer as a victim."""

    def __init__(self, op, mem_manager, metrics, conf=None):
        from auron_tpu import config as cfg
        conf = conf or cfg.get_config()
        self.op = op
        self.mem = mem_manager
        self.metrics = metrics
        self.codec_level = conf.get(cfg.SPILL_CODEC_LEVEL)
        self.consumer_name = f"exchange-{id(op):x}"
        #: entry = ["dev", DeviceBatch, offsets] | ["dev-spilling", ...] |
        #: ["spill", SpillRef, offsets, num_rows]
        self.entries: list = []
        self._dev_bytes = 0   # running counter, guarded by _lock
        self._lock = threading.RLock()
        if mem_manager is not None:
            mem_manager.register_consumer(self)

    # -- write side ---------------------------------------------------------

    def add(self, sorted_batch: DeviceBatch, offsets: np.ndarray) -> None:
        from auron_tpu.columnar.batch import batch_nbytes
        with self._lock:
            self.entries.append(["dev", sorted_batch, offsets])
            self._dev_bytes += batch_nbytes(sorted_batch)
            used = self._dev_bytes
        if self.mem is not None:
            self.mem.update_mem_used(self, used)

    def mem_used(self) -> int:
        with self._lock:
            return self._dev_bytes

    def spill(self) -> int:
        from auron_tpu.columnar.batch import batch_nbytes
        from auron_tpu.columnar.serde import (batch_to_host,
                                              serialize_host_batch,
                                              slice_host_batch)
        if self.mem is None or getattr(self.mem, "spill_manager", None) is None:
            return 0
        # claim victims under the lock (tag flip) so a concurrent spill()
        # can't serialize the same entries twice
        with self._lock:
            victims = [(i, e) for i, e in enumerate(self.entries)
                       if e[0] == "dev"]
            for _i, e in victims:
                e[0] = "dev-spilling"
            if not victims:
                return 0
        n_out = len(victims[0][1][2]) - 1
        freed = 0
        for i, e in victims:
            _tag, batch, offsets = e
            n = int(batch.num_rows)
            host = batch_to_host(batch, n)
            # ONE FRAME PER PARTITION (the reference's data file + offset
            # index, sort_repartitioner.rs:151+): a reducer later reads
            # only its own frame via Spill.frame_at — never
            # decompressing other partitions' rows
            spill = self.mem.spill_manager.new_spill()
            for p in range(n_out):
                part = slice_host_batch(host, int(offsets[p]),
                                        int(offsets[p + 1]))
                spill.write_frame(serialize_host_batch(
                    part, codec_level=self.codec_level))
            done = spill.finish()
            with self._lock:
                if i < len(self.entries) and self.entries[i] is e:
                    self.entries[i] = ["spill", done, offsets, n]
                    self._dev_bytes -= batch_nbytes(batch)
                    freed += batch_nbytes(batch)
                else:
                    # buffer was closed/cleared mid-spill
                    done.release()
        self.metrics.counter("mem_spill_count").add(len(victims))
        self.metrics.counter("mem_spill_size").add(freed)
        return freed

    # -- read side ----------------------------------------------------------

    def _entry_partition(self, e, p: int) -> Optional[DeviceBatch]:
        """Partition ``p``'s rows of ONE entry (device slice or restored
        host frame); None when the entry holds no rows for ``p``."""
        from auron_tpu.columnar.serde import (deserialize_host_batch,
                                              host_to_batch)
        offsets = e[2]
        lo, hi = int(offsets[p]), int(offsets[p + 1])
        n_p = hi - lo
        if n_p <= 0:
            return None
        if e[0].startswith("dev"):
            # "dev" or "dev-spilling": the device batch in this
            # snapshot's entry list stays valid even if a concurrent
            # spill swaps the entry afterwards
            batch = e[1]
            cap = bucket_rows(n_p)
            idx = jnp.minimum(lo + jnp.arange(cap, dtype=jnp.int32),
                              batch.capacity - 1)
            return gather_batch(batch, idx, jnp.asarray(n_p, jnp.int32))
        host, _extras = deserialize_host_batch(e[1].frame_at(p))
        return host_to_batch(host, bucket_rows(n_p))

    def partition_batches(self, p: int) -> Iterator[DeviceBatch]:
        with self._lock:
            entries = list(self.entries)
        for e in entries:
            out = self._entry_partition(e, p)
            if out is not None:
                yield out

    def entry_batches(self, p: int, indices) -> Iterator[DeviceBatch]:
        """Partition ``p``'s rows of the entries at ``indices`` only —
        the demoted read path's per-source slice (a spill swaps entries
        IN PLACE, so indices stay stable across pressure)."""
        with self._lock:
            picked = [self.entries[i] for i in indices]
        for e in picked:
            out = self._entry_partition(e, p)
            if out is not None:
                yield out

    def close(self) -> None:
        if self.mem is not None:
            self.mem.unregister_consumer(self)
        with self._lock:
            entries, self.entries = self.entries, []
            self._dev_bytes = 0
        for e in entries:
            if e[0] == "spill":
                e[1].release()

    def __del__(self):
        # backstop for spill files when the memoized buffer is dropped with
        # the query's op tree. Deliberately does NOT call close(): cyclic GC
        # can fire this finalizer on the same thread that currently holds
        # the MemManager lock (op -> buffer -> op cycle), and
        # unregister_consumer would deadlock on it. Registration needs no
        # cleanup — the manager holds consumers weakly.
        try:
            for e in self.entries:
                if e[0] == "spill":
                    e[1].release()
        except Exception:
            pass


class _MeshExchangeBuffer:
    """The SPMD twin of _ExchangeBuffer: received rows of a mesh-routed
    exchange, one entry per all-to-all round.

    Each entry holds the mesh-global output column tree (shard p =
    reducer partition p's rows in ``[src * quota + r]`` layout), the
    host recv-count matrix ``[n_dev, n_dev]`` (dest × source) and the
    round's quota. ``partition_batches(p)`` reads device p's shard
    zero-copy and slices per SOURCE — source-major, rounds-minor — so a
    reducer sees exactly the map-major batch sequence the host
    device-buffer path yields (the bit-identity contract of the mesh
    battery). Registered with the memory manager for visibility and the
    per-device footprint ledger; entries are device-resident by design
    and do not spill (``spill`` returns 0 — the mesh route is chosen
    only when the whole exchange fits the mesh; RSS remains the
    durable tier)."""

    def __init__(self, op, mesh, axis: str, n_out: int, mem_manager,
                 metrics):
        self.mesh = mesh
        self.axis = axis
        self.n_out = n_out
        self.mem = mem_manager
        self.metrics = metrics
        self.consumer_name = f"mesh-exchange-{id(op):x}"
        #: [(out_cols tree, counts np[n_dev, n_dev], quota), ...]
        self.entries: list = []
        self._dev_bytes = 0
        self._lock = threading.RLock()
        if mem_manager is not None:
            mem_manager.register_consumer(self)

    def add_round(self, out_cols, counts, quota: int) -> int:
        """Record one round. Returns the LIVE bytes this round moved
        (rows actually received × per-row width — the honest
        data-movement figure; the allocated buffers are zero-padded to
        ``n_dev² × quota`` row slots, which under skew overstates
        movement by an order of magnitude)."""
        nbytes = sum(l.nbytes for l in jax.tree_util.tree_leaves(out_cols))
        slots = self.n_out * self.n_out * max(int(quota), 1)
        live = int(counts.sum())
        live_bytes = int(nbytes * live / slots) if slots else 0
        with self._lock:
            self.entries.append((out_cols, counts, quota))
            self._dev_bytes += nbytes
        self.metrics.counter("mesh_bytes_moved").add(live_bytes)
        if self.mem is not None:
            # the ledger's unit is ONE device's HBM (the memmgr budget
            # is a fraction of a single chip): account the per-device
            # footprint, not the mesh-global total
            self.mem.update_mem_used(self, self.per_device_bytes())
        return live_bytes

    def mem_used(self) -> int:
        """MemConsumer contract: this buffer's charge against the
        (single-device) budget — the per-chip footprint."""
        return self.per_device_bytes()

    def global_bytes(self) -> int:
        """Allocated bytes summed across every shard of the mesh."""
        with self._lock:
            return self._dev_bytes

    def per_device_bytes(self) -> int:
        """The per-chip footprint the memmgr ledger accounts: global
        bytes divide evenly across the mesh (every leaf is batch-dim
        sharded)."""
        with self._lock:
            return self._dev_bytes // max(self.n_out, 1)

    def spill(self) -> int:
        return 0   # device-resident by design (see class docstring)

    def partition_shards(self, p: int) -> list:
        """Device ``p``'s zero-copy shard tree of every round — hoisted
        ONCE per partition by both read paths (recomputing per source
        would tree_map n_out× per reducer)."""
        from auron_tpu.parallel import mesh as mesh_mod
        with self._lock:
            entries = list(self.entries)
        return [jax.tree_util.tree_map(
            lambda a: mesh_mod.local_shard(a, p, self.mesh), cols)
            for cols, _counts, _quota in entries]

    def source_batches(self, p: int, source: int,
                       _shards=None) -> Iterator[DeviceBatch]:
        """Partition ``p``'s rows received from ONE source map, rounds
        in order — the per-source slice the demoted read path
        interleaves with host entries."""
        from auron_tpu.columnar.batch import DeviceBatch as _DB
        with self._lock:
            entries = list(self.entries)
        if _shards is None:
            _shards = self.partition_shards(p)
        home = self.mesh.devices.flat[0]
        for (cols, counts, quota), shard_cols in zip(entries, _shards):
            n_s = int(counts[p, source])
            if n_s <= 0:
                continue
            cap = bucket_rows(n_s)
            base = _DB(shard_cols, jnp.asarray(n_s, jnp.int32))
            idx = jnp.minimum(
                source * quota + jnp.arange(cap, dtype=jnp.int32),
                base.capacity - 1)
            out = gather_batch(base, idx, jnp.asarray(n_s, jnp.int32))
            # rebase onto the engine's home device: downstream
            # operators mix these rows with build sides / agg state
            # committed there (one ICI hop on a real slice; the
            # HBM-tier item keeps them resident per-device later)
            yield jax.device_put(out, home)

    def partition_batches(self, p: int) -> Iterator[DeviceBatch]:
        # device p's shard of every round, materialized zero-copy once
        shards = self.partition_shards(p)
        # SOURCE-major, rounds-minor: map s's round-r rows appear where
        # the host path's entry (map s, batch r) would
        for s in range(self.n_out):
            yield from self.source_batches(p, s, _shards=shards)

    def close(self) -> None:
        if self.mem is not None:
            self.mem.unregister_consumer(self)
        with self._lock:
            self.entries = []
            self._dev_bytes = 0


class _DemotedExchangeBuffer:
    """Read path of a MID-QUERY demoted exchange: the rounds that
    completed on the mesh plus the host-routed remainder.

    A demotion splits one exchange's entries across two tiers — rounds
    0..k-1 live in the mesh buffer (shard-resident received rows), the
    lost round's re-routed inputs and every later batch in a classic
    host ``_ExchangeBuffer`` (``host_sources[i]`` = the map partition
    host entry ``i`` came from). The read path interleaves them
    SOURCE-major: for each map, first its mesh rounds (rounds-minor),
    then its host entries in append order — exactly the map-major batch
    sequence both the pure-mesh and pure-host paths yield, so the
    bit-identity contract (group order included) survives the
    demotion. Both sub-buffers stay registered with the memory manager
    (the host half spills under pressure like any classic exchange)."""

    def __init__(self, mesh_buffer: "_MeshExchangeBuffer",
                 host_buffer: "_ExchangeBuffer", host_sources: list,
                 n_out: int):
        self.mesh_buffer = mesh_buffer
        self.host_buffer = host_buffer
        self.host_sources = list(host_sources)
        self.n_out = n_out

    def partition_batches(self, p: int) -> Iterator[DeviceBatch]:
        by_source: dict[int, list[int]] = {}
        for i, s in enumerate(self.host_sources):
            by_source.setdefault(s, []).append(i)
        # hoist the per-round shard trees ONCE per partition (the pure-
        # mesh read path's discipline) instead of once per source
        shards = self.mesh_buffer.partition_shards(p)
        for s in range(self.n_out):
            yield from self.mesh_buffer.source_batches(p, s,
                                                       _shards=shards)
            idxs = by_source.get(s)
            if idxs:
                yield from self.host_buffer.entry_batches(p, idxs)

    def close(self) -> None:
        self.mesh_buffer.close()
        self.host_buffer.close()


class ShuffleExchangeOp(PhysicalOp):
    name = "shuffle_exchange"
    #: SPMD layout: exchange entries shard on the batch dim; eligible
    #: hash exchanges are re-stamped "gang" by ir/planner.annotate_mesh
    mesh_buffer_kind = "shuffle_entry"

    def __init__(self, child: PhysicalOp, partitioning,
                 input_partitions: int = 1):
        self.child = child
        self.partitioning = partitioning
        self.input_partitions = input_partitions
        self._lock = threading.Lock()
        self._buffer: Optional[_ExchangeBuffer] = None
        #: map-side combine fold (ir/planner._fold_combine): when the
        #: child is an eligible partial AggOp, the planner stamps the
        #: fold mode here and the agg's combine stage joins the split
        #: program — 'combine' merges groups per batch/round BEFORE the
        #: rows cross, 'passthrough' ships state-layout rows uncombined
        #: (cost-model choice for high-cardinality sites / the
        #: auron.fusion.combine=off arm). None = no fold (ineligible
        #: child or fusion off); the agg then executes as its own op.
        self.combine_mode: Optional[str] = None
        self.combine_why: str = ""
        #: (plan fingerprint, preorder site label) — the ir/cost.py
        #: history key; None for ad-hoc plans without a fingerprint
        self.cost_site: Optional[tuple] = None
        #: (live rows in, live rows out) of the last materialization's
        #: combine stage, for the route record (set under _lock)
        self._combine_stats: Optional[tuple] = None

    @property
    def children(self):
        return [self.child]

    def schema(self) -> Schema:
        return self.child.schema()

    @property
    def num_partitions(self) -> int:
        return self.partitioning.num_partitions

    # -- map side -----------------------------------------------------------

    def _input_batches(self, ctx: ExecContext) -> Iterator[DeviceBatch]:
        for in_p in range(self.input_partitions):
            map_ctx = ctx.child(partition_id=in_p,
                                num_partitions=self.input_partitions)
            for b in self.child.execute(in_p, map_ctx):
                map_ctx.checkpoint("shuffle.map")
                yield b

    def _materialize(self, ctx: ExecContext) -> _ExchangeBuffer:
        """Run all map tasks; ONE sort-by-pid compaction per batch."""
        from auron_tpu.obs import trace
        with trace.span("shuffle", "shuffle.materialize",
                        maps=self.input_partitions,
                        partitions=self.num_partitions):
            return self._materialize_inner(ctx)

    def _materialize_inner(self, ctx: ExecContext):
        from auron_tpu.parallel import mesh as mesh_mod
        metrics = ctx.metrics_for(self)
        write_time = metrics.counter("shuffle_write_total_time")
        # SPMD routing: when source and sink stages share the mesh, the
        # hash repartition lowers to the on-device all-to-all; every
        # other shape keeps the host device-buffer path. The decision is
        # recorded per exchange (metric tree + 'mesh' trace events —
        # tools/mesh_report.py) so a route change is observable, never
        # inferred.
        route, reason = mesh_mod.exchange_route(
            self.partitioning, self.num_partitions, self.input_partitions,
            ctx.mesh_plane)
        if route == "all_to_all":
            return self._materialize_mesh(ctx, metrics, write_time, reason)
        buffer = _ExchangeBuffer(self, ctx.mem_manager, metrics, ctx.conf)
        self._combine_stats = None
        try:
            filled = self._fill_buffer(ctx, buffer, write_time)
        except BaseException:
            # a cancelled/failed materialization must not leave the
            # half-filled buffer registered with the memory manager (or
            # its spill files on disk) until gc finds it — the
            # zero-leaked-consumers contract of the cancel battery
            buffer.close()
            raise
        # recorded AFTER the fill (the mesh route's convention) so the
        # event carries the observed combine figures
        _record_route(self, metrics, route, reason,
                      **self._combine_attrs())
        return filled

    def _combine_attrs(self) -> dict:
        """exchange.route attributes of the fold's observed effect —
        empty when no combine stage ran (tools/mesh_report.py columns)."""
        if self._combine_stats is None:
            return {}
        rows_in, rows_out = self._combine_stats   # host ints (_note_combine)
        return dict(combine_mode=self.combine_mode,
                    combine_rows_in=rows_in,
                    combine_rows_out=rows_out,
                    combine_ratio=round(rows_out / rows_in, 4)
                    if rows_in else 1.0)

    def _note_combine(self, metrics, rows_in: int, rows_out: int,
                      batches: int) -> None:
        """Book one materialization's combine figures: metric counters,
        the route-event stash, and the ir/cost.py per-site history (only
        COMBINE-mode runs feed history — a passthrough run ships every
        row and would record a fake ratio of 1.0 over the honest one)."""
        rows_in = int(rows_in)     # graft: disable=GL001 -- summed on host from the fold's fenced counts readback
        rows_out = int(rows_out)   # graft: disable=GL001 -- host int like rows_in
        self._combine_stats = (rows_in, rows_out)
        metrics.counter("combine_rows_in").add(rows_in)
        metrics.counter("combine_rows_out").add(rows_out)
        if self.combine_mode == "combine":
            from auron_tpu.ir import cost as cost_mod
            cost_mod.observe(self.cost_site, rows_in, rows_out, batches)

    def _materialize_mesh(self, ctx: ExecContext, metrics, write_time,
                          reason: str) -> "_MeshExchangeBuffer":
        """SPMD materialization: the whole map side — fused chain (when
        one folded), partition ids, sort-by-pid split and the shuffle
        itself — runs as ONE shard_map program per round across the
        mesh, the shuffle riding ``lax.all_to_all`` instead of
        materializing through host buffers.

        Round r stacks batch r of every map partition into one
        batch-dim-sharded global batch (shard i = map i, zero-copy
        empty for exhausted maps); the program fences ONCE at its
        output boundary (the recv-counts/global-max readback — the
        PR 8 sync discipline extended to the sharded stage), and a
        bucket overflowing the row quota re-runs the round once at the
        exact needed pow2 quota. Inputs are NEVER donated into the
        exchange program — the re-run path still needs them, whatever
        ``yields_owned_batches`` says about the child.

        The stage occupies the whole mesh for its duration
        (``plane.gang``): the PR 9 scheduler's WRR turn orders queries'
        sharded stages, and the gang lock keeps two of them from ever
        interleaving inside the mesh."""
        from auron_tpu import config as cfg
        from auron_tpu import errors
        from auron_tpu.obs import profile as _profile
        from auron_tpu.parallel import mesh as mesh_mod
        from auron_tpu.parallel.mesh_exchange import stage_exchange_program
        from auron_tpu.runtime import faults
        from jax.sharding import NamedSharding, PartitionSpec as _P

        plane = ctx.mesh_plane
        n_out = self.num_partitions
        mesh = plane.mesh_for(n_out)
        axis = plane.axis
        out_schema = self.child.schema()

        fold = self._fold_spec() \
            if ctx.conf.get(cfg.FUSION_ENABLED) else None
        if fold is not None:
            fragments, frag_keys, input_op, combine, combine_sig = fold
            fmetrics = ctx.metrics_for(self.child)
            fmetrics.counter("split_folded").add(1)
        else:
            fragments, frag_keys = [], ()
            input_op = self.child
            combine = combine_sig = None
            fmetrics = None
        self._combine_stats = None
        comb_in_total = 0
        comb_out_total = 0
        comb_batches = 0
        in_schema = input_op.schema()
        part_exprs = self.partitioning.exprs
        part_key = ("hash", part_exprs)
        init = [f.init_carry for f in fragments]

        kmetrics = ctx.metrics_for("kernels")
        built_c = kmetrics.counter("mesh_stage_programs_built")
        hit_c = kmetrics.counter("mesh_stage_program_hits")

        from auron_tpu.parallel import mesh_exchange as mex
        from auron_tpu.runtime import watchdog

        buffer = _MeshExchangeBuffer(self, mesh, axis, n_out,
                                     ctx.mem_manager, metrics)
        rounds = escalations = 0   # rounds = COMPLETED mesh rounds
        bytes_moved = 0   # LIVE bytes through the all-to-all (unpadded)
        quota: Optional[int] = None   # sticky: escalated once, reused
        dest_rows = np.zeros(n_out, np.int64)
        straggler_factor = float(ctx.conf.get(cfg.MESH_STRAGGLER_FACTOR))
        demote_on_straggler = ctx.conf.get(cfg.MESH_DEMOTE_ON_STRAGGLER)
        demote_reason: Optional[str] = None
        pending: list = []         # (map, still-live batch) of a lost round
        carries_h = None           # host carry snapshot for the demoted path
        t_demote = 0.0

        def polled(in_p: int):
            map_ctx = ctx.child(partition_id=in_p,
                                num_partitions=self.input_partitions)
            for b in input_op.execute(in_p, map_ctx):
                map_ctx.checkpoint("shuffle.map")
                yield b

        try:
            with plane.gang(ctx.cancel_event, heartbeat=ctx.heartbeat):
                iters = [polled(p) if p < self.input_partitions
                         else iter(())
                         for p in range(n_out)]
                carries = jax.device_put(
                    jnp.broadcast_to(
                        jnp.asarray(init, jnp.int64), (n_out, len(init))),
                    NamedSharding(mesh, _P(axis, None)))
                while True:
                    batches = [next(it, None) for it in iters]
                    ref = next((b for b in batches if b is not None), None)
                    if ref is None:
                        break
                    live = [(p, b) for p, b in enumerate(batches)
                            if b is not None]
                    n_live = len(live)
                    # zero-copy empties for exhausted maps: a live
                    # batch's arrays with num_rows=0 (rows past
                    # num_rows are dead by the batch contract)
                    batches = [b if b is not None else
                               DeviceBatch(ref.columns,
                                           jnp.asarray(0, jnp.int32))
                               for b in batches]
                    # the sharded-stage fault site (chaos battery): a
                    # device fault mid-exchange must classify cleanly
                    faults.maybe_fail("device.compute",
                                      errors.DeviceExecutionError)
                    # gang-aware round guard: flags downgraded to "slow"
                    # when the round completes; a raise below is the
                    # dead-device verdict (watchdog.MeshRoundGuard)
                    guard = watchdog.MeshRoundGuard(ctx.heartbeat)
                    round_built = False   # compile time is not latency
                    try:
                        with guard:
                            # the mesh fault domain's per-round site
                            mex.round_fault_check(ctx)
                            with timer(write_time, sync=False):
                                cols, num_rows, cap = \
                                    mesh_mod.stack_global_batch(
                                        batches, mesh, axis)
                                if quota is None:
                                    quota = bucket_rows(
                                        max((2 * cap) // n_out, 1))
                                while True:
                                    kern, built = stage_exchange_program(
                                        mesh, axis, n_out, frag_keys,
                                        part_key, in_schema, out_schema,
                                        cap, quota, fragments, part_exprs,
                                        combine, combine_sig)
                                    round_built |= built
                                    (built_c if built else hit_c).add(1)
                                    if combine is not None:
                                        (out_cols, rc, _nr, gmax,
                                         new_carries, comb_in) = kern(
                                            cols, num_rows, carries)
                                    else:
                                        (out_cols, rc, _nr, gmax,
                                         new_carries) = kern(
                                            cols, num_rows, carries)
                                        comb_in = None
                                    # ONE fence at the sharded stage's
                                    # output boundary: the round's only
                                    # readback, booked as device wait
                                    # (PR 8 discipline — never per
                                    # shard, never per program step);
                                    # the pre-combine row count rides
                                    # the same fence
                                    if comb_in is not None:
                                        gmax_h, rc_h, comb_h = \
                                            _profile.timed_get(
                                                (gmax, rc, comb_in))
                                    else:
                                        gmax_h, rc_h = _profile.timed_get(
                                            (gmax, rc))
                                        comb_h = None
                                    needed = int(np.asarray(gmax_h))
                                    if needed <= quota:
                                        break
                                    # one-shot escalation at the exact
                                    # pow2 quota (the
                                    # exchange_device_batches contract);
                                    # the un-donated inputs are still
                                    # live for this re-run
                                    escalations += 1
                                    quota = bucket_rows(needed)
                    except BaseException as e:
                        err = mex.classify_collective(e)
                        if not mex.is_mesh_loss(err):
                            if err is e:
                                raise
                            raise err from e
                        # DEVICE LOSS mid-round: quarantine first (even
                        # if in-place demotion fails below, the next
                        # task attempt routes against the shrunken
                        # plane), then capture the still-live inputs of
                        # the lost round (donation-off contract) for
                        # the host re-route
                        t_demote = time.perf_counter()
                        # a stall the monitor flagged while the dying
                        # round blocked must not abort the recovery at
                        # the host continuation's first checkpoint
                        guard.forgive_stall()
                        if ctx.conf.get(cfg.MESH_QUARANTINE):
                            plane.quarantine(
                                getattr(err, "device", None),
                                f"{type(err).__name__} at round "
                                f"{rounds}")
                        try:
                            # the carry readback IS the demotion's sync
                            # point: timed_get books the wait as device
                            carries_h = np.asarray(
                                _profile.timed_get(carries))
                        except Exception:
                            # the carry shards are unreadable too: the
                            # loss reaches past this round — surface
                            # the classified verdict; the task-level
                            # retry (MeshUnavailable is transient)
                            # re-materializes host-side against the
                            # quarantined plane
                            raise err from e
                        pending = live
                        demote_reason = "device_loss"
                        self._emit_demote(metrics, err, rounds, plane)
                        break
                    carries = new_carries
                    rounds += 1
                    counts = np.asarray(rc_h).reshape(n_out, n_out)
                    dest_rows += counts.sum(axis=1)
                    bytes_moved += buffer.add_round(out_cols, counts,
                                                    quota)
                    if comb_h is not None:
                        # per-shard pre-combine rows of the COMPLETED
                        # round (escalation re-runs were discarded)
                        comb_in_total += int(np.asarray(comb_h).sum())   # graft: disable=GL001 -- comb_h rode the round's host counts readback
                        comb_out_total += int(counts.sum())
                        comb_batches += n_live
                    if fmetrics is not None:
                        # the folded chain still owns its plan node:
                        # post-chain live rows are what the exchange
                        # moved (the _materialize_fused convention)
                        fmetrics.counter("output_rows").add(
                            int(counts.sum()))
                        fmetrics.counter("output_batches").add(n_live)
                    # straggler defense: judge THIS round against the
                    # rolling p50 BEFORE it joins the window; a stall
                    # flag the guard forgave is a straggler by
                    # construction (the round outlived the watchdog
                    # timeout and still completed). Rounds that BUILT a
                    # program (first shape class, quota escalation) are
                    # excluded from verdict AND window — compile time is
                    # not chip latency, and billing it would demote a
                    # healthy mesh / inflate the baseline
                    if round_built:
                        slow = False
                    else:
                        slow = guard.forgiven or plane.round_stats \
                            .is_straggler(guard.elapsed_s,
                                          straggler_factor)
                        plane.round_stats.observe(guard.elapsed_s)
                    if slow:
                        plane.record_straggler()
                        metrics.counter("mesh_stragglers").add(1)
                        from auron_tpu.obs import trace
                        trace.event(
                            "mesh", "mesh.straggler", op=repr(self),
                            round=rounds - 1,
                            elapsed_ms=round(guard.elapsed_s * 1e3, 3),
                            p50_ms=round(
                                (plane.round_stats.p50() or 0.0) * 1e3,
                                3),
                            forgiven_stall=guard.forgiven,
                            demoting=bool(demote_on_straggler))
                        if demote_on_straggler:
                            # the slow round COMPLETED — its received
                            # rows stay valid on the mesh; only the
                            # remaining rounds re-route
                            t_demote = time.perf_counter()
                            carries_h = np.asarray(
                                _profile.timed_get(carries))
                            demote_reason = "straggler"
                            self._emit_demote(metrics, None, rounds,
                                              plane)
                            break
            # gang released HERE on every path (the with-block's exit):
            # the demoted host continuation below must never hold the
            # mesh, and neighbor queries are never wedged behind a dead
            # one
            if demote_reason is None:
                total = int(dest_rows.sum())
                skew = (float(dest_rows.max()
                              / max(dest_rows.mean(), 1e-9))
                        if total else 1.0)
                metrics.counter("mesh_rounds").add(rounds)
                metrics.counter("mesh_quota_escalations").add(escalations)
                if combine is not None:
                    self._note_combine(metrics, comb_in_total,
                                       comb_out_total, comb_batches)
                _record_route(self, metrics, "all_to_all", reason,
                              rounds=rounds, escalations=escalations,
                              bytes=bytes_moved, rows=total,
                              devices=n_out, skew=round(skew, 3),
                              **self._combine_attrs())
                return buffer
        except BaseException:
            buffer.close()
            raise
        plane.record_demotion(demote_reason)
        return self._demote_to_host(
            ctx, metrics, write_time, buffer, iters, pending, carries_h,
            demote_reason, rounds, escalations, bytes_moved, fragments,
            frag_keys, fmetrics, t_demote, input_op, combine, combine_sig,
            (comb_in_total, comb_out_total, comb_batches))

    def _emit_demote(self, metrics, err, rounds_done: int, plane) -> None:
        """Put the demotion DECISION on the timeline the moment it is
        taken (the chaos correlation links the injected fault to this
        event); the completed continuation's totals follow on the
        ``exchange.route`` record."""
        from auron_tpu.obs import trace
        metrics.counter("mesh_demotions").add(1)
        trace.event("mesh", "exchange.demote", op=repr(self),
                    reason="device_loss" if err is not None
                    else "straggler",
                    error=type(err).__name__ if err is not None else "",
                    rounds_completed=rounds_done,
                    quarantined=plane.quarantined(),
                    usable=plane.usable_width)

    def _demote_to_host(self, ctx: ExecContext, metrics, write_time,
                        mesh_buffer: "_MeshExchangeBuffer", iters,
                        pending, carries_h, demote_reason: str,
                        rounds_done: int, escalations: int,
                        bytes_moved: int, fragments, frag_keys,
                        fmetrics, t_demote: float, input_op=None,
                        combine=None, combine_sig=None,
                        comb_totals=(0, 0, 0)):
        """Host continuation of a demoted exchange: the REMAINING rounds
        re-route down the existing ladder (``all_to_all`` → host
        ``device_buffer``; RSS stays the durable tier below it), run
        OUTSIDE the gang — a demoted exchange never holds the mesh.

        Only the lost round's map inputs are recomputed (``pending`` —
        still live because inputs are never donated into the exchange
        program), and only rounds the mesh never completed are routed
        here: already-consumed rounds stay in the mesh buffer and are
        never re-yielded, the map-by-map streaming contract of the RSS
        recovery path applied to the SPMD tier. When the mesh program
        had a fused chain folded in, the same chain folds into the host
        split program with each map's member carries seeded from the
        last completed round's carry snapshot — the demoted path keeps
        computing the SAME rows."""
        n_out = self.num_partitions
        out_schema = self.child.schema()
        part_exprs = self.partitioning.exprs
        use_fused = bool(fragments) or combine is not None
        if input_op is None:
            input_op = self.child.input if fragments else self.child
        in_schema = input_op.schema()
        host = _ExchangeBuffer(self, ctx.mem_manager, metrics, ctx.conf)
        sources: list[int] = []
        recompute_rows = 0
        recompute_bytes = 0
        host_rows = 0
        comb_in_total, comb_out_total, comb_batches = comb_totals
        pending_by_map = dict(pending)
        _sync = ctx.device_sync
        from auron_tpu.obs import profile as _profile

        def route_batch(in_p: int, batch: DeviceBatch, carries):
            nonlocal host_rows, comb_in_total, comb_out_total, \
                comb_batches
            # the demoted path never donates: a classic one-launch
            # split per batch (chain — and the map-side combine, when
            # the mesh program had one folded — rides along), entry
            # tagged with its source map so the combined read path can
            # interleave map-major
            with timer(write_time, sync=_sync) as t:
                if use_fused:
                    kern, _built = _fused_split_program(
                        frag_keys, ("hash", part_exprs), in_schema,
                        out_schema, n_out, batch.capacity, False,
                        fragments, part_exprs, combine, combine_sig)
                    if combine is not None:
                        sorted_batch, counts, carries, comb_in = \
                            t.track(kern(batch, jnp.int32(in_p),
                                         carries))
                        counts_h, comb_in_h = _profile.timed_get(
                            (counts, comb_in))
                        counts_h = np.asarray(counts_h)   # graft: disable=GL001 -- already host: read via timed_get above
                        comb_in_total += int(comb_in_h)   # graft: disable=GL001 -- same fenced readback
                    else:
                        sorted_batch, counts, carries = t.track(
                            kern(batch, jnp.int32(in_p), carries))
                        counts_h = np.asarray(
                            _profile.timed_get(counts))
                else:
                    pids = self.partitioning.partition_ids(batch,
                                                           out_schema)
                    kern = _sort_by_pid_kernel(n_out, batch.capacity,
                                               False)
                    sorted_batch, counts = t.track(kern(batch, pids))
                    counts_h = np.asarray(_profile.timed_get(counts))
            n = int(counts_h.sum())
            if combine is not None:
                # pin the concrete group count (see _materialize_fused)
                sorted_batch = DeviceBatch(sorted_batch.columns, n)
                comb_out_total += n
                comb_batches += 1
            offsets = np.concatenate(
                [np.zeros(1, np.int64), np.cumsum(counts_h)])
            host.add(sorted_batch, offsets)
            sources.append(in_p)
            host_rows += n
            if fmetrics is not None:
                fmetrics.counter("output_rows").add(n)
                fmetrics.counter("output_batches").add(1)
            return carries, n

        try:
            for in_p in range(self.input_partitions):
                if use_fused:
                    # member carries from the last completed mesh round
                    # + the trailing split-seen slot (round-robin only —
                    # mesh routing is hash-only, the slot is inert)
                    carries = jnp.concatenate([
                        jnp.asarray(carries_h[in_p], jnp.int64),
                        jnp.zeros((1,), jnp.int64)])
                else:
                    carries = None
                pend = pending_by_map.pop(in_p, None)
                if pend is not None:
                    # the lost round's re-route: its rows are the
                    # demotion's recompute cost
                    ctx.checkpoint("exchange.demote")
                    from auron_tpu.columnar.batch import batch_nbytes
                    recompute_bytes += batch_nbytes(pend)
                    carries, n = route_batch(in_p, pend, carries)
                    recompute_rows += n
                for batch in iters[in_p]:
                    # polled() checkpoints per child batch already
                    carries, _n = route_batch(in_p, batch, carries)
        except BaseException:
            # every unwind path releases BOTH halves' consumers (and
            # the host half's spill files) — the zero-leak contract
            host.close()
            mesh_buffer.close()
            raise
        latency_ms = round((time.perf_counter() - t_demote) * 1e3, 3)
        metrics.counter("mesh_rounds").add(rounds_done)
        metrics.counter("mesh_quota_escalations").add(escalations)
        if combine is not None:
            self._note_combine(metrics, comb_in_total, comb_out_total,
                               comb_batches)
        _record_route(self, metrics, "demoted", demote_reason,
                      rounds=rounds_done, escalations=escalations,
                      bytes=bytes_moved, rows=host_rows,
                      recompute_rows=recompute_rows,
                      recompute_bytes=recompute_bytes,
                      latency_ms=latency_ms, devices=n_out,
                      **self._combine_attrs())
        logger.warning(
            "mesh exchange demoted to host (%s): %d mesh round(s) kept, "
            "%d host rows routed, %d rows recomputed from the lost "
            "round, %.1fms demote-to-reroute latency", demote_reason,
            rounds_done, host_rows, recompute_rows, latency_ms)
        return _DemotedExchangeBuffer(mesh_buffer, host, sources, n_out)

    def _fill_buffer(self, ctx: ExecContext, buffer: "_ExchangeBuffer",
                     write_time) -> "_ExchangeBuffer":
        from auron_tpu import config as cfg
        schema = self.child.schema()
        n_out = self.num_partitions
        _sync = ctx.device_sync

        part_sig = _split_signature(self.partitioning)
        fold = self._fold_spec() \
            if part_sig is not None and ctx.conf.get(cfg.FUSION_ENABLED) \
            else None
        if fold is not None:
            self._materialize_fused(ctx, buffer, write_time, part_sig,
                                    fold)
            return buffer

        batches = self._input_batches(ctx)
        partitioning = self.partitioning
        pending: list[DeviceBatch] = []
        if isinstance(partitioning, RangePartitioning) \
                and not partitioning.bounds:
            # sample bounds from the LEADING batches of this same pass —
            # the child is never executed twice
            from auron_tpu.parallel.partitioning import compute_range_bounds
            sampled = 0
            for batch in batches:
                pending.append(batch)
                sampled += int(batch.num_rows)
                if sampled >= _RANGE_SAMPLE_ROWS:
                    break
            bounds = compute_range_bounds(
                pending, list(partitioning.sort_orders), schema,
                partitioning.num_partitions)
            partitioning = RangePartitioning(
                partitioning.sort_orders, partitioning.num_partitions,
                bounds)
            self.partitioning = partitioning

        row_offset = 0
        donate = yields_owned_batches(self.child) \
            and jax.default_backend() != "cpu"
        import itertools
        for batch in itertools.chain(pending, batches):
            # donation hands the batch's buffers to XLA — read the row
            # count BEFORE the call (afterwards the donated leaves are
            # poisoned)
            n_in = int(batch.num_rows) if donate else None
            with timer(write_time, sync=_sync) as t:
                if isinstance(partitioning, RoundRobinPartitioning):
                    part = RoundRobinPartitioning(n_out, row_offset)
                    pids = part.partition_ids(batch, schema)
                else:
                    pids = partitioning.partition_ids(batch, schema)
                kern = _sort_by_pid_kernel(n_out, batch.capacity, donate)
                sorted_batch, counts = t.track(kern(batch, pids))
                # the counts readback is the shuffle materialize's
                # semantic sync point: read it inside the timer frame so
                # pipelined mode books the wait as device, not serde
                from auron_tpu.obs import profile as _profile
                counts_h = np.asarray(_profile.timed_get(counts))
            row_offset += n_in if donate else int(batch.num_rows)
            from auron_tpu.columnar.batch import batch_nbytes
            live_rows = int(counts_h.sum())   # graft: disable=GL001 -- counts_h is a host ndarray (timed_get above)
            cap = max(int(sorted_batch.capacity), 1)   # graft: disable=GL001 -- capacity is a python int by construction
            ctx.metrics_for(self).counter("shuffle_bytes_live").add(
                batch_nbytes(sorted_batch) * live_rows // cap)
            offsets = np.concatenate(
                [np.zeros(1, np.int64), np.cumsum(counts_h)])
            buffer.add(sorted_batch, offsets)
        return buffer

    def _split_fragments(self):
        """The child chain's fragments when they can fold into the split
        program, else None (no chain / fused limit / fan-out members) —
        None keeps the classic path, whose pid+sort kernel is keyed only
        on (n_out, capacity) and therefore SHARES across queries; a
        fragment-less per-schema split program would trade that sharing
        away for nothing."""
        from auron_tpu.ops.fused import FusedStageOp
        if not isinstance(self.child, FusedStageOp) \
                or self.child.has_limit():
            return None
        fragments, frag_keys = self.child.fragment_pipeline()
        if not fragments or any(f.fanout != 1 for f in fragments):
            return None
        return fragments, frag_keys

    def _fold_spec(self):
        """Fold-aware map side: (fragments, frag_keys, input_op,
        combine, combine_sig) or None for the classic per-op path.

        With a planner-stamped ``combine_mode`` the child IS the partial
        AggOp being elided: the exchange executes the agg's OWN child
        (chain fragments when one fused below it) and folds the agg's
        combine/passthrough stage into the split program. Without one,
        this is exactly the PR 2 chain fold (_split_fragments)."""
        from auron_tpu.ops.fused import FusedStageOp
        if self.combine_mode is not None:
            agg = self.child          # planner guaranteed: eligible AggOp
            inner = agg.child
            fragments, frag_keys, input_op = [], (), inner
            if isinstance(inner, FusedStageOp) and not inner.has_limit():
                frags, keys = inner.fragment_pipeline()
                if frags and all(f.fanout == 1 for f in frags):
                    fragments, frag_keys, input_op = \
                        frags, keys, inner.input
            return (fragments, frag_keys, input_op,
                    agg.build_combine_stage(self.combine_mode),
                    agg.combine_signature(self.combine_mode))
        frag_info = self._split_fragments()
        if frag_info is None:
            return None
        fragments, frag_keys = frag_info
        return fragments, frag_keys, self.child.input, None, None

    def _materialize_fused(self, ctx: ExecContext, buffer: _ExchangeBuffer,
                           write_time, part_sig: tuple,
                           fold: tuple) -> None:
        """Whole-stage split: the child chain's member fragments join the
        exchange's partition-id + sort-by-pid program, so a
        filter→project chain feeding a hash shuffle is ONE XLA launch
        per batch with the intermediates living only in registers/VMEM.
        With a map-side combine folded (``fold`` carries the elided
        partial agg's combine stage) the same launch also merges the
        batch's groups before the split — the bytes entering the buffer
        (and its RSS spill frames) are per-batch GROUPS, not rows."""
        n_out = self.num_partitions
        out_schema = self.child.schema()
        _sync = ctx.device_sync
        kmetrics = ctx.metrics_for("kernels")
        built_c = kmetrics.counter("fused_split_programs_built")
        hit_c = kmetrics.counter("fused_split_program_hits")
        # the folded chain/agg still OWNS its plan node (see the
        # hash-join probe fold): the sorted batch's live count IS the
        # folded work's output count, and the one-launch program's time
        # lands on the whole-stage node
        fmetrics = ctx.metrics_for(self.child)
        f_elapsed = fmetrics.counter("elapsed_compute")
        f_rows = fmetrics.counter("output_rows")
        f_batches = fmetrics.counter("output_batches")
        fmetrics.counter("split_folded").add(1)
        metrics = ctx.metrics_for(self)

        fragments, frag_keys, input_op, combine, combine_sig = fold
        in_schema = input_op.schema()
        part_exprs = self.partitioning.exprs \
            if isinstance(self.partitioning, HashPartitioning) else ()
        donate = yields_owned_batches(input_op) \
            and jax.default_backend() != "cpu"
        init = [f.init_carry for f in fragments]
        comb_in_total = 0
        comb_out_total = 0
        n_batches = 0
        from auron_tpu.columnar.batch import batch_nbytes

        # the trailing carry slot (rows seen at the split — the
        # round-robin start) persists across input partitions; member
        # carries reset per input partition like a fresh execute() would
        split_seen = jnp.zeros((1,), jnp.int64)
        for in_p in range(self.input_partitions):
            map_ctx = ctx.child(partition_id=in_p,
                                num_partitions=self.input_partitions)
            carries = jnp.concatenate(
                [jnp.asarray(init, jnp.int64), split_seen])
            for batch in input_op.execute(in_p, map_ctx):
                map_ctx.checkpoint("shuffle.map")
                kern, built = _fused_split_program(
                    frag_keys, part_sig, in_schema, out_schema, n_out,
                    batch.capacity, donate, fragments, part_exprs,
                    combine, combine_sig)
                (built_c if built else hit_c).add(1)
                t0v = f_elapsed.value
                with timer(f_elapsed, sync=_sync) as t:
                    from auron_tpu.obs import profile as _profile
                    if combine is not None:
                        sorted_batch, counts, carries, comb_in = t.track(
                            kern(batch, jnp.int32(in_p), carries))
                        # pre-combine live rows ride the SAME readback
                        # fence as the counts (no extra sync point)
                        counts_h, comb_in_h = _profile.timed_get(
                            (counts, comb_in))
                        counts_h = np.asarray(counts_h)   # graft: disable=GL001 -- already host: read via timed_get above
                        comb_in_total += int(comb_in_h)   # graft: disable=GL001 -- same fenced readback
                    else:
                        sorted_batch, counts, carries = t.track(
                            kern(batch, jnp.int32(in_p), carries))
                        # semantic sync point (see _materialize): the
                        # wait books as device inside this frame
                        counts_h = np.asarray(_profile.timed_get(counts))
                # the shuffle node keeps its canonical write-time view
                # of the same launch (chain + split are one program)
                write_time.add(f_elapsed.value - t0v)
                live = int(counts_h.sum())
                if combine is not None:
                    # a combined batch's row count is traced (the group
                    # count) — pin the concrete live total so buffer
                    # bookkeeping and spill slicing never sync on it
                    sorted_batch = DeviceBatch(sorted_batch.columns,
                                               live)
                    comb_out_total += live
                    n_batches += 1
                f_rows.add(live)
                f_batches.add(1)
                # honest data-movement figure for the host route: live
                # rows × per-row width (the mesh buffer's add_round
                # convention; the allocated batch is capacity-padded)
                nbytes = batch_nbytes(sorted_batch)
                cap = max(int(sorted_batch.capacity), 1)   # graft: disable=GL001 -- capacity is a python int by construction
                metrics.counter("shuffle_bytes_live").add(
                    nbytes * live // cap)
                offsets = np.concatenate(
                    [np.zeros(1, np.int64), np.cumsum(counts_h)])
                buffer.add(sorted_batch, offsets)
            split_seen = carries[-1:]
        if combine is not None:
            self._note_combine(metrics, comb_in_total, comb_out_total,
                               n_batches)

    # -- reduce side --------------------------------------------------------

    def execute(self, partition: int, ctx: ExecContext) -> Iterator[DeviceBatch]:
        with self._lock:
            if self._buffer is None:
                self._buffer = self._materialize(ctx)
        metrics = ctx.metrics_for(self, "_read")
        read_time = metrics.counter("shuffle_read_total_time")

        def polled(buf):
            # lifecycle poll per fetched batch: a cancel mid-fetch lands
            # within one batch, and the stall watchdog sees the reducer
            # making progress
            for b in buf.partition_batches(partition):
                ctx.checkpoint("shuffle.fetch")
                yield b

        # production-segment timing only (obs/trace.stream_spanned): the
        # read timer must not bill the consumer's compute, and the span
        # must not stay open across yields
        from auron_tpu.obs import trace
        stream = trace.stream_spanned(
            "shuffle", "shuffle.fetch", polled(self._buffer),
            time_counter=read_time, partition=partition)
        return count_output(stream, metrics, timed=True)

    def __repr__(self):
        return (f"ShuffleExchangeOp[{type(self.partitioning).__name__} "
                f"{self.input_partitions}->{self.num_partitions}]")


class RssShuffleExchangeOp(PhysicalOp):
    """Shuffle through the host shuffle service (the RSS tier, reference:
    shuffle/rss.rs + rss_shuffle_writer_exec.rs): the map side pushes
    per-partition serialized frames to shared storage instead of keeping
    buckets device-resident, so shuffle size is bounded by storage, not
    HBM, and reducers on OTHER HOSTS read the same shuffle through their
    own service instance (see RssShuffleReadOp)."""

    name = "rss_shuffle_exchange"

    def __init__(self, child: PhysicalOp, partitioning, service,
                 shuffle_id: int, input_partitions: int = 1):
        self.child = child
        self.partitioning = partitioning
        self.service = service
        self.shuffle_id = shuffle_id
        self.input_partitions = input_partitions
        self._lock = threading.Lock()
        self._written = False

    @property
    def children(self):
        return [self.child]

    def schema(self) -> Schema:
        return self.child.schema()

    @property
    def num_partitions(self) -> int:
        return self.partitioning.num_partitions

    def _journal(self, ctx: ExecContext):
        """The driving query's crash-safe journal (runtime/journal),
        resolved through the cancel token; None when journaling is off
        for this query."""
        return getattr(ctx.cancel_event, "journal", None)

    def _materialize(self, ctx: ExecContext) -> None:
        partitioning = self.partitioning
        schema = self.child.schema()
        # the RSS tier is routed by construction (durable / multihost —
        # readers on OTHER hosts cannot reach this host's mesh), but the
        # decision is still recorded so the per-exchange route table is
        # complete
        _record_route(self, ctx.metrics_for(self), "rss", "rss_tier")
        # invalidate any previous attempt's manifest so readers can't mix
        # stale map outputs into this attempt
        self.service.begin_shuffle(self.shuffle_id)
        journal = self._journal(ctx)
        # map-level resume: a resumed query skips exactly the map
        # outputs the journal proves committed AND intact on storage
        # (size + trailer CRC), recomputing only what the durable tier
        # never received. Range partitioning is excluded — its bounds
        # are sampled from map 0's live batches, so a skipped map 0
        # would leave later maps unboundable; a range exchange resumes
        # only at full-satisfied granularity (see execute()).
        map_skips_ok = (journal is not None and journal.resumed
                        and not isinstance(partitioning,
                                           RangePartitioning))
        jmetrics = ctx.metrics_for(self)

        for in_p in range(self.input_partitions):
            if map_skips_ok:
                size = journal.reusable_map(self.shuffle_id, in_p,
                                            self.service)
                if size is not None:
                    journal.note_map_skipped(self.shuffle_id, size)
                    jmetrics.counter("journal_maps_skipped").add(1)
                    jmetrics.counter("journal_bytes_reused").add(size)
                    continue
                journal.note_map_recomputed(self.shuffle_id)
                jmetrics.counter("journal_maps_recomputed").add(1)
            map_ctx = ctx.child(partition_id=in_p,
                                num_partitions=self.input_partitions)
            batches = self.child.execute(in_p, map_ctx)
            pending: list[DeviceBatch] = []
            if in_p == 0 and isinstance(partitioning, RangePartitioning) \
                    and not partitioning.bounds:
                # sample bounds from map 0's leading batches; all maps of
                # this shuffle then share the same bounds (the reference
                # samples once, driver-side)
                from auron_tpu.parallel.partitioning import \
                    compute_range_bounds
                sampled = 0
                for batch in batches:
                    pending.append(batch)
                    sampled += int(batch.num_rows)
                    if sampled >= _RANGE_SAMPLE_ROWS:
                        break
                bounds = compute_range_bounds(
                    pending, list(partitioning.sort_orders), schema,
                    partitioning.num_partitions)
                partitioning = RangePartitioning(
                    partitioning.sort_orders, partitioning.num_partitions,
                    bounds)
                self.partitioning = partitioning
            self._write_map(in_p, ctx, partitioning, pending, batches)
        self.service.commit_shuffle(self.shuffle_id, self.input_partitions)
        if journal is not None:
            # the journal's shuffle-level commit record rides the SAME
            # boundary as the durable tier's manifest (fsync here only)
            journal.record_shuffle_commit(self.shuffle_id,
                                          self.input_partitions)

    def _write_map(self, in_p: int, ctx: ExecContext, partitioning,
                   pending=(), batches=None) -> None:
        """Write ONE map task's output. Also the corruption-recovery
        entry point: a checksum failure on fetch recomputes exactly this
        map (``batches=None`` re-executes the child partition — the
        engine is functional, so the recompute is exact). The writer's
        context manager guarantees no exception path leaves a ``.part``
        file behind."""
        import itertools

        from auron_tpu import config as cfg
        from auron_tpu.columnar.serde import (batch_to_host,
                                              serialize_host_batch,
                                              slice_host_batch)
        from auron_tpu.obs import trace
        metrics = ctx.metrics_for(self)
        write_time = metrics.counter("shuffle_write_total_time")
        _sync = ctx.device_sync
        n_out = self.num_partitions
        schema = self.child.schema()
        codec_level = ctx.conf.get(cfg.SPILL_CODEC_LEVEL)
        if batches is None:
            map_ctx = ctx.child(partition_id=in_p,
                                num_partitions=self.input_partitions)
            batches = self.child.execute(in_p, map_ctx)
        row_offset = 0
        donate = yields_owned_batches(self.child) \
            and jax.default_backend() != "cpu"
        with trace.span("shuffle", "rss.map_write",
                        shuffle=self.shuffle_id, map=in_p), \
                self.service.partition_writer(self.shuffle_id, in_p,
                                              n_out) as writer:
            for batch in itertools.chain(pending, batches):
                # lifecycle poll per map batch: a cancel mid-write
                # aborts through the writer's context manager (no .part
                # left behind) and the heartbeat shows write progress
                ctx.checkpoint("rss.map_write")
                n_in = int(batch.num_rows) if donate else None
                with timer(write_time, sync=_sync) as t:
                    if isinstance(partitioning, RoundRobinPartitioning):
                        part = RoundRobinPartitioning(n_out, row_offset)
                        pids = part.partition_ids(batch, schema)
                    else:
                        pids = partitioning.partition_ids(batch, schema)
                    kern = _sort_by_pid_kernel(n_out, batch.capacity,
                                               donate)
                    sorted_batch, counts = t.track(kern(batch, pids))
                row_offset += n_in if donate else int(batch.num_rows)
                counts_h = np.asarray(counts)
                offsets = np.concatenate(
                    [np.zeros(1, np.int64), np.cumsum(counts_h)])
                n = int(sorted_batch.num_rows)
                with timer(write_time, bucket="serde"):
                    host = batch_to_host(sorted_batch, n)
                    for p in range(n_out):
                        lo, hi = int(offsets[p]), int(offsets[p + 1])
                        if hi > lo:
                            writer.write(p, serialize_host_batch(
                                slice_host_batch(host, lo, hi),
                                codec_level=codec_level))
            writer.commit()
            journal = self._journal(ctx)
            if journal is not None:
                # recorded AFTER the atomic rename: the journal never
                # claims more than the durable tier holds (async
                # append; made durable by the shuffle-commit fsync)
                journal.record_map(self.shuffle_id, in_p,
                                   writer.committed_size,
                                   writer.trailer_crc)

    #: per-map corruption-recovery bound: recompute + refetch this many
    #: times before surfacing the classified error (a fault plan that
    #: corrupts EVERY write would otherwise loop forever)
    _CORRUPTION_RECOVERY_ATTEMPTS = 3

    def _fetch_map(self, map_id: int, partition: int,
                   ctx: ExecContext) -> list[bytes]:
        """Verified frames of one map output, with corruption recovery:
        a checksum mismatch invalidates that map output and RECOMPUTES
        the map task (the lineage-recompute contract the reference
        inherits from Spark's shuffle-integrity layer) instead of
        blindly retrying the reducer over the same corrupt bytes."""
        from auron_tpu import errors as aerr
        attempt = 0
        while True:
            try:
                return self.service.map_partition_frames(
                    self.shuffle_id, map_id, partition)
            except aerr.ShuffleCorruption:
                if attempt >= self._CORRUPTION_RECOVERY_ATTEMPTS:
                    raise
                attempt += 1
                logger.warning(
                    "shuffle %d map %d corrupt on fetch (partition %d); "
                    "invalidating and recomputing the map task "
                    "(recovery attempt %d/%d)", self.shuffle_id, map_id,
                    partition, attempt, self._CORRUPTION_RECOVERY_ATTEMPTS)
                with self._lock:   # one recovery of a map at a time
                    try:
                        # another reducer may have repaired the map while
                        # we waited for the lock — re-verify before
                        # invalidating, or we would delete its clean file
                        return self.service.map_partition_frames(
                            self.shuffle_id, map_id, partition)
                    except aerr.ShuffleCorruption:
                        from auron_tpu.obs import trace
                        ctx.metrics_for("recovery").counter(
                            "corruption_recomputes").add(1)
                        trace.event(
                            "shuffle", "shuffle.corruption_recompute",
                            shuffle=self.shuffle_id, map=map_id,
                            partition=partition, attempt=attempt)
                        self.service.invalidate_map(self.shuffle_id,
                                                    map_id)
                        self._write_map(map_id, ctx, self.partitioning)

    def execute(self, partition: int, ctx: ExecContext) -> Iterator[DeviceBatch]:
        with self._lock:
            if not self._written:
                journal = self._journal(ctx)
                if journal is not None and journal.satisfied(
                        self.shuffle_id, self.input_partitions,
                        self.service):
                    # SATISFIED exchange (crash-safe journal): every
                    # map output is committed and intact on storage —
                    # the whole map side is skipped and reducers fetch
                    # straight from the journaled RSS files. Recorded
                    # like every other routing decision.
                    metrics = ctx.metrics_for(self)
                    metrics.counter("journal_maps_skipped").add(
                        self.input_partitions)
                    _record_route(self, metrics, "rss",
                                  "journal_satisfied")
                    from auron_tpu.obs import trace
                    trace.event("journal", "journal.satisfied",
                                shuffle=self.shuffle_id,
                                maps=self.input_partitions)
                else:
                    self._materialize(ctx)
                self._written = True
        metrics = ctx.metrics_for(self, "_read")
        read_time = metrics.counter("shuffle_read_total_time")

        def stream():
            from auron_tpu.columnar.serde import (deserialize_host_batch,
                                                  host_to_batch)
            # map-by-map fetch: each map's frames are fully verified
            # before any is yielded, so corruption recovery never
            # re-yields data a downstream operator already consumed
            maps = self.service.committed_maps(self.shuffle_id)
            for map_id in range(len(maps)):
                ctx.checkpoint("rss.fetch")
                for frame in self._fetch_map(map_id, partition, ctx):
                    # deserialize INSIDE the timer, yield OUTSIDE it: a
                    # yield under the timer would bill the consumer's
                    # compute to shuffle_read_total_time
                    with timer(read_time, bucket="serde"):
                        host, _ = deserialize_host_batch(frame)
                        batch = (host_to_batch(host,
                                               bucket_rows(host.num_rows))
                                 if host.num_rows else None)
                    if batch is not None:
                        yield batch

        return count_output(stream(), metrics, timed=True)

    def __repr__(self):
        return (f"RssShuffleExchangeOp[{type(self.partitioning).__name__} "
                f"{self.input_partitions}->{self.num_partitions} "
                f"shuffle={self.shuffle_id}]")


class RssShuffleReadOp(PhysicalOp):
    """Reducer-side read of a committed RSS shuffle — the entry point for
    a DIFFERENT host than the one that wrote (reference:
    AuronCelebornShuffleReader): needs only the shared service root, the
    shuffle id, and the schema."""

    name = "rss_shuffle_read"

    def __init__(self, service, shuffle_id: int, schema: Schema,
                 num_partitions: int):
        self.service = service
        self.shuffle_id = shuffle_id
        self._schema = schema
        self.num_partitions = num_partitions

    def schema(self) -> Schema:
        return self._schema

    def execute(self, partition: int, ctx: ExecContext) -> Iterator[DeviceBatch]:
        metrics = ctx.metrics_for(self)
        read_time = metrics.counter("shuffle_read_total_time")

        def stream():
            from auron_tpu.columnar.serde import (deserialize_host_batch,
                                                  host_to_batch)
            for frame in self.service.partition_frames(self.shuffle_id,
                                                       partition):
                # yield outside the timer (see RssShuffleExchangeOp)
                with timer(read_time, bucket="serde"):
                    host, _ = deserialize_host_batch(frame)
                    batch = (host_to_batch(host,
                                           bucket_rows(host.num_rows))
                             if host.num_rows else None)
                if batch is not None:
                    yield batch

        return count_output(stream(), metrics, timed=True)

    def __repr__(self):
        return f"RssShuffleReadOp[shuffle={self.shuffle_id}]"


class _BroadcastBuffer:
    """MemConsumer owning a broadcast's collected batches.

    The reference registers broadcast hash maps with its memory manager
    (join_hash_map.rs:365-387) so an oversized build side spills instead of
    OOMing; this is the same contract for the collected device batches. Each
    entry is ["dev", DeviceBatch] or ["spill", SpillRef, num_rows]; replay
    rehydrates spilled entries per consumer without pinning them back into
    the buffer (consumers stream them, HBM stays at one batch at a time)."""

    def __init__(self, op, mem_manager, metrics, conf=None):
        from auron_tpu import config as cfg
        conf = conf or cfg.get_config()
        self.mem = mem_manager
        self.metrics = metrics
        self.codec_level = conf.get(cfg.SPILL_CODEC_LEVEL)
        self.consumer_name = f"broadcast-{id(op):x}"
        self.entries: list = []
        self._dev_bytes = 0
        self._lock = threading.RLock()
        if mem_manager is not None:
            mem_manager.register_consumer(self)

    def add(self, batch: DeviceBatch) -> None:
        from auron_tpu.columnar.batch import batch_nbytes
        with self._lock:
            self.entries.append(["dev", batch])
            self._dev_bytes += batch_nbytes(batch)
            used = self._dev_bytes
        if self.mem is not None:
            self.mem.update_mem_used(self, used)

    def mem_used(self) -> int:
        with self._lock:
            return self._dev_bytes

    def spill(self) -> int:
        from auron_tpu.columnar.batch import batch_nbytes
        from auron_tpu.columnar.serde import (batch_to_host,
                                              serialize_host_batch)
        if self.mem is None or getattr(self.mem, "spill_manager", None) is None:
            return 0
        with self._lock:  # tag flip, same protocol as _ExchangeBuffer
            victims = [(i, e) for i, e in enumerate(self.entries)
                       if e[0] == "dev"]
            for _i, e in victims:
                e[0] = "dev-spilling"
            if not victims:
                return 0
        freed = 0
        for i, e in victims:
            batch = e[1]
            n = int(batch.num_rows)
            spill = self.mem.spill_manager.new_spill()
            spill.write_frame(serialize_host_batch(
                batch_to_host(batch, n), codec_level=self.codec_level))
            done = spill.finish()
            with self._lock:
                if i < len(self.entries) and self.entries[i] is e:
                    self.entries[i] = ["spill", done, n]
                    self._dev_bytes -= batch_nbytes(batch)
                    freed += batch_nbytes(batch)
                else:
                    done.release()
        self.metrics.counter("mem_spill_count").add(len(victims))
        self.metrics.counter("mem_spill_size").add(freed)
        return freed

    def replay(self) -> Iterator[DeviceBatch]:
        from auron_tpu.columnar.serde import (deserialize_host_batch,
                                              host_to_batch)
        with self._lock:
            entries = list(self.entries)
        for e in entries:
            if e[0].startswith("dev"):
                yield e[1]
            else:
                host, _extras = deserialize_host_batch(e[1].frame_at(0))
                yield host_to_batch(host, bucket_rows(e[2]))

    def close(self) -> None:
        if self.mem is not None:
            self.mem.unregister_consumer(self)
        with self._lock:
            entries, self.entries = self.entries, []
            self._dev_bytes = 0
        for e in entries:
            if e[0] == "spill":
                e[1].release()

    def __del__(self):
        # see _ExchangeBuffer.__del__ for why this must not call close()
        try:
            for e in self.entries:
                if e[0] == "spill":
                    e[1].release()
        except Exception:
            pass


class BroadcastExchangeOp(PhysicalOp):
    """Collect the child once, replay to every consumer partition
    (reference: NativeBroadcastExchangeBase collect→IPC→re-expose,
    SURVEY.md §3.4). Device batches are naturally shared on a single host;
    in SPMD execution the same batch is replicated into every shard. The
    collected set is a memmgr consumer (_BroadcastBuffer): a build side
    larger than the budget spills to host tiers and replays from there."""

    name = "broadcast_exchange"
    #: every consumer partition replays the same collected batches
    owns_output = False
    #: SPMD layout: the collected set replicates across the mesh
    #: (parallel/mesh.buffer_spec) — in sharded execution every shard
    #: reads the same broadcast relation
    mesh_buffer_kind = "broadcast"

    def __init__(self, child: PhysicalOp, input_partitions: int = 1,
                 subplan_key=None):
        self.child = child
        self.input_partitions = input_partitions
        self._lock = threading.Lock()
        self._buffer: Optional[_BroadcastBuffer] = None
        #: warm-path subplan identity (ir/planner computes it from the
        #: subtree's plan + source fingerprints; None = caching off or
        #: identity not capturable): a hit replays the cached host-side
        #: relation instead of collecting the child at all
        self._subplan_key = subplan_key
        self._cached_entries = None

    @property
    def children(self):
        return [self.child]

    def schema(self) -> Schema:
        return self.child.schema()

    def execute(self, partition: int, ctx: ExecContext) -> Iterator[DeviceBatch]:
        metrics = ctx.metrics_for(self)
        with self._lock:
            if self._buffer is None and self._cached_entries is None \
                    and self._subplan_key is not None:
                from auron_tpu.cache import result_cache as _rcache
                self._cached_entries = _rcache.get_cache().get_subplan(
                    self._subplan_key)
            if self._buffer is None and self._cached_entries is None:
                from auron_tpu.obs import trace
                with trace.span("shuffle", "broadcast.collect",
                                maps=self.input_partitions):
                    buf = _BroadcastBuffer(self, ctx.mem_manager, metrics,
                                           conf=ctx.config)
                    try:
                        for in_p in range(self.input_partitions):
                            map_ctx = ctx.child(
                                partition_id=in_p,
                                num_partitions=self.input_partitions)
                            for b in self.child.execute(in_p, map_ctx):
                                map_ctx.checkpoint("broadcast.collect")
                                buf.add(b)
                    except BaseException:
                        # cancelled/failed collect: release the
                        # half-filled buffer (consumer + spills) now,
                        # not at gc time
                        buf.close()
                        raise
                    self._buffer = buf
                self._store_subplan(buf)
        if self._cached_entries is not None:
            return count_output(self._replay_cached(), metrics,
                                timed=True)
        return count_output(self._buffer.replay(), metrics, timed=True)

    def _store_subplan(self, buf: "_BroadcastBuffer") -> None:
        """Publish the freshly-collected relation to the warm-path
        subplan cache as HOST entries (device buffers must not outlive
        this query's memmgr ledger). Skipped when any entry already
        spilled — the process is under pressure, exactly when adding a
        cache copy would be wrong."""
        if self._subplan_key is None:
            return
        from auron_tpu.columnar.batch import batch_nbytes
        from auron_tpu.columnar.serde import batch_to_host
        from auron_tpu.obs import profile as _profile
        with buf._lock:
            entries = list(buf.entries)
        if any(e[0] != "dev" for e in entries):
            return
        host_entries, nbytes = [], 0
        for e in entries:
            # sanctioned readback (GL001): the row-count scalar lives on
            # device; timed_get books the wait at this sync point
            n = int(_profile.timed_get(e[1].num_rows))
            host_entries.append((batch_to_host(e[1], n), n))
            nbytes += batch_nbytes(e[1])
        from auron_tpu.cache import result_cache as _rcache
        _rcache.get_cache().put_subplan(self._subplan_key, host_entries,
                                        nbytes)

    def _replay_cached(self) -> Iterator[DeviceBatch]:
        from auron_tpu.columnar.serde import host_to_batch
        for host, n in self._cached_entries:
            yield host_to_batch(host, bucket_rows(n))
