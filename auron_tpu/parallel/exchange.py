"""Stage exchange (shuffle + broadcast).

The reference's exchange is file-based: BufferedData staging → per-partition
compaction → one spill file + offset index, fetched through Spark's block
store (reference: datafusion-ext-plans/src/shuffle/buffered_data.rs:48-225,
sort_repartitioner.rs:44-254; SURVEY.md §3.3). On TPU the design target is
HBM-granularity exchange: rows are bucketed to target partitions on device
(one compaction kernel per partition), stay device-resident in local mode,
and ride ICI all-to-all when the stage runs SPMD over a mesh
(auron_tpu.parallel.mesh_exchange). A host spill path (serialize + compress)
covers datasets beyond HBM — that is the RSS-analogue tier.

ShuffleExchangeOp is a stage boundary: the upstream subtree runs once per
*input* partition (all materialized on first demand, memoized), downstream
partitions then stream their buckets.
"""

from __future__ import annotations

import threading
from functools import lru_cache
from typing import Iterator, Optional

import jax
import jax.numpy as jnp

from auron_tpu.columnar.batch import DeviceBatch, compact
from auron_tpu.columnar.schema import Schema
from auron_tpu.ops.base import ExecContext, PhysicalOp, count_output, timer
from auron_tpu.parallel.partitioning import (HashPartitioning,
                                             RangePartitioning,
                                             RoundRobinPartitioning,
                                             SinglePartitioning)


@lru_cache(maxsize=256)
def _split_kernel(num_partitions: int, capacity: int):
    """One launch computes all partition buckets: for each target p, compact
    rows with pid==p to the front (shared sort, N gathers)."""

    @jax.jit
    def kernel(batch: DeviceBatch, pids):
        live = batch.row_mask()
        outs = []
        for p in range(num_partitions):
            keep = live & (pids == p)
            outs.append(compact(batch, keep))
        return tuple(outs)

    return kernel


class ShuffleExchangeOp(PhysicalOp):
    name = "shuffle_exchange"

    def __init__(self, child: PhysicalOp, partitioning,
                 input_partitions: int = 1):
        self.child = child
        self.partitioning = partitioning
        self.input_partitions = input_partitions
        self._lock = threading.Lock()
        self._buckets: Optional[list[list[DeviceBatch]]] = None

    @property
    def children(self):
        return [self.child]

    def schema(self) -> Schema:
        return self.child.schema()

    @property
    def num_partitions(self) -> int:
        return self.partitioning.num_partitions

    def _materialize(self, ctx: ExecContext):
        """Run all map tasks, splitting every batch into output buckets."""
        metrics = ctx.metrics_for(self.name)
        write_time = metrics.counter("shuffle_write_total_time")
        n_out = self.num_partitions
        schema = self.child.schema()
        partitioning = self._resolve_partitioning(ctx, schema)

        buckets: list[list[DeviceBatch]] = [[] for _ in range(n_out)]
        for in_p in range(self.input_partitions):
            map_ctx = ExecContext(
                stage_id=ctx.stage_id, partition_id=in_p,
                num_partitions=self.input_partitions,
                metrics=ctx.metrics, mem_manager=ctx.mem_manager)
            row_offset = 0
            for batch in self.child.execute(in_p, map_ctx):
                with timer(write_time):
                    if isinstance(partitioning, RoundRobinPartitioning):
                        part = RoundRobinPartitioning(n_out, row_offset)
                        pids = part.partition_ids(batch, schema)
                    else:
                        pids = partitioning.partition_ids(batch, schema)
                    kern = _split_kernel(n_out, batch.capacity)
                    outs = kern(batch, pids)
                row_offset += int(batch.num_rows)
                for p, out in enumerate(outs):
                    if int(out.num_rows) > 0:
                        buckets[p].append(out)
        return buckets

    def _resolve_partitioning(self, ctx, schema):
        """Range partitioning needs bounds sampled from the input — resolve
        lazily, caching bounds on the op."""
        p = self.partitioning
        if isinstance(p, RangePartitioning) and not p.bounds:
            from auron_tpu.parallel.partitioning import compute_range_bounds
            samples = []
            sample_rows = 0
            for in_p in range(self.input_partitions):
                map_ctx = ExecContext(partition_id=in_p,
                                      num_partitions=self.input_partitions)
                for batch in self.child.execute(in_p, map_ctx):
                    samples.append(batch)
                    sample_rows += int(batch.num_rows)
                    if sample_rows >= 10000:
                        break
                if sample_rows >= 10000:
                    break
            bounds = compute_range_bounds(samples, list(p.sort_orders), schema,
                                          p.num_partitions)
            p = RangePartitioning(p.sort_orders, p.num_partitions, bounds)
            self.partitioning = p
        return p

    def execute(self, partition: int, ctx: ExecContext) -> Iterator[DeviceBatch]:
        with self._lock:
            if self._buckets is None:
                self._buckets = self._materialize(ctx)
        metrics = ctx.metrics_for(self.name + "_read")
        return count_output(iter(self._buckets[partition]), metrics)

    def __repr__(self):
        return (f"ShuffleExchangeOp[{type(self.partitioning).__name__} "
                f"{self.input_partitions}->{self.num_partitions}]")


class BroadcastExchangeOp(PhysicalOp):
    """Collect the child once, replay to every consumer partition
    (reference: NativeBroadcastExchangeBase collect→IPC→re-expose,
    SURVEY.md §3.4). Device batches are naturally shared on a single host;
    in SPMD execution the same batch is replicated into every shard."""

    name = "broadcast_exchange"

    def __init__(self, child: PhysicalOp, input_partitions: int = 1):
        self.child = child
        self.input_partitions = input_partitions
        self._lock = threading.Lock()
        self._collected: Optional[list[DeviceBatch]] = None

    @property
    def children(self):
        return [self.child]

    def schema(self) -> Schema:
        return self.child.schema()

    def execute(self, partition: int, ctx: ExecContext) -> Iterator[DeviceBatch]:
        with self._lock:
            if self._collected is None:
                out = []
                for in_p in range(self.input_partitions):
                    map_ctx = ExecContext(
                        partition_id=in_p, num_partitions=self.input_partitions,
                        metrics=ctx.metrics, mem_manager=ctx.mem_manager)
                    out.extend(self.child.execute(in_p, map_ctx))
                self._collected = out
        metrics = ctx.metrics_for(self.name)
        return count_output(iter(self._collected), metrics)
