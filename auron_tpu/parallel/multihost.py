"""Multi-controller (multi-host) SPMD support: the DCN-class analogue of
the reference's cross-executor shuffle transport.

The reference moves inter-node bytes through the host engine's block
store / RSS clients (SURVEY.md §5.8); the TPU-native design instead runs
ONE jax program per host in a multi-controller group
(`jax.distributed.initialize`), builds a GLOBAL mesh over every host's
devices, and lets the same `lax.all_to_all` / `psum` collectives that ride
ICI within a slice ride DCN (gRPC on CPU backends) across hosts — the
exchange code in parallel/mesh_exchange.py is byte-identical in both
settings because jax global meshes hide the fabric.

This module holds the thin host-runtime plumbing that setting needs:
process-group init, the global data mesh, and host-local ↔ global array
conversion for feeding per-host partitions into a global SPMD program.

Tested two-process-for-real in tests/test_multihost.py (each process owns
a disjoint set of virtual CPU devices; collectives cross the process
boundary), mirroring the reference's two-process RSS proof
(tests/test_rss_shuffle.py).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh, PartitionSpec as P


def init_process_group(coordinator: str, num_processes: int,
                       process_id: int,
                       local_device_count: Optional[int] = None) -> None:
    """Join the multi-controller group (reference analogue: executor
    registration with the driver's block-manager/RSS endpoints).

    Must run before any other jax call in the process. On CPU backends
    ``local_device_count`` forces the per-host virtual device count
    (the xla_force_host_platform_device_count flag) so tests can model an
    N-device host without hardware.
    """
    import os
    if local_device_count is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count="
                f"{local_device_count}").strip()
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)


def global_mesh(axis: str = "data") -> Mesh:
    """One-axis mesh over EVERY process's devices, in process order (so
    shard p of a host-local array lands on process p's devices)."""
    return Mesh(np.array(jax.devices()), (axis,))


def to_global(mesh: Mesh, host_local: np.ndarray, axis: str = "data"):
    """Per-host rows → one global sharded array: each process contributes
    its local block; the result's global shape concatenates all hosts."""
    from jax.experimental import multihost_utils
    return multihost_utils.host_local_array_to_global_array(
        host_local, mesh, P(axis))


def to_host_local(mesh: Mesh, global_arr, axis: str = "data") -> np.ndarray:
    """Global sharded array → this host's rows (the reverse boundary)."""
    from jax.experimental import multihost_utils
    return np.asarray(multihost_utils.global_array_to_host_local_array(
        global_arr, mesh, P(axis)))


def replicated_to_host(mesh: Mesh, global_arr) -> np.ndarray:
    from jax.experimental import multihost_utils
    return np.asarray(multihost_utils.global_array_to_host_local_array(
        global_arr, mesh, P()))


def exchange_host_partitions(mesh: Mesh, cols: Sequence[np.ndarray],
                             pids: np.ndarray, num_rows_local: int,
                             axis: str = "data"):
    """Cross-host hash exchange: every host feeds its local rows (padded
    to the shared per-device capacity), the global all-to-all routes each
    row to the device owning its partition id, and each host gets back
    the rows it owns.

    cols: host-local column arrays [local_cap * local_devices, ...]
    pids: int32 target GLOBAL device per row
    Returns (local_out_cols, local_out_num_rows) for THIS host.
    """
    from auron_tpu.parallel.mesh_exchange import exchange_device_batches
    n_local = len(jax.local_devices())
    per_dev = cols[0].shape[0] // n_local
    g_cols = tuple(to_global(mesh, np.asarray(c), axis) for c in cols)
    g_pids = to_global(mesh, np.asarray(pids, np.int32), axis)
    # per-device live-row counts for this host's devices
    counts = np.zeros(n_local, np.int32)
    remaining = num_rows_local
    for d in range(n_local):
        counts[d] = max(0, min(per_dev, remaining))
        remaining -= counts[d]
    g_counts = to_global(mesh, counts, axis)
    out_cols, out_nr, _quota = exchange_device_batches(
        mesh, g_cols, g_pids, g_counts)
    local_cols = [to_host_local(mesh, c, axis) for c in out_cols]
    local_nr = to_host_local(mesh, out_nr, axis)
    return local_cols, local_nr
