"""Host shuffle service: the RSS (remote-shuffle-service) tier.

The reference pushes shuffle data to Celeborn/Uniffle through
`RssPartitionWriterBase` — map tasks stream per-partition byte chunks to a
service, reducers fetch one merged stream per partition (reference:
datafusion-ext-plans/src/shuffle/rss.rs,
thirdparty/auron-celeborn-0.6/.../CelebornPartitionWriter.scala). On a TPU
pod the intra-slice exchange rides ICI all-to-all
(parallel/mesh_exchange.py); this tier is the complement for data that
exceeds slice HBM or must cross hosts without ICI: partition frames are
pushed to a service root on shared storage (NFS/FUSE-mounted object
store — the deployment substrate TPU pods already have for checkpoints),
and any host can read any partition back.

Layout (one directory per shuffle):
    root/shuffle_{id}/map_{m}.part        in-progress map output
    root/shuffle_{id}/map_{m}.data        committed map output
    root/shuffle_{id}/manifest           shuffle-level commit marker

A map output file is a sequence of length-prefixed frames grouped by
partition, followed by a trailer [per partition: run count + (offset,
length) runs] — the reference's one-data-file + partition-offset index
(sort_repartitioner.rs:151+). Commits are atomic renames at two levels:
per map output, and the shuffle-level ``manifest`` naming the exact map
count, so readers never observe partial attempts OR stale map outputs
from a previous attempt with different parallelism. Map retries overwrite
by map id (idempotent, the engine's partition-granular recovery contract,
SURVEY.md §5.3).
"""

from __future__ import annotations

import os
import struct
from typing import Iterator

_TRAILER_MAGIC = b"AURS"


class RssPartitionWriter:
    """Push-based writer for ONE map task's output across all partitions.

    Frames are buffered per partition and flushed to the map file grouped
    by partition id; `commit()` writes the offset trailer and atomically
    renames. The buffer bound makes host memory independent of map-output
    size (the push-based contract of the reference's RSS writers)."""

    def __init__(self, service: "FileShuffleService", shuffle_id: int,
                 map_id: int, num_partitions: int,
                 buffer_bytes: int = 8 << 20):
        self.service = service
        self.num_partitions = num_partitions
        self.buffer_bytes = buffer_bytes
        self._dir = service._shuffle_dir(shuffle_id)
        os.makedirs(self._dir, exist_ok=True)
        self._tmp = os.path.join(self._dir, f"map_{map_id}.part")
        self._final = os.path.join(self._dir, f"map_{map_id}.data")
        self._file = open(self._tmp, "wb")
        #: per-partition buffered frames awaiting a flush
        self._buffers: dict[int, list[bytes]] = {}
        self._buffered = 0
        #: per-partition list of (offset, length) runs already on disk
        self._runs: dict[int, list[tuple[int, int]]] = {}
        self._pos = 0
        self._committed = False

    def write(self, partition: int, frame: bytes) -> None:
        assert not self._committed
        self._buffers.setdefault(partition, []).append(frame)
        self._buffered += len(frame)
        if self._buffered >= self.buffer_bytes:
            self._flush()

    def _flush(self) -> None:
        for p in sorted(self._buffers):
            frames = self._buffers[p]
            start = self._pos
            for fr in frames:
                self._file.write(struct.pack("<I", len(fr)))
                self._file.write(fr)
                self._pos += 4 + len(fr)
            self._runs.setdefault(p, []).append((start, self._pos - start))
        self._buffers = {}
        self._buffered = 0

    def commit(self) -> None:
        """Flush, append the partition-run trailer, atomically publish."""
        self._flush()
        trailer_start = self._pos
        # trailer: per partition, run count then (offset, length) pairs
        for p in range(self.num_partitions):
            runs = self._runs.get(p, [])
            self._file.write(struct.pack("<I", len(runs)))
            for off, ln in runs:
                self._file.write(struct.pack("<QQ", off, ln))
        self._file.write(struct.pack("<QI", trailer_start,
                                     self.num_partitions))
        self._file.write(_TRAILER_MAGIC)
        self._file.close()
        os.replace(self._tmp, self._final)   # atomic commit
        self._committed = True

    def abort(self) -> None:
        if not self._committed:
            try:
                self._file.close()
                os.unlink(self._tmp)
            except OSError:
                pass


class FileShuffleService:
    """Shared-storage shuffle service. Each host creates its own instance
    over the same root; no coordination beyond the filesystem's atomic
    renames is needed."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _shuffle_dir(self, shuffle_id: int) -> str:
        return os.path.join(self.root, f"shuffle_{shuffle_id}")

    def partition_writer(self, shuffle_id: int, map_id: int,
                         num_partitions: int,
                         buffer_bytes: int = 8 << 20) -> RssPartitionWriter:
        return RssPartitionWriter(self, shuffle_id, map_id, num_partitions,
                                  buffer_bytes)

    # -- shuffle-level commit ------------------------------------------------

    def begin_shuffle(self, shuffle_id: int) -> None:
        """Invalidate any previous attempt: a re-planned stage (different
        map parallelism, AQE) must not leave stale map outputs visible."""
        d = self._shuffle_dir(shuffle_id)
        try:
            os.unlink(os.path.join(d, "manifest"))
        except OSError:
            pass

    def commit_shuffle(self, shuffle_id: int, num_maps: int) -> None:
        d = self._shuffle_dir(shuffle_id)
        os.makedirs(d, exist_ok=True)
        tmp = os.path.join(d, "manifest.part")
        with open(tmp, "w") as f:
            f.write(str(num_maps))
        os.replace(tmp, os.path.join(d, "manifest"))

    def map_outputs(self, shuffle_id: int) -> list[str]:
        """Committed map output files present on storage (diagnostics;
        readers use :meth:`committed_maps`, which honors the manifest)."""
        d = self._shuffle_dir(shuffle_id)
        if not os.path.isdir(d):
            return []
        return sorted(os.path.join(d, f) for f in os.listdir(d)
                      if f.endswith(".data"))

    def committed_maps(self, shuffle_id: int) -> list[str]:
        """Paths of EXACTLY the map outputs the manifest names; [] when the
        shuffle is not (yet) committed."""
        d = self._shuffle_dir(shuffle_id)
        try:
            with open(os.path.join(d, "manifest")) as f:
                num_maps = int(f.read().strip())
        except (OSError, ValueError):
            return []
        return [os.path.join(d, f"map_{m}.data") for m in range(num_maps)]

    # -- read side ------------------------------------------------------------

    def partition_frames(self, shuffle_id: int,
                         partition: int) -> Iterator[bytes]:
        """All committed map outputs' frames for one partition, reading
        only that partition's byte runs (offset-indexed fetch). One read
        for the whole trailer + one per run — no per-entry round trips
        (matters on NFS/FUSE substrates)."""
        for path in self.committed_maps(shuffle_id):
            with open(path, "rb") as f:
                # fixed footer: <QI trailer_start num_partitions> + magic
                foot = 12 + len(_TRAILER_MAGIC)
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(size - foot)
                tail = f.read(foot)
                assert tail[-4:] == _TRAILER_MAGIC, f"corrupt map output {path}"
                trailer_start, num_parts = struct.unpack("<QI", tail[:12])
                if partition >= num_parts:
                    continue
                f.seek(trailer_start)
                trailer = f.read(size - foot - trailer_start)
                pos = 0
                runs = []
                for p in range(num_parts):
                    (nruns,) = struct.unpack_from("<I", trailer, pos)
                    pos += 4
                    if p == partition:
                        runs = [struct.unpack_from("<QQ", trailer,
                                                   pos + 16 * r)
                                for r in range(nruns)]
                        break
                    pos += 16 * nruns
                for off, ln in runs:
                    f.seek(off)
                    blob = f.read(ln)
                    bpos = 0
                    while bpos < ln:
                        (flen,) = struct.unpack_from("<I", blob, bpos)
                        bpos += 4
                        yield blob[bpos:bpos + flen]
                        bpos += flen

    def delete_shuffle(self, shuffle_id: int) -> None:
        import shutil
        shutil.rmtree(self._shuffle_dir(shuffle_id), ignore_errors=True)
