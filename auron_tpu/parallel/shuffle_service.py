"""Host shuffle service: the RSS (remote-shuffle-service) tier.

The reference pushes shuffle data to Celeborn/Uniffle through
`RssPartitionWriterBase` — map tasks stream per-partition byte chunks to a
service, reducers fetch one merged stream per partition (reference:
datafusion-ext-plans/src/shuffle/rss.rs,
thirdparty/auron-celeborn-0.6/.../CelebornPartitionWriter.scala). On a TPU
pod the intra-slice exchange rides ICI all-to-all
(parallel/mesh_exchange.py); this tier is the complement for data that
exceeds slice HBM or must cross hosts without ICI: partition frames are
pushed to a service root on shared storage (NFS/FUSE-mounted object
store — the deployment substrate TPU pods already have for checkpoints),
and any host can read any partition back.

Layout (one directory per shuffle):
    root/shuffle_{id}/map_{m}.part        in-progress map output
    root/shuffle_{id}/map_{m}.data        committed map output
    root/shuffle_{id}/manifest           shuffle-level commit marker

A map output file (format v2, magic ``AUR2``) is a sequence of frame
records grouped by partition — each record ``<u32 len><u32 crc>`` +
frame bytes — followed by a trailer [per partition: run count +
(offset, length) runs] and a footer naming the trailer offset, the
partition count, the trailer's own CRC and the checksum algorithm id
(utils/checksum.py). Every fetch verifies the frame CRC before
deserializing: a flipped byte on storage surfaces as
``errors.ShuffleCorruption`` carrying the map id, which the RSS exchange
recovers by invalidating that map output and recomputing the map task —
never a blind reducer retry over the same corrupt bytes, never silently
wrong rows. v1 files (magic ``AURS``, no CRCs) are *rejected* with the
same corruption error, not misread.

Commits are atomic renames at two levels: per map output, and the
shuffle-level ``manifest`` naming the exact map count, so readers never
observe partial attempts OR stale map outputs from a previous attempt
with different parallelism. Map retries overwrite by map id (idempotent,
the engine's partition-granular recovery contract, SURVEY.md §5.3).
``commit_shuffle`` also sweeps orphaned ``.part`` files — an aborted map
attempt must not leak storage.

Fault-injection sites (runtime/faults.py): ``rss.write`` (buffered push
+ on-disk corruption after the CRC — durable bit rot), ``rss.flush``,
``rss.commit``, ``rss.fetch`` (fetch failure + in-flight corruption).
"""

from __future__ import annotations

import io
import os
import struct
import threading
from typing import Iterator, Optional

from auron_tpu import errors
from auron_tpu.utils import checksum as cks

#: v2 footer magic; v1 (``AURS``) files are rejected as corrupt
_TRAILER_MAGIC = b"AUR2"
_V1_MAGIC = b"AURS"
#: footer: <Q trailer_start><I num_partitions><I trailer_crc><B algo>
_FOOTER = struct.Struct("<QIIB")
#: per-frame record header (shared with the spill tier, utils/checksum.py)
_FRAME_HDR = cks.FRAME_HDR


class RssPartitionWriter:
    """Push-based writer for ONE map task's output across all partitions.

    Frames are buffered per partition and flushed to the map file grouped
    by partition id; `commit()` writes the offset trailer and atomically
    renames. The buffer bound makes host memory independent of map-output
    size (the push-based contract of the reference's RSS writers).

    Context-manager support guarantees no exception path leaves a
    ``.part`` file behind: exiting the ``with`` block without having
    committed — exception or not — aborts (abort after commit is a
    no-op)."""

    def __init__(self, service: "FileShuffleService", shuffle_id: int,
                 map_id: int, num_partitions: int,
                 buffer_bytes: int = 8 << 20):
        self.service = service
        self.shuffle_id = shuffle_id
        self.map_id = map_id
        self.num_partitions = num_partitions
        self.buffer_bytes = buffer_bytes
        self._algo = cks.write_algo()
        self._dir = service._shuffle_dir(shuffle_id)
        os.makedirs(self._dir, exist_ok=True)
        self._tmp = os.path.join(self._dir, f"map_{map_id}.part")
        self._final = os.path.join(self._dir, f"map_{map_id}.data")
        service._write_owner(self._dir)
        self._file = open(self._tmp, "wb")
        #: per-partition buffered frames awaiting a flush
        self._buffers: dict[int, list[bytes]] = {}
        self._buffered = 0
        #: per-partition list of (offset, length) runs already on disk
        self._runs: dict[int, list[tuple[int, int]]] = {}
        self._pos = 0
        self._committed = False
        #: commit artifacts the query journal records (runtime/journal):
        #: total committed file size and the trailer's CRC — the cheap
        #: resume-time validity check that needs only the footer
        self.committed_size = 0
        self.trailer_crc = 0

    def __enter__(self) -> "RssPartitionWriter":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        # uncommitted on exit — exception unwind OR a caller that never
        # reached commit() — is an abandoned attempt: abort
        self.abort()
        return False

    def write(self, partition: int, frame: bytes) -> None:
        assert not self._committed
        from auron_tpu.runtime import faults
        faults.maybe_fail("rss.write", errors.RssUnavailableError)
        # CRC here, not at flush time: the producer just serialized the
        # frame, so the bytes are cache-hot — the hardware CRC runs at
        # its warm rate instead of re-streaming a cold flush buffer
        crc = cks.compute(frame, self._algo)
        self._buffers.setdefault(partition, []).append((frame, crc))
        self._buffered += len(frame)
        if self._buffered >= self.buffer_bytes:
            self._flush()

    def _flush(self) -> None:
        from auron_tpu.obs import trace
        from auron_tpu.runtime import faults
        faults.maybe_fail("rss.flush", errors.RssUnavailableError)
        with trace.span("shuffle", "rss.flush", shuffle=self.shuffle_id,
                        map=self.map_id, bytes=self._buffered):
            self._flush_inner()

    def _flush_inner(self) -> None:
        from auron_tpu.runtime import faults
        for p in sorted(self._buffers):
            frames = self._buffers[p]
            start = self._pos
            for fr, crc in frames:
                # corruption injects AFTER the CRC over the clean bytes:
                # durable bit rot is the integrity layer's problem
                payload = faults.maybe_corrupt("rss.write", fr)
                self._file.write(_FRAME_HDR.pack(len(fr), crc))
                self._file.write(payload)
                self._pos += _FRAME_HDR.size + len(fr)
            self._runs.setdefault(p, []).append((start, self._pos - start))
        self._buffers = {}
        self._buffered = 0

    def commit(self) -> None:
        """Flush, append the partition-run trailer, atomically publish."""
        from auron_tpu.obs import trace
        from auron_tpu.runtime import faults
        faults.maybe_fail("rss.commit", errors.RssUnavailableError)
        with trace.span("shuffle", "rss.commit", shuffle=self.shuffle_id,
                        map=self.map_id, bytes=self._pos):
            self._commit_inner()

    def _commit_inner(self) -> None:
        self._flush()
        trailer_start = self._pos
        # trailer: per partition, run count then (offset, length) pairs —
        # assembled in memory so its own CRC rides the footer
        trailer = io.BytesIO()
        for p in range(self.num_partitions):
            runs = self._runs.get(p, [])
            trailer.write(struct.pack("<I", len(runs)))
            for off, ln in runs:
                trailer.write(struct.pack("<QQ", off, ln))
        tbytes = trailer.getvalue()
        self._file.write(tbytes)
        tcrc = cks.compute(tbytes, self._algo)
        self._file.write(_FOOTER.pack(trailer_start, self.num_partitions,
                                      tcrc, self._algo))
        self._file.write(_TRAILER_MAGIC)
        self._file.close()
        os.replace(self._tmp, self._final)   # atomic commit
        self._committed = True
        self.committed_size = (trailer_start + len(tbytes)
                               + _FOOTER.size + len(_TRAILER_MAGIC))
        self.trailer_crc = tcrc

    def abort(self) -> None:
        if not self._committed:
            try:
                self._file.close()
                os.unlink(self._tmp)
            except OSError:
                pass


#: roots already startup-swept by THIS process (one sweep per root per
#: process: the sweep targets a crashed PREDECESSOR's leftovers, and a
#: root is typically re-opened many times per query)
_SWEPT_ROOTS: set = set()
_SWEPT_LOCK = threading.Lock()


class FileShuffleService:
    """Shared-storage shuffle service. Each host creates its own instance
    over the same root; no coordination beyond the filesystem's atomic
    renames is needed.

    Every shuffle directory carries a ``.owner`` tag
    (``utils/liveness``: host:pid:epoch of the writing process), and
    service construction runs a STARTUP SWEEP over the root: a crashed
    predecessor's ``.part`` files are removed, and — in the default
    ``orphan_sweep=True`` mode — its whole UNCOMMITTED shuffle
    directories too (no manifest = no reader can ever observe them).
    ``orphan_sweep="parts"`` restricts the sweep to ``.part`` files
    (journal-managed roots: the journal's own sweep owns whole-dir
    lifecycle there, because a dead process's partially-committed maps
    are exactly what resume reuses). Liveness is pid+epoch checked and
    host-scoped, so a live writer — this process included — is never
    swept; unowned directories (pre-sweep format) are left alone."""

    def __init__(self, root: str, orphan_sweep=True):
        self.root = root
        os.makedirs(root, exist_ok=True)
        #: shuffle dirs this service already owner-stamped (one .owner
        #: read + liveness probe per dir, not per map writer)
        self._stamped: set = set()
        self._stamped_lock = threading.Lock()
        if orphan_sweep:
            # full-mode roots are memoized process-wide (a root is
            # re-opened many times per query and the sweep targets a
            # crashed PREDECESSOR); parts-mode roots are per-query
            # journal run dirs — unique per query, so memoizing them
            # would grow the set forever, and the liveness-gated .part
            # sweep is repeat-safe and near-free on a fresh dir
            first = True
            if orphan_sweep is True:
                with _SWEPT_LOCK:
                    first = root not in _SWEPT_ROOTS
                    _SWEPT_ROOTS.add(root)
            if first:
                self.sweep_dead_owners(
                    remove_uncommitted=(orphan_sweep is True))

    def _shuffle_dir(self, shuffle_id: int) -> str:
        return os.path.join(self.root, f"shuffle_{shuffle_id}")

    def _write_owner(self, shuffle_dir: str) -> None:
        """Stamp (or adopt) the directory's owner tag: written when
        absent or when the recorded owner is provably dead (a resumed
        query adopting a crashed predecessor's partial shuffle).  Memo
        per (service, dir): a wide exchange opens one writer per map —
        one .owner read + liveness probe per DIR, not per map."""
        from auron_tpu.utils import liveness
        with self._stamped_lock:
            if shuffle_dir in self._stamped:
                return
            self._stamped.add(shuffle_dir)
        path = os.path.join(shuffle_dir, ".owner")
        try:
            with open(path) as f:
                if liveness.is_live(f.read().strip()):
                    return
        except OSError:
            pass
        try:
            with open(path, "w") as f:
                f.write(liveness.own_tag())
        except OSError:   # pragma: no cover - best-effort tag
            pass

    def sweep_dead_owners(self, remove_uncommitted: bool = True) -> int:
        """The startup sweep (see class docstring); returns artifacts
        removed, counted on ``auron_rss_orphans_swept_total``."""
        import shutil

        from auron_tpu.utils import liveness
        removed = 0
        try:
            entries = sorted(os.listdir(self.root))
        except OSError:
            return 0
        for name in entries:
            d = os.path.join(self.root, name)
            if not (name.startswith("shuffle_") and os.path.isdir(d)):
                continue
            try:
                with open(os.path.join(d, ".owner")) as f:
                    owner = f.read().strip()
            except OSError:
                continue   # unowned (pre-sweep format): conservative
            if liveness.is_live(owner):
                continue
            committed = os.path.exists(os.path.join(d, "manifest"))
            if remove_uncommitted and not committed:
                shutil.rmtree(d, ignore_errors=True)
                removed += 1
                continue
            for f in os.listdir(d):
                if f.endswith(".part"):
                    try:
                        os.unlink(os.path.join(d, f))
                        removed += 1
                    except OSError:
                        pass
        liveness.note_swept("auron_rss_orphans_swept_total", removed,
                            self.root, "RSS")
        return removed

    def partition_writer(self, shuffle_id: int, map_id: int,
                         num_partitions: int,
                         buffer_bytes: int = 8 << 20) -> RssPartitionWriter:
        return RssPartitionWriter(self, shuffle_id, map_id, num_partitions,
                                  buffer_bytes)

    # -- shuffle-level commit ------------------------------------------------

    def begin_shuffle(self, shuffle_id: int) -> None:
        """Invalidate any previous attempt: a re-planned stage (different
        map parallelism, AQE) must not leave stale map outputs visible."""
        d = self._shuffle_dir(shuffle_id)
        try:
            os.unlink(os.path.join(d, "manifest"))
        except OSError:
            pass

    def commit_shuffle(self, shuffle_id: int, num_maps: int) -> None:
        d = self._shuffle_dir(shuffle_id)
        os.makedirs(d, exist_ok=True)
        self._write_owner(d)
        tmp = os.path.join(d, "manifest.part")
        with open(tmp, "w") as f:
            f.write(str(num_maps))
        os.replace(tmp, os.path.join(d, "manifest"))
        # committed shuffles carry no in-progress files: sweep orphans
        # from aborted/crashed map attempts (the .part leak audit)
        self.sweep_parts(shuffle_id)

    def sweep_parts(self, shuffle_id: int) -> int:
        """Remove orphaned ``.part`` files (crashed map attempts that
        never reached abort()); returns how many were removed."""
        d = self._shuffle_dir(shuffle_id)
        removed = 0
        if not os.path.isdir(d):
            return removed
        for f in os.listdir(d):
            if f.endswith(".part"):
                try:
                    os.unlink(os.path.join(d, f))
                    removed += 1
                except OSError:
                    pass
        return removed

    def invalidate_map(self, shuffle_id: int, map_id: int) -> None:
        """Drop ONE committed map output (corruption recovery: the map
        task recomputes and re-commits under the same id; the manifest —
        which names only the map COUNT — stays valid throughout)."""
        try:
            os.unlink(os.path.join(self._shuffle_dir(shuffle_id),
                                   f"map_{map_id}.data"))
        except OSError:
            pass

    def map_outputs(self, shuffle_id: int) -> list[str]:
        """Committed map output files present on storage (diagnostics;
        readers use :meth:`committed_maps`, which honors the manifest)."""
        d = self._shuffle_dir(shuffle_id)
        if not os.path.isdir(d):
            return []
        return sorted(os.path.join(d, f) for f in os.listdir(d)
                      if f.endswith(".data"))

    def committed_maps(self, shuffle_id: int) -> list[str]:
        """Paths of EXACTLY the map outputs the manifest names; [] when the
        shuffle is not (yet) committed."""
        d = self._shuffle_dir(shuffle_id)
        num_maps = self.manifest_maps(shuffle_id)
        return [os.path.join(d, f"map_{m}.data") for m in range(num_maps)]

    def manifest_maps(self, shuffle_id: int) -> int:
        """Map count the shuffle-level manifest names; 0 when the
        shuffle is not (yet) committed."""
        try:
            with open(os.path.join(self._shuffle_dir(shuffle_id),
                                   "manifest")) as f:
                return int(f.read().strip())
        except (OSError, ValueError):
            return 0

    def map_output_stat(self, shuffle_id: int,
                        map_id: int) -> Optional[tuple[int, int]]:
        """(size, trailer_crc) of one committed map output — the query
        journal's cheap resume-time validity probe (reads only the
        footer, never the frames; frame CRCs still verify on every
        fetch).  None when the file is missing or its footer is not a
        valid v2 trailer."""
        path = os.path.join(self._shuffle_dir(shuffle_id),
                            f"map_{map_id}.data")
        foot = _FOOTER.size + len(_TRAILER_MAGIC)
        try:
            with open(path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                if size < foot:
                    return None
                f.seek(size - foot)
                tail = f.read(foot)
        except OSError:
            return None
        if tail[-4:] != _TRAILER_MAGIC:
            return None
        _start, _nparts, trailer_crc, _algo = \
            _FOOTER.unpack(tail[:_FOOTER.size])
        return size, trailer_crc

    # -- read side ------------------------------------------------------------

    def partition_frames(self, shuffle_id: int,
                         partition: int) -> Iterator[bytes]:
        """All committed map outputs' frames for one partition, reading
        only that partition's byte runs (offset-indexed fetch). One read
        for the whole trailer + one per run — no per-entry round trips
        (matters on NFS/FUSE substrates). Every frame is CRC-verified;
        a mismatch raises ShuffleCorruption naming the map."""
        for map_id, path in enumerate(self.committed_maps(shuffle_id)):
            yield from self.map_partition_frames(shuffle_id, map_id,
                                                 partition)

    def map_partition_frames(self, shuffle_id: int, map_id: int,
                             partition: int) -> list[bytes]:
        """Verified frames of ONE committed map output for one partition
        — the recovery granularity: the RSS exchange fetches map by map
        so a ShuffleCorruption can recompute exactly the corrupt map
        without re-yielding earlier maps' data."""
        from auron_tpu.obs import trace
        from auron_tpu.runtime import faults
        path = os.path.join(self._shuffle_dir(shuffle_id),
                            f"map_{map_id}.data")
        with trace.span("shuffle", "rss.fetch", shuffle=shuffle_id,
                        map=map_id, partition=partition) as sp:
            frames = self._map_partition_frames(shuffle_id, map_id,
                                                partition, path)
            sp.set(frames=len(frames),
                   bytes=sum(len(f) for f in frames))
            return frames

    def _map_partition_frames(self, shuffle_id: int, map_id: int,
                              partition: int, path: str) -> list[bytes]:
        from auron_tpu.runtime import faults
        faults.maybe_fail("rss.fetch", errors.RssUnavailableError)

        def corrupt(msg):
            return errors.ShuffleCorruption(
                f"{msg} (shuffle {shuffle_id} map {map_id}: {path})",
                shuffle_id=shuffle_id, map_id=map_id, path=path,
                site="rss.fetch")

        frames: list[bytes] = []
        try:
            f = open(path, "rb")
        except FileNotFoundError as e:
            # a committed map output that is GONE (invalidated by a
            # corruption recovery that died before re-committing, or
            # external deletion) is recovered exactly like a corrupt
            # one: map recompute rewrites it — never an unclassified
            # OSError out of the fetch path
            raise corrupt("map output missing from storage") from e
        with f:
            foot = _FOOTER.size + len(_TRAILER_MAGIC)
            f.seek(0, os.SEEK_END)
            size = f.tell()
            if size < foot:
                raise corrupt("map output truncated below footer size")
            f.seek(size - foot)
            tail = f.read(foot)
            if tail[-4:] != _TRAILER_MAGIC:
                if tail[-4:] == _V1_MAGIC:
                    raise corrupt("unchecksummed v1 map output rejected "
                                  "(recompute rewrites it at v2)")
                raise corrupt("bad map-output trailer magic")
            trailer_start, num_parts, trailer_crc, algo = \
                _FOOTER.unpack(tail[:_FOOTER.size])
            if partition >= num_parts:
                return frames
            if trailer_start > size - foot:
                raise corrupt("map-output trailer offset out of range")
            f.seek(trailer_start)
            trailer = f.read(size - foot - trailer_start)
            cks.verify_or_raise(trailer, trailer_crc, algo, corrupt,
                                what="map-output trailer")
            pos = 0
            runs = []
            try:
                for p in range(num_parts):
                    (nruns,) = struct.unpack_from("<I", trailer, pos)
                    pos += 4
                    if p == partition:
                        runs = [struct.unpack_from("<QQ", trailer,
                                                   pos + 16 * r)
                                for r in range(nruns)]
                        break
                    pos += 16 * nruns
            except struct.error as e:
                raise corrupt("map-output trailer truncated") from e
            for off, ln in runs:
                f.seek(off)
                blob = f.read(ln)
                bpos = 0
                while bpos < ln:
                    try:
                        flen, crc = _FRAME_HDR.unpack_from(blob, bpos)
                    except struct.error as e:
                        raise corrupt("frame header truncated") from e
                    bpos += _FRAME_HDR.size
                    frame = blob[bpos:bpos + flen]
                    if len(frame) != flen:
                        raise corrupt("frame body truncated")
                    frame = faults.maybe_corrupt("rss.fetch", frame)
                    cks.verify_or_raise(frame, crc, algo, corrupt)
                    frames.append(frame)
                    bpos += flen
        return frames

    def delete_shuffle(self, shuffle_id: int) -> None:
        import shutil
        shutil.rmtree(self._shuffle_dir(shuffle_id), ignore_errors=True)
