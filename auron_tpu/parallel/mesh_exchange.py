"""SPMD shuffle over a jax.sharding.Mesh: the ICI all-to-all exchange.

This is the TPU-native replacement for the reference's file-based shuffle
(SURVEY.md §5.8): instead of compacted spill files fetched through the block
store, each mesh device buckets its rows by target partition *on device* and
one `lax.all_to_all` moves every bucket to its owner across ICI links in a
single collective. Static shapes are preserved by a per-(src,dst) row quota:
send buffers are [n_dev, quota, ...]; overflow (a bucket exceeding quota) is
reported per-device as the observed max bucket size so the host can rerun
the exchange ONCE at exactly the needed quota (rounded up to a power of two
so escalations land on a small reusable set of compiled programs) — same
contract as the engine's other capacity re-bucketing, without the
compile-per-doubling churn of a blind retry loop.

Works identically on a virtual CPU mesh (tests / driver dry-run) and a real
TPU slice; on multi-host deployments the same code spans hosts because jax
global meshes hide DCN vs ICI (collectives ride the fastest available
fabric).
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from auron_tpu.runtime.programs import program_cache

try:
    from jax import shard_map
except ImportError:          # older jax exposes it under experimental
    from jax.experimental.shard_map import shard_map


def make_mesh(num_devices: int | None = None, axis: str = "data") -> Mesh:
    devs = jax.devices()
    n = num_devices or len(devs)
    return Mesh(np.array(devs[:n]), (axis,))


# ---------------------------------------------------------------------------
# collective boundary: fault site + device-loss classification
# ---------------------------------------------------------------------------

def round_fault_check(cancel=None) -> None:
    """The per-round injection site of the mesh fault domain
    (``mesh.all_to_all``): fired once before every all-to-all round.
    ``io_error`` raises the classified :class:`errors.MeshUnavailable`
    (simulated device loss — the exchange's demotion handler routes the
    remaining rounds host-side), ``fatal`` an InjectedFatalError
    carrying this site (same demotion: a deterministically failing mesh
    is recovered by routing AROUND it), ``hang`` a straggling chip (the
    sleep lands inside the round guard's timer, so the straggler
    defense sees it)."""
    from auron_tpu import errors
    from auron_tpu.runtime import faults
    faults.maybe_fail("mesh.all_to_all", errors.MeshUnavailable,
                      cancel=cancel)


def classify_collective(e: BaseException) -> BaseException:
    """Classification at the collective boundary: a bare RuntimeError
    crossing out of a shard_map program routes through
    ``errors.classify_runtime``, whose device-loss signatures become
    :class:`errors.MeshUnavailable` — the verdict the demotion ladder
    keys on. Already-classified errors pass through unchanged."""
    from auron_tpu import errors
    if isinstance(e, errors.AuronError) or not isinstance(e, RuntimeError):
        return e
    return errors.classify_runtime(e)


def is_mesh_loss(e: BaseException) -> bool:
    """True when ``e`` is the mesh fault domain's DEMOTABLE class: a
    classified device loss (MeshUnavailable, injected or real) or any
    classified error raised AT a mesh fault site (an injected ``fatal``
    at ``mesh.all_to_all`` carries the site — a deterministic failure
    of the mesh plane is recovered by demotion, not by retrying the
    same collective). Errors from the map-side CHILD operators (e.g.
    ``device.compute`` faults inside the drive loop) are NOT mesh
    losses: they keep their own recovery semantics (task retry /
    surfaced verdict)."""
    from auron_tpu import errors
    if isinstance(e, errors.MeshUnavailable):
        return True
    return (isinstance(e, errors.AuronError)
            and (getattr(e, "site", None) or "").startswith("mesh."))


# ---------------------------------------------------------------------------
# sharded stage-exchange program (the SPMD execution plane's workhorse)
# ---------------------------------------------------------------------------

#: central compile site for the sharded stage programs: the fused member
#: chain (when the exchange folded one), the partition-id compute, the
#: sort-by-pid split and the all-to-all collective in ONE shard_map
#: program — the whole map side of a shuffle runs partition-parallel
#: across the mesh with no host round-trip between its steps
from auron_tpu.runtime import programs as _programs

_STAGE_EXCHANGE_PROGRAMS = _programs.register(
    _programs.ProgramCache("parallel.mesh_exchange.stage", maxsize=128))


def stage_exchange_program(mesh: Mesh, axis: str, n_dev: int,
                           frag_keys: tuple, part_key: tuple,
                           in_schema, out_schema, capacity: int,
                           quota: int, fragments, part_exprs,
                           combine=None, combine_sig=None):
    """Central-registry lookup of the sharded stage-exchange program for
    one (chain signature, hash keys, schema, capacity, quota) class.
    Returns ``(kernel, built)``.

    The program NEVER donates its inputs: a bucket overflowing the row
    quota triggers the one-shot host-side re-run at the exact needed
    pow2 quota (the ``exchange_device_batches`` contract), and a donated
    input would be poisoned for that re-run — the donate sweep from the
    pipelined-execution work must not reach across the exchange
    (``yields_owned_batches`` notwithstanding).

    ``combine`` (ops/agg.AggOp.build_combine_stage, keyed by
    ``combine_sig``) is the map-side combine fold: each shard merges its
    round's groups (or re-lays rows out in partial-state form) BETWEEN
    the chain and the partition-id compute, so what crosses
    ``lax.all_to_all`` is per-shard GROUPS — fewer live rows through the
    collective, the cheapest scale-out win available. Stateless, so the
    escalation re-run and the demoted host path replay it exactly.

    Kernel signature (all global, batch-dim sharded on ``axis`` unless
    noted)::

        kernel(columns, num_rows, carries) ->
            (out_columns, recv_counts, out_num_rows, global_max, carries'
             [, combine_rows_in])

    - ``columns``: the stacked input batch's column pytree, every leaf
      ``[n_dev * capacity, ...]`` (shard i = map partition i's rows);
    - ``num_rows``: ``int32[n_dev]`` live rows per shard;
    - ``carries``: ``int64[n_dev, n_frags]`` per-shard member carries;
    - ``out_columns``: received rows, shard p = reducer partition p; row
      layout per shard is ``[src * quota + r]`` (source-major, original
      row order within a source — NOT compacted, so the reducer can
      slice per source and preserve the host path's map-major order);
    - ``recv_counts``: ``int32[n_dev * n_dev]``, shard p's row = rows
      received from each source;
    - ``global_max``: REPLICATED int32 — the global largest bucket, the
      host's one output-boundary readback: rows were dropped iff it
      exceeds ``quota``, and its value is the exact quota the single
      re-run needs;
    - ``combine_rows_in``: ``int32[n_dev]`` pre-combine live rows per
      shard, present only when a combine stage is folded — read in the
      same output-boundary fence (telemetry adds no sync point).
    """
    key = (frag_keys, part_key, in_schema, out_schema, n_dev, capacity,
           quota, axis, combine_sig)

    def build():
        from auron_tpu.columnar.batch import DeviceBatch, gather_batch
        from auron_tpu.exprs.eval import EvalContext, evaluate
        from auron_tpu.ops import hashing
        from auron_tpu.ops.fused import sharded_fragment_chain
        chain = sharded_fragment_chain(fragments) if fragments else None
        n_frags = len(fragments)

        def local_fn(columns, num_rows, carries):
            nr = num_rows[0]
            batch = DeviceBatch(columns, nr)
            # this device IS its map partition (maps assigned in order)
            pid_dev = lax.axis_index(axis).astype(jnp.int32)
            if chain is not None:
                b, new_carry = chain(batch, pid_dev, carries[0])
            else:
                b, new_carry = batch, jnp.zeros((n_frags,), jnp.int64)
            comb_in = None
            if combine is not None:
                # map-side combine: this shard's round collapses to its
                # groups before any row is offered to the collective
                b, comb_in = combine(b)
            # partition ids on the chain output (Spark-exact pmod
            # murmur3 — the HashPartitioning contract)
            ctx = EvalContext()
            cols = [evaluate(e, b, out_schema, ctx).col
                    for e in part_exprs]
            h = hashing.murmur3_columns(cols, b.capacity,
                                        hashing.SPARK_SHUFFLE_SEED)
            nn = jnp.int32(n_dev)
            pids = ((h % nn) + nn) % nn
            # stable sort-by-pid split (the buffered_data.rs compaction,
            # exactly _split_body's shape — inlined because the bucket
            # scatter below needs the sorted pid column too)
            live = b.row_mask()
            pid_key = jnp.where(live, pids, nn)
            perm = jnp.argsort(pid_key, stable=True)
            sorted_b = gather_batch(b, perm, b.num_rows)
            sorted_pid = pid_key[perm]
            counts = jax.ops.segment_sum(
                live.astype(jnp.int32), jnp.clip(pid_key, 0, n_dev),
                num_segments=n_dev + 1)[:n_dev]
            offsets = jnp.cumsum(counts) - counts   # exclusive
            max_count = jnp.max(counts).astype(jnp.int32)
            cap_b = sorted_b.capacity
            pos = jnp.arange(cap_b, dtype=jnp.int32)
            tgt = jnp.clip(sorted_pid, 0, n_dev - 1)
            slot = pos - offsets[tgt]
            in_quota = (sorted_pid < nn) & (slot < quota)
            flat_slot = jnp.where(in_quota, tgt * quota + slot,
                                  n_dev * quota)
            send_counts = jnp.minimum(counts, quota)

            def send_recv(leaf):
                buf = jnp.zeros((n_dev * quota,) + leaf.shape[1:],
                                leaf.dtype)
                buf = buf.at[flat_slot].set(leaf, mode="drop")
                buf = buf.reshape((n_dev, quota) + leaf.shape[1:])
                recv = lax.all_to_all(buf, axis, split_axis=0,
                                      concat_axis=0, tiled=False)
                return recv.reshape((n_dev * quota,) + leaf.shape[1:])

            out_cols = jax.tree_util.tree_map(send_recv, sorted_b.columns)
            recv_counts = lax.all_to_all(send_counts, axis, split_axis=0,
                                         concat_axis=0, tiled=True)
            out_nr = jnp.sum(recv_counts).astype(jnp.int32)
            gmax = lax.pmax(max_count, axis)
            if comb_in is not None:
                return (out_cols, recv_counts, out_nr[None], gmax,
                        new_carry[None, :], comb_in[None])
            return (out_cols, recv_counts, out_nr[None], gmax,
                    new_carry[None, :])

        in_specs = (P(axis), P(axis), P(axis, None))
        out_specs = (P(axis), P(axis), P(axis), P(), P(axis, None))
        if combine is not None:
            out_specs = out_specs + (P(axis),)
        # donation deliberately OFF (see docstring): programs.jit with
        # no donate_argnums, on every backend
        return _programs.jit(shard_map(local_fn, mesh=mesh,
                                       in_specs=in_specs,
                                       out_specs=out_specs))

    return _STAGE_EXCHANGE_PROGRAMS.get_or_build(key, build)


@program_cache("parallel.mesh_exchange.exchange", maxsize=64)
def _exchange_fn(mesh: Mesh, n_cols: int, quota: int, axis: str):
    """Builds the jitted SPMD exchange for a given column arity and quota.

    Inputs (global, sharded on axis 0):
      cols:     tuple of arrays [n_dev*cap, ...]
      pids:     int32[n_dev*cap]  target partition per row
      num_rows: int32[n_dev]     live row count per shard
    Outputs:
      out_cols:     tuple of arrays [n_dev * (n_dev*quota), ...]
      out_num_rows: int32[n_dev]
      max_count:    replicated int32 scalar — the GLOBAL largest bucket
                    (pmax over the axis), readable on every controller of
                    a multi-host run; rows were dropped iff it exceeds
                    quota, and the value tells the host the exact quota a
                    single retry needs

    Program builds are countable via ``_exchange_fn.cache_info().misses``;
    tests assert skew escalation stays within a 2-compile budget.
    """
    n_dev = mesh.shape[axis]

    def local_fn(cols, pids, num_rows):
        cap = pids.shape[0]
        nr = num_rows[0]
        live = jnp.arange(cap, dtype=jnp.int32) < nr
        pid_key = jnp.where(live, pids, n_dev)
        perm = jnp.argsort(pid_key, stable=True)
        sorted_pid = pid_key[perm]

        ones = live.astype(jnp.int32)
        counts = jax.ops.segment_sum(ones, pid_key, num_segments=n_dev + 1)[:n_dev]
        offsets = jnp.cumsum(counts) - counts  # exclusive
        max_count = jnp.max(counts).astype(jnp.int32)

        pos = jnp.arange(cap, dtype=jnp.int32)
        tgt = jnp.clip(sorted_pid, 0, n_dev - 1)
        slot = pos - offsets[tgt]
        in_quota = (sorted_pid < n_dev) & (slot < quota)
        flat_slot = jnp.where(in_quota, tgt * quota + slot, n_dev * quota)

        send_counts = jnp.minimum(counts, quota)

        out_cols = []
        for c in cols:
            c_sorted = c[perm]
            buf_shape = (n_dev * quota,) + c.shape[1:]
            buf = jnp.zeros(buf_shape, c.dtype)
            buf = buf.at[flat_slot].set(c_sorted, mode="drop")
            buf = buf.reshape((n_dev, quota) + c.shape[1:])
            recv = lax.all_to_all(buf, axis, split_axis=0, concat_axis=0,
                                  tiled=False)
            out_cols.append(recv.reshape((n_dev * quota,) + c.shape[1:]))

        # counts from each source
        recv_counts = lax.all_to_all(send_counts, axis, split_axis=0,
                                     concat_axis=0, tiled=True)
        # compact received rows: row r of source s lives at s*quota + r,
        # valid while r < recv_counts[s]
        rr = jnp.arange(n_dev * quota, dtype=jnp.int32)
        src = rr // quota
        r_in = rr % quota
        valid = r_in < recv_counts[src]
        order = jnp.argsort(jnp.where(valid, 0, 1).astype(jnp.int32),
                            stable=True)
        out_cols = [c[order] for c in out_cols]
        out_nr = jnp.sum(recv_counts).astype(jnp.int32)
        # global (replicated) max bucket: the host-side quota check must
        # read this value on EVERY controller in a multi-host run, and a
        # P(axis)-sharded output is not fully addressable there — a pmax
        # into a replicated output is, and costs one tiny collective
        gmax = lax.pmax(max_count, axis)
        return (tuple(out_cols), out_nr[None], gmax)

    in_specs = (tuple(P(axis) for _ in range(n_cols)), P(axis), P(axis))
    out_specs = (tuple(P(axis) for _ in range(n_cols)), P(axis), P())

    return jax.jit(shard_map(local_fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs))


def mesh_all_to_all(mesh: Mesh, cols: tuple, pids, num_rows, quota: int,
                    axis: str = "data"):
    """Run the SPMD exchange; returns (cols, num_rows_per_shard, max_count)
    with max_count the replicated global max bucket size. Rows were
    dropped iff max_count > quota; rerun at that quota."""
    fn = _exchange_fn(mesh, len(cols), quota, axis)
    return fn(tuple(cols), pids, num_rows)


def exchange_device_batches(mesh: Mesh, cols: tuple, pids, num_rows,
                            axis: str = "data", initial_quota: int | None = None):
    """Overflow-safe wrapper, at most TWO compiled programs per shape class.

    Quotas are always powers of two: the first attempt uses a pow2 estimate,
    and if any bucket overflows, the returned max bucket size tells us the
    exact quota needed, so a single retry (at the next pow2 ≥ that size)
    always fits. Blind doubling would compile a fresh SPMD program per step
    (~seconds each on a real TPU slice); this escalates once, to a quota
    value drawn from a log-sized bucket set that future calls reuse.
    """
    from auron_tpu.utils.shapes import bucket_rows
    n_dev = mesh.shape[axis]
    cap = pids.shape[0] // n_dev
    quota = bucket_rows(initial_quota or (2 * cap) // n_dev)
    out_cols, out_nr, max_count = mesh_all_to_all(
        mesh, cols, pids, num_rows, quota, axis)
    needed = int(np.max(np.asarray(max_count)))
    if needed <= quota:
        return out_cols, out_nr, quota
    quota = bucket_rows(needed)
    out_cols, out_nr, _ = mesh_all_to_all(
        mesh, cols, pids, num_rows, quota, axis)
    return out_cols, out_nr, quota
