"""ctypes bindings for the host-side C++ kernels (native/auron_host.cc).

Lazy build-on-first-use with a graceful numpy fallback: environments
without a toolchain still run, native just accelerates (the reference's
equivalent layer is mandatory Rust; here XLA is the compute path and this
covers host-runtime hot spots: spill-merge ordering and row gathers)."""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional

import numpy as np

logger = logging.getLogger("auron_tpu.native")

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libauron_host.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SO_PATH):
            try:
                subprocess.run(["make", "-C", _NATIVE_DIR, "-s"],
                               check=True, capture_output=True, timeout=120)
            except (OSError, subprocess.SubprocessError) as e:
                logger.warning("native build failed, using numpy fallback: %s", e)
                return None
        try:
            lib = ctypes.CDLL(_SO_PATH)
        except OSError as e:
            logger.warning("native load failed, using numpy fallback: %s", e)
            return None

        u64p = ctypes.POINTER(ctypes.c_uint64)
        i64p = ctypes.POINTER(ctypes.c_int64)
        i32p = ctypes.POINTER(ctypes.c_int32)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        lib.at_lex_sort_words.argtypes = [u64p, ctypes.c_int64,
                                          ctypes.c_int64, i32p]
        lib.at_merge_runs.argtypes = [u64p, i64p, ctypes.c_int64,
                                      ctypes.c_int64, i32p]
        lib.at_take_rows.argtypes = [u8p, i32p, ctypes.c_int64,
                                     ctypes.c_int64, u8p]
        lib.at_version.restype = ctypes.c_int64
        if lib.at_version() != 1:
            logger.warning("native ABI mismatch, using numpy fallback")
            return None
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def _as_u64p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64))


def lex_sort_words(words: np.ndarray) -> np.ndarray:
    """Stable permutation sorting rows of a [n, w] uint64 word matrix
    lexicographically (most significant word first). Native radix sort when
    available, np.lexsort otherwise."""
    n, w = words.shape
    lib = _load()
    if lib is None or n == 0:
        if n == 0:
            return np.zeros(0, np.int32)
        return np.lexsort(tuple(words[:, i]
                                for i in range(w - 1, -1, -1))).astype(np.int32)
    words = np.ascontiguousarray(words, np.uint64)
    perm = np.empty(n, np.int32)
    lib.at_lex_sort_words(_as_u64p(words), n, w,
                          perm.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    return perm


def merge_runs(words: np.ndarray, run_offsets: np.ndarray) -> np.ndarray:
    """Global merge order (row indices into `words`) for k sorted runs —
    run r occupies rows [run_offsets[r], run_offsets[r+1]). Loser tree in
    native code; numpy fallback concatenates and lex-sorts (stable, so run
    order breaks ties the same way)."""
    n, w = words.shape
    k = len(run_offsets) - 1
    lib = _load()
    if lib is None or n == 0:
        # a stable sort of the concatenation merges sorted runs with the
        # same run-order tie-break as the loser tree
        return lex_sort_words(words)
    words = np.ascontiguousarray(words, np.uint64)
    offsets = np.ascontiguousarray(run_offsets, np.int64)
    out = np.empty(n, np.int32)
    lib.at_merge_runs(_as_u64p(words),
                      offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                      k, w,
                      out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    return out


def take_rows(src: np.ndarray, order: np.ndarray) -> np.ndarray:
    """out[i] = src[order[i]] over a row-major 2-D byte-like matrix."""
    lib = _load()
    if lib is None or src.size == 0:
        return src[order]
    src2 = np.ascontiguousarray(src)
    flat = src2.view(np.uint8).reshape(src2.shape[0], -1)
    order = np.ascontiguousarray(order, np.int32)
    out = np.empty((len(order), flat.shape[1]), np.uint8)
    lib.at_take_rows(
        flat.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        order.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        len(order), flat.shape[1],
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
    return out.view(src2.dtype).reshape((len(order),) + src2.shape[1:])
