"""Typed config system + adaptive partial-agg skipping.

Mirrors the reference's three-layer config design (typed ConfigOption +
engine binding + native mirror, reference: SparkAuronConfiguration.java:
42-526, auron-jni-bridge/src/conf.rs:20-63) and the partial-agg skip
behavior (reference: datafusion-ext-plans/src/agg/agg_ctx.rs:63-196).
"""

import os

import numpy as np
import pyarrow as pa
import pytest

from auron_tpu import config as cfg
from auron_tpu.columnar.arrow_bridge import schema_from_arrow
from auron_tpu.exprs import ir
from auron_tpu.io.parquet import MemoryScanOp
from auron_tpu.ops.agg import AggOp
from auron_tpu.ops.base import ExecContext
from auron_tpu.runtime.executor import collect

C = ir.ColumnRef


def mem_scan(rbs, capacity=64):
    if not isinstance(rbs, list):
        rbs = [rbs]
    return MemoryScanOp([rbs], schema_from_arrow(rbs[0].schema),
                        capacity=capacity)


class TestRegistry:
    def test_default(self):
        conf = cfg.AuronConfig()
        assert conf.get(cfg.AGG_INITIAL_CAPACITY) == 4096

    def test_override_beats_env_beats_default(self, monkeypatch):
        opt = cfg.AGG_PARTIAL_SKIP_RATIO
        env_var = "AURON_CONF_AGG_PARTIAL_SKIP_RATIO"
        monkeypatch.setenv(env_var, "0.5")
        conf = cfg.AuronConfig()
        assert conf.get(opt) == 0.5
        conf.set(opt, 0.25)
        assert conf.get(opt) == 0.25
        conf.unset(opt)
        assert conf.get(opt) == 0.5
        monkeypatch.delenv(env_var)
        assert conf.get(opt) == 0.8

    def test_bool_env_parsing(self, monkeypatch):
        monkeypatch.setenv("AURON_CONF_AGG_PARTIAL_SKIP_ENABLED", "false")
        assert cfg.AuronConfig().get(cfg.AGG_PARTIAL_SKIP_ENABLED) is False
        monkeypatch.setenv("AURON_CONF_AGG_PARTIAL_SKIP_ENABLED", "on")
        assert cfg.AuronConfig().get(cfg.AGG_PARTIAL_SKIP_ENABLED) is True

    def test_unknown_key_rejected(self):
        conf = cfg.AuronConfig()
        with pytest.raises(KeyError):
            conf.get("auron.definitely.not.an.option")
        with pytest.raises(KeyError):
            conf.set("auron.definitely.not.an.option", 1)

    def test_type_checked(self):
        conf = cfg.AuronConfig()
        with pytest.raises((TypeError, ValueError)):
            conf.set(cfg.AGG_INITIAL_CAPACITY, "not-an-int-able")
        # string form of the right type parses
        conf.set(cfg.AGG_INITIAL_CAPACITY, "512")
        assert conf.get(cfg.AGG_INITIAL_CAPACITY) == 512

    def test_doc_generator_covers_all_options(self):
        docs = cfg.generate_docs()
        for o in cfg.options():
            assert o.key in docs
            assert o.env_var in docs

    def test_xla_cache_dir_bound_at_session_init(self, tmp_path):
        """auron.xla_cache_dir (default off) binds jax's persistent
        compilation cache when a Session is constructed — the first step
        of the compile-budget diet (VERDICT round 5)."""
        import jax

        from auron_tpu.frontend.session import Session
        prev = getattr(jax.config, "jax_compilation_cache_dir", None)
        try:
            # default: off — no binding happens
            Session(config=cfg.AuronConfig())
            assert getattr(jax.config, "jax_compilation_cache_dir",
                           None) == prev
            cache = str(tmp_path / "xla-cache")
            Session(config=cfg.AuronConfig({cfg.XLA_CACHE_DIR: cache}))
            assert jax.config.jax_compilation_cache_dir == cache
        finally:
            jax.config.update("jax_compilation_cache_dir", prev)

    def test_config_md_up_to_date(self):
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        with open(os.path.join(repo, "CONFIG.md")) as f:
            on_disk = f.read()
        assert on_disk == cfg.generate_docs(), (
            "CONFIG.md is stale — regenerate with "
            "python -c 'from auron_tpu.config import generate_docs; "
            "open(\"CONFIG.md\", \"w\").write(generate_docs())'")


def _high_cardinality_batches(n_batches=6, rows=64):
    rbs = []
    for b in range(n_batches):
        base = b * rows
        rbs.append(pa.record_batch({
            "k": pa.array(list(range(base, base + rows)), pa.int64()),
            "v": pa.array([float(i) for i in range(rows)], pa.float64()),
        }))
    return rbs


class TestPartialAggSkip:
    def _run_partial(self, conf):
        rbs = _high_cardinality_batches()
        agg = AggOp(mem_scan(rbs, capacity=64), [C(0)],
                    [ir.AggFunction("sum", C(1)),
                     ir.AggFunction("count", C(1))],
                    mode="partial", group_names=["k"], agg_names=["s", "c"],
                    initial_capacity=64)
        ctx = ExecContext(config=conf)
        out = [b for b in agg.execute(0, ctx)]
        skipped = ctx.metrics["agg"].counter("partial_agg_skipped_rows").value
        return agg, out, skipped, ctx

    def test_skip_triggers_on_high_cardinality(self):
        conf = cfg.AuronConfig({cfg.AGG_PARTIAL_SKIP_MIN_ROWS: 128,
                                cfg.AGG_PARTIAL_SKIP_RATIO: 0.8})
        _agg, out, skipped, _ = self._run_partial(conf)
        assert skipped > 0, "all-unique keys must trigger pass-through"
        # pass-through yields one output batch per remaining input batch
        assert len(out) > 1

    def test_skip_disabled_by_config(self):
        conf = cfg.AuronConfig({cfg.AGG_PARTIAL_SKIP_ENABLED: False})
        _agg, out, skipped, _ = self._run_partial(conf)
        assert skipped == 0
        assert len(out) == 1

    def test_skip_output_correct_through_final(self):
        """partial (with skip active) → final must equal the unskipped
        answer: pass-through rows are state-layout contributions the final
        stage folds exactly like merged state."""
        conf = cfg.AuronConfig({cfg.AGG_PARTIAL_SKIP_MIN_ROWS: 128,
                                cfg.AGG_PARTIAL_SKIP_RATIO: 0.8})
        agg, out, skipped, _ = self._run_partial(conf)
        assert skipped > 0
        from auron_tpu.columnar.arrow_bridge import to_arrow
        partial_tables = [pa.Table.from_batches([to_arrow(b, agg.schema())])
                          for b in out if int(b.num_rows)]
        merged = pa.concat_tables(partial_tables).combine_chunks()
        rb = merged.to_batches()[0]
        final = AggOp(mem_scan(rb, capacity=512), [C(0)],
                      [ir.AggFunction("sum", None),
                       ir.AggFunction("count", None)],
                      mode="final", group_names=["k"], agg_names=["s", "c"],
                      initial_capacity=64)
        got = {r["k"]: (r["s"], r["c"])
               for r in collect(final).to_pylist()}
        rows = 64
        exp = {b * rows + i: (float(i), 1)
               for b in range(6) for i in range(rows)}
        assert got == exp

    def test_skip_with_low_cardinality_does_not_trigger(self):
        conf = cfg.AuronConfig({cfg.AGG_PARTIAL_SKIP_MIN_ROWS: 64,
                                cfg.AGG_PARTIAL_SKIP_RATIO: 0.8})
        rbs = [pa.record_batch({
            "k": pa.array([i % 4 for i in range(64)], pa.int64()),
            "v": pa.array([1.0] * 64, pa.float64()),
        }) for _ in range(4)]
        agg = AggOp(mem_scan(rbs, capacity=64), [C(0)],
                    [ir.AggFunction("sum", C(1))],
                    mode="partial", group_names=["k"], agg_names=["s"],
                    initial_capacity=16)
        ctx = ExecContext(config=conf)
        out = list(agg.execute(0, ctx))
        assert ctx.metrics["agg"].counter(
            "partial_agg_skipped_rows").value == 0
        assert len(out) == 1

    def test_skip_with_string_min(self):
        """Skip pass-through carries string accumulators too."""
        conf = cfg.AuronConfig({cfg.AGG_PARTIAL_SKIP_MIN_ROWS: 64,
                                cfg.AGG_PARTIAL_SKIP_RATIO: 0.5})
        rbs = []
        for b in range(4):
            ks = [b * 64 + i for i in range(64)]
            rbs.append(pa.record_batch({
                "k": pa.array(ks, pa.int64()),
                "s": pa.array([f"str-{k:04d}" for k in ks], pa.string()),
            }))
        agg = AggOp(mem_scan(rbs, capacity=64), [C(0)],
                    [ir.AggFunction("min", C(1))],
                    mode="partial", group_names=["k"], agg_names=["mn"],
                    initial_capacity=64)
        ctx = ExecContext(config=conf)
        out = list(agg.execute(0, ctx))
        assert ctx.metrics["agg"].counter(
            "partial_agg_skipped_rows").value > 0
        from auron_tpu.columnar.arrow_bridge import to_arrow
        tables = [pa.Table.from_batches([to_arrow(b, agg.schema())])
                  for b in out if int(b.num_rows)]
        rb = pa.concat_tables(tables).combine_chunks().to_batches()[0]
        final = AggOp(mem_scan(rb, capacity=512), [C(0)],
                      [ir.AggFunction("min", None)],
                      mode="final", group_names=["k"], agg_names=["mn"],
                      initial_capacity=64)
        got = {r["k"]: r["mn"] for r in collect(final).to_pylist()}
        assert got == {b * 64 + i: f"str-{b * 64 + i:04d}"
                       for b in range(4) for i in range(64)}
