"""Ops plane tests (ISSUE 14): always-on flight recorder, live
telemetry endpoint, post-mortem failure bundles.

The acceptance shape: with tracing OFF the flight recorder still holds
the control-plane events that explain a failure; the HTTP endpoint
serves a conformant /metrics and an untorn /queries WHILE the PR 9
four-query TPC-DS stress runs and shuts down with Session.close(); a
classified failure writes exactly one self-contained bundle whose
flight dump contains the events leading up to it, with oldest-first
retention.
"""

import json
import os
import tempfile
import threading
import time
import urllib.request

import pyarrow as pa
import pytest

from auron_tpu import config as cfg
from auron_tpu import errors
from auron_tpu.obs import bundle as bundle_mod
from auron_tpu.obs import flight_recorder as flight
from auron_tpu.obs import registry as reg
from auron_tpu.obs import trace

from conftest import spin_until


@pytest.fixture()
def conf_keys():
    """Save/restore a set of config overrides around one test."""
    conf = cfg.get_config()
    _missing = object()
    saved = {}

    def set_knob(key, value):
        if key not in saved:
            saved[key] = conf._overrides.get(key, _missing)
        conf.set(key, value)

    yield set_knob
    for key, prev in saved.items():
        if prev is _missing:
            conf.unset(key)
        else:
            conf.set(key, prev)


def _get(url: str, timeout: float = 10.0) -> bytes:
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read()


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

class TestFlightRecorder:
    def test_armed_with_tracing_off(self, conf_keys):
        """The black-box contract: trace.event lands in the ring even
        though auron.trace.enabled is off (the tee fires BEFORE the
        tracing enabled check)."""
        conf_keys(cfg.TRACE_ENABLED, False)
        before = len(trace.tracer().spans())
        trace.event("fault", "fault.injected", site="test.site",
                    kind="io_error", seed=99)
        assert len(trace.tracer().spans()) == before  # tracer untouched
        recs = [r for r in flight.recorder().snapshot()
                if r["name"] == "fault.injected"
                and r["attrs"].get("site") == "test.site"]
        assert recs, "flight recorder missed the event with tracing off"
        assert recs[-1]["attrs"]["seed"] == 99
        assert recs[-1]["cat"] == "fault"

    def test_spans_teed_when_tracing_on(self, conf_keys):
        conf_keys(cfg.TRACE_ENABLED, True)
        conf_keys(cfg.TRACE_EVENTS, "")
        with trace.span("task", "ops.test.span", marker=1):
            time.sleep(0.002)
        recs = [r for r in flight.recorder().snapshot()
                if r["name"] == "ops.test.span"]
        assert recs and recs[-1]["dur_us"] > 0

    def test_ring_bounded_per_thread(self, conf_keys):
        conf_keys(cfg.FLIGHT_RING_EVENTS, 64)
        for i in range(200):
            trace.event("task", "ops.test.flood", i=i)
        recs = [r for r in flight.recorder().snapshot()
                if r["name"] == "ops.test.flood"]
        assert len(recs) == 64               # oldest evicted
        assert recs[-1]["attrs"]["i"] == 199  # newest kept

    def test_query_attribution_and_filter(self):
        from auron_tpu.runtime.lifecycle import CancelToken, bind_token
        token = CancelToken(query_id="flightq1")
        prev = bind_token(token)
        try:
            trace.event("memory", "ops.test.tagged")
        finally:
            bind_token(prev)
        trace.event("memory", "ops.test.untagged")
        mine = flight.recorder().snapshot(query_id="flightq1")
        assert any(r["name"] == "ops.test.tagged" for r in mine)
        assert not any(r["name"] == "ops.test.untagged" for r in mine)

    def test_disarmed_records_nothing(self, conf_keys):
        conf_keys(cfg.FLIGHT_ENABLED, False)
        trace.event("task", "ops.test.disarmed")
        assert not any(r["name"] == "ops.test.disarmed"
                       for r in flight.recorder().snapshot())

    def test_dead_thread_rings_pruned_into_graveyard(self):
        """Thread-per-connection serving mints one ring per handler
        thread: dead threads' rings must not pin memory forever, but
        their recent events (the pre-failure evidence) must survive in
        the bounded graveyard."""
        rec = flight.recorder()

        def emit():
            trace.event("task", "ops.test.dying_thread", mark=1)

        for _ in range(6):
            t = threading.Thread(target=emit)
            t.start()
            t.join(10)
        # registering a NEW ring prunes the dead ones
        trace.event("task", "ops.test.alive")
        with rec._lock:
            dead = [1 for tref, _d in rec._rings
                    if tref() is None or not tref().is_alive()]
        assert len(dead) <= 1, \
            f"{len(dead)} dead-thread rings still pinned"
        # the dead threads' events survived the prune
        assert sum(1 for r in rec.snapshot()
                   if r["name"] == "ops.test.dying_thread") == 6

    def test_dump_round_trip(self, tmp_path):
        trace.event("sched", "ops.test.roundtrip", x="y")
        path = tmp_path / "flight.jsonl"
        path.write_text(flight.recorder().dump_jsonl(last=50))
        recs = flight.read_jsonl(str(path))
        assert recs and all("name" in r and "ts_us" in r for r in recs)
        assert any(r["name"] == "ops.test.roundtrip" for r in recs)


# ---------------------------------------------------------------------------
# ops HTTP endpoint
# ---------------------------------------------------------------------------

class TestOpsServer:
    def test_disabled_by_default(self):
        from auron_tpu.frontend.session import Session
        s = Session()
        try:
            assert s.ops_address is None
        finally:
            s.close()

    def test_endpoints_and_clean_shutdown(self, conf_keys):
        from auron_tpu.frontend.session import Session
        conf_keys(cfg.OPS_ENABLED, True)
        conf_keys(cfg.OPS_PORT, 0)
        s = Session()
        try:
            assert s.ops_address is not None
            host, port = s.ops_address
            assert port > 0   # ephemeral port bound and surfaced
            base = f"http://{host}:{port}"
            s.register("t", pa.table({"a": [1, 2, 3]}))
            s.execute(s.table("t"))
            # /metrics: strict conformance parse + the SLO family
            fams = reg.parse_prometheus(_get(base + "/metrics").decode())
            assert "auron_query_duration_seconds" in fams
            # /healthz: verdict + per-plane sections
            h = json.loads(_get(base + "/healthz"))
            assert h["status"] in ("ok", "degraded")
            assert "scheduler" in h and "watchdog" in h
            # /queries: idle table, well-formed
            q = json.loads(_get(base + "/queries"))
            assert q["queries"] == []
            assert "session" in q["admission"]
            assert q["admission"]["session"]["admitted"] >= 1
            # /flight: JSONL, every line parses
            for ln in _get(base + "/flight?last=20").decode().splitlines():
                json.loads(ln)
            # 404 contract
            with pytest.raises(urllib.error.HTTPError):
                _get(base + "/nope")
        finally:
            s.close()
        with pytest.raises(OSError):
            _get(f"http://{host}:{port}/metrics", timeout=2)

    def test_refcounted_across_sessions(self, conf_keys):
        from auron_tpu.frontend.session import Session
        conf_keys(cfg.OPS_ENABLED, True)
        conf_keys(cfg.OPS_PORT, 0)
        s1 = Session()
        s2 = Session()
        assert s1.ops_address == s2.ops_address   # one shared server
        host, port = s1.ops_address
        s1.close()
        # still serving: s2 holds a reference
        assert _get(f"http://{host}:{port}/healthz")
        s2.close()
        with pytest.raises(OSError):
            _get(f"http://{host}:{port}/healthz", timeout=2)


# ---------------------------------------------------------------------------
# scrape-under-concurrency (ISSUE 14 satellite): the PR 9 four-query
# TPC-DS stress with a scraper hammering /metrics and /queries
# ---------------------------------------------------------------------------

_QUERY_NAMES = ["q3", "q96", "q42", "q52"]


@pytest.fixture(scope="module")
def tpcds_tables():
    from auron_tpu.it.tpcds import generate
    with tempfile.TemporaryDirectory(prefix="ops_tpcds_") as d:
        yield generate(d, scale=0.01)


def test_scrape_during_four_query_stress(tpcds_tables, conf_keys):
    from auron_tpu.frontend.session import Session
    from auron_tpu.it.tpcds_queries import QUERIES
    by_name = {q.name: q for q in QUERIES}
    queries = [by_name[n] for n in _QUERY_NAMES]
    conf_keys(cfg.OPS_ENABLED, True)
    conf_keys(cfg.OPS_PORT, 0)
    s = Session()
    host, port = s.ops_address
    base = f"http://{host}:{port}"
    try:
        for q in queries:      # warm compiles (off the scrape clock)
            q.run(s, tpcds_tables)
        stop = threading.Event()
        scrape_stats = {"metrics": 0, "queries": 0, "live_rows": 0}
        scrape_errors: list = []

        def scraper():
            while not stop.is_set():
                try:
                    # every /metrics poll must STRICT-parse — a torn
                    # exposition under concurrent writers is the bug
                    # this test exists to catch
                    reg.parse_prometheus(
                        _get(base + "/metrics").decode())
                    scrape_stats["metrics"] += 1
                    body = json.loads(_get(base + "/queries"))
                    rows = body["queries"]
                    for row in rows:
                        # no torn rows: every row carries the full
                        # column set with sane values
                        assert row["state"] in ("running", "queued")
                        assert row["wall_s"] >= 0
                        assert isinstance(row["query"], str)
                        assert row["tasks_done"] >= 0
                    scrape_stats["queries"] += 1
                    if rows:
                        scrape_stats["live_rows"] += len(rows)
                except Exception as e:   # noqa: BLE001 — test verdict
                    scrape_errors.append(f"{type(e).__name__}: {e}")
                    return
                stop.wait(0.001)

        scraper_t = threading.Thread(target=scraper, daemon=True)
        scraper_t.start()
        failures: list = []
        results = [None] * len(queries)

        def worker(i):
            try:
                # two rounds each so the window stays busy
                for _ in range(2):
                    results[i] = queries[i].run(s, tpcds_tables)
            except BaseException as e:   # noqa: BLE001
                failures.append((queries[i].name, e))

        threads = [threading.Thread(target=worker, args=(i,),
                                    daemon=True)
                   for i in range(len(queries))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(120)
            assert not t.is_alive(), "stressed query wedged"
        stop.set()
        scraper_t.join(15)
        assert not failures, f"stress queries failed: {failures}"
        assert not scrape_errors, \
            f"scrape failed mid-stress: {scrape_errors[:3]}"
        assert scrape_stats["metrics"] >= 5, scrape_stats
        assert scrape_stats["queries"] >= 5, scrape_stats
        # the live table actually showed the concurrent queries
        assert scrape_stats["live_rows"] > 0, \
            "no scrape ever observed a live query row"
    finally:
        s.close()
    # clean shutdown with the stress finished
    with pytest.raises(OSError):
        _get(base + "/healthz", timeout=2)


# ---------------------------------------------------------------------------
# post-mortem bundles
# ---------------------------------------------------------------------------

class TestBundleClassify:
    def test_eligible_classes(self):
        assert bundle_mod.classify(
            errors.MemoryExhausted("x")) == "memory_exhausted"
        assert bundle_mod.classify(
            errors.DeadlineExceeded("x")) == "deadline"
        assert bundle_mod.classify(errors.TaskStalled("x")) == "stalled"
        assert bundle_mod.classify(
            errors.MeshUnavailable("x")) == "mesh_unavailable"
        assert bundle_mod.classify(
            errors.JournalCorrupt("x")) == "journal_corrupt"
        assert bundle_mod.classify(
            errors.JournalInvalidated("x")) == "journal_invalidated"

    def test_ineligible_classes(self):
        # plain cancels are the caller's verdict; admission sheds never
        # held resources; unclassified crashes carry tracebacks
        assert bundle_mod.classify(errors.QueryCancelled("x")) is None
        assert bundle_mod.classify(
            errors.AdmissionRejected("x", reason="queue_full")) is None
        assert bundle_mod.classify(RuntimeError("x")) is None
        assert bundle_mod.classify(None) is None

    def test_disarmed_writes_nothing(self):
        assert bundle_mod.maybe_write(
            errors.MemoryExhausted("x")) is None


class TestBundleWrite:
    def _table(self, rows=50000):
        return pa.table({"a": list(range(rows)),
                         "b": [float(i) for i in range(rows)]})

    def test_deadline_failure_writes_bundle(self, tmp_path, conf_keys):
        from auron_tpu.frontend.session import Session
        bdir = str(tmp_path / "bundles")
        conf_keys(cfg.BUNDLE_ENABLED, True)
        conf_keys(cfg.BUNDLE_DIR, bdir)
        s = Session()
        try:
            s.register("t", self._table())
            with pytest.raises(errors.DeadlineExceeded):
                s.execute(s.table("t"), timeout_s=1e-6)
        finally:
            s.close()
        bundles = bundle_mod.list_bundles(bdir)
        assert len(bundles) == 1
        b = bundles[0]
        mf = bundle_mod.read_manifest(b)
        assert mf["outcome"] == "deadline"
        assert mf["error_type"] == "DeadlineExceeded"
        assert mf["query_id"].startswith("q")
        assert os.path.basename(b) == f"bundle_{mf['query_id']}"
        # self-contained artifacts
        files = set(os.listdir(b))
        assert {"bundle.json", "flight.jsonl", "metrics.prom",
                "scheduler.json", "memmgr.json", "config.json",
                "explain.txt"} <= files
        # flight dump: the failing query's own timeline is present
        events = flight.read_jsonl(os.path.join(b, "flight.jsonl"))
        assert any(e.get("query") == mf["query_id"] for e in events)
        # config snapshot carries the trace salt
        with open(os.path.join(b, "config.json")) as f:
            snap = json.load(f)
        assert "trace_salt" in snap
        assert "auron.bundle.enabled" in snap["resolved"]
        # exposition snapshot parses
        with open(os.path.join(b, "metrics.prom")) as f:
            reg.parse_prometheus(f.read())
        # the explain tree rendered (plan structure, metrics from
        # whatever tasks completed)
        assert os.path.getsize(os.path.join(b, "explain.txt")) > 0

    def test_plain_cancel_writes_no_bundle(self, tmp_path, conf_keys):
        from auron_tpu.frontend.session import Session
        bdir = str(tmp_path / "bundles")
        conf_keys(cfg.BUNDLE_ENABLED, True)
        conf_keys(cfg.BUNDLE_DIR, bdir)
        s = Session()
        try:
            s.register("t", self._table(5000))
            df = s.table("t")
            done = threading.Event()
            caught: list = []

            def run():
                try:
                    s.execute(df)
                except BaseException as e:   # noqa: BLE001
                    caught.append(e)
                finally:
                    done.set()

            t = threading.Thread(target=run, daemon=True)
            t.start()
            spin_until(lambda: bool(s.active_queries()) or done.is_set(),
                       what="query registration")
            for token in s.active_queries().values():
                token.cancel()
            done.wait(30)
        finally:
            s.close()
        assert bundle_mod.list_bundles(bdir) == []

    def test_oldest_first_eviction(self, tmp_path, conf_keys):
        from auron_tpu.runtime.lifecycle import CancelToken
        bdir = str(tmp_path / "bundles")
        conf_keys(cfg.BUNDLE_ENABLED, True)
        conf_keys(cfg.BUNDLE_DIR, bdir)
        conf_keys(cfg.BUNDLE_MAX_BUNDLES, 3)
        written = []
        for i in range(5):
            p = bundle_mod.maybe_write(
                errors.MemoryExhausted(f"pressure {i}"),
                token=CancelToken(query_id=f"evict{i}"))
            assert p is not None
            written.append(os.path.basename(p))
            time.sleep(0.02)   # distinct mtimes for the eviction order
        kept = [os.path.basename(p)
                for p in bundle_mod.list_bundles(bdir)]
        assert len(kept) == 3
        assert kept == written[-3:], \
            f"eviction must drop oldest first: kept={kept}"

    def test_recycled_query_id_never_overwrites(self, tmp_path,
                                                conf_keys):
        from auron_tpu.runtime.lifecycle import CancelToken
        bdir = str(tmp_path / "bundles")
        conf_keys(cfg.BUNDLE_ENABLED, True)
        conf_keys(cfg.BUNDLE_DIR, bdir)
        token = CancelToken(query_id="dup")
        p1 = bundle_mod.maybe_write(errors.TaskStalled("a"), token=token)
        p2 = bundle_mod.maybe_write(errors.TaskStalled("b"), token=token)
        assert p1 != p2
        assert len(bundle_mod.list_bundles(bdir)) == 2

    def test_ops_report_renders_bundle_and_live(self, tmp_path,
                                                conf_keys):
        """tools/ops_report.py turns a bundle (and a live endpoint
        poll) into a human post-mortem whose timeline names the
        failure's events."""
        import subprocess
        import sys

        from auron_tpu.frontend.session import Session
        bdir = str(tmp_path / "bundles")
        conf_keys(cfg.BUNDLE_ENABLED, True)
        conf_keys(cfg.BUNDLE_DIR, bdir)
        conf_keys(cfg.OPS_ENABLED, True)
        conf_keys(cfg.OPS_PORT, 0)
        s = Session()
        try:
            host, port = s.ops_address
            s.register("t", self._table())
            with pytest.raises(errors.DeadlineExceeded):
                s.execute(s.table("t"), timeout_s=1e-6)
            bundles = bundle_mod.list_bundles(bdir)
            assert len(bundles) == 1
            repo = os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))
            tool = os.path.join(repo, "tools", "ops_report.py")
            out = subprocess.run(
                [sys.executable, tool, bundles[0]],
                capture_output=True, text=True, timeout=120)
            assert out.returncode == 0, out.stderr
            assert "outcome   : deadline" in out.stdout
            assert "event timeline" in out.stdout
            assert "DeadlineExceeded" in out.stdout
            # live poll (in-process render: the subprocess would need
            # its own backend init just to format JSON)
            import importlib.util
            spec = importlib.util.spec_from_file_location(
                "ops_report", tool)
            ops_report = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(ops_report)
            live = ops_report.render_live(f"http://{host}:{port}")
            assert "live ops poll" in live
            assert "query outcomes" in live
            inv = ops_report.render_inventory(bdir)
            assert "bundle_" in inv and "deadline" in inv
        finally:
            s.close()

    def test_query_duration_outcomes_recorded(self, conf_keys):
        """The SLO histogram sees both the ok and the failure path of
        the Session admission scope."""
        from auron_tpu.frontend.session import Session
        r = reg.get_registry()

        def count(outcome):
            return r.histogram("auron_query_duration_seconds",
                               outcome=outcome).count

        ok0, cancelled0 = count("ok"), count("cancelled")
        s = Session()
        try:
            s.register("t", self._table(50000))
            s.execute(s.table("t").limit(10))
            with pytest.raises(errors.DeadlineExceeded):
                s.execute(s.table("t"), timeout_s=1e-6)
        finally:
            s.close()
        assert count("ok") == ok0 + 1
        assert count("cancelled") == cancelled0 + 1
