"""Query lifecycle control plane (PR 8): CancelToken semantics, the
task-level stall watchdog, the memory-pressure degradation ladder, and
the spill-tier orphan sweep.

The e2e cancel/deadline races live in tests/test_cancel.py; the seeded
chaos proofs in tests/test_zz_chaos_battery.py. This module pins the
primitives: token state machine, heartbeat/monitor mechanics, TaskStalled
transient-once routing, ladder rungs + policy/quota, sweep ledger."""

import os
import threading
import time

import numpy as np
import pyarrow as pa
import pytest

from auron_tpu import config as cfg
from auron_tpu import errors
from auron_tpu.runtime.lifecycle import CancelToken


# ---------------------------------------------------------------------------
# CancelToken
# ---------------------------------------------------------------------------

class TestCancelToken:
    def test_deadline_self_cancels_with_reason(self):
        t = CancelToken("q", deadline_s=0.05)
        assert not t.is_set() and t.remaining() > 0
        time.sleep(0.06)
        assert t.is_set() and t.reason == "deadline"
        with pytest.raises(errors.DeadlineExceeded):
            t.raise_for_status()

    def test_cancel_first_wins_and_is_idempotent(self):
        t = CancelToken("q")
        t.cancel()
        first_ts = t.cancelled_at_ns
        t.cancel("deadline")      # loses: reason/timestamp unchanged
        assert t.reason == "cancelled" and t.cancelled_at_ns == first_ts
        with pytest.raises(errors.QueryCancelled):
            t.raise_for_status()

    def test_event_compat_set_alias(self):
        t = CancelToken("q")
        t.set()                   # the serving control reader's call
        assert t.is_set() and t.reason == "cancelled"

    def test_wait_clamps_to_deadline(self):
        t = CancelToken("q", deadline_s=0.1)
        t0 = time.time()
        assert t.wait(5.0) is True          # woke at the deadline
        assert time.time() - t0 < 2.0
        assert t.reason == "deadline"

    def test_sleep_interrupted_by_cancel_raises(self):
        t = CancelToken("q")
        threading.Timer(0.05, t.cancel).start()
        t0 = time.time()
        with pytest.raises(errors.QueryCancelled):
            t.sleep(5.0)
        assert time.time() - t0 < 2.0

    def test_unwind_latency_measured_from_cancel(self):
        t = CancelToken("q")
        assert t.unwind_latency_s() is None
        t.cancel()
        time.sleep(0.02)
        assert t.unwind_latency_s() >= 0.02


# ---------------------------------------------------------------------------
# stall watchdog
# ---------------------------------------------------------------------------

class TestStallWatchdog:
    def test_disarmed_registers_nothing(self):
        from auron_tpu.runtime import watchdog
        assert watchdog.register_heartbeat(task_id=1) is None

    def test_silent_task_flagged_and_report_written(self, tmp_path):
        from auron_tpu.runtime import watchdog
        conf = cfg.get_config()
        conf.set(cfg.WATCHDOG_STALL_TIMEOUT_S, 0.15)
        conf.set(cfg.TRACE_DIR, str(tmp_path))
        hb = None
        try:
            before = watchdog.stall_totals()
            hb = watchdog.register_heartbeat(task_id=42, stage_id=1,
                                             partition_id=2, attempt=0)
            assert hb is not None
            deadline = time.time() + 5.0
            while not hb.stalled and time.time() < deadline:
                time.sleep(0.02)
            assert hb.stalled, "monitor never flagged the silent task"
            assert watchdog.stall_totals() == before + 1
            report = tmp_path / "stall_report_42.json"
            assert report.exists()
            import json
            d = json.loads(report.read_text())
            assert d["task_id"] == 42 and d["last_site"] == "task.start"
            assert d["schema_version"] == watchdog.STALL_SCHEMA_VERSION
            assert d["silent_s"] >= 0.15
        finally:
            watchdog.unregister_heartbeat(hb)
            conf.unset(cfg.WATCHDOG_STALL_TIMEOUT_S)
            conf.unset(cfg.TRACE_DIR)

    def test_session_scoped_timeout_detected_with_global_default_zero(self):
        """A session-scoped stall_timeout_s must arm detection even
        while the process-global knob stays at its 0 default: the
        timeout is resolved at registration and carried per heartbeat
        (code-review regression)."""
        from auron_tpu.runtime import watchdog
        session_conf = cfg.AuronConfig(
            {cfg.WATCHDOG_STALL_TIMEOUT_S: 0.15})
        hb = None
        try:
            hb = watchdog.register_heartbeat(task_id=44,
                                             config=session_conf)
            assert hb is not None and hb.timeout_s == 0.15
            deadline = time.time() + 5.0
            while not hb.stalled and time.time() < deadline:
                time.sleep(0.02)
            assert hb.stalled
        finally:
            watchdog.unregister_heartbeat(hb)

    def test_beating_task_never_flagged(self):
        from auron_tpu.runtime import watchdog
        conf = cfg.get_config()
        conf.set(cfg.WATCHDOG_STALL_TIMEOUT_S, 0.15)
        hb = None
        try:
            hb = watchdog.register_heartbeat(task_id=43)
            for _ in range(10):
                hb.beat("test.loop")
                time.sleep(0.05)
            assert not hb.stalled
        finally:
            watchdog.unregister_heartbeat(hb)
            conf.unset(cfg.WATCHDOG_STALL_TIMEOUT_S)

    def test_stalled_heartbeat_raises_task_stalled_at_checkpoint(self):
        from auron_tpu.ops.base import ExecContext
        from auron_tpu.runtime.watchdog import TaskHeartbeat
        hb = TaskHeartbeat(task_id=7)
        hb.stalled = True
        ctx = ExecContext(task_id=7, heartbeat=hb)
        with pytest.raises(errors.TaskStalled):
            ctx.checkpoint("unit")

    def test_task_stalled_is_retried_exactly_once(self):
        """The retry driver's transient-once contract: a plan that
        stalls every attempt runs exactly twice, then surfaces."""
        from auron_tpu.columnar.schema import DataType, Field, Schema
        from auron_tpu.ops.base import PhysicalOp
        from auron_tpu.runtime.executor import run_task_with_retries

        attempts = []

        class AlwaysStalls(PhysicalOp):
            def schema(self):
                return Schema((Field("x", DataType.INT64, True),))

            def execute(self, partition, ctx):
                attempts.append(1)
                raise errors.TaskStalled("wedged")
                yield  # pragma: no cover

        conf = cfg.AuronConfig().set(cfg.TASK_MAX_RETRIES, 5)
        with pytest.raises(errors.TaskStalled):
            run_task_with_retries(AlwaysStalls(), 0, 1, config=conf)
        assert len(attempts) == 2   # first attempt + ONE stall retry


# ---------------------------------------------------------------------------
# memory-pressure degradation ladder
# ---------------------------------------------------------------------------

class _Consumer:
    def __init__(self, name, used=0, spillable=True, shrinkable=0):
        self.consumer_name = name
        self.used = used
        self.spill_calls = 0
        self.shrink_calls = 0
        self._spillable = spillable
        self._shrinkable = shrinkable

    def mem_used(self):
        return self.used

    def spill(self):
        self.spill_calls += 1
        if not self._spillable:
            return 0
        freed, self.used = self.used, 0
        return freed

    def shrink(self):
        self.shrink_calls += 1
        freed = min(self._shrinkable, self.used)
        self.used -= freed
        return freed


class TestPressureLadder:
    def _mm(self, total=100):
        from auron_tpu.memmgr.manager import MemManager
        return MemManager(total_bytes=total, min_trigger=0)

    def test_shrink_rung_relieves_without_shed(self):
        mm = self._mm(100)
        c = _Consumer("a", used=150, spillable=False, shrinkable=100)
        mm.register_consumer(c)
        assert mm.update_mem_used(c, 150) == "spilled"
        assert c.shrink_calls == 1
        assert mm.pressure_counts["shrink"] == 1
        assert mm.pressure_counts["shed"] == 0
        # the shrink rung also shrinks the advised scan batch rows
        assert mm.advised_batch_rows(1 << 16) == 1 << 15

    def test_force_spill_rung_waives_min_trigger(self):
        from auron_tpu.memmgr.manager import MemManager
        # min_trigger ABOVE every consumer: the normal loop refuses,
        # the force rung spills the largest anyway
        mm = MemManager(total_bytes=100, min_trigger=1 << 30)
        big = _Consumer("big", used=90)
        mm.register_consumer(big)
        small = _Consumer("small", used=60)
        mm.register_consumer(small)
        mm.update_mem_used(big, 90)
        assert mm.update_mem_used(small, 60) == "spilled"
        assert big.spill_calls == 1          # largest, despite trigger
        assert mm.pressure_counts["force_spill"] == 1

    def test_degrade_policy_denies_survivably(self):
        mm = self._mm(10)
        stuck = _Consumer("stuck", used=50, spillable=False)
        mm.register_consumer(stuck)
        assert mm.update_mem_used(stuck, 50) == "nothing"
        assert mm.pressure_counts["deny"] == 1

    def test_shed_policy_raises_memory_exhausted(self):
        conf = cfg.get_config()
        conf.set(cfg.MEMMGR_PRESSURE_POLICY, "shed")
        try:
            mm = self._mm(10)
            stuck = _Consumer("stuck", used=50, spillable=False)
            mm.register_consumer(stuck)
            with pytest.raises(errors.MemoryExhausted) as ei:
                mm.update_mem_used(stuck, 50)
            assert not errors.is_transient(ei.value)
            assert mm.pressure_counts["shed"] == 1
        finally:
            conf.unset(cfg.MEMMGR_PRESSURE_POLICY)

    def test_query_quota_breach_sheds_under_degrade(self):
        conf = cfg.get_config()
        conf.set(cfg.MEMMGR_QUERY_QUOTA_BYTES, 30)
        try:
            mm = self._mm(1000)     # global budget is NOT the problem
            stuck = _Consumer("hog", used=50, spillable=False)
            mm.register_consumer(stuck)
            with pytest.raises(errors.MemoryExhausted):
                mm.update_mem_used(stuck, 50)
        finally:
            conf.unset(cfg.MEMMGR_QUERY_QUOTA_BYTES)

    def test_legacy_policy_restores_deny_only(self):
        conf = cfg.get_config()
        conf.set(cfg.MEMMGR_PRESSURE_POLICY, "legacy")
        try:
            mm = self._mm(10)
            stuck = _Consumer("stuck", used=50, spillable=False)
            mm.register_consumer(stuck)
            assert mm.update_mem_used(stuck, 50) == "nothing"
            assert stuck.shrink_calls == 0
            assert mm.pressure_counts["deny"] == 1
            assert mm.pressure_counts["shrink"] == 0
        finally:
            conf.unset(cfg.MEMMGR_PRESSURE_POLICY)

    def test_injected_deny_forces_ladder(self):
        from auron_tpu.runtime import faults
        conf = cfg.get_config()
        conf.set(cfg.FAULTS_PLAN, "memmgr.deny:deny@1.0")
        faults.reset()
        try:
            mm = self._mm(1000)
            c = _Consumer("fine", used=5)
            mm.register_consumer(c)
            # well under budget, but the injected deny walks the ladder
            mm.update_mem_used(c, 5)
            assert mm.pressure_counts["deny"] == 1
        finally:
            conf.unset(cfg.FAULTS_PLAN)
            faults.reset()

    def test_buffered_consumer_shrink_sheds_oldest_half(self, tmp_path):
        from auron_tpu.columnar.arrow_bridge import to_device
        from auron_tpu.memmgr.consumer import BufferedSpillConsumer
        from auron_tpu.memmgr.manager import MemManager
        from auron_tpu.memmgr.spill import SpillManager
        from auron_tpu.ops.base import MetricsSet
        mm = MemManager(total_bytes=1 << 30, min_trigger=0,
                        spill_manager=SpillManager(
                            host_budget_bytes=1 << 20,
                            spill_dir=str(tmp_path)))
        consumer = BufferedSpillConsumer("t", mm, MetricsSet(),
                                         cfg.get_config())
        rb = pa.record_batch({"x": pa.array(np.arange(64), pa.int64())})
        for _ in range(4):
            consumer.add(to_device(rb, capacity=64)[0])
        freed = consumer.shrink()
        assert freed > 0
        assert len(consumer.buffered) == 2      # newest half kept
        assert len(consumer.spills) == 1        # oldest half is a run
        consumer.close()


# ---------------------------------------------------------------------------
# spill-tier orphan sweep
# ---------------------------------------------------------------------------

class TestSpillSweep:
    def test_sweep_removes_unreleased_disk_files(self, tmp_path):
        from auron_tpu.memmgr.spill import SpillManager
        mgr = SpillManager(host_budget_bytes=0, spill_dir=str(tmp_path))
        s = mgr.new_spill()
        s.write_frame(b"x" * 1000)
        s.finish()
        path = s._path
        assert path is not None and os.path.exists(path)
        assert mgr.live_disk_files() == 1
        # the attempt "crashes": nobody calls release()
        assert mgr.sweep_orphans() == 1
        assert not os.path.exists(path)
        assert mgr.live_disk_files() == 0

    def test_released_spills_are_not_swept_twice(self, tmp_path):
        from auron_tpu.memmgr.spill import SpillManager
        mgr = SpillManager(host_budget_bytes=0, spill_dir=str(tmp_path))
        s = mgr.new_spill()
        s.write_frame(b"y" * 100)
        s.finish()
        s.release()
        assert mgr.live_disk_files() == 0
        assert mgr.sweep_orphans() == 0

    def test_session_close_sweeps_spill_tier(self, tmp_path):
        from auron_tpu.frontend.session import Session
        from auron_tpu.memmgr.manager import MemManager
        from auron_tpu.memmgr.spill import SpillManager
        sm = SpillManager(host_budget_bytes=0, spill_dir=str(tmp_path))
        orphan = sm.new_spill()
        orphan.write_frame(b"z" * 500)
        orphan.finish()        # never released — the crashed attempt
        with Session(mem_manager=MemManager(total_bytes=1 << 20,
                                            spill_manager=sm)):
            pass
        assert sm.live_disk_files() == 0
        assert not [f for f in os.listdir(str(tmp_path))
                    if f.startswith("auron-spill-")]


# ---------------------------------------------------------------------------
# session lifecycle + fault helpers
# ---------------------------------------------------------------------------

def test_session_close_cancels_active_queries():
    from auron_tpu.frontend.session import Session
    s = Session()
    token = s._begin_query(timeout_s=None)
    assert s.active_queries() == {token.query_id: token}
    s.close()
    # the deterministic drain (queued first, then running) stamps its
    # OWN reason so a close-time unwind is distinguishable from a user
    # cancel in telemetry; still raises QueryCancelled at poll sites
    assert token.is_set() and token.reason == "session-closed"


def test_injected_hang_polls_cancel_registry():
    from auron_tpu.runtime import faults
    conf = cfg.get_config()
    conf.set(cfg.FAULTS_PLAN, "task.hang:hang@1.0")
    conf.set(cfg.FAULTS_HANG_S, 10.0)
    faults.reset()
    try:
        token = CancelToken("hang")
        threading.Timer(0.1, token.cancel).start()
        t0 = time.time()
        faults.maybe_fail("task.hang", errors.DeviceExecutionError,
                          cancel=token)
        # woke on the cancel, nowhere near the 10s interval
        assert time.time() - t0 < 5.0
    finally:
        conf.unset(cfg.FAULTS_PLAN)
        conf.unset(cfg.FAULTS_HANG_S)
        faults.reset()


def test_maybe_cancel_fires_target_deterministically():
    from auron_tpu.runtime import faults
    conf = cfg.get_config()
    conf.set(cfg.FAULTS_PLAN, "cancel.race:cancel@1.0")
    faults.reset()
    try:
        token = CancelToken("race")
        assert faults.maybe_cancel("cancel.race", token) is True
        assert token.is_set()
        # seeded and replayable like every other site
        assert faults.snapshot() == {"cancel.race": {"cancel": 1}}
    finally:
        conf.unset(cfg.FAULTS_PLAN)
        faults.reset()


def test_cancel_latency_histogram_is_fed():
    from auron_tpu.obs import registry as obs_registry
    from auron_tpu.runtime import lifecycle
    token = CancelToken("lat")
    token.cancel()
    lifecycle.observe_unwind(token, kind="cancelled")
    snap = obs_registry.get_registry().snapshot()
    key = 'auron_cancel_latency_seconds{kind="cancelled"}'
    assert key in snap and snap[key]["count"] >= 1
