"""Host-adaptor SPI + streaming calc operator lifecycle (reference:
AuronAdaptor ServiceLoader seam + FlinkAuronCalcOperator.java:87-267
buffer/flush/checkpoint lifecycle, exercised like the reference's
MockAuronAdaptor tests — without the real host engine)."""

import numpy as np
import pyarrow as pa

from auron_tpu.columnar.schema import DataType, Field, Schema
from auron_tpu.exprs import ir
from auron_tpu.integration.adaptor import (HostEngineAdaptor, get_adaptor,
                                           register_adaptor,
                                           registered_adaptors)
from auron_tpu.ir import serde
from auron_tpu.streaming.calc_operator import CalcOperator

from google.protobuf import json_format


def _calc_spec():
    """SELECT k, v * 2 AS v2 WHERE v > 10 — as the streaming host's raw
    plan encoding (ExprNode JSON dicts)."""
    exprs = [serde.expr_to_proto(ir.ColumnRef(0, "k")),
             serde.expr_to_proto(ir.BinaryExpr(
                 "*", ir.ColumnRef(1, "v"),
                 ir.Literal(2.0, DataType.FLOAT64)))]
    preds = [serde.expr_to_proto(ir.BinaryExpr(
        ">", ir.ColumnRef(1, "v"), ir.Literal(10.0, DataType.FLOAT64)))]
    return {"exprs": [json_format.MessageToDict(e) for e in exprs],
            "names": ["k", "v2"],
            "predicates": [json_format.MessageToDict(e) for e in preds]}


_SCHEMA = Schema((Field("k", DataType.INT64),
                  Field("v", DataType.FLOAT64)))


def test_registry_has_default_adaptors():
    assert {"spark", "streaming_calc"} <= set(registered_adaptors())
    assert get_adaptor("spark").name == "spark"


def test_custom_adaptor_registration():
    class MockAdaptor(HostEngineAdaptor):
        name = "mock_engine"

        def convert_plan(self, raw_plan, path_rewrite=None):
            raise NotImplementedError("mock")

    register_adaptor(MockAdaptor())
    assert get_adaptor("mock_engine").name == "mock_engine"


def test_calc_operator_buffer_flush_and_close():
    node, report = get_adaptor("streaming_calc").convert_plan(_calc_spec())
    assert not report.never_converted
    op = CalcOperator(node, _SCHEMA, buffer_rows=8)
    op.open()
    rng = np.random.default_rng(5)
    vals = rng.normal(10.0, 5.0, 20)
    out = []
    for i, v in enumerate(vals):
        out.extend(op.process({"k": i, "v": float(v)}))
    out.extend(op.close())
    exp = [(i, float(v) * 2.0) for i, v in enumerate(vals) if v > 10.0]
    got = sorted((r["k"], r["v2"]) for r in out)
    assert got == sorted(exp)


def test_checkpoint_flushes_buffered_rows_and_restores():
    node, _ = get_adaptor("streaming_calc").convert_plan(_calc_spec())
    emitted = []
    op = CalcOperator(node, _SCHEMA, buffer_rows=1000,
                      on_emit=emitted.append)
    op.open()
    op.process({"k": 1, "v": 20.0})
    op.process({"k": 2, "v": 5.0})
    state = op.snapshot()    # barrier: must flush the 2 buffered rows
    assert [r["k"] for r in emitted] == [1]   # v=5 filtered out
    # restore into a fresh operator: counters survive, buffer is empty
    op2 = CalcOperator(node, _SCHEMA, buffer_rows=1000,
                       on_emit=emitted.append)
    op2.restore(state)
    op2.process({"k": 3, "v": 30.0})
    final = op2.close()
    assert [r["k"] for r in final] == [3]


def test_snapshot_without_sink_refuses_to_drop_rows():
    import pytest
    node, _ = get_adaptor("streaming_calc").convert_plan(_calc_spec())
    op = CalcOperator(node, _SCHEMA, buffer_rows=1000)
    op.open()
    op.process({"k": 1, "v": 20.0})
    with pytest.raises(RuntimeError, match="on_emit"):
        op.snapshot()
