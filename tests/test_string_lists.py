"""List-of-string columns end to end (round-5: unblocks split /
array_join / explode-over-strings / string-list scan+serde; reference:
spark_strings.rs string_split + Arrow list<utf8> handling)."""

import pyarrow as pa
import pytest

from auron_tpu.columnar.arrow_bridge import (schema_from_arrow, to_arrow,
                                             to_device)
from auron_tpu.columnar.schema import DataType
from auron_tpu.columnar.serde import deserialize_batch, serialize_batch
from auron_tpu.exprs import ir
from auron_tpu.io.parquet import MemoryScanOp
from auron_tpu.ops.generate import GenerateOp
from auron_tpu.ops.project import ProjectOp
from auron_tpu.runtime.executor import collect

C = ir.ColumnRef
L = ir.Literal

ROWS = [["a", "bb", None], [], None, ["xyz"], ["q", "q"]]


def _rb():
    return pa.record_batch({
        "s": pa.array(ROWS, pa.list_(pa.string())),
        "t": pa.array(["a,b,c", "", None, "x", "a,,b"], pa.string()),
        "k": pa.array([1, 2, 3, 4, 5], pa.int64()),
    })


def _scan(rb=None):
    rb = rb if rb is not None else _rb()
    return MemoryScanOp([[rb]], schema_from_arrow(rb.schema), capacity=8)


def fn(name, *args):
    return ir.ScalarFunction(name, tuple(args))


def test_roundtrip_scan_and_wire():
    got = collect(ProjectOp(_scan(), [C(0), C(2)], ["s", "k"]))
    assert got.column("s").to_pylist() == ROWS
    batch, sch = to_device(_rb(), capacity=8)
    back = to_arrow(deserialize_batch(serialize_batch(batch), 8), sch)
    assert back.column("s").to_pylist() == ROWS


def test_split():
    got = collect(ProjectOp(_scan(), [fn(
        "split", C(1), L(",", DataType.STRING))], ["p"]))
    assert got.schema.field("p").type == pa.list_(pa.string())
    assert got.column("p").to_pylist() == \
        [["a", "b", "c"], [""], None, ["x"], ["a", "", "b"]]


def test_split_regex_and_limit():
    rb = pa.record_batch({"t": pa.array(["a1b22c333d"])})
    got = collect(ProjectOp(_scan(rb), [fn(
        "split", C(0), L(r"\d+", DataType.STRING))], ["p"]))
    assert got.column("p").to_pylist() == [["a", "b", "c", "d"]]
    got = collect(ProjectOp(_scan(rb), [fn(
        "split", C(0), L(r"\d+", DataType.STRING),
        L(2, DataType.INT32))], ["p"]))
    assert got.column("p").to_pylist() == [["a", "b22c333d"]]


def test_array_join():
    got = collect(ProjectOp(_scan(), [fn(
        "array_join", C(0), L("-", DataType.STRING))], ["j"]))
    # null elements are skipped without a replacement
    assert got.column("j").to_pylist() == ["a-bb", "", None, "xyz", "q-q"]
    got = collect(ProjectOp(_scan(), [fn(
        "array_join", C(0), L("-", DataType.STRING),
        L("NA", DataType.STRING))], ["j"]))
    assert got.column("j").to_pylist() == \
        ["a-bb-NA", "", None, "xyz", "q-q"]


def test_split_then_join_composition():
    got = collect(ProjectOp(_scan(), [fn(
        "array_join", fn("split", C(1), L(",", DataType.STRING)),
        L("|", DataType.STRING))], ["j"]))
    assert got.column("j").to_pylist() == ["a|b|c", "", None, "x", "a||b"]


def test_element_at_and_size():
    got = collect(ProjectOp(_scan(), [
        fn("element_at", C(0), L(1, DataType.INT32)),
        fn("element_at", C(0), L(-1, DataType.INT32)),
        fn("size", C(0))], ["e1", "em1", "n"]))
    assert got.column("e1").to_pylist() == ["a", None, None, "xyz", "q"]
    # element_at(-1): last element; row 0's last is NULL
    assert got.column("em1").to_pylist() == [None, None, None, "xyz", "q"]
    assert got.column("n").to_pylist() == [3, 0, -1, 1, 2]


def test_array_contains_string():
    got = collect(ProjectOp(_scan(), [fn(
        "array_contains", C(0), L("bb", DataType.STRING))], ["c"]))
    # row 0 contains 'bb'; row 4 has no 'bb' and no nulls -> False;
    # rows with null elements and no hit -> NULL
    assert got.column("c").to_pylist() == [True, False, None, False, False]


def test_array_constructor_over_strings():
    rb = pa.record_batch({"a": pa.array(["x", "yy"]),
                          "b": pa.array(["zzz", None])})
    got = collect(ProjectOp(_scan(rb), [fn("array", C(0), C(1))], ["arr"]))
    assert got.column("arr").to_pylist() == [["x", "zzz"], ["yy", None]]


def test_explode_string_list():
    op = GenerateOp(_scan(), "explode", generator=C(0),
                    required_child_output=[2], output_names=["w"])
    got = collect(op)
    assert got.column("k").to_pylist() == [1, 1, 1, 4, 5, 5]
    assert got.column("w").to_pylist() == ["a", "bb", None, "xyz", "q", "q"]


def test_explode_split_composition():
    op = GenerateOp(_scan(), "explode",
                    generator=fn("split", C(1), L(",", DataType.STRING)),
                    required_child_output=[2], output_names=["w"])
    got = collect(op)
    by_k = {}
    for r in got.to_pylist():
        by_k.setdefault(r["k"], []).append(r["w"])
    assert by_k == {1: ["a", "b", "c"], 2: [""], 4: ["x"],
                    5: ["a", "", "b"]}


def test_sort_limit_over_string_list_projection():
    """Generic batch plumbing (resize/concat/order) carries string-list
    columns: ORDER BY + LIMIT over a split() projection."""
    from auron_tpu.ops.limit import LimitOp
    from auron_tpu.ops.sort import SortOp
    op = LimitOp(SortOp(
        ProjectOp(_scan(), [C(2), fn("split", C(1),
                                     L(",", DataType.STRING))],
                  ["k", "p"]),
        [ir.SortOrder(C(0), False, False)]), 3)
    got = collect(op)
    assert got.column("k").to_pylist() == [5, 4, 3]
    assert got.column("p").to_pylist() == [["a", "", "b"], ["x"], None]


def test_split_zero_width_regex_java_semantics():
    # Spark 3.4+ (SPARK-40194): split('abc', '') = ['a','b','c'] — no
    # leading OR trailing empty part for a zero-width regex
    rb = pa.record_batch({"t": pa.array(["abc", ""])})
    got = collect(ProjectOp(_scan(rb), [fn(
        "split", C(0), L("", DataType.STRING))], ["p"]))
    assert got.column("p").to_pylist()[0] == ["a", "b", "c"]


def test_group_by_string_list_rejects_cleanly():
    import pytest

    from auron_tpu.ops.agg import AggOp
    op = AggOp(_scan(), [C(0)], [ir.AggFunction("count", None)],
               mode="complete")
    with pytest.raises(NotImplementedError, match="StringList"):
        collect(op)


class TestStringMaps:
    """map<string,string>: str_to_map + accessors (reference:
    spark_map.rs:417 str_to_map)."""

    def test_str_to_map_defaults(self):
        rb = pa.record_batch({"t": pa.array(
            ["a:1,b:2", "x:9", None, "k", ""], pa.string())})
        got = collect(ProjectOp(_scan(rb), [fn("str_to_map", C(0))], ["m"]))
        assert got.schema.field("m").type == pa.map_(pa.string(),
                                                     pa.string())
        assert got.column("m").to_pylist() == [
            [("a", "1"), ("b", "2")], [("x", "9")], None,
            [("k", None)], [("", None)]]

    def test_str_to_map_custom_delims_and_last_wins(self):
        rb = pa.record_batch({"t": pa.array(["a=1;b=2;a=3"], pa.string())})
        got = collect(ProjectOp(_scan(rb), [fn(
            "str_to_map", C(0), L(";", DataType.STRING),
            L("=", DataType.STRING))], ["m"]))
        assert got.column("m").to_pylist() == [[("a", "3"), ("b", "2")]]

    def test_lookup_duplicate_keys_last_wins(self):
        # ingested maps may hold duplicate keys: lookup takes the LAST
        rows = [[("a", "1"), ("a", "2")]]
        rb = pa.record_batch({
            "m": pa.array(rows, pa.map_(pa.string(), pa.string()))})
        got = collect(ProjectOp(_scan(rb), [fn(
            "element_at", C(0), L("a", DataType.STRING))], ["v"]))
        assert got.column("v").to_pylist() == ["2"]

    def test_lookup_contains_keys_values_size(self):
        rows = [[("a", "1"), ("b", None)], [], None, [("k", "vvv")]]
        rb = pa.record_batch({
            "m": pa.array(rows, pa.map_(pa.string(), pa.string()))})
        got = collect(ProjectOp(_scan(rb), [
            fn("element_at", C(0), L("a", DataType.STRING)),
            fn("map_contains_key", C(0), L("b", DataType.STRING)),
            fn("map_keys", C(0)),
            fn("map_values", C(0)),
            fn("size", C(0))], ["va", "hb", "mk", "mv", "n"]))
        assert got.column("va").to_pylist() == ["1", None, None, None]
        assert got.column("hb").to_pylist() == [True, False, None, False]
        assert got.column("mk").to_pylist() == [["a", "b"], [], None, ["k"]]
        assert got.column("mv").to_pylist() == [["1", None], [], None,
                                                ["vvv"]]
        assert got.column("n").to_pylist() == [2, 0, -1, 1]

    def test_str_to_map_then_lookup(self):
        rb = pa.record_batch({"t": pa.array(["env:prod,region:us"],
                                            pa.string())})
        got = collect(ProjectOp(_scan(rb), [fn(
            "element_at", fn("str_to_map", C(0)),
            L("region", DataType.STRING))], ["r"]))
        assert got.column("r").to_pylist() == ["us"]


def test_sort_array_strings():
    rows = [["pear", "apple", None, "fig"], [], None, ["b", "a", "b"]]
    rb = pa.record_batch({"s": pa.array(rows, pa.list_(pa.string()))})
    got = collect(ProjectOp(_scan(rb), [fn("sort_array", C(0))], ["x"]))
    # Spark sort_array asc: nulls first, then lexicographic
    assert got.column("x").to_pylist() == \
        [[None, "apple", "fig", "pear"], [], None, ["a", "b", "b"]]
    got = collect(ProjectOp(_scan(rb), [fn(
        "sort_array", C(0), L(False, DataType.BOOL))], ["x"]))
    assert got.column("x").to_pylist() == \
        [["pear", "fig", "apple", None], [], None, ["b", "b", "a"]]
