"""RSS (host shuffle service) tier tests.

Mirrors the reference's Celeborn/Uniffle integration contract
(shuffle/rss.rs, CelebornPartitionWriter.scala): push-based map outputs
with atomic commit, offset-indexed partition fetch, cross-host reads
through a separate service instance over the same root, and idempotent
map retries."""

import numpy as np
import pyarrow as pa
import pytest

from auron_tpu.columnar.arrow_bridge import schema_from_arrow
from auron_tpu.exprs import ir
from auron_tpu.io.parquet import MemoryScanOp
from auron_tpu.ops.base import ExecContext
from auron_tpu.parallel.exchange import (RssShuffleExchangeOp,
                                         RssShuffleReadOp)
from auron_tpu.parallel.partitioning import (HashPartitioning,
                                             RangePartitioning)
from auron_tpu.parallel.shuffle_service import FileShuffleService
from auron_tpu.runtime.executor import collect

C = ir.ColumnRef


def _table(n, seed=0, keys=200):
    rng = np.random.default_rng(seed)
    return pa.record_batch({
        "k": pa.array(rng.integers(0, keys, n), pa.int64()),
        "v": pa.array(np.arange(n), pa.int64()),
    })


def _scan(rb, nparts, capacity=256):
    per = rb.num_rows // nparts
    parts = []
    for i in range(nparts):
        sl = rb.slice(i * per, per)
        parts.append([sl.slice(o, capacity)
                      for o in range(0, sl.num_rows, capacity)])
    return MemoryScanOp(parts, schema_from_arrow(rb.schema),
                        capacity=capacity)


class TestServiceLayer:
    def test_writer_commit_and_fetch(self, tmp_path):
        svc = FileShuffleService(str(tmp_path))
        w = svc.partition_writer(7, map_id=0, num_partitions=4,
                                 buffer_bytes=64)
        frames = {p: [f"p{p}-f{i}".encode() for i in range(3)]
                  for p in range(4)}
        for i in range(3):                       # interleaved pushes
            for p in range(4):
                w.write(p, frames[p][i])
        w.commit()
        svc.commit_shuffle(7, num_maps=1)
        for p in range(4):
            got = list(svc.partition_frames(7, p))
            assert got == frames[p], (p, got)

    def test_uncommitted_output_invisible(self, tmp_path):
        svc = FileShuffleService(str(tmp_path))
        w = svc.partition_writer(1, 0, 2)
        w.write(0, b"data")
        # no commit: readers must not see the in-progress file
        assert list(svc.partition_frames(1, 0)) == []
        w.abort()
        assert svc.map_outputs(1) == []

    def test_map_retry_overwrites(self, tmp_path):
        svc = FileShuffleService(str(tmp_path))
        w1 = svc.partition_writer(2, 0, 2)
        w1.write(0, b"attempt-1")
        w1.commit()
        w2 = svc.partition_writer(2, 0, 2)   # retry of the same map
        w2.write(0, b"attempt-2")
        w2.commit()
        svc.commit_shuffle(2, num_maps=1)
        assert list(svc.partition_frames(2, 0)) == [b"attempt-2"]

    def test_stale_maps_excluded_by_manifest(self, tmp_path):
        """A re-planned attempt with FEWER maps must hide the previous
        attempt's extra map outputs (the manifest is the source of
        truth)."""
        svc = FileShuffleService(str(tmp_path))
        for m in range(4):                        # attempt 1: 4 maps
            w = svc.partition_writer(6, m, 2)
            w.write(0, f"a1-m{m}".encode())
            w.commit()
        svc.commit_shuffle(6, num_maps=4)
        svc.begin_shuffle(6)                      # attempt 2: 2 maps
        for m in range(2):
            w = svc.partition_writer(6, m, 2)
            w.write(0, f"a2-m{m}".encode())
            w.commit()
        svc.commit_shuffle(6, num_maps=2)
        assert list(svc.partition_frames(6, 0)) == [b"a2-m0", b"a2-m1"]


class TestRssExchange:
    def test_hash_shuffle_roundtrip_multimap(self, tmp_path):
        rb = _table(2048, seed=1)
        svc = FileShuffleService(str(tmp_path))
        op = RssShuffleExchangeOp(
            _scan(rb, nparts=4), HashPartitioning([C(0)], 8), svc,
            shuffle_id=11, input_partitions=4)
        got_rows = 0
        key_sets = []
        for p in range(8):
            ctx = ExecContext(partition_id=p, num_partitions=8)
            from auron_tpu.columnar.arrow_bridge import to_arrow
            parts = [to_arrow(b, op.schema()) for b in op.execute(p, ctx)]
            if parts:
                tbl = pa.Table.from_batches(parts)
                got_rows += tbl.num_rows
                key_sets.append(set(tbl.column("k").to_pylist()))
        assert got_rows == 2048
        # hash partitioning: key sets are disjoint across partitions
        for i in range(len(key_sets)):
            for j in range(i + 1, len(key_sets)):
                assert not (key_sets[i] & key_sets[j])

    def test_cross_host_read(self, tmp_path):
        """Writer host materializes; a DIFFERENT service instance (the
        'other host') reads the committed shuffle with RssShuffleReadOp."""
        rb = _table(1000, seed=3)
        schema = schema_from_arrow(rb.schema)
        svc_a = FileShuffleService(str(tmp_path))
        op = RssShuffleExchangeOp(_scan(rb, nparts=2),
                                  HashPartitioning([C(0)], 4), svc_a,
                                  shuffle_id=5, input_partitions=2)
        # host A materializes by reading one partition
        from auron_tpu.columnar.arrow_bridge import to_arrow
        list(op.execute(0, ExecContext()))

        svc_b = FileShuffleService(str(tmp_path))   # host B
        reader = RssShuffleReadOp(svc_b, 5, schema, 4)
        rows = 0
        vals = []
        for p in range(4):
            ctx = ExecContext(partition_id=p, num_partitions=4)
            for b in reader.execute(p, ctx):
                t = to_arrow(b, schema)
                rows += t.num_rows
                vals.extend(t.column("v").to_pylist())
        assert rows == 1000
        assert sorted(vals) == list(range(1000))

    def test_range_partitioned_rss(self, tmp_path):
        rb = _table(1200, seed=7, keys=10_000)
        svc = FileShuffleService(str(tmp_path))
        op = RssShuffleExchangeOp(
            _scan(rb, nparts=3),
            RangePartitioning((ir.SortOrder(C(0)),), 4, ()), svc,
            shuffle_id=9, input_partitions=3)
        from auron_tpu.columnar.arrow_bridge import to_arrow
        maxes = []
        total = 0
        for p in range(4):
            ctx = ExecContext(partition_id=p, num_partitions=4)
            ks = []
            for b in op.execute(p, ctx):
                ks.extend(to_arrow(b, op.schema()).column("k").to_pylist())
            total += len(ks)
            if ks:
                maxes.append((p, min(ks), max(ks)))
        assert total == 1200
        # range property: partition p's max <= partition p+1's min
        for (p1, _lo1, hi1), (p2, lo2, _hi2) in zip(maxes, maxes[1:]):
            assert hi1 <= lo2, (maxes,)

    def test_two_process_shuffle(self, tmp_path):
        """VERDICT r3 directive 9: the map side runs in a SEPARATE engine
        process (driven over the serving boundary); the reducer side runs
        here, reading the committed frames from the shared service root —
        byte-identical content with the in-process path (reference role:
        thirdparty/auron-celeborn-0.6/.../CelebornPartitionWriter.scala)."""
        import os
        import subprocess
        import sys
        import pyarrow.parquet as pq
        from auron_tpu.ir import pb
        from auron_tpu.ir.serde import expr_to_proto, schema_to_proto
        from auron_tpu.ir.planner import PlannerContext, plan_from_bytes
        from auron_tpu.runtime.serving import AuronClient
        from auron_tpu.utils.envsafe import cpu_child_env

        rb = _table(2_000, seed=13)
        src = str(tmp_path / "src.parquet")
        pq.write_table(pa.Table.from_batches([rb]), src)
        rss_root = str(tmp_path / "rss")
        n_out = 4

        def writer_task(partition_id):
            node = pb.PlanNode(shuffle_writer=pb.ShuffleWriterNode(
                child=pb.PlanNode(parquet_scan=pb.ParquetScanNode(
                    files=[src])),
                partitioning=pb.PartitioningP(
                    kind="hash", num_partitions=n_out,
                    hash_keys=[expr_to_proto(C(0))]),
                rss_root=rss_root, shuffle_id=9))
            return pb.TaskDefinition(partition_id=partition_id,
                                     num_partitions=1,
                                     plan=node).SerializeToString()

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = cpu_child_env(repo, n_devices=2)
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        proc = subprocess.Popen(
            [sys.executable, "-m", "auron_tpu.runtime.serving"],
            stdout=subprocess.PIPE, text=True, env=env, cwd=repo)
        try:
            line = proc.stdout.readline().strip()
            host, port = line.split()[1].split(":")
            client = AuronClient(host, int(port), timeout_s=180)
            _tbl, metrics = client.execute(writer_task(0))
            assert metrics is not None
        finally:
            proc.terminate()
            proc.wait(timeout=30)

        # reducer side in THIS process: read through a plan node over the
        # shared root, as a remote reducer host would
        schema = schema_from_arrow(rb.schema)
        read_node = pb.PlanNode(rss_shuffle_read=pb.RssShuffleReadNode(
            rss_root=rss_root, shuffle_id=9,
            schema=schema_to_proto(schema), num_partitions=n_out))
        read_op = plan_from_bytes(
            pb.TaskDefinition(plan=read_node).SerializeToString(),
            PlannerContext())
        from auron_tpu.columnar.arrow_bridge import to_arrow
        got = {}
        for p in range(n_out):
            ctx = ExecContext(partition_id=p, num_partitions=n_out)
            for b in read_op.execute(p, ctx):
                t = to_arrow(b, read_op.schema())
                for r in t.to_pylist():
                    got.setdefault(r["k"], []).append(r["v"])
        exp = {}
        for k, v in zip(rb.column(0).to_pylist(), rb.column(1).to_pylist()):
            exp.setdefault(k, []).append(v)
        assert set(got) == set(exp)
        for k in exp:
            assert sorted(got[k]) == sorted(exp[k])

    def test_proto_plan_rss(self, tmp_path):
        """ShuffleWriterNode.rss_root routes through the service tier."""
        import pyarrow.parquet as pq
        from auron_tpu.ir import pb
        from auron_tpu.ir.planner import PlannerContext, plan_from_bytes
        from auron_tpu.ir.serde import expr_to_proto
        from auron_tpu.columnar.arrow_bridge import to_arrow

        rb = _table(500, seed=11)
        src = str(tmp_path / "src.parquet")
        pq.write_table(pa.Table.from_batches([rb]), src)
        node = pb.PlanNode(shuffle_writer=pb.ShuffleWriterNode(
            child=pb.PlanNode(parquet_scan=pb.ParquetScanNode(files=[src])),
            partitioning=pb.PartitioningP(
                kind="hash", num_partitions=4,
                hash_keys=[expr_to_proto(C(0))]),
            rss_root=str(tmp_path / "rss"), shuffle_id=3))
        task = pb.TaskDefinition(stage_id=0, partition_id=0, task_id=1,
                                 plan=node)
        op = plan_from_bytes(task.SerializeToString(), PlannerContext())
        rows = 0
        for p in range(4):
            ctx = ExecContext(partition_id=p, num_partitions=4)
            for b in op.execute(p, ctx):
                rows += to_arrow(b, op.schema()).num_rows
        assert rows == 500
        # frames really live under the service root
        svc = FileShuffleService(str(tmp_path / "rss"))
        assert svc.map_outputs(3)